#!/usr/bin/env python
"""Training entry point — parity with the reference's ``train.py``
(SURVEY.md §4.1; BASELINE.json:5): config → panel → model → (ensemble)
training → checkpoints + metrics.

Usage:
    python train.py --preset c1                 # ladder preset (c1..c5)
    python train.py --config my_config.json     # explicit config file
    python train.py --preset c2 --seed 3 --epochs 5 --echo

Multi-seed presets (n_seeds > 1) run the vmap'd ensemble trainer.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__,
                                 formatter_class=argparse.RawDescriptionHelpFormatter)
    g = ap.add_mutually_exclusive_group(required=True)
    g.add_argument("--preset", help="ladder preset name (c1..c5 or full name)")
    g.add_argument("--config", help="path to a RunConfig JSON file")
    ap.add_argument("--seed", type=int, default=None, help="override seed")
    ap.add_argument("--epochs", type=int, default=None, help="override epochs")
    ap.add_argument("--n-seeds", type=int, default=None,
                    help="override ensemble size")
    ap.add_argument("--out", default=None, help="override output dir")
    ap.add_argument("--echo", action="store_true", help="print metrics lines")
    ap.add_argument("--scale", type=float, default=None,
                    help="shrink the synthetic panel by this factor (smoke runs)")
    ap.add_argument("--resume", action="store_true",
                    help="resume from the latest checkpoint in the run dir")
    ap.add_argument("--debug", action="store_true",
                    help="sanitizer mode: raise on any NaN/Inf inside jit")
    ap.add_argument("--profile", metavar="DIR", default=None,
                    help="write a jax.profiler trace (Perfetto) to DIR")
    ap.add_argument("--walk-forward", metavar="STEP_MONTHS", type=int,
                    default=None,
                    help="walk-forward mode: retrain every STEP_MONTHS "
                         "months and stitch the out-of-sample forecasts "
                         "(train/walkforward.py); writes walkforward.npz "
                         "for backtest.py --forecast-npz")
    ap.add_argument("--wf-start", type=int, default=None,
                    help="first fold's train_end (YYYYMM; default: 60%% "
                         "through the panel)")
    ap.add_argument("--wf-val-months", type=int, default=24,
                    help="validation window per fold (months)")
    ap.add_argument("--wf-folds", type=int, default=None,
                    help="cap the number of folds (default: run to the "
                         "panel's end)")
    ap.add_argument("--wf-warm-start", action="store_true",
                    help="initialize each fold's weights from the previous "
                         "fold's best state (optimizer restarts fresh) — "
                         "the wall-clock lever for long retraining sweeps; "
                         "no lookahead (the prior fold saw strictly "
                         "earlier data)")
    ap.add_argument("--wf-train-months", type=int, default=None,
                    help="rolling train window per fold (months; default: "
                         "expanding window). Fixed-length folds keep "
                         "identical batch shapes, so the cross-fold reuse "
                         "layer compiles the whole sweep exactly once")
    ap.add_argument("--wf-foldstack", action="store_true",
                    help="train ALL same-shape folds as ONE stacked, "
                         "fold-sharded, pipelined program "
                         "(train/foldstack.py; needs --wf-train-months) "
                         "instead of sequential fits — per-fold results "
                         "match sequential execution; LFM_FOLDSTACK=1 is "
                         "the env equivalent")
    ap.add_argument("--sweep-grid", metavar="SPEC", default=None,
                    help="hyperparameter config sweep: semicolon-"
                         "separated axes of comma-separated values "
                         "(e.g. 'lr=1e-3,5e-4;weight_decay=1e-4,0'), "
                         "cartesian-expanded and trained as ONE stacked "
                         "compiled program (train/stacked.py) with "
                         "per-config LR/weight-decay threaded as vmapped "
                         "per-run operands — zero per-config traces. "
                         "LFM_SWEEP_STACKED=0 forces the sequential "
                         "per-config reference; per-config run dirs + "
                         "sweep_summary.json land under "
                         "<out>/<name>/sweep. COMPOSES with "
                         "--walk-forward: the fold × config PRODUCT "
                         "trains as one stack (each run carries its own "
                         "(cfg, splits) pair; use --wf-train-months so "
                         "folds stay same-shape/stackable) and "
                         "sweep_summary.json ranks configs by mean best "
                         "val IC across folds — run dirs under "
                         "<out>/<name>/wf_sweep/fold_<k>/config_<j>")
    ap.add_argument("--wf-score", metavar="MODES", default=None,
                    help="grade the stitched out-of-sample panel at the "
                         "end of the sweep: comma-separated aggregation "
                         "modes, each optionally MODE@LAMBDA (e.g. "
                         "'mean,mean_minus_std@0.5,mean_minus_std@2'). "
                         "Runs through the fused device-resident scoring "
                         "path (LFM_JAX_BACKTEST, default on; numpy "
                         "engine as fallback); reports land in "
                         "summary.json under 'backtest'")
    args = ap.parse_args(argv)
    if args.walk_forward is None and (
            args.wf_start is not None or args.wf_folds is not None
            or args.wf_val_months != 24 or args.wf_warm_start
            or args.wf_train_months is not None or args.wf_score is not None
            or args.wf_foldstack):
        ap.error("--wf-start/--wf-val-months/--wf-folds/--wf-warm-start/"
                 "--wf-train-months/--wf-score/--wf-foldstack need "
                 "--walk-forward STEP_MONTHS")
    if args.wf_foldstack and args.wf_train_months is None:
        ap.error("--wf-foldstack needs --wf-train-months (fold-stacking "
                 "requires the rolling-window same-shape schedule)")
    if args.wf_foldstack and (args.wf_warm_start or args.resume):
        ap.error("--wf-foldstack is incompatible with --wf-warm-start/"
                 "--resume (the stacked fit checkpoints folds only at "
                 "finalize; the warm-start carry is serial)")
    sweep_grid = None
    if args.sweep_grid is not None:
        if args.walk_forward is not None and (
                args.wf_foldstack or args.wf_warm_start
                or args.wf_score is not None):
            ap.error("--sweep-grid × --walk-forward selects configs "
                     "(no stitching), so --wf-foldstack/--wf-warm-start/"
                     "--wf-score don't apply — pick the winning config "
                     "here, then run the plain walk-forward with it")
        if args.resume:
            ap.error("--sweep-grid is incompatible with --resume (the "
                     "stacked sweep writes config checkpoints only at "
                     "finalize — nothing per-epoch to resume from)")
        # Validate at parse time, not after hours of panel/device setup:
        # a typo'd axis must fail before any backend is touched.
        from lfm_quant_tpu.train.stacked import parse_sweep_grid

        try:
            sweep_grid = parse_sweep_grid(args.sweep_grid)
        except ValueError as e:
            ap.error(f"--sweep-grid: {e}")
    wf_score_modes = None
    if args.wf_score:
        # Validate HERE, not at end-of-sweep: a typo'd mode must fail at
        # parse time, not after hours of fold training (normalize_modes
        # is numpy-only — no jax init cost at argparse time).
        from lfm_quant_tpu.backtest.engine import normalize_modes

        wf_score_modes = []
        try:
            for tok in args.wf_score.split(","):
                mode, _, lam = tok.strip().partition("@")
                wf_score_modes.append((mode, float(lam)) if lam else mode)
            normalize_modes(wf_score_modes)
        except ValueError as e:
            ap.error(f"--wf-score: {e}")

    # Import late so --help works instantly without initializing JAX.
    import dataclasses

    from lfm_quant_tpu.config import RunConfig, get_preset

    if args.preset:
        cfg = get_preset(args.preset)
    else:
        with open(args.config) as fh:
            cfg = RunConfig.from_json(fh.read())
    if args.seed is not None:
        cfg = dataclasses.replace(cfg, seed=args.seed)
    if args.epochs is not None:
        cfg = dataclasses.replace(
            cfg, optim=dataclasses.replace(cfg.optim, epochs=args.epochs))
    if args.n_seeds is not None:
        cfg = dataclasses.replace(cfg, n_seeds=args.n_seeds)
    if args.out is not None:
        cfg = dataclasses.replace(cfg, out_dir=args.out)
    if wf_score_modes is not None:
        names = [m[0] if isinstance(m, tuple) else m for m in wf_score_modes]
        if cfg.n_seeds < 2 and "mean_minus_std" in names:
            ap.error("--wf-score mean_minus_std needs stacked forecasts "
                     "(n_seeds > 1); a single-seed sweep stitches one "
                     "model's panel, whose seed-axis std is identically 0")
        if "mean_minus_total_std" in names and not cfg.is_heteroscedastic:
            ap.error("--wf-score mean_minus_total_std needs stitched "
                     "aleatoric variances — train the walk-forward with a "
                     "heteroscedastic config (loss='nll')")
    if args.scale is not None:
        d = cfg.data
        cfg = dataclasses.replace(cfg, data=dataclasses.replace(
            d,
            n_firms=max(64, int(d.n_firms * args.scale)),
            # Floor keeps the scaled panel valid: longer than the synthetic
            # generator's min_history (72) with room for window + splits.
            n_months=max(d.window + d.horizon + 96, 120,
                         int(d.n_months * args.scale)),
        ))

    import contextlib
    import os

    from lfm_quant_tpu.utils import sanitized, telemetry, trace_context
    from lfm_quant_tpu.utils.distributed import maybe_initialize

    maybe_initialize()  # multi-host pods; no-op on a single host

    # The run dir each branch will write into — known up front so the
    # telemetry run scope (manifest.json at start; spans.jsonl +
    # trace.json + ledger.jsonl over the run) covers the whole run.
    # LFM_TELEMETRY=0 makes the scope a no-op.
    if args.walk_forward is not None and sweep_grid is not None:
        run_dir = os.path.join(cfg.out_dir, cfg.name, "wf_sweep")
    elif args.walk_forward is not None:
        run_dir = os.path.join(cfg.out_dir, cfg.name, "wf")
    elif sweep_grid is not None:
        run_dir = os.path.join(cfg.out_dir, cfg.name, "sweep")
    elif cfg.n_seeds > 1:
        run_dir = os.path.join(cfg.out_dir, cfg.name, "ensemble")
    else:
        run_dir = os.path.join(cfg.out_dir, cfg.name, f"seed{cfg.seed}")

    from lfm_quant_tpu.train.preempt import Preempted, grace_scope

    try:
        with contextlib.ExitStack() as ctx:
            if args.debug:
                ctx.enter_context(sanitized())
            ctx.enter_context(trace_context(args.profile))
            ctx.enter_context(telemetry.run_scope(
                run_dir, cfg, extra={"entry": "train"}))
            # SIGTERM grace (train/preempt.py, DESIGN.md §18):
            # preemptible capacity delivers SIGTERM with a grace window;
            # the scope turns it into a clean stop at the next epoch
            # boundary with the checkpoint lines flushed, surfaced
            # below as exit code 75 (EX_TEMPFAIL: re-run with --resume).
            ctx.enter_context(grace_scope())
            if args.walk_forward is not None and sweep_grid is not None:
                from lfm_quant_tpu.train.loop import resolve_panel
                from lfm_quant_tpu.train.stacked import run_walkforward_sweep

                panel = resolve_panel(cfg.data)
                start = args.wf_start or int(
                    panel.dates[int(panel.n_months * 0.6)])
                summary = run_walkforward_sweep(
                    cfg, sweep_grid, panel=panel, start=start,
                    step_months=args.walk_forward,
                    val_months=args.wf_val_months, n_folds=args.wf_folds,
                    train_months=args.wf_train_months, out_dir=run_dir,
                    echo=args.echo)
                summary["run_dir"] = run_dir
            elif args.walk_forward is not None:
                from lfm_quant_tpu.train.loop import resolve_panel
                from lfm_quant_tpu.train.walkforward import run_walkforward

                panel = resolve_panel(cfg.data)
                start = args.wf_start or int(
                    panel.dates[int(panel.n_months * 0.6)])
                wf_dir = run_dir
                _, _, summary = run_walkforward(
                    cfg, panel, start=start, step_months=args.walk_forward,
                    val_months=args.wf_val_months, n_folds=args.wf_folds,
                    out_dir=wf_dir, echo=args.echo, resume=args.resume,
                    warm_start=args.wf_warm_start,
                    train_months=args.wf_train_months,
                    score_modes=wf_score_modes,
                    foldstack=True if args.wf_foldstack else None)
                summary["run_dir"] = wf_dir
            elif sweep_grid is not None:
                from lfm_quant_tpu.train.stacked import run_config_sweep

                summary = run_config_sweep(cfg, sweep_grid, out_dir=run_dir,
                                           echo=args.echo)
                summary["run_dir"] = run_dir
            elif cfg.n_seeds > 1:
                from lfm_quant_tpu.train.ensemble import run_ensemble_experiment
                summary, _, _ = run_ensemble_experiment(
                    cfg, echo=args.echo, resume=args.resume)
            else:
                from lfm_quant_tpu.train.loop import run_experiment
                summary, _, _ = run_experiment(
                    cfg, echo=args.echo, resume=args.resume)
    except Preempted as e:
        # Graceful preemption: everything recorded is durable. 75 =
        # EX_TEMPFAIL — the scheduler-facing "transient, re-run me".
        print(json.dumps({"preempted": True, "detail": str(e),
                          "run_dir": run_dir,
                          "resume_hint": "re-run with --resume"},
                         indent=2))
        return 75
    print(json.dumps({k: v for k, v in summary.items() if k != "history"},
                     indent=2, default=str))
    return 0


if __name__ == "__main__":
    sys.exit(main())
