"""TPU-native framework for recurrent factor models on firm×month panels.

A from-scratch JAX/XLA rebuild of the capabilities of ``lakshaykc/lfm_quant``
(TensorFlow/CUDA lineage — see SURVEY.md; the reference checkout was empty
when surveyed, so parity is defined against the functional spec in
SURVEY.md §2–§7 / BASELINE.json, not against reference file:line cites).

Layer map (SURVEY.md §2):
  data/      — L1 panel store + L2 windowing pipeline
  models/    — L3 MLP / LSTM / GRU / transformer factor models
  ops/       — losses (masked MSE, cross-sectional rank-IC) and metrics
  train/     — L4 training loop, checkpointing, L5 multi-seed ensembles
  parallel/  — device mesh + shardings (DP over dates, ensemble over seeds)
  backtest/  — forecasts → monthly ranks → portfolio → CAGR/Sharpe/IC
  utils/     — profiling/throughput harness, misc
"""

__version__ = "0.1.0"
