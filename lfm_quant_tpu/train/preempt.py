"""SIGTERM / preemption grace layer for fits (DESIGN.md §18).

The pod-scale flagship plan (ROADMAP open item 1) runs on preemptible
capacity, where the scheduler delivers SIGTERM with a short grace
window. Pre-chaos, a SIGTERM mid-epoch killed the process wherever it
stood: async Orbax saves could die half-staged and resume correctness
rested on the sidecar-reconciliation crash path alone. This module
turns the signal into a CLEAN stop at the next epoch boundary:

* :func:`grace_scope` installs a SIGTERM handler (ref-counted — nested
  fits share one installation; restored on exit) that does nothing but
  set a flag. Installation is skipped off the main thread (CPython
  restriction) — a fit driven from a worker thread keeps default
  delivery.
* The epoch driver (train/pipeline.py ``run_fit_epochs``) checks
  :func:`requested` once per loop iteration: when set, it SETTLES the
  in-flight epoch (recorded and checkpointed like any other — never
  discarded), flushes both async checkpoint lines via the harness's
  ``preempt_flush`` (bounded waits, train/checkpoint.py), and raises
  :class:`Preempted`.
* The entry points (train.py) catch :class:`Preempted` and exit 75
  (EX_TEMPFAIL — "transient, re-run me"); a re-run with ``--resume``
  continues from the last recorded epoch with IDENTICAL history
  (samplers are deterministic in (seed, epoch); the kill-mid-epoch
  subprocess test in tests/test_chaos.py pins bit-identical history and
  best params vs an uninterrupted fit).

:class:`Preempted` subclasses ``BaseException`` (like
``KeyboardInterrupt``) on purpose: blanket ``except Exception``
degrade-don't-die paths (e.g. the walk-forward fold recovery) must
never swallow a preemption and keep training into the kill window.

Deterministic preemption for tests comes from the fault harness: a
``ckpt_write:at=K,kind=sigterm`` ``LFM_FAULTS`` spec (utils/faults.py)
delivers the SIGTERM at an exact checkpoint write.
"""

from __future__ import annotations

import contextlib
import signal
import threading
from typing import Optional


class Preempted(BaseException):
    """Raised by the epoch driver after a SIGTERM grace stop: the last
    settled epoch is recorded and durable; nothing after it ran."""

    def __init__(self, epoch: Optional[int] = None):
        super().__init__(
            "fit preempted by SIGTERM"
            + (f" (grace stop after epoch {epoch})" if epoch is not None
               else " (grace stop before the first epoch settled)"))
        self.epoch = epoch


_EVENT = threading.Event()
_LOCK = threading.Lock()
_DEPTH = 0
_PREV = None
_INSTALLED = False


def requested() -> bool:
    """Whether a SIGTERM arrived since the last :func:`clear`."""
    return _EVENT.is_set()


def clear() -> None:
    """Reset the flag (tests / long-lived drivers that survived a
    graceful stop). The entry points never clear — the process exits."""
    _EVENT.clear()


def _handler(signum, frame):
    # Signal-handler minimal: set the flag; the epoch driver does the
    # settle + flush at the next boundary. The counter bump is safe —
    # Python handlers run between bytecodes, not in async-signal
    # context — and makes the request visible in the run record.
    _EVENT.set()
    try:
        from lfm_quant_tpu.utils import telemetry

        telemetry.COUNTERS.set("preempt_requested", 1)
    except Exception:  # noqa: BLE001 — the flag is the contract
        pass


@contextlib.contextmanager
def grace_scope():
    """Install the SIGTERM grace handler for the duration of a fit (or
    a whole entry-point run). Ref-counted: nested scopes (entry point →
    walk-forward → per-fold fit) share one installation; the outermost
    exit restores the previous handler. No-op off the main thread."""
    global _DEPTH, _PREV, _INSTALLED
    with _LOCK:
        _DEPTH += 1
        if _DEPTH == 1:
            try:
                _PREV = signal.signal(signal.SIGTERM, _handler)
                _INSTALLED = True
            except ValueError:  # not the main thread
                _INSTALLED = False
    try:
        yield
    finally:
        with _LOCK:
            _DEPTH -= 1
            if _DEPTH == 0 and _INSTALLED:
                try:
                    signal.signal(signal.SIGTERM, _PREV)
                except ValueError:  # pragma: no cover — symmetric guard
                    pass
                _INSTALLED = False
                _PREV = None
