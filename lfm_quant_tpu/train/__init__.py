"""Training layer (L4) + experiment orchestration (L5)."""

from lfm_quant_tpu.train.checkpoint import CheckpointManager
from lfm_quant_tpu.train.loop import TrainState, Trainer, make_loss_fn

__all__ = ["Trainer", "TrainState", "make_loss_fn", "CheckpointManager"]
