"""Walk-forward retraining — the multi-decade out-of-sample protocol.

The reference lineage backtests 1970–2024 (BASELINE.json:5). A single
train/val/test split over five decades leaks regime information: one
model, selected once, is graded on thirty years it never had to adapt
to — and its validation period sits decades before most of the test
months. The standard protocol (SURVEY.md §2 L5 "experiment
orchestration") is walk-forward: at each fold, train on everything up to
``train_end``, early-stop on the next ``val_months``, forecast ONLY the
following ``step_months``, then roll forward and retrain. Stitching the
per-fold forecasts yields one out-of-sample forecast panel where every
prediction comes from a model that saw strictly earlier data — the input
``backtest.py`` grades.

TPU notes: each fold retrains over the SAME HBM-resident panel
(PanelSplits never slices, so fold boundaries are free); the per-fold
prediction window is a bounded month-index range passed straight to
``predict(date_range=...)``. The sweep holds ONE trainer and
``rebind()``s it per fold, so the cross-fold reuse layer
(train/reuse.py) makes the whole sweep compile once and transfer the
panel once: fold k+1 binds fold k's jitted executables and device-
resident panel whenever the program key is unchanged (same-shape folds —
any ``train_months`` rolling window, or an expanding window whose
eligible-date count doesn't cross a ``dates_per_batch`` boundary). Every
fold record carries the measured compile/transfer deltas (``reuse``
key), so the amortization is asserted by tests and the
``bench.py walkforward_reuse`` metric, not assumed.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from lfm_quant_tpu.config import RunConfig
from lfm_quant_tpu.data.panel import Panel, PanelSplits


def month_add(yyyymm: int, months: int) -> int:
    """Calendar-correct YYYYMM arithmetic (months may be negative)."""
    y, m = divmod(yyyymm, 100)
    t = y * 12 + (m - 1) + months
    return (t // 12) * 100 + t % 12 + 1


def walkforward_folds(panel: Panel, start: int, step_months: int,
                      val_months: int,
                      n_folds: Optional[int] = None
                      ) -> List[Tuple[int, int, Tuple[int, int]]]:
    """Fold schedule: [(train_end, val_end, (pred_lo_idx, pred_hi_idx))].

    Fold k trains on anchors < train_end (embargoed by the horizon, see
    PanelSplits), validates on [train_end, val_end), and forecasts the
    month-INDEX range [pred_lo, pred_hi) covering the ``step_months``
    right after val_end. Folds advance by ``step_months``, so prediction
    windows tile the out-of-sample period without overlap. The schedule
    stops once a fold's window would start inside the panel's final
    ``horizon`` months — anchors there have no realized target yet
    (windows.py anchor_index), so they are neither predictable by the
    samplers nor gradeable by the backtest.
    """
    if step_months < 1:
        raise ValueError(f"step_months must be >= 1, got {step_months}")
    if val_months < 1:
        raise ValueError(f"val_months must be >= 1, got {val_months}")
    dates = panel.dates
    usable = panel.n_months - panel.horizon  # last month with a target
    folds = []
    train_end = start
    while n_folds is None or len(folds) < n_folds:
        val_end = month_add(train_end, val_months)
        test_end = month_add(val_end, step_months)
        lo = int(np.searchsorted(dates, val_end))
        hi = int(np.searchsorted(dates, test_end))
        if lo >= usable or lo == hi:
            break  # no gradeable out-of-sample months left
        folds.append((train_end, val_end, (lo, hi)))
        train_end = month_add(train_end, step_months)
    if not folds:
        raise ValueError(
            f"no walk-forward folds fit: start={start} val={val_months}mo "
            f"step={step_months}mo vs panel [{dates[0]}, {dates[-1]}]")
    return folds


def _report_scalars(rep) -> Dict[str, Any]:
    """JSON-friendly digest of a BacktestReport: every scalar field plus
    the one-line summary (the monthly arrays stay out of summary.json —
    the stitched npz already carries the underlying panel)."""
    digest = {
        k: v for k, v in dataclasses.asdict(rep).items()
        if isinstance(v, (int, float))
    }
    digest["summary"] = rep.summary()
    return digest


def score_stitched(forecast: np.ndarray, valid: np.ndarray, panel: Panel,
                   score_modes: Sequence, variance=None,
                   **backtest_kw) -> Dict[str, Any]:
    """Grade a stitched out-of-sample forecast panel over an aggregation-
    mode grid through the device-resident scoring path.

    With ``LFM_JAX_BACKTEST`` on (the default), ALL modes are aggregated
    from one stacked tensor and backtested in ONE fused dispatch
    (backtest/jax_engine.py); otherwise each mode takes the numpy
    reference path — identical reports either way, within float32
    tolerance (the parity suite's contract). Returns
    {mode label: report digest}.
    """
    from lfm_quant_tpu.backtest import jax_backtest_enabled
    from lfm_quant_tpu.backtest.engine import mode_label, normalize_modes

    kw = dict(backtest_kw)
    specs = normalize_modes(score_modes, kw.pop("risk_lambda", 1.0))
    if forecast.ndim == 2 and any(m == "mean_minus_std" for m, _ in specs):
        # Same rule as the backtest.py CLI: a single stitched model has a
        # degenerate seed axis — every λ would silently relabel "mean".
        raise ValueError(
            "mean_minus_std needs stacked forecasts (n_seeds > 1 walk-"
            "forward); this sweep stitched a single model's panel")
    stacked = forecast if forecast.ndim == 3 else forecast[None]
    avar = None
    if variance is not None:
        avar = variance if variance.ndim == 3 else variance[None]
    reports = None
    if jax_backtest_enabled():
        try:
            from lfm_quant_tpu.backtest.jax_engine import run_scoring_pipeline

            reports = run_scoring_pipeline(stacked, valid, panel,
                                           modes=specs, aleatoric_var=avar,
                                           **kw)
        except ImportError:
            reports = None  # no jax on this host — numpy fallback below
    if reports is None:
        from lfm_quant_tpu.backtest import aggregate_ensemble, run_backtest

        reports = {}
        for mode, lam in specs:
            fc, v = aggregate_ensemble(stacked, valid, mode, lam,
                                       aleatoric_var=avar)
            reports[mode_label(mode, lam)] = run_backtest(fc, v, panel, **kw)
    return {label: _report_scalars(rep) for label, rep in reports.items()}


def write_fold_run_dir(fold_cfg: RunConfig, run_dir: str, train_end: int,
                       val_end: int, train_start: Optional[int],
                       ensemble: bool) -> None:
    """Make a fold dir a standalone loadable run dir
    (``load_trainer``/``load_ensemble``): config.json pins the FOLD's
    split boundaries so a reload reconstructs the exact training-time
    splits, and the ensemble marker routes ``load_forecaster``. Written
    BEFORE fit so a crashed fold is still inspectable (``forecast.py``
    uses the LAST fold — the model trained on the most recent data — for
    live rankings). Shared by the sequential and fold-stacked paths."""
    from lfm_quant_tpu.train.forecast import mark_ensemble_run_dir

    os.makedirs(run_dir, exist_ok=True)
    save_cfg = dataclasses.replace(
        fold_cfg, data=dataclasses.replace(
            fold_cfg.data, train_end=train_end, val_end=val_end,
            train_start=train_start))
    with open(os.path.join(run_dir, "config.json"), "w") as fh:
        fh.write(save_cfg.to_json())
    # Also CLEARS a stale flag when a reused dir flips trainer kind
    # between runs.
    mark_ensemble_run_dir(run_dir, ensemble)


def _load_fold_best_params(trainer, fold_dir: str):
    """Best params of a previously-completed fold, restored from its
    ``ckpt/best`` line — the warm-start carry for folds whose in-memory
    predecessor state is gone (``resume`` skipped the fold in this
    process). Returns None (fresh init, with a warning) when the
    checkpoint line is missing or unreadable: a degraded carry must not
    kill a multi-fold resume."""
    import warnings

    from lfm_quant_tpu.train.checkpoint import CheckpointManager
    from lfm_quant_tpu.train.loop import restore_state_dict

    mgr = CheckpointManager(os.path.join(fold_dir, "ckpt", "best"),
                            max_to_keep=1)
    try:
        if mgr.latest_step() is None:
            warnings.warn(
                f"warm_start: no best checkpoint under {fold_dir} — "
                "fold falls back to a fresh init")
            return None
        restored = restore_state_dict(mgr, trainer.init_state()._asdict())
        return restored["params"]
    except Exception as e:  # noqa: BLE001 — degrade, don't kill the sweep
        warnings.warn(
            f"warm_start: could not restore {fold_dir} best checkpoint "
            f"({type(e).__name__}: {e}) — fold falls back to a fresh init")
        return None
    finally:
        mgr.close()


def run_walkforward(cfg: RunConfig, panel: Panel, *, start: int,
                    step_months: int = 12, val_months: int = 24,
                    n_folds: Optional[int] = None, out_dir: Optional[str] = None,
                    echo: bool = False, resume: bool = False,
                    warm_start: bool = False,
                    train_months: Optional[int] = None,
                    score_modes: Optional[Sequence] = None,
                    score_kwargs: Optional[Dict[str, Any]] = None,
                    foldstack: Optional[bool] = None
                    ) -> Tuple[np.ndarray, np.ndarray, Dict[str, Any]]:
    """Train a model (or seed ensemble, ``cfg.n_seeds > 1``) per fold and
    stitch the out-of-sample forecasts.

    Returns ``(forecast, valid, summary)`` where forecast is [N, T]
    (single) or [S, N, T] (ensemble — aggregate downstream exactly like
    ``EnsembleTrainer.predict`` output), valid is [N, T] and True only in
    the stitched out-of-sample months, and summary carries per-fold
    records. Heteroscedastic configs (``model.heteroscedastic`` or
    ``loss="nll"``) additionally stitch per-fold aleatoric variances into
    the saved ``walkforward.npz`` (key ``variance``, forecast-shaped) so
    ``backtest.py --forecast-npz --mode mean_minus_total_std`` works on
    the strictly-out-of-sample panel. When ``out_dir`` is set, each
    fold's run dir lands under
    ``<out_dir>/fold_<k>``, a progress snapshot (``partial.npz`` +
    ``partial.json``) is written after every fold, and ``walkforward.npz``
    + ``summary.json`` at the end.

    ``resume=True`` (needs ``out_dir``) skips folds already recorded in
    the progress snapshot and resumes the in-flight fold from its own
    ``ckpt/latest`` — crash recovery for multi-fold runs.

    ``warm_start=True`` initializes each fold's weights from the previous
    fold's final state instead of a fresh draw (optimizer restarts
    fresh) — the early-stop BEST state when fold run dirs exist
    (``out_dir`` set: ``fit`` restores ckpt/best at finalize), the
    last-epoch state otherwise (no run dir → no best checkpoint line).
    Measured at toy scale (ledger ``walkforward_warm_start`` rows,
    2026-07-31: 4 folds × 2 seeds): NO epoch savings when fresh folds
    already converge in ~4 epochs, but a small accuracy gain
    (+0.008 mean fold val IC — the carry acts as extra training
    signal). The wall-clock case is for production folds that need many
    epochs; don't expect savings on quick-converging configs.
    No lookahead: fold k-1 trained on strictly earlier data than fold k's
    prediction window, so the out-of-sample property is intact — the carry
    only moves the fold's starting point closer to a solution, the
    wall-clock lever for multi-decade retraining sweeps. Off by default
    (fresh folds are independent draws, the reference protocol). Folds
    skipped by ``resume`` no longer break the carry chain: when the
    predecessor's in-memory params are gone, the first trained fold
    restores them from the predecessor fold dir's ``ckpt/best``
    (falling back to a fresh init, with a warning, only when that
    checkpoint line is missing).

    ``score_modes``: when set, the stitched out-of-sample panel is graded
    END-OF-SWEEP through the device-resident scoring path
    (backtest/jax_engine.py ``run_scoring_pipeline`` when
    ``LFM_JAX_BACKTEST`` is on, the numpy engine otherwise): every listed
    aggregation mode — names or explicit ``(mode, λ)`` pairs, the
    uncertainty_aggregation sweep's grid — is evaluated from ONE stacked
    forecast tensor and backtested in one fused dispatch.
    ``summary["backtest"]`` maps each mode label to the report's summary
    dict (and the full reports land in ``summary.json``). Single-model
    sweeps accept only ["mean"]; ``score_kwargs`` forwards backtest knobs
    (quantile, long_short, costs_bps, ...).

    ``train_months``: rolling train window length in months (None =
    expanding window, the reference protocol — every fold trains on all
    history). A rolling window keeps every fold's batch shapes identical,
    which is what lets the cross-fold reuse layer run the whole sweep on
    ONE set of compiled programs: each fold record's ``reuse`` dict
    carries the measured per-fold compile/transfer deltas
    (``jit_traces``, ``panel_transfers``, cache hit/miss counts — see
    utils/profiling.py ReuseCounters), and on a same-shape schedule every
    fold after the first reports zero for both.

    ``foldstack``: train ALL same-shape folds as ONE stacked, fold-
    sharded, pipelined program (train/foldstack.py) instead of F
    sequential fits — None defers to the ``LFM_FOLDSTACK`` env knob
    (default off). Needs the rolling ``train_months`` window; per-fold
    histories, best epochs, early-stop epochs and restored best params
    match sequential execution (the ``foldstack`` test lane's contract).
    Incompatible with ``resume``/``warm_start`` (the stacked fit writes
    fold checkpoints only at finalize, and the warm-start carry is
    inherently serial) — those raise rather than silently degrade. A
    data-dependent shape mismatch (ragged fold schedules) falls back to
    the sequential path with a warning. When stacked, each fold record
    carries ``"foldstack": True`` and its ``reuse`` delta covers the
    fold's UNSTACK phase (checkpoint write + predict); the whole stacked
    fit's compile/transfer delta lands in ``summary["foldstack"]``.
    """
    from lfm_quant_tpu.train.ensemble import EnsembleTrainer
    from lfm_quant_tpu.train.loop import Trainer
    from lfm_quant_tpu.utils import telemetry
    from lfm_quant_tpu.utils.profiling import REUSE_COUNTERS

    folds = walkforward_folds(panel, start, step_months, val_months, n_folds)
    ensemble = cfg.n_seeds > 1
    # Heteroscedastic members: stitch per-fold aleatoric variances too,
    # so the stitched file supports mean_minus_total_std downstream.
    het = cfg.is_heteroscedastic
    lead = (cfg.n_seeds,) if ensemble else ()
    forecast = np.zeros(lead + (panel.n_firms, panel.n_months), np.float32)
    variance = np.zeros_like(forecast) if het else None
    valid = np.zeros((panel.n_firms, panel.n_months), bool)
    records: List[Dict[str, Any]] = []

    partial_npz = os.path.join(out_dir, "partial.npz") if out_dir else None
    partial_json = os.path.join(out_dir, "partial.json") if out_dir else None
    if resume:
        if not out_dir:
            raise ValueError("resume=True needs out_dir (the progress "
                             "snapshot lives there)")
        if os.path.exists(partial_npz):
            snap = np.load(partial_npz)
            forecast, valid = snap["forecast"], snap["valid"].astype(bool)
            if het:
                if "variance" not in snap:
                    raise ValueError(
                        "resume snapshot lacks variances but the config "
                        "is heteroscedastic — snapshot from a different "
                        "model config?")
                variance = snap["variance"]
            with open(partial_json) as fh:
                records = json.load(fh)
            if len(records) > len(folds):
                raise ValueError(
                    f"resume fold schedule mismatch: snapshot has "
                    f"{len(records)} folds, new schedule only "
                    f"{len(folds)} — same start/step/val arguments "
                    "required")
            for rec, fold in zip(records, folds):
                if (rec["train_end"], rec["val_end"]) != fold[:2]:
                    raise ValueError(
                        "resume fold schedule mismatch: snapshot fold "
                        f"{rec['fold']} is (train_end={rec['train_end']}, "
                        f"val_end={rec['val_end']}), schedule says "
                        f"{fold[:2]} — same start/step/val arguments "
                        "required")
            if forecast.shape != lead + (panel.n_firms, panel.n_months):
                raise ValueError("resume snapshot shape mismatch "
                                 f"{forecast.shape} — n_seeds changed?")

    from lfm_quant_tpu.train import reuse

    use_stack = (foldstack if foldstack is not None
                 else reuse.foldstack_enabled())
    stacked_info = None
    if use_stack:
        if resume or warm_start:
            raise ValueError(
                "foldstack is incompatible with resume/warm_start: the "
                "stacked fit writes fold checkpoints only at finalize "
                "(nothing per-epoch to resume from) and the warm-start "
                "carry is inherently serial — run those protocols with "
                "the sequential walk-forward")
        from lfm_quant_tpu.train.foldstack import run_stacked_walkforward

        stacked = run_stacked_walkforward(
            cfg, panel, folds, train_months=train_months,
            out_dir=out_dir, echo=echo)
        if stacked is not None:
            fold_sums, fold_preds, stacked_info = stacked
            for k, (fold, fs, pred) in enumerate(
                    zip(folds, fold_sums, fold_preds)):
                train_end, val_end, pred_range = fold
                if het:
                    fc, avar, v = pred
                    variance[..., v] = avar[..., v]
                else:
                    fc, v = pred
                assert not (valid & v).any(), \
                    "fold prediction windows overlap"
                forecast[..., v] = fc[..., v]
                valid |= v
                records.append({
                    "fold": k,
                    "train_end": train_end,
                    "val_end": val_end,
                    "pred_months": [int(panel.dates[pred_range[0]]),
                                    int(panel.dates[pred_range[1] - 1])],
                    "n_pred_cells": int(v.sum()),
                    "best_val_ic": fs["best_val_ic"],
                    "best_epoch": fs["best_epoch"],
                    "epochs_run": fs["epochs_run"],
                    "warm_started": False,
                    "foldstack": True,
                    "reuse": fs["reuse"],
                })

    prev_params = None
    trainer = None
    for k, (train_end, val_end, pred_range) in enumerate(
            folds if stacked_info is None else []):
        if k < len(records):
            continue  # fold already completed in a previous run
        # Per-fold compile/transfer accounting: the deltas land in the
        # fold record, making the reuse layer's zero-recompile claim a
        # measured per-fold property. The fold telemetry span carries
        # the same deltas per-span (run → fold → fit → epoch hierarchy).
        with telemetry.span("fold", cat="fold", fold=k,
                            train_end=train_end,
                            val_end=val_end) as fold_span:
            reuse_snap = REUSE_COUNTERS.snapshot()
            train_start = (month_add(train_end, -train_months)
                           if train_months else None)
            splits = PanelSplits.by_date(panel, train_end, val_end,
                                         train_start=train_start)
            run_dir = os.path.join(out_dir, f"fold_{k}") if out_dir else None
            # Per-fold seed offset keeps fold models independent draws while
            # staying replayable.
            fold_cfg = dataclasses.replace(cfg, seed=cfg.seed + 1000 * k)
            if run_dir:
                write_fold_run_dir(fold_cfg, run_dir, train_end, val_end,
                                   train_start, ensemble)
            # ONE trainer for the whole sweep, rebound per fold: rebind()
            # resets TrainState, sampler seeds and split boundaries without
            # rebuilding the jit wrappers (an unchanged program key keeps the
            # exact executables; a changed one rebuilds through the cache —
            # never stale reuse). Constructing fresh trainers would reuse
            # programs too (the caches are module-level), but rebind keeps
            # the sweep's intent explicit and skips re-running construction-
            # time validation per fold.
            if trainer is None:
                trainer = (EnsembleTrainer if ensemble else Trainer)(
                    fold_cfg, splits, run_dir=run_dir, echo=echo)
            else:
                trainer.rebind(fold_cfg, splits, run_dir=run_dir)
            if warm_start and prev_params is None and k > 0 and out_dir:
                # The in-memory carry is gone (folds skipped by resume in
                # this process) — restore the predecessor fold's best params
                # from its run dir so the chain survives crash recovery.
                prev_params = _load_fold_best_params(
                    trainer, os.path.join(out_dir, f"fold_{k - 1}"))
            used_warm = warm_start and prev_params is not None
            fit = trainer.fit(resume=resume and run_dir is not None,
                              init_params=prev_params if used_warm else None)
            if warm_start:
                # Best state when this fold had a run dir (finalize restored
                # ckpt/best); the last-epoch state otherwise — see docstring.
                prev_params = trainer.state.params
            with telemetry.span("predict", cat="predict", fold=k):
                if het:
                    fc, avar, v = trainer.predict(date_range=pred_range,
                                                  return_variance=True)
                    variance[..., v] = avar[..., v]
                else:
                    fc, v = trainer.predict(date_range=pred_range)
            assert not (valid & v).any(), "fold prediction windows overlap"
            forecast[..., v] = fc[..., v]
            valid |= v
            records.append({
                "fold": k,
                "train_end": train_end,
                "val_end": val_end,
                "pred_months": [int(panel.dates[pred_range[0]]),
                                int(panel.dates[pred_range[1] - 1])],
                "n_pred_cells": int(v.sum()),
                "best_val_ic": fit["best_val_ic"],
                "best_epoch": fit["best_epoch"],
                "epochs_run": fit["epochs_run"],
                "warm_started": used_warm,
                # Fold-level compile/transfer cost: 0 jit_traces and 0
                # panel_transfers on every fold after the first is the reuse
                # layer's contract on a same-shape schedule (tests/test_reuse
                # and bench.py walkforward_reuse assert it here). The same
                # delta carries the epoch pipeline's sync-point accounting
                # (host_syncs / host_sync_s / device_idle_s — one blocking
                # fetch per epoch, near-zero idle with LFM_ASYNC on), so
                # every fold record prices its host-sync overhead too.
                "reuse": {k: (round(v, 4) if isinstance(v, float) else v)
                          for k, v in REUSE_COUNTERS.delta(reuse_snap).items()},
            })
            if out_dir:
                os.makedirs(out_dir, exist_ok=True)
                extra = {"variance": variance} if het else {}
                np.savez_compressed(partial_npz, forecast=forecast, valid=valid,
                                    **extra)
                with open(partial_json, "w") as fh:
                    json.dump(records, fh)
            fold_span.set(epochs_run=fit["epochs_run"],
                          warm_started=used_warm)
    summary = {
        "n_folds": len(folds),
        "step_months": step_months,
        "val_months": val_months,
        "train_months": train_months,
        "n_seeds": cfg.n_seeds,
        "warm_start": warm_start,
        "oos_months": [int(panel.dates[folds[0][2][0]]),
                       int(panel.dates[folds[-1][2][1] - 1])],
        "folds": records,
    }
    if stacked_info is not None:
        summary["foldstack"] = stacked_info
    def _save_summary():
        if out_dir:
            with open(os.path.join(out_dir, "summary.json"), "w") as fh:
                json.dump(summary, fh, indent=2)

    # Persist the sweep's primary artifacts BEFORE end-of-sweep grading:
    # a scoring failure (bad score_kwargs, device OOM) must never
    # discard hours of trained folds' stitched forecasts.
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
        extra = {"variance": variance} if het else {}
        np.savez_compressed(os.path.join(out_dir, "walkforward.npz"),
                            forecast=forecast, valid=valid, **extra)
        with open(os.path.join(out_dir, "config.json"), "w") as fh:
            fh.write(cfg.to_json())
        _save_summary()
    if score_modes:
        # End-of-sweep grading of the stitched strictly-out-of-sample
        # panel through the fused scoring path (numpy fallback when the
        # LFM_JAX_BACKTEST knob is off); only summary.json needs the
        # re-write (the npz would just recompress identical arrays).
        with telemetry.span("score", cat="score",
                            n_modes=len(score_modes)):
            summary["backtest"] = score_stitched(
                forecast, valid, panel, score_modes, variance=variance,
                **(score_kwargs or {}))
        _save_summary()
    return forecast, valid, summary
