"""Checkpoint / resume via Orbax (SURVEY.md §6).

The reference's TF ``Saver``-style checkpointing [INFERRED] becomes Orbax
PyTree checkpoints. Ensembles are stored as ONE stacked PyTree with a
leading seed axis, so 64 vmap'd replicas save and restore in a single
read/write (SURVEY.md §6 "checkpoint/resume" row).
"""

from __future__ import annotations

import os
import threading
import warnings
from typing import Any, Optional

import orbax.checkpoint as ocp

from lfm_quant_tpu.utils import faults, telemetry


def fold_slice(state_dict: Any, idx: int) -> Any:
    """Per-fold slice of a fold-stacked train-state pytree (leading fold
    axis on every array leaf) — the checkpoint UNSTACKING the
    fold-vectorized walk-forward (train/foldstack.py) uses to write each
    fold's ``ckpt/best`` line out of the stacked fit's device-side best
    params, so every fold run dir stays loadable by the exact same
    ``load_trainer``/``load_ensemble`` path a sequential sweep feeds.
    Leaves come back as ndarrays (never numpy SCALARS — indexing a 1-d
    leaf like the optimizer step count would otherwise yield np.int32,
    which Orbax's StandardSave rejects)."""
    import jax
    import numpy as np

    return jax.tree.map(lambda x: np.asarray(x[idx]), state_dict)


class CheckpointManager:
    """Thin wrapper over ocp.CheckpointManager for train-state pytrees.

    ASYNC BY DEFAULT: Orbax's manager runs saves on a background thread
    (``enable_async_checkpointing`` defaults True on the pinned
    version), so :meth:`save` with ``wait=False`` returns as soon as the
    write is staged — the epoch pipeline (train/pipeline.py) hands it a
    HOST-FETCHED state copy precisely so the background writer never
    races buffer donation on device. Durability contract: commits are
    atomic (tmp-dir + rename), ``latest_step`` only ever reports
    committed steps, and a second ``save`` on the same manager while one
    is in flight serializes internally — overlapping the *best* and
    *latest* lines needs two managers, which is what FitHarness holds.
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        self._line = os.path.basename(directory)  # "best" | "latest"
        self._mgr = ocp.CheckpointManager(
            os.path.abspath(directory),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )
        # The single bounded-wait worker (see :meth:`wait`): a timed-out
        # wait leaves its thread blocked inside Orbax, and a SECOND
        # wait()/close() must re-join that same thread — two concurrent
        # wait_until_finished() calls on one manager race its finalize.
        self._wait_thread: Optional[threading.Thread] = None
        self._wait_done = threading.Event()
        self._wait_err: list = []

    def save(self, step: int, state: Any, wait: bool = False) -> None:
        """Stage a save of ``state`` at ``step``; ``wait=True`` blocks
        until it is durably committed (the synchronous reference path —
        ``LFM_ASYNC_CKPT=0`` semantics; deliberately UNBOUNDED: sync
        mode's contract is "durable before proceeding", which a timeout
        cannot honor). ``ckpt_write`` is a chaos fault site
        (utils/faults.py) — the kill-mid-epoch preemption test schedules
        its SIGTERM here."""
        faults.check("ckpt_write", line=self._line, step=int(step))
        with telemetry.span("ckpt_save", cat="ckpt", line=self._line,
                            step=step, wait=wait):
            self._mgr.save(step, args=ocp.args.StandardSave(state))
            if wait:
                self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, abstract_state: Any, step: Optional[int] = None) -> Any:
        """Restore into the structure/shardings of ``abstract_state``
        (a concrete or jax.eval_shape'd pytree of the train state)."""
        if step is None:
            step = self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError("no checkpoint found")
        return self._mgr.restore(
            step, args=ocp.args.StandardRestore(abstract_state)
        )

    def wait(self, timeout_s: Optional[float] = None) -> bool:
        """Block until in-flight async saves commit — BOUNDED. Orbax's
        ``wait_until_finished`` has no timeout, and an async writer
        wedged on storage used to hang finalize/shutdown forever; the
        wait now runs on a daemon thread joined for ``timeout_s``
        (default ``LFM_CKPT_WAIT_S``, 120 s; <= 0 restores the
        unbounded wait). Returns True when the line is durable; on
        timeout it warns LOUDLY, bumps the ``ckpt_wait_timeouts``
        counter and returns False — the save may still commit in the
        background, but the caller's shutdown path proceeds."""
        if timeout_s is None:
            timeout_s = float(os.environ.get("LFM_CKPT_WAIT_S", "120"))
        with telemetry.span("ckpt_wait", cat="ckpt", line=self._line):
            if timeout_s <= 0:
                self._mgr.wait_until_finished()
                return True
            # Reuse a still-running worker from a PREVIOUS timed-out
            # wait: it is still blocked inside wait_until_finished, and
            # starting a second concurrent one would race Orbax's
            # finalize if the wedge clears mid-shutdown.
            if self._wait_thread is None or not self._wait_thread.is_alive():
                self._wait_done = threading.Event()
                self._wait_err = []
                done, err = self._wait_done, self._wait_err

                def _wait():
                    try:
                        self._mgr.wait_until_finished()
                    except BaseException as e:  # noqa: BLE001 — re-raised below
                        err.append(e)
                    finally:
                        done.set()

                self._wait_thread = threading.Thread(
                    target=_wait, daemon=True,
                    name=f"ckpt-wait-{self._line}")
                self._wait_thread.start()
            done, err = self._wait_done, self._wait_err
            if not done.wait(timeout_s):
                warnings.warn(
                    f"checkpoint line {self._line!r}: async save still "
                    f"unfinished after {timeout_s:.0f}s (LFM_CKPT_WAIT_S) — "
                    "abandoning the wait so shutdown cannot hang; the save "
                    "may still commit in the background",
                    RuntimeWarning, stacklevel=2)
                telemetry.COUNTERS.bump("ckpt_wait_timeouts")
                return False
            if err:
                raise err[0]
            return True

    def close(self, timeout_s: Optional[float] = None):
        """Flush (bounded — see :meth:`wait`) and close. A wedged async
        save is ABANDONED with a loud warning instead of hanging
        shutdown forever: Orbax's own ``close`` waits unboundedly, so
        it only runs once the bounded wait confirmed the line drained."""
        if self.wait(timeout_s):
            self._mgr.close()
        else:
            warnings.warn(
                f"checkpoint line {self._line!r}: close() abandoned with a "
                "save still in flight (see the ckpt_wait warning above)",
                RuntimeWarning, stacklevel=2)
