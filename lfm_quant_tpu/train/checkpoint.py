"""Checkpoint / resume via Orbax (SURVEY.md §6).

The reference's TF ``Saver``-style checkpointing [INFERRED] becomes Orbax
PyTree checkpoints. Ensembles are stored as ONE stacked PyTree with a
leading seed axis, so 64 vmap'd replicas save and restore in a single
read/write (SURVEY.md §6 "checkpoint/resume" row).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import orbax.checkpoint as ocp

from lfm_quant_tpu.utils import telemetry


def fold_slice(state_dict: Any, idx: int) -> Any:
    """Per-fold slice of a fold-stacked train-state pytree (leading fold
    axis on every array leaf) — the checkpoint UNSTACKING the
    fold-vectorized walk-forward (train/foldstack.py) uses to write each
    fold's ``ckpt/best`` line out of the stacked fit's device-side best
    params, so every fold run dir stays loadable by the exact same
    ``load_trainer``/``load_ensemble`` path a sequential sweep feeds.
    Leaves come back as ndarrays (never numpy SCALARS — indexing a 1-d
    leaf like the optimizer step count would otherwise yield np.int32,
    which Orbax's StandardSave rejects)."""
    import jax
    import numpy as np

    return jax.tree.map(lambda x: np.asarray(x[idx]), state_dict)


class CheckpointManager:
    """Thin wrapper over ocp.CheckpointManager for train-state pytrees.

    ASYNC BY DEFAULT: Orbax's manager runs saves on a background thread
    (``enable_async_checkpointing`` defaults True on the pinned
    version), so :meth:`save` with ``wait=False`` returns as soon as the
    write is staged — the epoch pipeline (train/pipeline.py) hands it a
    HOST-FETCHED state copy precisely so the background writer never
    races buffer donation on device. Durability contract: commits are
    atomic (tmp-dir + rename), ``latest_step`` only ever reports
    committed steps, and a second ``save`` on the same manager while one
    is in flight serializes internally — overlapping the *best* and
    *latest* lines needs two managers, which is what FitHarness holds.
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        self._line = os.path.basename(directory)  # "best" | "latest"
        self._mgr = ocp.CheckpointManager(
            os.path.abspath(directory),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, step: int, state: Any, wait: bool = False) -> None:
        """Stage a save of ``state`` at ``step``; ``wait=True`` blocks
        until it is durably committed (the synchronous reference path —
        ``LFM_ASYNC_CKPT=0`` semantics)."""
        with telemetry.span("ckpt_save", cat="ckpt", line=self._line,
                            step=step, wait=wait):
            self._mgr.save(step, args=ocp.args.StandardSave(state))
            if wait:
                self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, abstract_state: Any, step: Optional[int] = None) -> Any:
        """Restore into the structure/shardings of ``abstract_state``
        (a concrete or jax.eval_shape'd pytree of the train state)."""
        if step is None:
            step = self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError("no checkpoint found")
        return self._mgr.restore(
            step, args=ocp.args.StandardRestore(abstract_state)
        )

    def wait(self):
        with telemetry.span("ckpt_wait", cat="ckpt", line=self._line):
            self._mgr.wait_until_finished()

    def close(self):
        self._mgr.close()
