"""Universal stacked-run engine: compile once, train R independent runs.

PR 5's fold-vectorized walk-forward (train/foldstack.py) proved the
move: stack same-shape independent runs on a leading axis of ONE
TrainState, drive every epoch as one jitted program (vmapped multi-step
train scan + chained per-run validation sweep + masked device-side
early stopping), pay ONE host sync per stacked epoch, and shard the run
axis over the mesh's spare devices. Nothing in that core is
fold-specific — it is the replicate-independent-work batching of
Khomenko et al. (1708.05604) one level up — so this module hoists it
into a generic :class:`StackedRuns` engine whose leading axis can be:

* **walk-forward folds** — train/foldstack.py is now a thin adapter
  over this engine (its parity lane pins that the adapterization
  changed nothing);
* **hyperparameter configs** — an LR × weight-decay grid trained as ONE
  compiled program (:func:`run_config_sweep`, ``train.py --sweep-grid``):
  per-run hyperparameters are threaded as vmapped per-run *operands*
  into the optimizer update — never baked constants — so a 200-config
  grid pays zero per-config traces (the training-side twin of the PR 2
  compile-once mode × λ × cost scoring grid);
* **compositions** — axes compose by cartesian-flattening the run list
  (fold × config: each run carries its own splits AND its own config;
  seeds compose through the ensemble's existing inner 'seed' mesh axis).

Per-run-operand hyperparameters and bit-identity: the sequential
reference for config c is a Trainer whose optax chain bakes c's LR and
weight decay in as constants. The stacked hyper step reproduces those
updates bit-exactly by reusing the SAME gradient code
(``TrainerPrograms._grads_impl``) and mirroring the optax chain
(clip → scale_by_adam → +wd·p → −lr·unit_schedule(count)·u) with the
peak LR factored out of the schedule: optax's warmup-cosine value is
linear in the peak (init 0, end 0.1·peak), so ``lr ⊗ unit(count)``
reproduces the baked ``schedule(count)`` to the bit — the ``stacked``
test lane pins per-config histories, best epochs and restored best
params bit-identical to sequential execution on the unsharded stack.

Run-axis microbatching (``LFM_STACK_BLOCK``): the generalization of
``RunConfig.seed_block`` one axis up — an R-run stack whose vmapped
backward would overflow HBM is stepped in blocks of B runs via
``lax.scan`` (:func:`scan_in_blocks`, shared with the ensemble's
seed-block path), bounding peak activation memory to B × per-run while
params/opt state stay resident. Runs are independent, so blocking is a
pure re-batching; the block size is part of the stacked program keys.

The mesh axis is 'stack' (parallel/mesh.py ``make_stack_mesh``) — or
'fold' for the walk-forward adapter, so fold meshes fingerprint exactly
as before — composed OUTERMOST around the trainer's seed × data axes:
runs exchange no traffic, so no collective ever crosses the axis.
"""

from __future__ import annotations

import dataclasses
import os
import warnings
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from lfm_quant_tpu.config import RunConfig
from lfm_quant_tpu.data.panel import Panel, PanelSplits
from lfm_quant_tpu.data.windows import (
    DateBatchSampler,
    cached_device_panel,
    stack_fold_epochs,
)
from lfm_quant_tpu.parallel.mesh import (
    DATA_AXIS,
    FOLD_AXIS,
    SEED_AXIS,
    STACK_AXIS,
    make_stack_mesh,
    shard_map_compat,
)
from lfm_quant_tpu.train.loop import TrainState
from lfm_quant_tpu.utils import telemetry
from lfm_quant_tpu.utils.logging import MetricsLogger
from lfm_quant_tpu.utils.profiling import REUSE_COUNTERS, StepTimer

#: Hyperparameters a config grid may vary — each is threaded into the
#: stacked epoch program as a vmapped [R] operand (never a baked
#: constant). Anything else that differs across run configs changes the
#: traced program or its data and must stay uniform within one stack.
HYPER_KEYS = ("lr", "weight_decay")


class StackUnavailable(RuntimeError):
    """A precondition for run-stacking is unmet (ragged run shapes,
    R < 2, a config field varying that cannot ride a per-run operand,
    sequence parallelism). Drivers catch this and degrade to sequential
    execution with a warning + telemetry instant — a data-dependent
    mismatch must not kill a sweep the sequential path handles fine."""


class RunCtrl(NamedTuple):
    """Device-side per-run early-stopping state — the FitHarness
    counters, vectorized over the run axis and kept on device so the
    control decision needs no host sync and no lookahead lag: a run that
    stops at epoch e is frozen in epoch e+1's program because e+1's
    dispatch consumes e's output control state directly."""

    live: jax.Array        # [R] bool — run still training
    best_ic: jax.Array     # [R] f32 — running best val IC (-inf start)
    best_epoch: jax.Array  # [R] i32 — epoch of best_ic (-1 start)
    bad_epochs: jax.Array  # [R] i32 — epochs since last improvement


def scan_in_blocks(vfn, block: int, args: Tuple):
    """Apply a run-axis-vmapped ``vfn`` to ``args`` in blocks of
    ``block`` runs via ``lax.scan`` — the run-axis generalization of the
    ensemble's ``seed_block`` microbatching (train/ensemble.py
    ``_step_shards`` routes through here too): peak activation memory
    drops from all-local-runs × per-run to block × per-run, while the
    per-run math is untouched (runs are independent, so blocking is a
    pure re-batching). ``block`` of 0, >= the local run count, or not
    dividing it falls through to the plain vmapped call — callers that
    want a loud non-divisor warn at construction time."""
    lead = jax.tree.leaves(args)[0].shape[0]
    if not block or block >= lead or lead % block:
        return vfn(*args)
    nb = lead // block

    def to_blocks(t):
        return jax.tree.map(
            lambda x: x.reshape((nb, block) + x.shape[1:]), t)

    def body(_, xs):
        return None, vfn(*xs)

    _, out = jax.lax.scan(body, None, tuple(to_blocks(a) for a in args))
    return jax.tree.map(lambda x: x.reshape((lead,) + x.shape[2:]), out)


class StackedPrograms:
    """The stacked epoch program, cached in the cross-fold program cache
    (train/reuse.py ``foldstack_program_key`` / ``stacked_program_key``):
    ONE jitted (and, under a stack mesh, shard_mapped) function runs the
    vmapped multi-step train scan, the chained per-run validation sweep,
    the bit-freeze select for stopped runs, and the device-side control
    update. Donation is preserved: the whole carry (stacked TrainState +
    best params + control) is donated, so XLA aliases the run-stacked
    params/opt_state in place exactly like the sequential multi-step
    wrappers do (train/reuse.py ``multi_step_donate_argnums``).

    ``hyper_keys`` names the per-run hyperparameters arriving as [R]
    operands; with any set, the train scan runs the mirrored-optax hyper
    step instead of the inner bundle's baked multi-step. ``block`` is
    the ``LFM_STACK_BLOCK`` run-axis microbatch.

    Holds only the inner program bundle (TrainerPrograms /
    EnsemblePrograms) and static geometry — no panel, samplers or
    TrainState — so cache entries stay lightweight (same invariant as
    the inner bundles)."""

    def __init__(self, inner, mesh, run_count: int, patience: int,
                 ensemble: bool, axis_name: str = FOLD_AXIS,
                 hyper_keys: Tuple[str, ...] = (), block: int = 0,
                 steps_per_epoch: int = 0, optim_cfg=None):
        from lfm_quant_tpu.train.reuse import (ledger_jit,
                                               multi_step_donate_argnums)

        self.inner = inner
        self.mesh = mesh
        self.run_count = run_count
        self.patience = patience
        self.ensemble = ensemble
        self.axis_name = axis_name
        self.hyper_keys = tuple(hyper_keys)
        self.hyper = bool(self.hyper_keys)
        self.block = int(block)
        axes = dict(mesh.shape) if mesh is not None else {}
        # Axis names live inside the stack shard_map: the inner step's
        # gradient psum needs 'data'; the control aggregation needs
        # 'seed' when the ensemble's members are seed-sharded.
        self._data_axis = DATA_AXIS if DATA_AXIS in axes else None
        self._seed_axis = (SEED_AXIS if ensemble and SEED_AXIS in axes
                           else None)
        if self.hyper:
            if ensemble:
                raise ValueError(
                    "per-run hyperparameter operands are single-seed "
                    "only (the ensemble's seed axis composes through "
                    "its own mesh axis)")
            self._build_hyper_tx(optim_cfg, steps_per_epoch)
        donate = multi_step_donate_argnums()
        self._batch_spec = None
        hp_spec = {k: P(axis_name) for k in self.hyper_keys}
        if mesh is None:
            self._jit_epoch = ledger_jit("stack_epoch", self._epoch_impl,
                                         donate_argnums=donate)
            return
        state_spec = (P(axis_name, SEED_AXIS) if self._seed_axis
                      else P(axis_name))
        if ensemble:
            batch_spec = P(axis_name, None, self._seed_axis or None,
                           self._data_axis or None)
        elif self._data_axis:
            batch_spec = P(axis_name, None, DATA_AXIS)
        else:
            batch_spec = P(axis_name)
        run_spec = P(axis_name)
        # Exposed: the driver stages batches with THIS spec, so H2D
        # placement and the shard_map in_specs can never drift apart.
        self._batch_spec = batch_spec
        carry_spec = (state_spec, state_spec, run_spec)
        metric_spec = {"loss": run_spec, "ic": (P(axis_name, SEED_AXIS)
                                                if self._seed_axis
                                                else run_spec)}
        if not ensemble:
            metric_spec.update(grad_norm=run_spec, mse=run_spec)
        self._jit_epoch = ledger_jit(
            "stack_epoch",
            shard_map_compat(
                self._epoch_impl,
                mesh=mesh,
                in_specs=(carry_spec, P(), batch_spec, batch_spec,
                          batch_spec, run_spec, run_spec, run_spec,
                          hp_spec, P()),
                out_specs=(carry_spec, metric_spec),
                check_vma=False,
            ),
            donate_argnums=donate)
        self._state_spec = state_spec

    # ---- per-run-operand optimizer (the hyper step) ------------------

    def _build_hyper_tx(self, o, steps_per_epoch: int) -> None:
        """Mirror of the inner bundle's optax chain with the per-run
        hyperparameters factored out as operands. The baked chain is
        ``chain(clip_by_global_norm, adamw|lamb(schedule, wd))``; the
        mirror applies the SAME transforms in the SAME order — clip,
        scale_by_adam, ``u + wd·p``, (trust ratio for lamb,)
        ``u · (−lr·unit_schedule(count))`` — where ``unit_schedule`` is
        the baked warmup-cosine with peak 1.0 and end 0.1 (optax's value
        is linear in the peak: init 0, alpha = end/peak = 0.1 either
        way), so ``lr ⊗ unit(count)`` equals ``schedule(count)`` to the
        bit when ``lr`` equals the baked peak. The ``stacked`` lane's
        bit-identity tests are the proof, not this comment."""
        total_steps = max(1, steps_per_epoch * o.epochs)
        self._unit_sched = optax.warmup_cosine_decay_schedule(
            0.0, 1.0, min(o.warmup_steps, total_steps // 2),
            total_steps, end_value=0.1)
        self._clip = optax.clip_by_global_norm(o.grad_clip)
        if o.optimizer == "adamw":
            self._adam = optax.scale_by_adam()
            self._trust = None
        elif o.optimizer == "lamb":
            # optax.lamb's defaults differ from adamw's: eps=1e-6.
            self._adam = optax.scale_by_adam(eps=1e-6)
            self._trust = optax.scale_by_trust_ratio()
        else:
            raise ValueError(
                f"per-run-operand sweep supports adamw|lamb, got "
                f"{o.optimizer!r}")

    def _hyper_update(self, grads, opt_state, params, lr, wd):
        """One optimizer update with (lr, wd) as traced per-run scalars,
        consuming/producing the baked chain's opt_state tree positionally
        — (clip, (adam, decay, [trust,] schedule)) — so states init'd by
        the inner ``tx.init`` (and checkpoints written from them) stay
        structure-compatible with the sequential path."""
        clip_s, chain_s = opt_state
        u, clip_s = self._clip.update(grads, clip_s)
        u, adam_s = self._adam.update(u, chain_s[0], params)
        u = jax.tree.map(lambda g, p: g + wd * p, u, params)
        if self._trust is not None:
            u, trust_s = self._trust.update(u, chain_s[2], params)
        sched_s = chain_s[-1]
        step_size = -1 * (lr * self._unit_sched(sched_s.count))
        u = jax.tree.map(
            lambda g: jnp.array(step_size, dtype=g.dtype) * g, u)
        sched_s = type(sched_s)(
            count=optax.safe_int32_increment(sched_s.count))
        if self._trust is not None:
            chain_s = (adam_s, chain_s[1], trust_s, sched_s)
        else:
            chain_s = (adam_s, chain_s[1], sched_s)
        return u, (clip_s, chain_s)

    def _hyper_multi_step(self, state: TrainState, dev: dict, fi, ti, w,
                          lr, wd, axis=None):
        """K training steps of ONE run in one scan, with this run's
        (lr, wd) operands applied by the mirrored chain — the hyper twin
        of ``TrainerPrograms._multi_step_impl`` (gradients come from the
        same ``_grads_impl``, so the loss/gather/psum path is shared)."""
        def body(st, batch):
            f, t, ww = batch
            loss, grads = self.inner._grads_impl(st, dev, f, t, ww,
                                                 axis=axis)
            updates, opt_state = self._hyper_update(
                grads, st.opt_state, st.params, lr, wd)
            params = optax.apply_updates(st.params, updates)
            gnorm = optax.global_norm(grads)
            return TrainState(params, opt_state, st.step + 1, st.rng), {
                "loss": loss, "grad_norm": gnorm}

        return jax.lax.scan(body, state, (fi, ti, w))

    # ---- the fused epoch program ------------------------------------

    def _epoch_impl(self, carry, dev: dict, fi, ti, w, vfi, vti, vw, hp,
                    epoch):
        """One stacked epoch: train all live runs, evaluate every run,
        update the device-side control state. ``epoch`` is a traced i32
        scalar (no retrace per epoch); ``hp`` is the (possibly empty)
        dict of [R] per-run hyperparameter operands. Under the stack
        mesh this body runs per shard on the local run block; all arrays
        below carry the LOCAL run axis."""
        state, best_params, ctrl = carry
        inner = self.inner
        live = ctrl.live

        if self.hyper:
            ax = (self._data_axis,) if self._data_axis else None
            multi = lambda st, f, t, ww, lr, wd: self._hyper_multi_step(
                st, dev, f, t, ww, lr, wd, axis=ax)
            new_state, ms = scan_in_blocks(
                jax.vmap(multi), self.block,
                (state, fi, ti, w, hp["lr"], hp["weight_decay"]))
        elif self.ensemble:
            multi = lambda st, f, t, ww: inner._multi_step_impl(
                st, dev, f, t, ww)
            new_state, ms = scan_in_blocks(jax.vmap(multi), self.block,
                                           (state, fi, ti, w))
        else:
            ax = (self._data_axis,) if self._data_axis else None
            multi = lambda st, f, t, ww: inner._multi_step_impl(
                st, dev, f, t, ww, axis=ax)
            new_state, ms = scan_in_blocks(jax.vmap(multi), self.block,
                                           (state, fi, ti, w))

        # Bit-freeze stopped runs: a SELECT back to the input state, not
        # a zero-weight arithmetic step — Adam moment decay, weight decay
        # and the step counter would all still move under zeroed
        # gradients, and the parity contract is bit-frozen params.
        def sel_live(n, o):
            m = live.reshape(live.shape + (1,) * (n.ndim - 1))
            return jnp.where(m, n, o)

        state = jax.tree.map(sel_live, new_state, state)

        # Chained per-run validation sweep on the post-select params (a
        # frozen run re-evaluates its frozen params — masked out of the
        # control update below, so only live runs' ICs matter).
        counts = vw.sum(axis=-1)  # [R, M] f32
        if self.ensemble:
            seed_fwd = jax.vmap(inner.inner._forward_impl,
                                in_axes=(0, None, None, None, None))

            def run_eval(p, vf, vt, vww):
                _, ic, _ = seed_fwd(p, dev, vf, vt, vww)
                return ic  # [S_local, M]

            ic = jax.vmap(run_eval)(state.params, vfi, vti, vw)
            per_seed = ((ic * counts[:, None, :]).sum(-1)
                        / counts.sum(-1)[:, None])  # [R, S_local]
            if self._seed_axis:
                val_ic = (jax.lax.psum(per_seed.sum(axis=1),
                                       self._seed_axis)
                          / inner.n_seeds)
            else:
                val_ic = per_seed.mean(axis=1)
            k_steps = fi.shape[1]
            loss_sum = ms["loss"].sum(axis=(1, 2))
            if self._seed_axis:
                loss_sum = jax.lax.psum(loss_sum, self._seed_axis)
            metrics = {"loss": loss_sum / (k_steps * inner.n_seeds),
                       "ic": ic}
        else:
            def run_eval(p, vf, vt, vww):
                _, ic, mse = inner._forward_impl(p, dev, vf, vt, vww)
                return ic, mse

            ic, mse = jax.vmap(run_eval)(state.params, vfi, vti, vw)
            val_ic = (ic * counts).sum(-1) / counts.sum(-1)  # [R] f32
            metrics = {"loss": ms["loss"].mean(axis=1),
                       "grad_norm": ms["grad_norm"].mean(axis=1),
                       "ic": ic, "mse": mse}

        # Device-side FitHarness: same comparisons, vectorized. A run
        # improves strictly (val_ic > best_ic, -inf start ⇒ epoch 0
        # always improves), otherwise its patience counter advances; a
        # run whose counter reaches patience leaves the live set for
        # every later epoch — including a speculative overrun epoch,
        # which therefore cannot move any state.
        improved = live & (val_ic > ctrl.best_ic)
        best_ic = jnp.where(improved, val_ic, ctrl.best_ic)
        best_epoch = jnp.where(improved, epoch, ctrl.best_epoch)
        bad = jnp.where(improved, 0,
                        jnp.where(live, ctrl.bad_epochs + 1,
                                  ctrl.bad_epochs))

        def sel_best(n, o):
            m = improved.reshape(improved.shape + (1,) * (n.ndim - 1))
            return jnp.where(m, n, o)

        best_params = jax.tree.map(sel_best, state.params, best_params)
        ctrl = RunCtrl(live & (bad < self.patience), best_ic, best_epoch,
                       bad)
        return (state, best_params, ctrl), metrics


class _StackHarness:
    """Duck-typed FitHarness shell for ``pipeline.run_fit_epochs``:
    epoch accounting only. Early stopping lives DEVICE-SIDE in the
    stacked control state; the ``finish`` callback sets ``all_dead``
    from the fetched live mask, and ``end_epoch`` just reports it (no
    checkpointing — run checkpoints are unstacked at finalize)."""

    def __init__(self, epochs: int):
        self.epochs = epochs
        self.all_dead = False
        self._epoch = -1

    def next_epoch(self) -> Optional[int]:
        nxt = self._epoch + 1
        if nxt >= self.epochs or self.all_dead:
            return None
        self._epoch = nxt
        return nxt

    def end_epoch(self, epoch, step, state_dict, val_ic) -> bool:
        return self.all_dead

    @property
    def last_epoch(self) -> int:
        return self._epoch


def _normalized_cfg(cfg: RunConfig) -> RunConfig:
    """A run config with every legally-varying field zeroed: seed (each
    run draws its own init/data streams), the per-run-operand
    hyperparameters, and pure labels. Two configs may share a stack iff
    they normalize equal — anything else reaching a traced program as a
    constant would silently train the wrong program."""
    return dataclasses.replace(
        cfg, seed=0, name="",
        optim=dataclasses.replace(cfg.optim, lr=0.0, weight_decay=0.0))


class StackedRuns:
    """Driver for one stacked sweep over R independent same-shape runs.

    Construction validates every stacking precondition (raising
    :class:`StackUnavailable` on data-dependent mismatches), binds ONE
    trainer (programs + resident panel through the reuse caches), builds
    per-run samplers with the exact per-run PRNG streams, stages the
    per-run hyperparameter operands, and fetches the stacked epoch
    program through the program cache. :meth:`fit` trains the stack
    through the PR 3 pipeline driver and unstacks per-run results
    (histories, best checkpoints); adapters add their own per-run work
    (the walk-forward's per-fold predictions) via the ``per_run``
    callback so its cost lands inside the run's reuse delta.

    ``kind`` labels the run axis: "fold" keeps the walk-forward
    adapter's axis name, telemetry span names ("foldstack_fit",
    "fold_stopped"), program-key family and summary keys exactly as
    PR 5 shipped them; any other kind uses the generic 'stack' axis,
    "stack_fit"/"run_stopped" telemetry and ``stacked_program_key``.
    """

    def __init__(self, run_cfgs: Sequence[RunConfig],
                 run_splits: Sequence[PanelSplits], panel: Panel, *,
                 kind: str = "config",
                 run_dirs: Optional[Sequence[Optional[str]]] = None,
                 echo: bool = False):
        from lfm_quant_tpu.train import reuse
        from lfm_quant_tpu.train.ensemble import EnsembleTrainer
        from lfm_quant_tpu.train.loop import Trainer

        if len(run_cfgs) < 2:
            raise StackUnavailable(
                f"run-stacking needs >= 2 runs, got {len(run_cfgs)}")
        from lfm_quant_tpu.buckets import buckets_enabled

        if buckets_enabled():
            # The stacked epoch program is one fused fixed-shape
            # dispatch; per-bucket [R, K_b, D, w_b] stacks would need a
            # restructured carry. The sequential path the drivers
            # degrade to IS bucket-capable (Trainer/EnsembleTrainer fit
            # per run), so the composition stays loud, correct and
            # compile-once — just not stacked (DESIGN.md §16).
            raise StackUnavailable(
                "geometry-bucketed batching (LFM_BUCKETS=1) does not "
                "compose with the stacked-run engines yet — runs degrade "
                "to the sequential bucketed path")
        if len(run_splits) != len(run_cfgs):
            raise ValueError("run_cfgs and run_splits length mismatch")
        cfg = run_cfgs[0]
        ref = _normalized_cfg(cfg)
        for k, c in enumerate(run_cfgs[1:], 1):
            if _normalized_cfg(c) != ref:
                raise StackUnavailable(
                    f"run {k}'s config differs beyond the per-run axes "
                    f"(seed, {', '.join(HYPER_KEYS)}) — a field that "
                    "reaches the traced program as a constant cannot "
                    "vary within one stack")
        self.kind = kind
        self.fold_kind = kind == "fold"
        self.axis_name = FOLD_AXIS if self.fold_kind else STACK_AXIS
        self.cfg = cfg
        self.panel = panel
        self.run_cfgs = list(run_cfgs)
        self.splits = list(run_splits)
        self.run_count = len(run_cfgs)
        self.run_dirs = (list(run_dirs) if run_dirs is not None
                         else [None] * self.run_count)
        self.checkpointing = any(rd for rd in self.run_dirs)
        self.ensemble = cfg.n_seeds > 1
        self.het = cfg.is_heteroscedastic
        self.window = cfg.data.window
        d = cfg.data
        R = self.run_count

        lrs = [c.optim.lr for c in run_cfgs]
        wds = [c.optim.weight_decay for c in run_cfgs]
        self.hyper = len(set(lrs)) > 1 or len(set(wds)) > 1
        self.hyper_keys = HYPER_KEYS if self.hyper else ()
        if self.hyper:
            if self.ensemble:
                raise StackUnavailable(
                    "per-run hyperparameter operands are single-seed "
                    "only for now (n_seeds > 1 configs stack uniformly "
                    "or run sequentially)")
            if cfg.optim.optimizer not in ("adamw", "lamb"):
                raise StackUnavailable(
                    f"per-run-operand sweep supports adamw|lamb, got "
                    f"{cfg.optim.optimizer!r}")

        # ONE trainer, bound to run 0: supplies the compiled inner
        # programs, the resolved gather/panel geometry, predict(), and
        # the state-commit machinery — all through the reuse caches.
        self.trainer = (EnsembleTrainer if self.ensemble else Trainer)(
            run_cfgs[0], self.splits[0], run_dir=None, echo=echo)
        n_seq = getattr(self.trainer, "_n_seq", 1)
        if n_seq > 1:
            raise StackUnavailable(
                "run-stacking does not compose with sequence "
                "parallelism (the seq axis' ring collectives assume "
                "innermost ICI placement)")

        # Per-run samplers with the run's own seed and anchor range —
        # the exact streams the sequential run would consume.
        if self.ensemble:
            self.run_samplers = [
                [DateBatchSampler(
                    panel, d.window, d.dates_per_batch, d.firms_per_date,
                    seed=rc.seed + s, min_valid_months=d.min_valid_months,
                    date_range=sp.train_range, engine=d.sampler_engine)
                 for s in range(cfg.n_seeds)]
                for rc, sp in zip(run_cfgs, self.splits)
            ]
            steps = [min(s.batches_per_epoch() for s in per_run)
                     for per_run in self.run_samplers]
        else:
            self.run_samplers = [
                DateBatchSampler(
                    panel, d.window, d.dates_per_batch, d.firms_per_date,
                    seed=rc.seed, min_valid_months=d.min_valid_months,
                    date_range=sp.train_range, engine=d.sampler_engine)
                for rc, sp in zip(run_cfgs, self.splits)
            ]
            steps = [s.batches_per_epoch() for s in self.run_samplers]
        if len(set(steps)) != 1:
            raise StackUnavailable(
                f"runs disagree on steps-per-epoch {steps} — stacking "
                "requires the same-shape schedule")
        self.steps = steps[0]

        # Per-run validation sweeps, stacked. The eval batch width is
        # panel-wide (windows.py _eval_bf), so only the month COUNT can
        # differ — runs that disagree degrade to sequential.
        val_samplers = [
            DateBatchSampler(panel, d.window, 1, d.firms_per_date,
                             seed=rc.seed,
                             min_valid_months=d.min_valid_months,
                             min_cross_section=1, date_range=sp.val_range)
            for rc, sp in zip(run_cfgs, self.splits)
        ]
        months = [vs.stacked_eval_months() for vs in val_samplers]
        if len(set(months)) != 1:
            raise StackUnavailable(
                f"runs disagree on eligible val months {months} — "
                "cannot stack the validation sweeps")
        vbs = [vs.stacked_cross_sections() for vs in val_samplers]
        self.counts = np.stack([b.weight.sum(axis=1) for b in vbs])

        # Stack mesh: the run axis composed outside the trainer's own
        # seed/data axes (the LFM_FOLDSTACK_SHARDS / LFM_STACK_SHARDS
        # knobs cap/disable it per kind).
        shards = (reuse.foldstack_shards() if self.fold_kind
                  else reuse.stack_shards())
        self.mesh = make_stack_mesh(R, self.trainer.mesh, shards,
                                    axis_name=self.axis_name)
        n_axis = (self.mesh.shape[self.axis_name]
                  if self.mesh is not None else 1)
        blk = reuse.stack_block()
        r_local = R // n_axis
        if blk >= r_local:
            blk = 0  # whole local stack in one vmap — the unblocked trace
        elif blk and r_local % blk:
            warnings.warn(
                f"LFM_STACK_BLOCK={blk} does not divide the per-shard "
                f"run count {r_local}; running unblocked", stacklevel=3)
            blk = 0
        self.stack_block = blk

        inner = self.trainer.programs
        patience = cfg.optim.early_stop_patience
        if self.fold_kind:
            self.program_key = reuse.foldstack_program_key(
                self.trainer.program_key, self.mesh, R, patience, blk)
        else:
            self.program_key = reuse.stacked_program_key(
                self.trainer.program_key, self.mesh, R, patience, kind,
                self.hyper_keys, blk)
        self.programs = reuse.get_programs(
            self.program_key,
            lambda: StackedPrograms(
                inner, self.mesh, R, patience, self.ensemble,
                axis_name=self.axis_name, hyper_keys=self.hyper_keys,
                block=blk, steps_per_epoch=self.steps,
                optim_cfg=cfg.optim))
        # ONE spec source: the programs' shard_map in_specs — H2D staging
        # placed with anything else would silently reshard per dispatch.
        self._batch_spec = self.programs._batch_spec

        if self.mesh is not None:
            t_mesh = self.trainer.mesh
            if (t_mesh is not None
                    and {dv.id for dv in self.mesh.devices.flat}
                    == {dv.id for dv in t_mesh.devices.flat}):
                # Same device SET (e.g. the inner mesh already spans all
                # devices, so the stack axis degraded to 1): replicated
                # placement is device-set-invariant, so the trainer's
                # resident panel serves the stack mesh as-is — no second
                # full-panel H2D, no duplicate HBM copy for the sweep.
                self.dev = self.trainer.dev
            else:
                gather_impl = (self.trainer.inner._gather_impl
                               if self.ensemble
                               else self.trainer._gather_impl)
                # Bind the trainer's RESOLVED compute dtype (same
                # pattern as serve/zoo.py) rather than re-resolving the
                # env knob here: the stack-mesh copy must match the
                # dtype the compiled programs were traced against even
                # if LFM_PRECISION flips between trainer construction
                # and this panel build.
                self.dev = cached_device_panel(
                    panel, self.mesh,
                    compute_dtype=self.trainer._compute_dtype,
                    raw=False, lane_pad=gather_impl == "pallas")
        else:
            self.dev = self.trainer.dev  # same placement — zero extra H2D

        self._vargs = tuple(
            self._put(np.stack([getattr(b, f) for b in vbs]),
                      P(self.axis_name))
            for f in ("firm_idx", "time_idx", "weight"))
        # Per-run hyperparameter operands: [R] f32, placed ONCE on the
        # run axis — every epoch dispatch reuses the same small arrays
        # (never donated; only the carry is).
        self._hp = {}
        if self.hyper:
            self._hp = {
                "lr": self._put(np.asarray(lrs, np.float32),
                                P(self.axis_name)),
                "weight_decay": self._put(np.asarray(wds, np.float32),
                                          P(self.axis_name)),
            }

    # ---- placement ---------------------------------------------------

    def _put(self, a, spec):
        if self.mesh is None:
            return jnp.asarray(a)
        return jax.device_put(a, NamedSharding(self.mesh, spec))

    def init_carry(self):
        """Fresh stacked carry: per-run independent init draws (exact
        sequential parity — see ``init_stacked_states``), best-params
        copies, and the all-live control state — committed to the stack
        mesh."""
        state = self.trainer.init_stacked_states(
            [rc.seed for rc in self.run_cfgs])
        best_params = jax.tree.map(jnp.copy, state.params)
        R = self.run_count
        ctrl = RunCtrl(
            live=jnp.ones((R,), bool),
            best_ic=jnp.full((R,), -jnp.inf, jnp.float32),
            best_epoch=jnp.full((R,), -1, jnp.int32),
            bad_epochs=jnp.zeros((R,), jnp.int32),
        )
        carry = (state, best_params, ctrl)
        if self.mesh is None:
            return carry
        state_spec = getattr(self.programs, "_state_spec",
                             P(self.axis_name))

        def shard_of(spec):
            return lambda x: NamedSharding(
                self.mesh,
                spec if getattr(x, "ndim", 0) >= len(spec)
                else P(self.axis_name))

        shardings = (jax.tree.map(shard_of(state_spec), state),
                     jax.tree.map(shard_of(state_spec), best_params),
                     jax.tree.map(shard_of(P(self.axis_name)), ctrl))
        return jax.device_put(carry, shardings)

    # ---- epoch callbacks (pipeline.run_fit_epochs contract) ----------

    def build_epoch(self, epoch: int):
        """Host sampling + H2D staging for one stacked epoch — runs on
        the prefetch thread under ``LFM_ASYNC`` (pure deterministic reads
        per (seed, epoch), the same thread-safety contract as the
        sequential build)."""
        with telemetry.span("sample", epoch=epoch, runs=self.run_count):
            if self.ensemble:
                stacks = []
                for per_run in self.run_samplers:
                    per_seed = [s.stacked_epoch(epoch) for s in per_run]
                    # Same loud contract as stack_fold_epochs: the
                    # truncate-to-min-K the sequential ensemble applies
                    # is only legal down to the init-time steps count —
                    # a shorter member epoch would silently train this
                    # run on a partial epoch.
                    if min(b.firm_idx.shape[0] for b in per_seed) \
                            < self.steps:
                        raise ValueError(
                            "stacked ensemble epoch shorter than the "
                            f"{self.steps}-step schedule — member "
                            "samplers drifted out of shape")
                    stacks.append(tuple(
                        np.stack([getattr(b, f)[:self.steps]
                                  for b in per_seed], axis=1)
                        for f in ("firm_idx", "time_idx", "weight")))
                fi, ti, w = (np.stack([s[i] for s in stacks])
                             for i in range(3))
            else:
                b = stack_fold_epochs(self.run_samplers, epoch)
                fi, ti, w = b.firm_idx, b.time_idx, b.weight
            fm = float(w.sum()) * self.window
        with telemetry.span("h2d", epoch=epoch):
            spec = self._batch_spec
            args = tuple(self._put(a, spec) for a in (fi, ti, w))
        return args + (jnp.asarray(epoch, jnp.int32),), fm

    def dispatch_epoch(self, carry, args):
        """Queue one stacked epoch (train + eval + control in ONE jitted
        dispatch). The fetched scalars are COPIES: the next epoch's
        dispatch donates the carry, and a fetched value must never alias
        a donated buffer (same rule as the sequential pipeline)."""
        fi, ti, w, epoch = args
        carry, vals = self.programs._jit_epoch(
            carry, self.dev, fi, ti, w, *self._vargs, self._hp, epoch)
        state, _, ctrl = carry
        vals = dict(vals, step=jnp.copy(state.step),
                    live=jnp.copy(ctrl.live))
        return carry, vals

    # ---- the full sweep ---------------------------------------------

    def run_state(self, k: int) -> TrainState:
        """Run ``k``'s final TrainState, unstacked from the trained
        carry — best-tracked params when the run checkpoints (its dir's
        ckpt/best is restored downstream exactly like a sequential
        run's), the last recorded state otherwise (a sequential ``fit``
        without a run dir has no best line to restore and ends on the
        last epoch's state — mirror that, or stacking would silently
        flip forecasts for non-checkpointing callers)."""
        state, best_params = self._final_state, self._best_params
        src = best_params if self.run_dirs[k] else state.params
        return TrainState(
            params=jax.tree.map(lambda x: x[k], src),
            opt_state=jax.tree.map(lambda x: x[k], state.opt_state),
            step=state.step[k],
            rng=state.rng[k],
        )

    def fit(self, per_run: Optional[Callable[[int], None]] = None
            ) -> Tuple[List[Dict[str, Any]], Dict[str, Any]]:
        """Train the stack, unstack per-run results. Returns
        ``(run_summaries, stack_summary)``; ``per_run(k)`` (when given)
        executes inside run k's reuse-delta window after its checkpoint
        unstack — the walk-forward adapter predicts there."""
        from lfm_quant_tpu.train import pipeline
        from lfm_quant_tpu.train.checkpoint import (CheckpointManager,
                                                    fold_slice)

        R = self.run_count
        snap_stack = REUSE_COUNTERS.snapshot()
        histories: List[List[Dict[str, Any]]] = [[] for _ in range(R)]
        loggers = [MetricsLogger(rd) for rd in self.run_dirs]
        live_mask = np.ones(R, bool)
        harness = _StackHarness(self.cfg.optim.epochs)
        timer = StepTimer()
        stop_name = "fold_stopped" if self.fold_kind else "run_stopped"
        stop_key = "fold" if self.fold_kind else "run"

        def finish(epoch, host, fm):
            nonlocal live_mask
            live_in = live_mask
            ic = np.asarray(host["ic"])
            live_ics = []
            for r in range(R):
                if not live_in[r]:
                    continue
                if self.ensemble:
                    per_seed = ((ic[r] * self.counts[r]).sum(axis=1)
                                / self.counts[r].sum())
                    val_ic = float(per_seed.mean())
                    rec = loggers[r].log(
                        int(np.asarray(host["step"][r]).reshape(-1)[0]),
                        epoch=epoch,
                        train_loss=float(host["loss"][r]),
                        val_ic=val_ic,
                        val_ic_std=float(per_seed.std()),
                        firm_months_per_sec=timer.throughput(),
                    )
                else:
                    # f64 np.average — the exact aggregation finish()
                    # applies on the sequential path, over the same
                    # per-month ICs, so recorded histories match.
                    val_ic = float(np.average(ic[r],
                                              weights=self.counts[r]))
                    rec = loggers[r].log(
                        int(host["step"][r]),
                        epoch=epoch,
                        train_loss=float(host["loss"][r]),
                        grad_norm=float(host["grad_norm"][r]),
                        val_ic=val_ic,
                        val_mse=float(host["mse"][r]),
                        firm_months_per_sec=timer.throughput(),
                    )
                histories[r].append(rec)
                live_ics.append(val_ic)
            new_live = np.asarray(host["live"])
            for r in range(R):
                if live_in[r] and not new_live[r]:
                    telemetry.instant(stop_name, epoch=epoch,
                                      **{stop_key: r})
            live_mask = new_live
            harness.all_dead = not bool(new_live.any())
            step = int(np.max(np.asarray(host["step"])))
            return step, (float(np.mean(live_ics)) if live_ics else 0.0)

        mesh_items = (list(self.mesh.shape.items())
                      if self.mesh is not None else None)
        if self.fold_kind:
            span_name, span_kw = "foldstack_fit", dict(
                fold_count=R, fold_mesh=mesh_items)
        else:
            span_name, span_kw = "stack_fit", dict(
                kind=self.kind, run_count=R, stack_mesh=mesh_items,
                hyper=list(self.hyper_keys), stack_block=self.stack_block)
        with telemetry.span(span_name, cat="fit", **span_kw) as sp:
            carry, overrun = pipeline.run_fit_epochs(
                harness, self.init_carry(), build=self.build_epoch,
                dispatch=self.dispatch_epoch, finish=finish, timer=timer,
                checkpointing=False)
            state, best_params, ctrl = carry
            host_ctrl = jax.device_get(ctrl)
            sp.set(epochs_run=[len(h) for h in histories],
                   best_epochs=[int(e) for e in host_ctrl.best_epoch],
                   overrun=overrun is not None)
        for lg in loggers:
            lg.close()
        self._final_state, self._best_params = state, best_params
        self.host_ctrl = host_ctrl

        host_best = host_aux = None
        if self.checkpointing:
            host_best = jax.device_get(best_params)
            host_aux = jax.device_get({"opt_state": state.opt_state,
                                       "step": state.step,
                                       "rng": state.rng})
        stack_reuse = {
            k: (round(v, 4) if isinstance(v, float) else v)
            for k, v in REUSE_COUNTERS.delta(snap_stack).items()}

        run_summaries: List[Dict[str, Any]] = []
        for r in range(R):
            snap_run = REUSE_COUNTERS.snapshot()
            best_epoch = int(host_ctrl.best_epoch[r])
            best_val_ic = (histories[r][best_epoch]["val_ic"]
                           if 0 <= best_epoch < len(histories[r])
                           else float(host_ctrl.best_ic[r]))
            best_step = (best_epoch + 1) * self.steps
            if self.run_dirs[r]:
                # Unstack this run's ckpt/best line so the run dir is
                # loadable exactly like a sequential run's. The params
                # are the device-tracked best; the aux leaves come from
                # the final state (predict/backtest only consume
                # params). The step leaf keeps the FINAL state's SHAPE
                # — scalar for a Trainer, [S] for the ensemble's
                # vmapped init — with the best step's value, or Orbax
                # restore would reject the ensemble's abstract tree.
                step_leaf = np.full_like(
                    np.asarray(fold_slice(host_aux["step"], r)), best_step)
                mgr = CheckpointManager(
                    os.path.join(self.run_dirs[r], "ckpt", "best"),
                    max_to_keep=1)
                mgr.save(best_step, {
                    "params": fold_slice(host_best, r),
                    "opt_state": fold_slice(host_aux["opt_state"], r),
                    "step": step_leaf,
                    "rng": host_aux["rng"][r],
                }, wait=True)
                mgr.close()
            if per_run is not None:
                per_run(r)
            run_summaries.append({
                "best_val_ic": best_val_ic,
                "best_epoch": best_epoch,
                "epochs_run": len(histories[r]),
                "history": histories[r],
                "reuse": {k: (round(v, 4) if isinstance(v, float) else v)
                          for k, v in
                          REUSE_COUNTERS.delta(snap_run).items()},
            })

        stack_summary: Dict[str, Any] = {"enabled": True}
        if self.fold_kind:
            stack_summary.update(fold_count=R, fold_mesh=mesh_items)
        else:
            stack_summary.update(kind=self.kind, run_count=R,
                                 stack_mesh=mesh_items,
                                 hyper=list(self.hyper_keys),
                                 stack_block=self.stack_block)
        stack_summary.update(
            steps_per_epoch=self.steps,
            lookahead_overrun=overrun is not None,
            reuse=stack_reuse,
        )
        return run_summaries, stack_summary


# ---- the config-sweep workload ------------------------------------------


def parse_sweep_grid(spec: str) -> List[Dict[str, float]]:
    """``"lr=1e-3,5e-4;weight_decay=1e-4,0"`` → the cartesian grid as a
    list of per-config override dicts (the ``--sweep-grid`` CLI format:
    semicolon-separated axes, comma-separated values). Only the
    per-run-operand hyperparameters (:data:`HYPER_KEYS`) are legal axes
    — anything else changes the traced program and must be swept as
    separate stacks."""
    axes: List[Tuple[str, List[float]]] = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        name, eq, vals = part.partition("=")
        name = name.strip()
        if not eq or name not in HYPER_KEYS:
            raise ValueError(
                f"sweep axis {name!r} is not sweepable as a per-run "
                f"operand; supported: {', '.join(HYPER_KEYS)}")
        if any(name == n for n, _ in axes):
            raise ValueError(f"duplicate sweep axis {name!r}")
        values = [float(v) for v in vals.split(",") if v.strip()]
        if not values:
            raise ValueError(f"sweep axis {name!r} has no values")
        axes.append((name, values))
    if not axes:
        raise ValueError("empty sweep grid spec")
    grid: List[Dict[str, float]] = [{}]
    for name, values in axes:
        grid = [dict(g, **{name: v}) for g in grid for v in values]
    return grid


def sweep_stacked_enabled() -> bool:
    """``LFM_SWEEP_STACKED=0`` forces a config sweep down the
    sequential per-config path (the parity/A-B reference); default on —
    the stacked engine IS the point of the sweep workload."""
    return os.environ.get("LFM_SWEEP_STACKED", "1") != "0"


def run_config_sweep(cfg: RunConfig, grid: Sequence[Dict[str, float]],
                     panel: Optional[Panel] = None,
                     out_dir: Optional[str] = None, echo: bool = False,
                     stacked: Optional[bool] = None) -> Dict[str, Any]:
    """Train every config of an LR × weight-decay ``grid`` on the run
    config's train/val split — as ONE stacked compiled program when the
    stack preconditions hold (``StackedRuns`` with per-run hyperparameter
    operands), else as sequential per-config fits (also the explicit
    reference via ``stacked=False`` / ``LFM_SWEEP_STACKED=0``). A
    data-dependent :class:`StackUnavailable` degrades to sequential with
    a warning, a ``stack_degraded`` telemetry instant and a
    ``stack_degrades`` counter bump — visible in
    ``scripts/trace_report.py``, never silent.

    Per-config run dirs land under ``out_dir/config_<i>`` (config.json +
    metrics.jsonl + ckpt/best — loadable by ``load_trainer`` exactly
    like a sequential run), and ``sweep_summary.json`` ranks the grid.
    Returns the summary dict (per-config best val ICs, best epochs,
    ``best_index``/``best_config``, and the stack's reuse delta)."""
    import json

    from lfm_quant_tpu.train.ensemble import EnsembleTrainer
    from lfm_quant_tpu.train.loop import (Trainer, default_split_dates,
                                          resolve_panel)
    from lfm_quant_tpu.train.walkforward import write_fold_run_dir

    grid = [dict(g) for g in grid]
    if not grid:
        raise ValueError("empty sweep grid")
    bad = sorted(set().union(*(set(g) for g in grid)) - set(HYPER_KEYS))
    if bad:
        raise ValueError(
            f"unsupported sweep axes {bad}; per-run operands cover "
            f"{', '.join(HYPER_KEYS)}")
    if stacked is None:
        stacked = sweep_stacked_enabled()
    run_cfgs = [
        dataclasses.replace(cfg, optim=dataclasses.replace(cfg.optim, **g))
        for g in grid
    ]
    if panel is None:
        panel = resolve_panel(cfg.data)
    train_end, val_end = default_split_dates(panel, cfg.data)
    splits = PanelSplits.by_date(panel, train_end, val_end,
                                 train_start=cfg.data.train_start)
    R = len(grid)
    ensemble = cfg.n_seeds > 1
    run_dirs: List[Optional[str]] = [
        os.path.join(out_dir, f"config_{i:03d}") if out_dir else None
        for i in range(R)
    ]
    for i, rd in enumerate(run_dirs):
        if rd:
            write_fold_run_dir(run_cfgs[i], rd, train_end, val_end,
                               cfg.data.train_start, ensemble)

    run_sums = None
    stack_info = None
    with telemetry.span("config_sweep", cat="fit", n_configs=R):
        if stacked and R >= 2:
            try:
                eng = StackedRuns(run_cfgs, [splits] * R, panel,
                                  kind="config", run_dirs=run_dirs,
                                  echo=echo)
                run_sums, stack_info = eng.fit()
            except StackUnavailable as e:
                warnings.warn(
                    f"stacked config sweep unavailable ({e}); running "
                    "the configs sequentially", stacklevel=2)
                telemetry.instant("stack_degraded", kind="config",
                                  reason=str(e))
                telemetry.COUNTERS.bump("stack_degrades")
        if run_sums is None:
            run_sums = []
            for rc, rd in zip(run_cfgs, run_dirs):
                trainer = (EnsembleTrainer if ensemble else Trainer)(
                    rc, splits, run_dir=rd, echo=echo)
                fit = trainer.fit()
                run_sums.append({
                    "best_val_ic": fit["best_val_ic"],
                    "best_epoch": fit["best_epoch"],
                    "epochs_run": fit["epochs_run"],
                    "history": fit["history"],
                })

    runs = [{
        "config": grid[i],
        "run_dir": run_dirs[i],
        "best_val_ic": run_sums[i]["best_val_ic"],
        "best_epoch": run_sums[i]["best_epoch"],
        "epochs_run": run_sums[i]["epochs_run"],
    } for i in range(R)]
    best_index = int(max(range(R), key=lambda i: runs[i]["best_val_ic"]))
    summary = {
        "n_configs": R,
        "grid": grid,
        "train_end": train_end,
        "val_end": val_end,
        "runs": runs,
        "stacked": stack_info,
        "best_index": best_index,
        "best_config": grid[best_index],
        "best_val_ic": runs[best_index]["best_val_ic"],
    }
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
        with open(os.path.join(out_dir, "sweep_summary.json"), "w") as fh:
            json.dump(summary, fh, indent=2)
    return summary


def run_walkforward_sweep(cfg: RunConfig, grid: Sequence[Dict[str, float]],
                          panel: Optional[Panel] = None, *, start: int,
                          step_months: int = 12, val_months: int = 24,
                          n_folds: Optional[int] = None,
                          train_months: Optional[int] = None,
                          out_dir: Optional[str] = None, echo: bool = False,
                          stacked: Optional[bool] = None) -> Dict[str, Any]:
    """The fold × config PRODUCT sweep (``train.py --sweep-grid``
    composed with ``--walk-forward``): every (walk-forward fold,
    hyperparameter config) pair trained as one run of a single
    :class:`StackedRuns` stack — each run carries its OWN (cfg, splits)
    pair, which is exactly the per-run surface the engine already
    exposes (ROADMAP open item 2: "wiring, not architecture"). Per-config
    LR/weight-decay ride as vmapped per-run operands; per-fold split
    boundaries and fold-offset seeds ride as per-run data, so the whole
    F × C product compiles ONCE.

    The product answers the question a single-split sweep cannot: does
    the winning config WIN ACROSS REGIMES, or only on one validation
    window? ``summary["by_config"]`` carries each config's mean/min best
    val IC over folds; ``summary["folds"]`` each fold's own ranking.

    A rolling ``train_months`` window keeps every fold the same shape
    (stackable); expanding-window folds usually differ in
    steps-per-epoch and degrade LOUDLY to sequential per-run fits
    (warning + ``stack_degraded`` instant + ``stack_degrades`` counter),
    as does ``LFM_BUCKETS=1`` — the degrade path trains the identical
    runs, just serially. Run dirs land under
    ``<out_dir>/fold_<k>/config_<j>`` (loadable like any fold dir);
    ``sweep_summary.json`` ranks the product. No forecast stitching:
    stitching wants ONE config per fold — pick the winner here, then run
    the plain walk-forward with it."""
    import json

    from lfm_quant_tpu.train.ensemble import EnsembleTrainer
    from lfm_quant_tpu.train.loop import Trainer, resolve_panel
    from lfm_quant_tpu.train.walkforward import (month_add,
                                                 walkforward_folds,
                                                 write_fold_run_dir)

    grid = [dict(g) for g in grid]
    if not grid:
        raise ValueError("empty sweep grid")
    bad = sorted(set().union(*(set(g) for g in grid)) - set(HYPER_KEYS))
    if bad:
        raise ValueError(
            f"unsupported sweep axes {bad}; per-run operands cover "
            f"{', '.join(HYPER_KEYS)}")
    if stacked is None:
        stacked = sweep_stacked_enabled()
    if panel is None:
        panel = resolve_panel(cfg.data)
    folds = walkforward_folds(panel, start, step_months, val_months,
                              n_folds)
    F, C = len(folds), len(grid)
    ensemble = cfg.n_seeds > 1

    run_cfgs: List[RunConfig] = []
    run_splits: List[PanelSplits] = []
    run_dirs: List[Optional[str]] = []
    for k, (train_end, val_end, _pred) in enumerate(folds):
        train_start = (month_add(train_end, -train_months)
                       if train_months else None)
        splits = PanelSplits.by_date(panel, train_end, val_end,
                                     train_start=train_start)
        for j, g in enumerate(grid):
            rc = dataclasses.replace(
                cfg, seed=cfg.seed + 1000 * k,
                optim=dataclasses.replace(cfg.optim, **g))
            rd = (os.path.join(out_dir, f"fold_{k}", f"config_{j:03d}")
                  if out_dir else None)
            if rd:
                write_fold_run_dir(rc, rd, train_end, val_end,
                                   train_start, ensemble)
            run_cfgs.append(rc)
            run_splits.append(splits)
            run_dirs.append(rd)

    run_sums = None
    stack_info = None
    with telemetry.span("wf_config_sweep", cat="fit", n_folds=F,
                        n_configs=C):
        if stacked and F * C >= 2:
            try:
                eng = StackedRuns(run_cfgs, run_splits, panel, kind="grid",
                                  run_dirs=run_dirs, echo=echo)
                run_sums, stack_info = eng.fit()
            except StackUnavailable as e:
                warnings.warn(
                    f"stacked fold×config sweep unavailable ({e}); "
                    "running the runs sequentially", stacklevel=2)
                telemetry.instant("stack_degraded", kind="grid",
                                  reason=str(e))
                telemetry.COUNTERS.bump("stack_degrades")
        if run_sums is None:
            run_sums = []
            for rc, sp, rd in zip(run_cfgs, run_splits, run_dirs):
                trainer = (EnsembleTrainer if ensemble else Trainer)(
                    rc, sp, run_dir=rd, echo=echo)
                fit = trainer.fit()
                run_sums.append({
                    "best_val_ic": fit["best_val_ic"],
                    "best_epoch": fit["best_epoch"],
                    "epochs_run": fit["epochs_run"],
                })

    fold_recs = []
    for k, (train_end, val_end, _pred) in enumerate(folds):
        runs = [{
            "config": grid[j],
            "run_dir": run_dirs[k * C + j],
            "best_val_ic": run_sums[k * C + j]["best_val_ic"],
            "best_epoch": run_sums[k * C + j]["best_epoch"],
            "epochs_run": run_sums[k * C + j]["epochs_run"],
        } for j in range(C)]
        fold_recs.append({
            "fold": k,
            "train_end": train_end,
            "val_end": val_end,
            "runs": runs,
            "best_index": int(max(range(C),
                                  key=lambda j: runs[j]["best_val_ic"])),
        })
    by_config = []
    for j in range(C):
        ics = [run_sums[k * C + j]["best_val_ic"] for k in range(F)]
        by_config.append({
            "config": grid[j],
            "mean_best_val_ic": float(np.mean(ics)),
            "min_best_val_ic": float(np.min(ics)),
            "per_fold": [float(v) for v in ics],
        })
    best_index = int(max(range(C),
                         key=lambda j: by_config[j]["mean_best_val_ic"]))
    summary = {
        "n_folds": F,
        "n_configs": C,
        "grid": grid,
        "step_months": step_months,
        "val_months": val_months,
        "train_months": train_months,
        "folds": fold_recs,
        "by_config": by_config,
        "best_index": best_index,
        "best_config": grid[best_index],
        "stacked": stack_info,
    }
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
        with open(os.path.join(out_dir, "sweep_summary.json"), "w") as fh:
            json.dump(summary, fh, indent=2)
    return summary
