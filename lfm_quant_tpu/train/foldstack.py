"""Fold-vectorized walk-forward: train every fold as ONE stacked program.

PRs 1–4 made each walk-forward fold cheap — compile-once programs,
donated buffers, an async epoch pipeline, telemetry — but folds still
ran strictly one-after-another in ``run_walkforward``, so the sweep paid
every per-fold fixed cost serially and left the mesh's spare axes idle.
PR 5 stacked all same-shape folds on a leading, mesh-shardable fold axis
and trained them as one pipelined jitted program per epoch; PR 7 then
extracted the axis-agnostic core of that engine into
``train/stacked.py`` (:class:`~lfm_quant_tpu.train.stacked.StackedRuns`)
— leading run axis, vmapped multi-step scan, masked per-run early stop,
device-side best tracking, one host sync per epoch — so the same
machinery now also drives hyperparameter-config sweeps. This module is
the walk-forward ADAPTER over that engine: it owns everything
fold-shaped (the fold schedule → per-fold configs/splits/run dirs,
per-fold prediction windows, the degrade-to-sequential contract) while
the engine owns the stacked execution. Its parity lane
(``pytest -m foldstack``) pins that the adapterization changed nothing:

* F same-shape folds stack on a leading ``fold`` axis of one
  TrainState; every epoch is ONE jitted program: the vmapped multi-step
  train scan, the chained per-fold validation sweep, and the early-stop
  CONTROL UPDATE — all device-side (DESIGN.md §13, §15).
* The fold axis is mesh-shardable and composes OUTSIDE the existing
  ``seed`` × ``data`` axes (parallel/mesh.py ``make_fold_mesh``): folds
  are independent, so no collective ever crosses 'fold'.
* Divergent early stopping is handled by masking: a stopped fold's
  state update is a select back to its input — params, optimizer
  moments, step counter and dropout stream are BIT-FROZEN while live
  folds continue — and per-fold best-epoch/best-params are tracked
  device-side, so the control loop needs no host round-trip.
* The PR 3 pipeline contract is kept: the epoch loop runs through
  ``pipeline.run_fit_epochs`` (``LFM_ASYNC`` lookahead included), pays
  ONE blocking host sync per stacked epoch, and an overrun epoch
  dispatched after every fold died is a device-side no-op.
* Per-fold PRNG streams are exact: each fold keeps its own sampler seeds
  (``data/windows.py stack_fold_epochs``) and its own init key
  (``Trainer.init_stacked_states``), so fold k samples and initializes
  exactly as its sequential run would.
* The precision lane (``LFM_PRECISION=bf16``, DESIGN.md §17) composes
  transparently: the stacked state holds each fold's f32 MASTER params
  and f32 moments over the one shared bf16 resident panel, the
  device-side ``FoldCtrl`` early-stop control compares the f32 val ICs
  the f32 head/reduction boundary produces (decisions stay exact), and
  the lane reaches the fold-stack program key through the inner
  trainer key it embeds — an env flip rebuilds, never stale reuse.

Durability trade (documented, not hidden): the stacked fit writes NO
per-epoch checkpoint lines — each fold's ``ckpt/best`` is unstacked from
the device-side best params at finalize (train/checkpoint.py
``fold_slice``), keeping every fold run dir loadable by the same
``load_trainer``/``load_ensemble`` path as a sequential sweep. A crash
mid-stack therefore loses the in-flight stacked fit; ``resume=True`` and
``warm_start=True`` (an inherently serial carry) are rejected up front.
"""

from __future__ import annotations

import dataclasses
import os
import warnings
from typing import Any, Dict, List, Optional, Sequence, Tuple

from lfm_quant_tpu.config import RunConfig
from lfm_quant_tpu.data.panel import Panel, PanelSplits
from lfm_quant_tpu.train.stacked import (
    RunCtrl,
    StackedPrograms,
    StackedRuns,
    StackUnavailable,
)
from lfm_quant_tpu.utils import telemetry


class FoldstackUnavailable(StackUnavailable):
    """A FOLD-specific precondition for stacking is unmet (no rolling
    window, F < 2). The walk-forward driver catches the shared
    :class:`StackUnavailable` base — this subclass plus the engine's
    own data-dependent raises — and degrades to the sequential path
    with a warning: a shape mismatch must not kill a sweep that the
    sequential mode handles fine."""


#: Back-compat aliases: the device-side control state and the stacked
#: epoch program now live on the generic engine (train/stacked.py).
FoldCtrl = RunCtrl
FoldstackPrograms = StackedPrograms


class StackedWalkforward(StackedRuns):
    """One fold-stacked walk-forward sweep: the fold adapter over the
    generic :class:`StackedRuns` engine.

    Construction maps the fold schedule onto the engine's run axis —
    per-fold configs (fold-offset seeds), per-fold rolling-window
    splits, per-fold run dirs — and validates the FOLD preconditions
    (rolling ``train_months``; >= 2 folds) before the engine validates
    the generic ones (same-shape schedules, no seq axis).
    :meth:`run` trains the stack through the engine and adds the
    fold-specific tail: each fold's out-of-sample prediction from its
    unstacked state, executed inside the fold's reuse-delta window.
    """

    def __init__(self, cfg: RunConfig, panel: Panel,
                 folds: Sequence[Tuple[int, int, Tuple[int, int]]], *,
                 train_months: Optional[int], out_dir: Optional[str] = None,
                 echo: bool = False):
        from lfm_quant_tpu.train.walkforward import (month_add,
                                                     write_fold_run_dir)

        if len(folds) < 2:
            raise FoldstackUnavailable(
                f"fold-stacking needs >= 2 folds, schedule has "
                f"{len(folds)}")
        if train_months is None:
            raise FoldstackUnavailable(
                "fold-stacking needs the rolling train_months window "
                "(same-shape folds); expanding-window folds have "
                "fold-varying shapes")
        self.folds = list(folds)
        self.out_dir = out_dir
        fold_count = len(folds)
        ensemble = cfg.n_seeds > 1

        fold_cfgs = [dataclasses.replace(cfg, seed=cfg.seed + 1000 * k)
                     for k in range(fold_count)]
        splits = [
            PanelSplits.by_date(panel, te, ve,
                                train_start=month_add(te, -train_months))
            for te, ve, _ in folds
        ]
        run_dirs = [os.path.join(out_dir, f"fold_{k}") if out_dir
                    else None for k in range(fold_count)]
        for k, run_dir in enumerate(run_dirs):
            if run_dir:
                write_fold_run_dir(fold_cfgs[k], run_dir,
                                   folds[k][0], folds[k][1],
                                   month_add(folds[k][0], -train_months),
                                   ensemble)
        super().__init__(fold_cfgs, splits, panel, kind="fold",
                         run_dirs=run_dirs, echo=echo)

    @property
    def fold_cfgs(self):
        return self.run_cfgs

    @property
    def fold_count(self) -> int:
        return self.run_count

    @property
    def fold_samplers(self):
        return self.run_samplers

    # ---- the full sweep ---------------------------------------------

    def run(self) -> Tuple[List[Dict[str, Any]], List[Tuple],
                           Dict[str, Any]]:
        """Train the stack, unstack per-fold results. Returns
        ``(fold_summaries, fold_predictions, stack_summary)`` — the
        walk-forward driver stitches the predictions and composes the
        final per-fold records."""
        fold_preds: List[Tuple] = []

        def per_fold(k: int) -> None:
            # Prediction-state parity with the sequential path: see
            # StackedRuns.run_state — best-tracked params for
            # checkpointing folds, last recorded state otherwise.
            self.trainer.state = self.trainer._commit_state(
                self.run_state(k))
            pred_range = self.folds[k][2]
            with telemetry.span("predict", cat="predict", fold=k):
                if self.het:
                    pred = self.trainer.predict(date_range=pred_range,
                                                return_variance=True)
                else:
                    pred = self.trainer.predict(date_range=pred_range)
            fold_preds.append(pred)

        fold_summaries, stack_summary = self.fit(per_run=per_fold)
        return fold_summaries, fold_preds, stack_summary


def run_stacked_walkforward(cfg: RunConfig, panel: Panel, folds, *,
                            train_months: Optional[int],
                            out_dir: Optional[str] = None,
                            echo: bool = False):
    """Fold-stacked sweep entry point for ``run_walkforward``: returns
    ``(fold_summaries, fold_predictions, stack_summary)``, or ``None``
    after a warning when a stacking precondition is data-dependently
    unmet (the caller then runs the sequential path — degrade, don't
    kill a sweep the sequential mode handles). The degrade is never
    silent beyond the warning: it also lands a ``stack_degraded``
    telemetry instant and bumps the ``stack_degrades`` counter, so
    ``scripts/trace_report.py`` surfaces it from the run dir alone."""
    try:
        sw = StackedWalkforward(cfg, panel, folds,
                                train_months=train_months,
                                out_dir=out_dir, echo=echo)
    except StackUnavailable as e:
        warnings.warn(f"fold-stacking unavailable ({e}); running the "
                      "sequential walk-forward", stacklevel=3)
        telemetry.instant("stack_degraded", kind="fold", reason=str(e))
        telemetry.COUNTERS.bump("stack_degrades")
        return None
    return sw.run()
