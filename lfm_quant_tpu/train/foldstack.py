"""Fold-vectorized walk-forward: train every fold as ONE stacked program.

PRs 1–4 made each walk-forward fold cheap — compile-once programs,
donated buffers, an async epoch pipeline, telemetry — but folds still
ran strictly one-after-another in ``run_walkforward``, so the sweep paid
every per-fold fixed cost serially and left the mesh's spare axes idle.
The serial dependency between folds is weak by our own measurement
(``walkforward_warm_start``: warm 4.0 vs cold 3.83 epochs-to-stop), and
PR 1's rolling ``train_months`` window already guarantees identical fold
shapes — exactly the precondition for stacking folds into one batched
program, the replicate-independent-work-into-one-dispatch move of
multi-GPU RNN data parallelization (PAPERS.md: Khomenko et al. 1708.05604;
You et al. 1901.08256) applied to the retraining campaign itself.

Execution model (``LFM_FOLDSTACK`` / ``--wf-foldstack``):

* F same-shape folds stack on a NEW leading ``fold`` axis of one
  TrainState; every epoch is ONE jitted program: the vmapped multi-step
  train scan, the chained per-fold validation sweep, and the early-stop
  CONTROL UPDATE — all device-side (DESIGN.md §13).
* The fold axis is mesh-shardable and composes OUTSIDE the existing
  ``seed`` × ``data`` axes (parallel/mesh.py ``make_fold_mesh``): folds
  are independent, so no collective ever crosses 'fold'.
* Divergent early stopping is handled by masking: a stopped fold's
  state update is a select back to its input — params, optimizer
  moments, step counter and dropout stream are BIT-FROZEN while live
  folds continue — and per-fold best-epoch/best-params are tracked
  device-side (``FoldCtrl`` + the stacked ``best_params`` carry), so the
  control loop needs no host round-trip between epochs.
* The PR 3 pipeline contract is kept: the epoch loop runs through
  ``pipeline.run_fit_epochs`` (``LFM_ASYNC`` lookahead included), pays
  ONE blocking host sync per stacked epoch, and an overrun epoch
  dispatched after every fold died is a device-side no-op (the all-dead
  mask freezes the whole state) that is never recorded.
* Per-fold PRNG streams are exact: each fold keeps its own sampler seeds
  (``data/windows.py stack_fold_epochs``) and its own init key
  (``Trainer.init_stacked_states``), so fold k samples and initializes
  exactly as its sequential run would — the parity the ``foldstack``
  test lane pins per fold against sequential execution.

Durability trade (documented, not hidden): the stacked fit writes NO
per-epoch checkpoint lines — each fold's ``ckpt/best`` is unstacked from
the device-side best params at finalize (train/checkpoint.py
``fold_slice``), keeping every fold run dir loadable by the same
``load_trainer``/``load_ensemble`` path as a sequential sweep. A crash
mid-stack therefore loses the in-flight stacked fit; ``resume=True`` and
``warm_start=True`` (an inherently serial carry) are rejected up front.
"""

from __future__ import annotations

import dataclasses
import os
import warnings
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from lfm_quant_tpu.config import RunConfig
from lfm_quant_tpu.data.panel import Panel, PanelSplits
from lfm_quant_tpu.data.windows import (
    DateBatchSampler,
    cached_device_panel,
    stack_fold_epochs,
)
from lfm_quant_tpu.parallel.mesh import (
    DATA_AXIS,
    FOLD_AXIS,
    SEED_AXIS,
    make_fold_mesh,
    shard_map_compat,
)
from lfm_quant_tpu.train.loop import TrainState
from lfm_quant_tpu.utils import telemetry
from lfm_quant_tpu.utils.logging import MetricsLogger
from lfm_quant_tpu.utils.profiling import REUSE_COUNTERS, StepTimer


class FoldstackUnavailable(RuntimeError):
    """A precondition for fold-stacking is unmet (no rolling window,
    ragged fold shapes, sequence parallelism, F < 2). The walk-forward
    driver catches this and degrades to the sequential path with a
    warning — a data-dependent shape mismatch must not kill a sweep that
    the sequential mode handles fine."""


class FoldCtrl(NamedTuple):
    """Device-side per-fold early-stopping state — the FitHarness
    counters, vectorized over folds and kept on device so the control
    decision needs no host sync and no lookahead lag: a fold that stops
    at epoch e is frozen in epoch e+1's program because e+1's dispatch
    consumes e's output control state directly."""

    live: jax.Array        # [F] bool — fold still training
    best_ic: jax.Array     # [F] f32 — running best val IC (-inf start)
    best_epoch: jax.Array  # [F] i32 — epoch of best_ic (-1 start)
    bad_epochs: jax.Array  # [F] i32 — epochs since last improvement


class FoldstackPrograms:
    """The fold-stacked epoch program, cached in the cross-fold program
    cache (train/reuse.py ``foldstack_program_key``): ONE jitted (and,
    under a fold mesh, shard_mapped) function runs the vmapped
    multi-step train scan, the chained per-fold validation sweep, the
    bit-freeze select for stopped folds, and the device-side control
    update. Donation is preserved: the whole carry (stacked TrainState +
    best params + control) is donated, so XLA aliases the fold-stacked
    params/opt_state in place exactly like the sequential multi-step
    wrappers do (train/reuse.py ``multi_step_donate_argnums``).

    Holds only the inner program bundle (TrainerPrograms /
    EnsemblePrograms) and static geometry — no panel, samplers or
    TrainState — so cache entries stay lightweight (same invariant as
    the inner bundles)."""

    def __init__(self, inner, mesh, fold_count: int, patience: int,
                 ensemble: bool):
        from lfm_quant_tpu.train.reuse import (ledger_jit,
                                               multi_step_donate_argnums)

        self.inner = inner
        self.mesh = mesh
        self.fold_count = fold_count
        self.patience = patience
        self.ensemble = ensemble
        axes = dict(mesh.shape) if mesh is not None else {}
        # Axis names live inside the fold shard_map: the inner step's
        # gradient psum needs 'data'; the control aggregation needs
        # 'seed' when the ensemble's members are seed-sharded.
        self._data_axis = DATA_AXIS if DATA_AXIS in axes else None
        self._seed_axis = (SEED_AXIS if ensemble and SEED_AXIS in axes
                           else None)
        donate = multi_step_donate_argnums()
        self._batch_spec = None
        if mesh is None:
            self._jit_epoch = ledger_jit("fold_epoch", self._epoch_impl,
                                         donate_argnums=donate)
            return
        state_spec = (P(FOLD_AXIS, SEED_AXIS) if self._seed_axis
                      else P(FOLD_AXIS))
        if ensemble:
            batch_spec = P(FOLD_AXIS, None, self._seed_axis or None,
                           self._data_axis or None)
        elif self._data_axis:
            batch_spec = P(FOLD_AXIS, None, DATA_AXIS)
        else:
            batch_spec = P(FOLD_AXIS)
        fold_spec = P(FOLD_AXIS)
        # Exposed: the driver stages batches with THIS spec, so H2D
        # placement and the shard_map in_specs can never drift apart.
        self._batch_spec = batch_spec
        carry_spec = (state_spec, state_spec, fold_spec)
        metric_spec = {"loss": fold_spec, "ic": (P(FOLD_AXIS, SEED_AXIS)
                                                 if self._seed_axis
                                                 else fold_spec)}
        if not ensemble:
            metric_spec.update(grad_norm=fold_spec, mse=fold_spec)
        self._jit_epoch = ledger_jit(
            "fold_epoch",
            shard_map_compat(
                self._epoch_impl,
                mesh=mesh,
                in_specs=(carry_spec, P(), batch_spec, batch_spec,
                          batch_spec, fold_spec, fold_spec, fold_spec,
                          P()),
                out_specs=(carry_spec, metric_spec),
                check_vma=False,
            ),
            donate_argnums=donate)
        self._state_spec = state_spec

    # ---- the fused epoch program ------------------------------------

    def _epoch_impl(self, carry, dev: dict, fi, ti, w, vfi, vti, vw,
                    epoch):
        """One stacked epoch: train all live folds, evaluate every fold,
        update the device-side control state. ``epoch`` is a traced i32
        scalar (no retrace per epoch). Under the fold mesh this body
        runs per shard on the local fold block; all arrays below carry
        the LOCAL fold axis."""
        state, best_params, ctrl = carry
        inner = self.inner
        live = ctrl.live

        if self.ensemble:
            multi = lambda st, f, t, ww: inner._multi_step_impl(
                st, dev, f, t, ww)
        else:
            ax = (self._data_axis,) if self._data_axis else None
            multi = lambda st, f, t, ww: inner._multi_step_impl(
                st, dev, f, t, ww, axis=ax)
        new_state, ms = jax.vmap(multi)(state, fi, ti, w)

        # Bit-freeze stopped folds: a SELECT back to the input state, not
        # a zero-weight arithmetic step — Adam moment decay, weight decay
        # and the step counter would all still move under zeroed
        # gradients, and the parity contract is bit-frozen params.
        def sel_live(n, o):
            m = live.reshape(live.shape + (1,) * (n.ndim - 1))
            return jnp.where(m, n, o)

        state = jax.tree.map(sel_live, new_state, state)

        # Chained per-fold validation sweep on the post-select params (a
        # frozen fold re-evaluates its frozen params — masked out of the
        # control update below, so only live folds' ICs matter).
        counts = vw.sum(axis=-1)  # [F, M] f32
        if self.ensemble:
            seed_fwd = jax.vmap(inner.inner._forward_impl,
                                in_axes=(0, None, None, None, None))

            def fold_eval(p, vf, vt, vww):
                _, ic, _ = seed_fwd(p, dev, vf, vt, vww)
                return ic  # [S_local, M]

            ic = jax.vmap(fold_eval)(state.params, vfi, vti, vw)
            per_seed = ((ic * counts[:, None, :]).sum(-1)
                        / counts.sum(-1)[:, None])  # [F, S_local]
            if self._seed_axis:
                val_ic = (jax.lax.psum(per_seed.sum(axis=1),
                                       self._seed_axis)
                          / inner.n_seeds)
            else:
                val_ic = per_seed.mean(axis=1)
            k_steps = fi.shape[1]
            loss_sum = ms["loss"].sum(axis=(1, 2))
            if self._seed_axis:
                loss_sum = jax.lax.psum(loss_sum, self._seed_axis)
            metrics = {"loss": loss_sum / (k_steps * inner.n_seeds),
                       "ic": ic}
        else:
            def fold_eval(p, vf, vt, vww):
                _, ic, mse = inner._forward_impl(p, dev, vf, vt, vww)
                return ic, mse

            ic, mse = jax.vmap(fold_eval)(state.params, vfi, vti, vw)
            val_ic = (ic * counts).sum(-1) / counts.sum(-1)  # [F] f32
            metrics = {"loss": ms["loss"].mean(axis=1),
                       "grad_norm": ms["grad_norm"].mean(axis=1),
                       "ic": ic, "mse": mse}

        # Device-side FitHarness: same comparisons, vectorized. A fold
        # improves strictly (val_ic > best_ic, -inf start ⇒ epoch 0
        # always improves), otherwise its patience counter advances; a
        # fold whose counter reaches patience leaves the live set for
        # every later epoch — including a speculative overrun epoch,
        # which therefore cannot move any state.
        improved = live & (val_ic > ctrl.best_ic)
        best_ic = jnp.where(improved, val_ic, ctrl.best_ic)
        best_epoch = jnp.where(improved, epoch, ctrl.best_epoch)
        bad = jnp.where(improved, 0,
                        jnp.where(live, ctrl.bad_epochs + 1,
                                  ctrl.bad_epochs))

        def sel_best(n, o):
            m = improved.reshape(improved.shape + (1,) * (n.ndim - 1))
            return jnp.where(m, n, o)

        best_params = jax.tree.map(sel_best, state.params, best_params)
        ctrl = FoldCtrl(live & (bad < self.patience), best_ic, best_epoch,
                        bad)
        return (state, best_params, ctrl), metrics


class _StackHarness:
    """Duck-typed FitHarness shell for ``pipeline.run_fit_epochs``:
    epoch accounting only. Early stopping lives DEVICE-SIDE in the
    stacked control state; the ``finish`` callback sets ``all_dead``
    from the fetched live mask, and ``end_epoch`` just reports it (no
    checkpointing — fold checkpoints are unstacked at finalize)."""

    def __init__(self, epochs: int):
        self.epochs = epochs
        self.all_dead = False
        self._epoch = -1

    def next_epoch(self) -> Optional[int]:
        nxt = self._epoch + 1
        if nxt >= self.epochs or self.all_dead:
            return None
        self._epoch = nxt
        return nxt

    def end_epoch(self, epoch, step, state_dict, val_ic) -> bool:
        return self.all_dead

    @property
    def last_epoch(self) -> int:
        return self._epoch


class StackedWalkforward:
    """Driver for one fold-stacked walk-forward sweep.

    Construction validates every stacking precondition (raising
    :class:`FoldstackUnavailable` on data-dependent mismatches), binds
    ONE trainer (programs + resident panel through the reuse caches),
    builds per-fold samplers with the exact per-fold PRNG streams, and
    fetches the stacked epoch program through the program cache.
    :meth:`run` trains the stack through the PR 3 pipeline driver and
    unstacks per-fold results (histories, best checkpoints, predictions).
    """

    def __init__(self, cfg: RunConfig, panel: Panel,
                 folds: Sequence[Tuple[int, int, Tuple[int, int]]], *,
                 train_months: Optional[int], out_dir: Optional[str] = None,
                 echo: bool = False):
        from lfm_quant_tpu.train import reuse
        from lfm_quant_tpu.train.ensemble import EnsembleTrainer
        from lfm_quant_tpu.train.loop import Trainer
        from lfm_quant_tpu.train.walkforward import (month_add,
                                                     write_fold_run_dir)

        if len(folds) < 2:
            raise FoldstackUnavailable(
                f"fold-stacking needs >= 2 folds, schedule has "
                f"{len(folds)}")
        if train_months is None:
            raise FoldstackUnavailable(
                "fold-stacking needs the rolling train_months window "
                "(same-shape folds); expanding-window folds have "
                "fold-varying shapes")
        self.cfg = cfg
        self.panel = panel
        self.folds = list(folds)
        self.out_dir = out_dir
        self.fold_count = len(folds)
        self.ensemble = cfg.n_seeds > 1
        self.het = cfg.is_heteroscedastic
        self.window = cfg.data.window
        d = cfg.data

        self.fold_cfgs = [dataclasses.replace(cfg, seed=cfg.seed + 1000 * k)
                          for k in range(self.fold_count)]
        self.splits = [
            PanelSplits.by_date(panel, te, ve,
                                train_start=month_add(te, -train_months))
            for te, ve, _ in folds
        ]
        self.run_dirs = [os.path.join(out_dir, f"fold_{k}") if out_dir
                         else None for k in range(self.fold_count)]
        for k, run_dir in enumerate(self.run_dirs):
            if run_dir:
                write_fold_run_dir(self.fold_cfgs[k], run_dir,
                                   self.folds[k][0], self.folds[k][1],
                                   month_add(self.folds[k][0],
                                             -train_months),
                                   self.ensemble)

        # ONE trainer, bound to fold 0: supplies the compiled inner
        # programs, the resolved gather/panel geometry, predict(), and
        # the state-commit machinery — all through the reuse caches.
        self.trainer = (EnsembleTrainer if self.ensemble else Trainer)(
            self.fold_cfgs[0], self.splits[0], run_dir=None, echo=echo)
        n_seq = getattr(self.trainer, "_n_seq", 1)
        if n_seq > 1:
            raise FoldstackUnavailable(
                "fold-stacking does not compose with sequence "
                "parallelism (the seq axis' ring collectives assume "
                "innermost ICI placement)")

        # Per-fold samplers with the fold's own seed and anchor range —
        # the exact streams the sequential fold would consume.
        if self.ensemble:
            self.fold_samplers = [
                [DateBatchSampler(
                    panel, d.window, d.dates_per_batch, d.firms_per_date,
                    seed=fc.seed + s, min_valid_months=d.min_valid_months,
                    date_range=sp.train_range, engine=d.sampler_engine)
                 for s in range(cfg.n_seeds)]
                for fc, sp in zip(self.fold_cfgs, self.splits)
            ]
            steps = [min(s.batches_per_epoch() for s in per_fold)
                     for per_fold in self.fold_samplers]
        else:
            self.fold_samplers = [
                DateBatchSampler(
                    panel, d.window, d.dates_per_batch, d.firms_per_date,
                    seed=fc.seed, min_valid_months=d.min_valid_months,
                    date_range=sp.train_range, engine=d.sampler_engine)
                for fc, sp in zip(self.fold_cfgs, self.splits)
            ]
            steps = [s.batches_per_epoch() for s in self.fold_samplers]
        if len(set(steps)) != 1:
            raise FoldstackUnavailable(
                f"folds disagree on steps-per-epoch {steps} — the "
                "rolling window crossed a dates_per_batch boundary")
        self.steps = steps[0]

        # Per-fold validation sweeps, stacked. The eval batch width is
        # panel-wide (windows.py _eval_bf), so only the month COUNT can
        # differ — and with a fixed val_months it doesn't; a panel whose
        # eligible-month count still differs degrades to sequential.
        val_samplers = [
            DateBatchSampler(panel, d.window, 1, d.firms_per_date,
                             seed=fc.seed,
                             min_valid_months=d.min_valid_months,
                             min_cross_section=1, date_range=sp.val_range)
            for fc, sp in zip(self.fold_cfgs, self.splits)
        ]
        months = [vs.stacked_eval_months() for vs in val_samplers]
        if len(set(months)) != 1:
            raise FoldstackUnavailable(
                f"folds disagree on eligible val months {months} — "
                "cannot stack the validation sweeps")
        vbs = [vs.stacked_cross_sections() for vs in val_samplers]
        self.counts = np.stack([b.weight.sum(axis=1) for b in vbs])

        # Fold mesh: the new 'fold' axis composed outside the trainer's
        # own seed/data axes (LFM_FOLDSTACK_SHARDS caps/disables it).
        self.mesh = make_fold_mesh(self.fold_count, self.trainer.mesh,
                                   reuse.foldstack_shards())
        inner = self.trainer.programs
        self.program_key = reuse.foldstack_program_key(
            self.trainer.program_key, self.mesh, self.fold_count,
            cfg.optim.early_stop_patience)
        self.programs = reuse.get_programs(
            self.program_key,
            lambda: FoldstackPrograms(inner, self.mesh, self.fold_count,
                                      cfg.optim.early_stop_patience,
                                      self.ensemble))
        # ONE spec source: the programs' shard_map in_specs — H2D staging
        # placed with anything else would silently reshard per dispatch.
        self._batch_spec = self.programs._batch_spec

        if self.mesh is not None:
            t_mesh = self.trainer.mesh
            if (t_mesh is not None
                    and {d.id for d in self.mesh.devices.flat}
                    == {d.id for d in t_mesh.devices.flat}):
                # Same device SET (e.g. the inner mesh already spans all
                # devices, so the fold axis degraded to 1): replicated
                # placement is device-set-invariant, so the trainer's
                # resident panel serves the fold mesh as-is — no second
                # full-panel H2D, no duplicate HBM copy for the sweep.
                self.dev = self.trainer.dev
            else:
                gather_impl = (self.trainer.inner._gather_impl
                               if self.ensemble
                               else self.trainer._gather_impl)
                self.dev = cached_device_panel(
                    panel, self.mesh,
                    compute_dtype=(jnp.bfloat16 if cfg.model.bf16
                                   else None),
                    raw=False, lane_pad=gather_impl == "pallas")
        else:
            self.dev = self.trainer.dev  # same placement — zero extra H2D

        self._vargs = tuple(
            self._put(np.stack([getattr(b, f) for b in vbs]), P(FOLD_AXIS))
            for f in ("firm_idx", "time_idx", "weight"))

    # ---- placement ---------------------------------------------------

    def _put(self, a, spec):
        if self.mesh is None:
            return jnp.asarray(a)
        return jax.device_put(a, NamedSharding(self.mesh, spec))

    def init_carry(self):
        """Fresh stacked carry: per-fold independent init draws (exact
        sequential parity — see ``init_stacked_states``), best-params
        copies, and the all-live control state — committed to the fold
        mesh."""
        state = self.trainer.init_stacked_states(
            [fc.seed for fc in self.fold_cfgs])
        best_params = jax.tree.map(jnp.copy, state.params)
        F = self.fold_count
        ctrl = FoldCtrl(
            live=jnp.ones((F,), bool),
            best_ic=jnp.full((F,), -jnp.inf, jnp.float32),
            best_epoch=jnp.full((F,), -1, jnp.int32),
            bad_epochs=jnp.zeros((F,), jnp.int32),
        )
        carry = (state, best_params, ctrl)
        if self.mesh is None:
            return carry
        state_spec = getattr(self.programs, "_state_spec", P(FOLD_AXIS))

        def shard_of(spec):
            return lambda x: NamedSharding(
                self.mesh,
                spec if getattr(x, "ndim", 0) >= len(spec) else P(FOLD_AXIS))

        shardings = (jax.tree.map(shard_of(state_spec), state),
                     jax.tree.map(shard_of(state_spec), best_params),
                     jax.tree.map(shard_of(P(FOLD_AXIS)), ctrl))
        return jax.device_put(carry, shardings)

    # ---- epoch callbacks (pipeline.run_fit_epochs contract) ----------

    def build_epoch(self, epoch: int):
        """Host sampling + H2D staging for one stacked epoch — runs on
        the prefetch thread under ``LFM_ASYNC`` (pure deterministic reads
        per (seed, epoch), the same thread-safety contract as the
        sequential build)."""
        with telemetry.span("sample", epoch=epoch, folds=self.fold_count):
            if self.ensemble:
                stacks = []
                for per_fold in self.fold_samplers:
                    per_seed = [s.stacked_epoch(epoch) for s in per_fold]
                    # Same loud contract as stack_fold_epochs: the
                    # truncate-to-min-K the sequential ensemble applies
                    # is only legal down to the init-time steps count —
                    # a shorter member epoch would silently train this
                    # fold on a partial epoch.
                    if min(b.firm_idx.shape[0] for b in per_seed) \
                            < self.steps:
                        raise ValueError(
                            "fold-stacked ensemble epoch shorter than "
                            f"the {self.steps}-step schedule — member "
                            "samplers drifted out of shape")
                    stacks.append(tuple(
                        np.stack([getattr(b, f)[:self.steps]
                                  for b in per_seed], axis=1)
                        for f in ("firm_idx", "time_idx", "weight")))
                fi, ti, w = (np.stack([s[i] for s in stacks])
                             for i in range(3))
            else:
                b = stack_fold_epochs(self.fold_samplers, epoch)
                fi, ti, w = b.firm_idx, b.time_idx, b.weight
            fm = float(w.sum()) * self.window
        with telemetry.span("h2d", epoch=epoch):
            spec = self._batch_spec
            args = tuple(self._put(a, spec) for a in (fi, ti, w))
        return args + (jnp.asarray(epoch, jnp.int32),), fm

    def dispatch_epoch(self, carry, args):
        """Queue one stacked epoch (train + eval + control in ONE jitted
        dispatch). The fetched scalars are COPIES: the next epoch's
        dispatch donates the carry, and a fetched value must never alias
        a donated buffer (same rule as the sequential pipeline)."""
        fi, ti, w, epoch = args
        carry, vals = self.programs._jit_epoch(
            carry, self.dev, fi, ti, w, *self._vargs, epoch)
        state, _, ctrl = carry
        vals = dict(vals, step=jnp.copy(state.step),
                    live=jnp.copy(ctrl.live))
        return carry, vals

    # ---- the full sweep ---------------------------------------------

    def run(self) -> Tuple[List[Dict[str, Any]], List[Tuple],
                           Dict[str, Any]]:
        """Train the stack, unstack per-fold results. Returns
        ``(fold_summaries, fold_predictions, stack_summary)`` — the
        walk-forward driver stitches the predictions and composes the
        final per-fold records."""
        from lfm_quant_tpu.train import pipeline
        from lfm_quant_tpu.train.checkpoint import (CheckpointManager,
                                                    fold_slice)

        F = self.fold_count
        snap_stack = REUSE_COUNTERS.snapshot()
        histories: List[List[Dict[str, Any]]] = [[] for _ in range(F)]
        loggers = [MetricsLogger(rd) for rd in self.run_dirs]
        live_mask = np.ones(F, bool)
        harness = _StackHarness(self.cfg.optim.epochs)
        timer = StepTimer()

        def finish(epoch, host, fm):
            nonlocal live_mask
            live_in = live_mask
            ic = np.asarray(host["ic"])
            live_ics = []
            for f in range(F):
                if not live_in[f]:
                    continue
                if self.ensemble:
                    per_seed = ((ic[f] * self.counts[f]).sum(axis=1)
                                / self.counts[f].sum())
                    val_ic = float(per_seed.mean())
                    rec = loggers[f].log(
                        int(np.asarray(host["step"][f]).reshape(-1)[0]),
                        epoch=epoch,
                        train_loss=float(host["loss"][f]),
                        val_ic=val_ic,
                        val_ic_std=float(per_seed.std()),
                        firm_months_per_sec=timer.throughput(),
                    )
                else:
                    # f64 np.average — the exact aggregation finish()
                    # applies on the sequential path, over the same
                    # per-month ICs, so recorded histories match.
                    val_ic = float(np.average(ic[f],
                                              weights=self.counts[f]))
                    rec = loggers[f].log(
                        int(host["step"][f]),
                        epoch=epoch,
                        train_loss=float(host["loss"][f]),
                        grad_norm=float(host["grad_norm"][f]),
                        val_ic=val_ic,
                        val_mse=float(host["mse"][f]),
                        firm_months_per_sec=timer.throughput(),
                    )
                histories[f].append(rec)
                live_ics.append(val_ic)
            new_live = np.asarray(host["live"])
            for f in range(F):
                if live_in[f] and not new_live[f]:
                    telemetry.instant("fold_stopped", fold=f, epoch=epoch)
            live_mask = new_live
            harness.all_dead = not bool(new_live.any())
            step = int(np.max(np.asarray(host["step"])))
            return step, (float(np.mean(live_ics)) if live_ics else 0.0)

        with telemetry.span("foldstack_fit", cat="fit",
                            fold_count=F,
                            fold_mesh=(list(self.mesh.shape.items())
                                       if self.mesh is not None
                                       else None)) as sp:
            carry, overrun = pipeline.run_fit_epochs(
                harness, self.init_carry(), build=self.build_epoch,
                dispatch=self.dispatch_epoch, finish=finish, timer=timer,
                checkpointing=False)
            state, best_params, ctrl = carry
            host_ctrl = jax.device_get(ctrl)
            sp.set(epochs_run=[len(h) for h in histories],
                   best_epochs=[int(e) for e in host_ctrl.best_epoch],
                   overrun=overrun is not None)
        for lg in loggers:
            lg.close()

        host_best = host_aux = None
        if self.out_dir:
            host_best = jax.device_get(best_params)
            host_aux = jax.device_get({"opt_state": state.opt_state,
                                       "step": state.step,
                                       "rng": state.rng})
        stack_reuse = {
            k: (round(v, 4) if isinstance(v, float) else v)
            for k, v in REUSE_COUNTERS.delta(snap_stack).items()}

        fold_summaries: List[Dict[str, Any]] = []
        fold_preds: List[Tuple] = []
        for f in range(F):
            snap_fold = REUSE_COUNTERS.snapshot()
            best_epoch = int(host_ctrl.best_epoch[f])
            best_val_ic = (histories[f][best_epoch]["val_ic"]
                           if 0 <= best_epoch < len(histories[f])
                           else float(host_ctrl.best_ic[f]))
            best_step = (best_epoch + 1) * self.steps
            if self.out_dir:
                # Unstack this fold's ckpt/best line so the run dir is
                # loadable exactly like a sequential fold's. The params
                # are the device-tracked best; the aux leaves come from
                # the final state (predict/backtest only consume
                # params). The step leaf keeps the FINAL state's SHAPE
                # — scalar for a Trainer, [S] for the ensemble's
                # vmapped init — with the best step's value, or Orbax
                # restore would reject the ensemble's abstract tree.
                step_leaf = np.full_like(
                    np.asarray(fold_slice(host_aux["step"], f)), best_step)
                mgr = CheckpointManager(
                    os.path.join(self.run_dirs[f], "ckpt", "best"),
                    max_to_keep=1)
                mgr.save(best_step, {
                    "params": fold_slice(host_best, f),
                    "opt_state": fold_slice(host_aux["opt_state"], f),
                    "step": step_leaf,
                    "rng": host_aux["rng"][f],
                }, wait=True)
                mgr.close()
            # Prediction-state parity with the sequential path: a fold
            # WITH a run dir predicts from its restored ckpt/best (the
            # device-tracked best params here); without one, sequential
            # `fit` has no best line to restore and ends on the last
            # RECORDED epoch's state — mirror that, or LFM_FOLDSTACK
            # would silently flip forecasts for out_dir=None callers.
            src = best_params if self.out_dir else state.params
            fold_state = TrainState(
                params=jax.tree.map(lambda x: x[f], src),
                opt_state=jax.tree.map(lambda x: x[f], state.opt_state),
                step=state.step[f],
                rng=state.rng[f],
            )
            self.trainer.state = self.trainer._commit_state(fold_state)
            pred_range = self.folds[f][2]
            with telemetry.span("predict", cat="predict", fold=f):
                if self.het:
                    pred = self.trainer.predict(date_range=pred_range,
                                                return_variance=True)
                else:
                    pred = self.trainer.predict(date_range=pred_range)
            fold_preds.append(pred)
            fold_summaries.append({
                "best_val_ic": best_val_ic,
                "best_epoch": best_epoch,
                "epochs_run": len(histories[f]),
                "history": histories[f],
                "reuse": {k: (round(v, 4) if isinstance(v, float) else v)
                          for k, v in
                          REUSE_COUNTERS.delta(snap_fold).items()},
            })

        stack_summary = {
            "enabled": True,
            "fold_count": F,
            "fold_mesh": (list(self.mesh.shape.items())
                          if self.mesh is not None else None),
            "steps_per_epoch": self.steps,
            "lookahead_overrun": overrun is not None,
            "reuse": stack_reuse,
        }
        return fold_summaries, fold_preds, stack_summary


def run_stacked_walkforward(cfg: RunConfig, panel: Panel, folds, *,
                            train_months: Optional[int],
                            out_dir: Optional[str] = None,
                            echo: bool = False):
    """Fold-stacked sweep entry point for ``run_walkforward``: returns
    ``(fold_summaries, fold_predictions, stack_summary)``, or ``None``
    after a warning when a stacking precondition is data-dependently
    unmet (the caller then runs the sequential path — degrade, don't
    kill a sweep the sequential mode handles)."""
    try:
        sw = StackedWalkforward(cfg, panel, folds,
                                train_months=train_months,
                                out_dir=out_dir, echo=echo)
    except FoldstackUnavailable as e:
        warnings.warn(f"fold-stacking unavailable ({e}); running the "
                      "sequential walk-forward", stacklevel=3)
        return None
    return sw.run()
