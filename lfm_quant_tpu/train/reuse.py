"""Cross-fold reuse layer: compile once, transfer once, retrain many.

The walk-forward protocol (train/walkforward.py) used to construct a
fresh ``Trainer``/``EnsembleTrainer`` per fold, and every construction
re-built the jitted step/multi-step/forward wrappers and re-transferred
the HBM-resident panel. For a ~15-fold sweep over the 1970–2024 panel
that is ~15× XLA compilation and ~15× panel H2D for byte-identical
same-shape programs — pure fixed cost, the amortization argument of
PAPERS.md's "Large-Batch Training for LSTM and Beyond" applied to a
retraining campaign instead of a single run.

Three layers of reuse, outermost first:

1. **Compiled-program cache** (this module): ``TrainerPrograms`` /
   ``EnsemblePrograms`` (train/loop.py, train/ensemble.py) bundle every
   trace-relevant object — models, optimizer, jitted wrappers — and are
   cached here under a key covering everything that can change the
   traced program OR its numerics: mesh fingerprint, resolved model
   kwargs (scan impl, bf16, heteroscedastic, dropout), optimizer/
   schedule constants (including ``steps_per_epoch`` — the LR schedule
   bakes ``total_steps`` in as a constant), loss, resolved gather impls,
   packed panel width, window geometry, and backend. Fold k+1 with an
   equal key binds fold k's jit wrappers, so same-shape dispatches hit
   jit's executable cache: zero re-tracing, zero XLA recompilation.
   A key MISMATCH (changed model config, n_seeds, fold-varying
   steps_per_epoch, …) builds fresh programs — there is no partial or
   stale reuse by construction.
2. **Device-panel residency** (data/windows.py ``cached_device_panel``):
   one H2D transfer per (panel, mesh, dtype, padding) per process, with
   explicit invalidation.
3. **JAX persistent compilation cache** (:func:`enable_persistent_cache`):
   even a cold process skips XLA re-optimization for programs any prior
   process compiled, keyed by JAX on the serialized HLO. Config knob
   ``RunConfig.compilation_cache_dir`` with the ``LFM_COMPILATION_CACHE``
   env fallback.

Everything is measured, not asserted: cache hits/misses, jit traces and
panel transfers all bump ``utils/profiling.py`` ``REUSE_COUNTERS``,
which walk-forward surfaces per fold and ``bench.py walkforward_reuse``
turns into a ledger metric.

Known limit (documented, not hidden): an expanding-window sweep whose
eligible-date count grows enough to change ``steps_per_epoch`` changes
the LR-schedule constants, so those folds correctly miss the cache (the
alternative — reusing fold 1's schedule — would silently change
numerics). Same-shape folds, the common toy/bench case and any rolling-
window protocol, reuse fully.

``LFM_PROGRAM_REUSE=0`` disables the program cache (every trainer builds
fresh wrappers) — the A/B switch the numerical-identity tests use.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

from lfm_quant_tpu.utils import telemetry
from lfm_quant_tpu.utils.profiling import REUSE_COUNTERS

_PROGRAM_CACHE: Dict[Tuple, Any] = {}
# The serving process fetches programs from several threads (the
# micro-batcher warming a bucket, a refresh thread rebuilding a
# trainer); the lock makes hit/miss/evict atomic, and the per-key
# in-progress map below makes a racing cold key build exactly once
# WITHOUT serializing unrelated keys behind a multi-second build —
# cache hits on the serving hot path must never wait out a refresh's
# trainer construction.
_PROGRAM_LOCK = threading.RLock()
_PROGRAM_BUILDING: Dict[Tuple, threading.Event] = {}

# LRU bound on cached program bundles. A walk-forward sweep needs 1–2
# live keys (trainer + ensemble); the cap covers a handful of coexisting
# geometries (e.g. an expanding-window sweep drifting across
# dates_per_batch boundaries, an A/B of model configs, or a serving
# model zoo's per-bucket scoring programs — the reason the default grew
# 8 → 32 with the scoring service: U universes × B buckets of serve
# keys must not evict the trainer bundles a monthly refresh rebinds)
# while keeping the cache from pinning every bundle a long-lived
# process ever built — each entry holds models, optax chains and jit
# wrappers whose executable caches hold compiled programs. Evicted
# bundles keep working for trainers already bound to them (they hold
# their own references — the model zoo additionally memoizes its
# bucket programs per entry for exactly this reason); only the NEXT
# construction with that key rebuilds.
_PROGRAM_CACHE_SIZE = max(1, int(os.environ.get("LFM_PROGRAM_CACHE_SIZE",
                                                "32")))


def reuse_enabled() -> bool:
    """Program-cache kill switch: ``LFM_PROGRAM_REUSE=0`` forces every
    trainer to build fresh jit wrappers (the pre-reuse serial path)."""
    return os.environ.get("LFM_PROGRAM_REUSE", "1") != "0"


def donation_enabled() -> bool:
    """Buffer-donation kill switch: ``LFM_DONATE=0`` turns off
    ``donate_argnums`` on the multi-step wrappers (the pre-donation
    double-buffered path — the A/B switch for the donation regression
    test and an escape hatch for platforms where XLA cannot alias)."""
    return os.environ.get("LFM_DONATE", "1") != "0"


def async_enabled() -> bool:
    """Epoch-pipeline kill switch: ``LFM_ASYNC=0`` forces the lock-step
    training loop (build → dispatch → sync → checkpoint serially per
    epoch) — the parity reference for the one-epoch-lookahead pipeline
    (train/pipeline.py). Default ON, mirroring the ``LFM_JAX_BACKTEST``
    / ``LFM_DONATE`` convention: the fast path is the default and the
    knob is the escape hatch / A/B switch. Pipelining changes dispatch
    ORDER only, never a traced program or its numerics, so it is
    deliberately NOT part of the program cache key."""
    return os.environ.get("LFM_ASYNC", "1") != "0"


def async_ckpt_enabled() -> bool:
    """Async-checkpoint kill switch: ``LFM_ASYNC_CKPT=0`` makes
    ``FitHarness.end_epoch`` flush both checkpoint lines before
    returning (the two saves still overlap each other — one barrier per
    line at the end). With it ON (default), Orbax saves run entirely in
    the background from a host-fetched copy of the state and the loop
    only waits at ``finalize``/resume boundaries. Durability contract:
    Orbax commits are atomic (tmp-dir rename), so a crash mid-save can
    lose AT MOST the in-flight epoch's checkpoint — ``FitHarness.resume``
    reconciles a progress sidecar that ran ahead of the last committed
    step. Orthogonal to ``LFM_ASYNC`` (all four combinations are legal
    and parity-tested)."""
    return os.environ.get("LFM_ASYNC_CKPT", "1") != "0"


def foldstack_enabled() -> bool:
    """Fold-stacked walk-forward mode switch: ``LFM_FOLDSTACK=1`` makes
    ``run_walkforward`` train all same-shape folds as ONE stacked,
    fold-sharded program (train/foldstack.py) instead of F sequential
    fits. Default OFF — unlike the other fast-path knobs — because the
    mode trades per-epoch crash-resume durability for throughput (fold
    checkpoints are unstacked at finalize, not written per epoch) and
    requires the rolling ``train_months`` schedule; the ``--wf-foldstack``
    CLI flag and the ``foldstack=`` argument opt in explicitly."""
    return os.environ.get("LFM_FOLDSTACK", "0") not in ("0", "")


def foldstack_shards() -> Optional[int]:
    """``LFM_FOLDSTACK_SHARDS``: cap on the fold mesh axis. Unset/"auto"
    = largest divisor of the fold count that fits the devices left by
    the trainer's own seed/data axes; ``0`` pins the fold axis to 1
    (pure-vmap stacking — the sharding A/B switch); ``N`` caps it."""
    v = os.environ.get("LFM_FOLDSTACK_SHARDS")
    if v in (None, "", "auto"):
        return None
    return max(0, int(v))


def stack_shards() -> Optional[int]:
    """``LFM_STACK_SHARDS``: cap on the generic stacked-run mesh axis
    (train/stacked.py config sweeps — the fold adapter keeps its own
    ``LFM_FOLDSTACK_SHARDS``). Unset/"auto" = largest divisor of the run
    count that fits the devices left by the trainer's own seed/data
    axes; ``0`` pins the stack axis to 1 (pure-vmap stacking — the
    sharding A/B switch the bit-identity tests use); ``N`` caps it."""
    v = os.environ.get("LFM_STACK_SHARDS")
    if v in (None, "", "auto"):
        return None
    return max(0, int(v))


def stack_block() -> int:
    """``LFM_STACK_BLOCK``: microbatch size for the stacked run axis —
    the run-axis generalization of ``RunConfig.seed_block``. ``B > 0``
    steps an R-run stack in blocks of B runs via ``lax.scan`` inside the
    stacked epoch program, bounding peak activation memory to B × per-run
    instead of all local runs at once (params/opt state stay resident
    either way) — the same HBM-fit lever that lets a 64-seed ensemble
    train on one chip (``seed_block=16`` is the flagship's pre-registered
    plan). 0/unset = all local runs in one vmapped step. Runs are
    independent, so blocking is numerically a pure re-batching; a block
    that does not divide the per-shard run count degrades to unblocked
    with a warning (train/stacked.py). Part of the stacked program keys —
    a changed block is a different traced program, never stale reuse."""
    v = os.environ.get("LFM_STACK_BLOCK")
    if v in (None, ""):
        return 0
    return max(0, int(v))


def foldstack_program_key(inner_key: Tuple, mesh, fold_count: int,
                          patience: int, block: int = 0) -> Tuple:
    """Cache key for the fold-stacked epoch program: the inner trainer/
    ensemble bundle's key (already backend/mesh/donation-qualified) plus
    the fold-stack geometry — fold count and fold-mesh placement change
    the traced program's shapes/collectives, the early-stop ``patience``
    is baked into the device-side control update as a constant (the
    sequential path keeps it host-side, so only this key needs it), and
    the run-axis microbatch ``block`` (``LFM_STACK_BLOCK``) changes the
    traced vmap-vs-scan structure."""
    from lfm_quant_tpu.parallel.mesh import mesh_fingerprint

    return ("foldstack", inner_key, mesh_fingerprint(mesh), fold_count,
            patience, block)


def stacked_program_key(inner_key: Tuple, mesh, run_count: int,
                        patience: int, kind: str,
                        hyper_keys: Tuple[str, ...],
                        block: int = 0) -> Tuple:
    """Cache key for a generic stacked-run epoch program
    (train/stacked.py ``StackedRuns``): the inner trainer bundle's key
    plus the stack geometry. Every field is a TAGGED tuple component —
    same construction as :func:`serve_program_key` — so keys from the
    three stacked families ("foldstack", "stacked", "serve") cannot
    collide by construction, whatever their inner components. ``kind``
    labels the run axis ("config", "seed", ...); ``hyper_keys`` names
    the per-run hyperparameters threaded as vmapped OPERANDS into the
    epoch program — their VALUES are deliberately absent (they arrive as
    [R]-shaped arguments, which is exactly what makes a 200-config grid
    one compiled program), only the set of operand names shapes the
    trace. ``block`` is the ``LFM_STACK_BLOCK`` run-axis microbatch."""
    from lfm_quant_tpu.parallel.mesh import mesh_fingerprint

    return ("stacked", inner_key, mesh_fingerprint(mesh), int(run_count),
            int(patience), ("kind", str(kind)),
            ("hyper", tuple(hyper_keys)), ("block", int(block)))


def train_bucket_program_key(inner_key: Tuple,
                             bucket: Tuple[int, int]) -> Tuple:
    """Cache key for a TRAINING geometry-bucket program family
    (``LFM_BUCKETS``, DESIGN.md §16): the inner trainer/ensemble
    bundle's key plus the ``(lookback_rows, cross_section_width)``
    bucket the batch supply quantized to (data/windows.py
    ``bucket_geometry``). Same tagged-tuple construction as
    :func:`serve_program_key` — the leading ``"trainbucket"`` tag and
    the tagged bucket component make keys collision-free against the
    trainer/ensemble/foldstack/stacked/serve families by construction
    (a serve bucket ``(rows, width)`` with the same numbers is a
    DIFFERENT key). Deliberately absent: the epoch, the per-bucket
    step count K_b and the batch contents — those arrive as jit
    ARGUMENTS, so each bucket compiles exactly once and warm epochs
    re-dispatch cached executables (the reuse-lane zero-trace
    contract, per bucket)."""
    lookback, width = bucket
    return ("trainbucket", inner_key,
            ("bucket", int(lookback), int(width)))


def serve_program_key(inner_key: Tuple, bucket: Tuple[int, int]) -> Tuple:
    """Cache key for a serving (bucketed scoring) program: the inner
    trainer bundle's key (already backend/mesh/gather/window-qualified —
    the LOOKBACK bucket rides in there as ``cfg.data.window``) plus the
    padded request-shape bucket ``(rows, cross_section)``. Every field
    is a TAGGED tuple component, so keys for distinct (inner program,
    bucket) pairs — and therefore for distinct (universe geometry,
    bucket, model generation) serving triples — cannot collide by
    construction: there is no string concatenation or positional
    ambiguity for adversarial names to exploit, and model GENERATIONS
    are deliberately ABSENT (generations of one universe share the same
    compiled programs — that absence is what makes a monthly refresh
    recompile-free, exactly like the per-fold knobs absent from
    ``trainer_program_key``)."""
    rows, width = bucket
    return ("serve", inner_key, ("bucket", int(rows), int(width)))


def multi_step_donate_argnums() -> Tuple[int, ...]:
    """``donate_argnums`` for the jitted MULTI-step wrappers: the
    TrainState argument (position 0) is donated so XLA aliases the
    input params/opt_state buffers into the outputs instead of double-
    buffering them in HBM for the whole epoch-long dispatch — at c5
    ensemble scale that is a full extra copy of 64 seeds × (params +
    two Adam moments). Donation is applied ONLY to the multi-step
    wrappers: ``fit`` consumes states linearly (the returned state
    replaces the input), while the SINGLE-step wrappers are the
    numerical-A/B surface (tests re-dispatch one state on purpose) and
    run one step per dispatch, where the transient double-buffer is
    bounded by one step's activations anyway.

    Guarded by the reuse zero-trace contract: donation changes the
    executable's aliasing metadata, not its trace — the ``reuse``-lane
    tests assert warm folds still pay zero traces, and the donation
    check asserts the input state is actually consumed."""
    return (0,) if donation_enabled() else ()


def freeze(obj):
    """Recursively convert ``obj`` into a hashable cache-key component
    (dicts → sorted item tuples, lists/tuples → tuples)."""
    if isinstance(obj, dict):
        return tuple(sorted((k, freeze(v)) for k, v in obj.items()))
    if isinstance(obj, (list, tuple)):
        return tuple(freeze(v) for v in obj)
    if isinstance(obj, set):
        return frozenset(freeze(v) for v in obj)
    hash(obj)  # fail loudly on an unhashable leaf, not deep in dict ops
    return obj


def trainer_program_key(cfg, mesh, n_seq: int, gather_impl: str,
                        eval_gather_impl: str, eval_gather_sharded: str,
                        fp: int, steps_per_epoch: int) -> Tuple:
    """Cache key for a single-seed trainer's compiled programs.

    Covers every input that reaches a traced program as a constant or
    changes which program gets built. Anything arriving as a jit
    ARGUMENT (panel arrays, index batches, TrainState) is deliberately
    absent — jit's own executable cache keys on those avals, so a shape
    change re-traces without any staleness risk here. Per-fold knobs
    that must NOT trigger recompilation (seed, run name/dir, split
    boundaries) are equally absent — that absence IS the reuse.
    """
    import jax

    from lfm_quant_tpu.config import resolve_precision
    from lfm_quant_tpu.parallel.mesh import mesh_fingerprint

    m, o, d = cfg.model, cfg.optim, cfg.data
    return (
        "trainer",
        jax.default_backend(),
        mesh_fingerprint(mesh),
        n_seq,
        # Model: build_model inputs (resolved via config.model_kwargs,
        # which is deterministic in these plus backend/n_seq).
        (m.kind, freeze(m.kwargs), m.bf16, m.scan_impl,
         cfg.is_heteroscedastic),
        # Optimizer/schedule: all constants baked into the traced update,
        # including the schedule horizon (steps_per_epoch × epochs).
        (o.lr, o.weight_decay, o.warmup_steps, o.grad_clip, o.epochs,
         o.loss, o.optimizer, steps_per_epoch),
        # Data geometry reaching traces as constants.
        (d.window, d.dates_per_batch),
        (gather_impl, eval_gather_impl, eval_gather_sharded, fp),
        # Donation changes the executables' aliasing metadata: a bundle
        # built with donation on must not be served to a trainer
        # constructed under LFM_DONATE=0 (and vice versa).
        donation_enabled(),
        # Compute-precision lane (LFM_PRECISION / RunConfig.precision,
        # DESIGN.md §17): bf16 compute + bf16 panel residency change the
        # traced programs AND their numerics, so the RESOLVED lane is a
        # tagged key member — and because every other program-key family
        # (ensemble/foldstack/stacked/serve/trainbucket) embeds this
        # inner key, the lane is a member of all of them by
        # construction. An env flip mid-process therefore builds fresh
        # programs, never reuses a stale-precision executable. Appended
        # LAST so the key's positional layout (tests and tooling index
        # the model/optim tuples) is unchanged.
        ("precision", resolve_precision(cfg)),
    )


def ensemble_program_key(inner_key: Tuple, mesh, n_seeds: int,
                         seed_block: int) -> Tuple:
    """Cache key for the seed-vmapped ensemble wrappers: the inner
    trainer's key (already mesh/backend-qualified) plus the seed-stack
    geometry. A changed ``n_seeds`` or ``seed_block`` is a different
    vmapped program — fresh compile, never stale reuse."""
    from lfm_quant_tpu.parallel.mesh import mesh_fingerprint

    return ("ensemble", inner_key, mesh_fingerprint(mesh), n_seeds,
            seed_block)


def get_programs(key: Tuple, builder: Callable[[], Any]) -> Any:
    """Fetch the compiled-program bundle for ``key``, building (and
    caching) on miss. With reuse disabled, always builds and never
    caches — the serial-path A/B baseline. Thread-safe with per-key
    build serialization: a cold key raced by two threads builds exactly
    once (the loser waits on the key's event, then hits), while hits
    and builds of OTHER keys proceed untouched — the builder runs
    outside the cache lock."""
    # Counter bumps go through the locked registry bump(): the property
    # view's `+=` is a two-step read-modify-write that loses increments
    # under exactly the cross-thread builds this path now allows.
    if not reuse_enabled():
        telemetry.COUNTERS.bump("program_cache_misses")
        return builder()
    while True:
        with _PROGRAM_LOCK:
            entry = _PROGRAM_CACHE.pop(key, None)
            if entry is not None:
                _PROGRAM_CACHE[key] = entry  # re-insert: LRU recency order
                telemetry.COUNTERS.bump("program_cache_hits")
                return entry
            evt = _PROGRAM_BUILDING.get(key)
            if evt is None:
                _PROGRAM_BUILDING[key] = threading.Event()
        if evt is not None:
            evt.wait()
            continue  # built (hit on re-read) or failed (we build next)
        telemetry.COUNTERS.bump("program_cache_misses")
        try:
            entry = builder()
        except BaseException:
            with _PROGRAM_LOCK:
                _PROGRAM_BUILDING.pop(key).set()  # waiters retry
            raise
        with _PROGRAM_LOCK:
            _PROGRAM_CACHE[key] = entry
            while len(_PROGRAM_CACHE) > _PROGRAM_CACHE_SIZE:
                _PROGRAM_CACHE.pop(next(iter(_PROGRAM_CACHE)))
            _PROGRAM_BUILDING.pop(key).set()
        return entry


def clear_program_cache() -> None:
    """Drop all cached program bundles (tests / explicit invalidation).
    Outstanding trainers keep working — they hold their own references —
    but the next construction rebuilds from scratch."""
    with _PROGRAM_LOCK:
        _PROGRAM_CACHE.clear()


def program_cache_size() -> int:
    return len(_PROGRAM_CACHE)


def program_cache_keys() -> Tuple[Tuple, ...]:
    """The cached keys in LRU order, oldest first (tests/introspection:
    the eviction-order and serve-key regression suites read this)."""
    with _PROGRAM_LOCK:
        return tuple(_PROGRAM_CACHE)


# ---- program ledger -----------------------------------------------------


class _LedgeredJit:
    """A jitted program wrapped for the telemetry program ledger.

    Warm calls pay one counter read + compare on top of the jit
    dispatch (nanoseconds against a multi-ms dispatch). A call that
    TRACED (detected via the :func:`count_traces` counter delta —
    Python trace == fresh XLA compile for these programs) records a
    ledger entry: compile wall seconds (trace start → call return:
    trace + lower + XLA compile; jit blocks on compilation before
    dispatching) and, when a telemetry run is active, the program's XLA
    ``cost_analysis`` FLOPs/bytes and ``memory_analysis`` HBM footprint
    via the AOT API on the post-call avals (donated buffers keep their
    shape/dtype/sharding, so this never touches data). The analysis
    re-lower runs under ``suspend_trace_counting`` — it is ledger
    bookkeeping, not a new program on the training path, and the reuse
    lane's zero-trace contract must not see it.

    Everything analysis-shaped is guarded for jax-0.4.x availability:
    any step that raises degrades to an entry without those fields.

    Stopwatch discipline: a WARM call reads the clock ZERO times — the
    compile wall time is measured from the trace-start stamp
    ``count_traces`` records (utils/profiling.py ``last_trace_t0``) to
    one post-call read, both of which only happen when the call
    actually traced. The pre-fix version read ``perf_counter`` once per
    warm dispatch, which broke the tick parity of frozen-clock test
    harnesses (an extra read landed a caller's ``t0``/``end`` pair on
    the same tick → dt == 0 → ZeroDivisionError in the caller's rate
    arithmetic; tests/test_train.py measure_eval had to pin
    LFM_TELEMETRY=0). A degenerate dt is additionally guarded to 0.0
    here rather than ever going negative."""

    __slots__ = ("name", "_jitted")

    def __init__(self, name: str, jitted: Any):
        self.name = name
        self._jitted = jitted

    def __call__(self, *args, **kwargs):
        if not telemetry.enabled():
            return self._jitted(*args, **kwargs)
        from lfm_quant_tpu.utils.profiling import (last_trace_t0,
                                                   thread_trace_count)

        # "This call traced" must be judged per THREAD: the global
        # jit_traces counter can move on another thread (a zoo
        # warmup/refresh compiling while a batcher thread dispatches
        # warm), which would ledger dt measured from this thread's
        # stale (or absent) stamp — unbounded wall-clock attributed to
        # a compile that happened elsewhere. The thread-local trace
        # count moves iff THIS thread traced (an integer, so it can't
        # false-negative the way a repeated clock VALUE can under a
        # monkeypatched test clock); reading it costs zero clock reads,
        # preserving the warm-path tick-parity contract.
        before = thread_trace_count()
        out = self._jitted(*args, **kwargs)
        traces = thread_trace_count() - before
        if traces:
            t0 = last_trace_t0()
            self._record(args, kwargs,
                         max(time.perf_counter() - t0, 0.0), traces)
        return out

    def lower(self, *args, **kwargs):
        """AOT passthrough (tests/tooling)."""
        return self._jitted.lower(*args, **kwargs)

    def _record(self, args, kwargs, compile_s: float, traces: int) -> None:
        entry: Dict[str, Any] = {"program": self.name,
                                 "compile_s": round(compile_s, 6),
                                 "traces": traces}
        try:
            import jax

            leaves = [x for x in jax.tree.leaves(args)
                      if hasattr(x, "shape") and hasattr(x, "dtype")]
            entry["arg_leaves"] = len(leaves)
            entry["arg_bytes"] = int(sum(
                x.size * x.dtype.itemsize for x in leaves))
        except Exception:
            pass
        if telemetry.analysis_active():
            entry.update(self._analyze(args, kwargs))
        telemetry.record_program_build(entry)

    def _analyze(self, args, kwargs) -> Dict[str, Any]:
        """XLA cost analysis of the just-compiled signature — a cheap
        re-lower (the jaxpr/lowering caches usually hit). The
        ``memory_analysis`` HBM footprint needs ``lowered.compile()``,
        a SECOND full XLA compile per program: with default-on
        telemetry every production run has an active telemetry run, so
        that cost would land synchronously on every cold start — it is
        therefore opt-in (``LFM_TELEMETRY_ANALYSIS=1``); the always-
        recorded ``arg_bytes`` serves as the resident-footprint proxy
        otherwise."""
        out: Dict[str, Any] = {}
        try:
            import jax

            from lfm_quant_tpu.utils.profiling import suspend_trace_counting

            # ONE aval rule (module-level _to_aval) shared with AOT
            # export — the two re-lower paths must never disagree on
            # what reaches lower() as an aval.
            avals = jax.tree.map(_to_aval, args)
            with suspend_trace_counting():
                lowered = self._jitted.lower(*avals, **kwargs)
                try:
                    cost = lowered.cost_analysis() or {}
                    if isinstance(cost, (list, tuple)):
                        cost = cost[0] if cost else {}
                    for src, dst in (("flops", "flops"),
                                     ("bytes accessed", "bytes_accessed"),
                                     ("transcendentals", "transcendentals")):
                        if src in cost:
                            out[dst] = float(cost[src])
                except Exception as e:  # noqa: BLE001 — availability guard
                    out["cost_analysis_error"] = type(e).__name__
                if not telemetry.deep_analysis_active():
                    return out
                try:
                    mem = lowered.compile().memory_analysis()
                    for attr in ("generated_code_size_in_bytes",
                                 "argument_size_in_bytes",
                                 "output_size_in_bytes",
                                 "temp_size_in_bytes",
                                 "alias_size_in_bytes"):
                        v = getattr(mem, attr, None)
                        if v is not None:
                            out[attr.replace("_size_in_bytes", "_bytes")] = \
                                int(v)
                    hbm = sum(out.get(k, 0) for k in
                              ("generated_code_bytes", "argument_bytes",
                               "output_bytes", "temp_bytes"))
                    hbm -= out.get("alias_bytes", 0)
                    out["hbm_bytes"] = max(0, int(hbm))
                except Exception as e:  # noqa: BLE001 — availability guard
                    out["memory_analysis_error"] = type(e).__name__
        except Exception as e:  # noqa: BLE001 — never kill a dispatch
            out["analysis_error"] = type(e).__name__
        return out


def ledger_jit(name: str, fn: Callable, **jit_kwargs) -> _LedgeredJit:
    """``jax.jit`` + :func:`count_traces` + program-ledger recording in
    one wrapper — the construction every reuse-layer program goes
    through, so the ledger covers exactly the programs the compiled-
    program cache manages (plus any other caller that opts in, e.g. the
    fused backtest core)."""
    import jax

    from lfm_quant_tpu.utils.profiling import count_traces

    return _LedgeredJit(name, jax.jit(count_traces(name, fn), **jit_kwargs))


# ---- serialized lowered executables (AOT export, DESIGN.md §20) ---------
# The cross-PROCESS twin of the executable caches above, one level below
# the persistent compilation cache: where the jax version supports it
# (jax.experimental.serialize_executable on this pin), a compiled
# program can be serialized at publish time and loaded by a cold process
# WITHOUT tracing or compiling anything — the durable serving store
# (serve/persist.py) ships these as deploy artifacts so a restore's
# warm ladder pays zero compiles. Every step degrades loudly-but-safely:
# unsupported jax / unserializable backend / topology mismatch returns
# None and the caller falls back to a counted recompile.


def aot_supported() -> bool:
    """Whether this jax build can serialize/deserialize compiled
    executables (the AOT export API + picklable pytree defs)."""
    try:
        from jax.experimental import serialize_executable  # noqa: F401

        return True
    except Exception:  # noqa: BLE001 — availability guard
        return False


def _to_aval(x):
    """Concrete array → ShapeDtypeStruct (NamedSharding kept, other
    shardings dropped) — the ledger's aval rule, shared by AOT export."""
    import jax

    if not (hasattr(x, "shape") and hasattr(x, "dtype")):
        return x
    sharding = getattr(x, "sharding", None)
    if not isinstance(sharding, jax.sharding.NamedSharding):
        sharding = None
    return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)


def aot_serialize(jitted: Any, args: Tuple) -> Optional[bytes]:
    """Serialize the executable ``jitted`` compiles for ``args``'
    avals: one self-contained blob (executable + arg/result pytrees) a
    cold process can :func:`aot_load` without tracing or compiling.
    ``jitted`` may be a raw ``jax.jit`` wrapper or a :class:`_LedgeredJit`
    (its ``lower`` passthrough). The lower runs under
    ``suspend_trace_counting`` — export is publish-time bookkeeping, not
    a program on the serving path, and the zero-trace contracts must not
    see it. With the persistent compilation cache enabled the embedded
    ``compile()`` is a disk hit for a program warmup already built.
    Returns None (never raises) when this jax/backend cannot export."""
    import pickle

    from lfm_quant_tpu.utils.profiling import suspend_trace_counting

    if not aot_supported():
        return None
    try:
        import jax
        from jax.experimental import serialize_executable as se

        avals = jax.tree.map(_to_aval, args)
        with suspend_trace_counting():
            compiled = jitted.lower(*avals).compile()
            blob, in_tree, out_tree = se.serialize(compiled)
        return pickle.dumps((blob, in_tree, out_tree))
    except Exception as e:  # noqa: BLE001 — export is optional, never fatal
        telemetry.COUNTERS.bump("aot_serialize_failures")
        import warnings

        warnings.warn(
            f"AOT executable export unavailable ({type(e).__name__}: "
            f"{e}) — restores will recompile this program",
            RuntimeWarning, stacklevel=2)
        return None


def aot_load(data: bytes) -> Optional[Any]:
    """Deserialize an :func:`aot_serialize` blob into a callable
    ``jax.stages.Compiled``. Returns None (never raises) on any
    deserialize/backend/topology mismatch — the caller counts the
    fallback and recompiles."""
    import pickle

    if not aot_supported():
        return None
    try:
        from jax.experimental import serialize_executable as se

        blob, in_tree, out_tree = pickle.loads(data)
        return se.deserialize_and_load(blob, in_tree, out_tree)
    except Exception:  # noqa: BLE001 — mismatch is the documented fallback
        return None


_PERSISTENT_CACHE_DIR: Optional[str] = None


def enable_persistent_cache(cache_dir: Optional[str] = None
                            ) -> Optional[str]:
    """Point JAX's persistent compilation cache at ``cache_dir`` (the
    ``RunConfig.compilation_cache_dir`` knob), falling back to the
    ``LFM_COMPILATION_CACHE`` env var; JAX's own
    ``JAX_COMPILATION_CACHE_DIR`` keeps working independently. Returns
    the directory in effect (None = feature off). Idempotent; the
    min-compile-time/entry-size floors are dropped to zero so even the
    toy walk-forward programs persist (the default 1 s floor would skip
    exactly the many-small-programs workload this repo runs). Unknown
    options on older jax degrade silently — the cache is an
    optimization, never a requirement.

    Ordering constraint (measured on jax 0.4.37): the cache must be
    configured before the process's FIRST XLA compile — once anything
    jits without a cache dir, later ``config.update`` calls never attach
    the cache in-process. Trainer construction calls this before its
    first dispatch, so a cold ``train.py``/walk-forward process is in
    time; a REPL that already ran jitted code is not (entries silently
    stop being written — same degrade-don't-fail contract as above)."""
    global _PERSISTENT_CACHE_DIR
    cache_dir = cache_dir or os.environ.get("LFM_COMPILATION_CACHE")
    if not cache_dir or _PERSISTENT_CACHE_DIR == cache_dir:
        return _PERSISTENT_CACHE_DIR
    import jax

    os.makedirs(cache_dir, exist_ok=True)
    for opt, val in (("jax_compilation_cache_dir", cache_dir),
                     ("jax_persistent_cache_min_compile_time_secs", 0.0),
                     ("jax_persistent_cache_min_entry_size_bytes", 0)):
        try:
            jax.config.update(opt, val)
        except AttributeError:
            if opt == "jax_compilation_cache_dir":
                return None  # cache unsupported on this jax — feature off
    _PERSISTENT_CACHE_DIR = cache_dir
    return cache_dir
