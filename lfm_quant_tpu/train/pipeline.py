"""Async epoch pipeline: overlap host work and I/O with device compute.

PR 1 made compilation and panel transfer one-time costs and PR 2 fused
the scoring path, but the training loop stayed lock-step: between two
epoch-long dispatches the device idled while the host sampled the next
epoch's indices, synced metrics, and blocked on two serial Orbax saves.
This module hides those per-epoch fixed costs behind device compute
(PAPERS.md: "Large-Batch Training for LSTM and Beyond"; "Accelerating
recurrent neural network training using sequence bucketing and
multi-GPU data parallelization"):

* **Fused train+eval epoch** — the validation sweep is chained onto the
  same dispatch stream as the multi-step train program, and ALL of an
  epoch's scalars (loss, grad-norm, per-month val IC, mse, step) come
  back in ONE ``jax.device_get`` instead of a scatter of ``float()`` /
  ``np.asarray`` syncs.
* **One-epoch lookahead** (``LFM_ASYNC``, default on) — epoch e+1's
  stacked index batches are built and H2D-staged on a background thread
  while epoch e computes, and epoch e+1 is DISPATCHED before epoch e's
  metrics are synced. The early-stopping decision therefore runs one
  epoch behind: when it fires, the already-dispatched epoch is
  discarded (never recorded, never checkpointed) — at most one wasted
  epoch of compute, and the device never idles between epochs.
* **Async checkpointing** (``LFM_ASYNC_CKPT``, default on) — both
  checkpoint lines are saved in the background from a HOST-FETCHED copy
  of the state; the loop waits only at ``finalize``/resume boundaries.

Donation safety: the multi-step wrappers donate their input TrainState
(train/reuse.py), so once epoch e+1 is dispatched, epoch e's output
buffers are gone. The pipeline therefore queues a device-side copy of
the state BEFORE the donating dispatch (a data dependency XLA orders
correctly); the copy is what checkpointing reads. With donation off
(``LFM_DONATE=0``) the copy is skipped — the buffers stay alive.

Numerics: pipelining reorders host/dispatch work only. Every traced
program, every input, and every recorded metric is identical to the
lock-step loop — ``LFM_ASYNC=0/1`` produce the same epoch history, best
epoch, early-stop epoch and restored best params (tests/test_pipeline.py
pins this), which is why the knobs are not program-cache keys.

Precision lane (``LFM_PRECISION=bf16``, DESIGN.md §17): nothing here
changes — the driver's early-stop comparisons consume the f32 scalars
the dispatch returns (f32 head boundary + ≥f32 reduction accumulators
upstream), so the lookahead/lock-step decision parity above holds
identically under mixed precision; the lane reaches this module only
through the already-compiled programs it dispatches.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from lfm_quant_tpu.train import preempt, reuse
from lfm_quant_tpu.utils import telemetry
from lfm_quant_tpu.utils.profiling import REUSE_COUNTERS, timed_device_get


class EpochPrefetcher:
    """One-epoch-lookahead batch builder: runs ``build(epoch)`` — host
    sampling (native or python engine) PLUS the ``jnp.asarray`` /
    ``shard_batch`` H2D staging — on a daemon thread so it overlaps the
    in-flight epoch's device compute. One outstanding epoch at a time
    (serializing the staging keeps H2D bandwidth off the critical path);
    ``get`` for a different epoch than the one staged falls back to an
    inline build, so resumes and non-contiguous schedules stay correct.
    Safe because ``DateBatchSampler`` calls with an EXPLICIT epoch are
    pure reads (deterministic in (seed, epoch), no shared counters)."""

    def __init__(self, build: Callable[[int], Any]):
        self._build = build
        self._epoch: Optional[int] = None
        self._thread: Optional[threading.Thread] = None
        self._out: Optional[Dict[str, Any]] = None

    def start(self, epoch: int) -> None:
        if self._thread is not None and self._epoch == epoch:
            return
        self.cancel()
        out: Dict[str, Any] = {}

        def run():
            try:
                # The build callback's own sample/h2d spans emit on this
                # thread; the wrapper span shows the prefetch window
                # itself overlapping the in-flight epoch in the trace.
                with telemetry.span("prefetch", cat="sample", epoch=epoch):
                    out["result"] = self._build(epoch)
            except BaseException as e:  # noqa: BLE001 — re-raised in get()
                out["error"] = e

        self._epoch, self._out = epoch, out
        self._thread = threading.Thread(
            target=run, name=f"lfm-epoch-prefetch-{epoch}", daemon=True)
        self._thread.start()

    def get(self, epoch: int) -> Any:
        """The staged batches for ``epoch`` (joins the builder thread),
        or an inline build on a miss."""
        if self._thread is None or self._epoch != epoch:
            self.cancel()
            return self._build(epoch)
        self._thread.join()
        out = self._out
        self._thread, self._epoch, self._out = None, None, None
        if "error" in out:
            raise out["error"]
        return out["result"]

    def cancel(self) -> None:
        """Join-and-discard any staged build. A build is not
        interruptible, but it is bounded by one epoch of host sampling —
        joining here keeps the builder from racing a ``rebind()`` that
        mutates the sampler/panel bindings after ``fit`` returns."""
        if self._thread is not None:
            self._thread.join()
        self._thread, self._epoch, self._out = None, None, None


class _InFlight(NamedTuple):
    """A dispatched-but-unsynced epoch: the device scalars to fetch, the
    state snapshot checkpointing will read, the host-known firm-month
    count for throughput accounting, and the epoch's telemetry span
    (begun at dispatch; closed when the epoch settles)."""

    epoch: int
    vals: Dict[str, Any]
    snap: Any
    fm: float
    span: Any


def _snapshot(state, checkpointing: bool, async_mode: bool):
    """The state object ``end_epoch`` may checkpoint for this epoch —
    and, in async mode, the ROLLBACK target when early stopping strands
    a speculative epoch (the driver returns the last RECORDED epoch's
    state, keeping the final state pipeline-invariant even without a
    best checkpoint to restore).

    Lookahead + donation is the hazardous combination: the NEXT dispatch
    consumes the state's buffers, so a device-side copy is queued first
    (ordered before the donating dispatch by data dependency). Without
    donation the live state reference suffices. Async mode snapshots
    even when the run doesn't checkpoint — the rollback needs it; the
    copy overlaps device compute and at most one extra state copy is
    live at a time. Lock-step mode has no speculative epochs, so ``None``
    when not checkpointing — zero overhead."""
    if not async_mode:
        return state if checkpointing else None
    if reuse.donation_enabled():
        return jax.tree.map(jnp.copy, state)
    return state


def _all_ready(vals: Dict[str, Any]) -> bool:
    """Non-blocking completion probe: True when every device value of an
    epoch's fetch set has materialized (the eval outputs are queued
    LAST, so all-ready ⇒ the epoch's dispatch chain has drained).
    Conservatively False on runtimes without ``Array.is_ready``."""
    try:
        return all(leaf.is_ready() for leaf in jax.tree.leaves(vals))
    except AttributeError:
        return False


def run_fit_epochs(harness, state, *, build, dispatch, finish, timer,
                   checkpointing: bool) -> Tuple[Any, Optional[int]]:
    """Drive a fit's epoch loop — lock-step or pipelined (``LFM_ASYNC``).

    ``harness`` is duck-typed: the driver consumes only ``epochs``,
    ``next_epoch()`` and ``end_epoch(epoch, step, state_dict, val_ic) ->
    stop`` — ``FitHarness`` for the sequential trainers, the stacked-run
    engine's thin shell (train/stacked.py ``_StackHarness``) when
    early stopping lives device-side and the stop flag is derived by
    ``finish`` from the fetched per-run live mask. ``state`` is equally
    opaque: any pytree consumed linearly by ``dispatch`` works (the
    stacked path threads a (TrainState, best_params, ctrl) carry);
    async-mode snapshots/rollbacks ``jax.tree.map`` over it wholesale.

    Callback contract (shared by Trainer, EnsembleTrainer and the
    stacked-run engine):

    * ``build(epoch) -> (batches, firm_months)`` — host sampling + H2D
      staging; MUST be thread-safe for explicit epochs (runs on the
      prefetch thread in async mode).
    * ``dispatch(state, batches) -> (state, vals)`` — queue the
      multi-step train program AND the chained validation sweep; ``vals``
      is a dict of DEVICE arrays (must include ``"step"``) that one
      ``jax.device_get`` fetches per epoch. Must not sync.
    * ``finish(epoch, host_vals, firm_months) -> (step, val_ic)`` —
      host-side: log the epoch record, append history, return the int
      step and scalar val IC for the harness.

    Returns ``(final_state, overrun_epoch)`` — ``overrun_epoch`` is the
    epoch that was speculatively dispatched when early stopping fired
    (its results were discarded; None when the stop was clean). The
    harness's counters (``last_epoch``, ``bad_epochs``) always reflect
    RECORDED epochs only, so ``epochs_run`` is pipeline-invariant.

    Preemption (train/preempt.py, DESIGN.md §18): the loop runs inside
    a SIGTERM ``grace_scope``; a signal stops it at the next iteration
    boundary — the in-flight epoch settles (recorded, checkpointed),
    the harness's ``preempt_flush`` (duck-typed, optional) makes the
    checkpoint lines durable with bounded waits, and
    :class:`~lfm_quant_tpu.train.preempt.Preempted` propagates so the
    entry point can exit 75 for a clean ``--resume``.
    """
    async_mode = reuse.async_enabled()
    prefetch = EpochPrefetcher(build) if async_mode else None
    drained_at: Optional[float] = None

    def settle(p: _InFlight, drained: bool) -> bool:
        """Sync one epoch's scalars (ONE device_get, snapshot included
        when async checkpointing needs the host copy), record it, and
        run the harness bookkeeping. Returns True on early stop."""
        nonlocal drained_at
        snap_dict = (p.snap._asdict()
                     if checkpointing and p.snap is not None else None)
        with telemetry.span("eval_sync", epoch=p.epoch):
            if snap_dict is not None and reuse.async_ckpt_enabled():
                host_vals, snap_dict = timed_device_get((p.vals, snap_dict))
            else:
                host_vals = timed_device_get(p.vals)
        if drained:
            drained_at = time.perf_counter()
        timer.stop(firm_months=p.fm)
        timer.start()
        step, val_ic = finish(p.epoch, host_vals, p.fm)
        with telemetry.span("ckpt", epoch=p.epoch, step=step):
            stop = harness.end_epoch(p.epoch, step, snap_dict, val_ic)
        p.span.end(val_ic=round(val_ic, 6), stop=stop)
        return stop

    # Async-mode idle probe: (timestamp, was-the-in-flight-epoch-done)
    # sampled at the END of each loop iteration. If the in-flight epoch
    # had already drained by then, every second until the next dispatch
    # is measured device idle — a LOWER bound (an epoch finishing
    # mid-gap contributes zero), so a reported non-zero async idle is
    # real, and zero means "not observed", not "proven absent".
    probe: Optional[Tuple[float, bool]] = None

    timer.start()
    epoch = harness.next_epoch()
    inflight: Optional[_InFlight] = None
    overrun: Optional[int] = None
    try:
        with preempt.grace_scope():
            while epoch is not None:
                if preempt.requested():
                    # SIGTERM grace stop (train/preempt.py, DESIGN.md
                    # §18): settle the in-flight epoch — recorded and
                    # checkpointed like any other, never discarded —
                    # flush the async checkpoint lines (bounded), and
                    # raise. The next dispatch never happens, so the
                    # grace window is spent committing work, not
                    # computing more of it.
                    if inflight is not None:
                        settle(inflight, drained=True)
                        last: Optional[int] = inflight.epoch
                        inflight = None
                    else:
                        # Nothing in flight (lock-step mode, or before
                        # the first async dispatch): the harness counter
                        # already points at the NEXT epoch to dispatch,
                        # so the last recorded epoch is one behind it
                        # (resumed fits count the predecessor run's
                        # epochs); < 0 means nothing ever settled.
                        le = getattr(harness, "last_epoch", 0) - 1
                        last = le if le >= 0 else None
                    flush = getattr(harness, "preempt_flush", None)
                    if flush is not None:
                        flush()
                    telemetry.instant("preempted", cat="fit", epoch=last)
                    raise preempt.Preempted(last)
                if prefetch is not None:
                    with telemetry.span("sample_wait", epoch=epoch):
                        batches, fm = prefetch.get(epoch)
                else:
                    batches, fm = build(epoch)
                if drained_at is not None:
                    REUSE_COUNTERS.device_idle_s += (
                        time.perf_counter() - drained_at)
                    drained_at = None
                if probe is not None and probe[1]:
                    REUSE_COUNTERS.device_idle_s += (
                        time.perf_counter() - probe[0])
                probe = None
                # Epoch span: dispatch → settle. Under lookahead these
                # OVERLAP (epoch e+1 dispatches before e settles), hence
                # an async telemetry span, not a nested one.
                esp = telemetry.begin_async("epoch", epoch=epoch)
                with telemetry.span("dispatch", epoch=epoch):
                    state, vals = dispatch(state, batches)
                    snap = _snapshot(state, checkpointing, async_mode)
                if not async_mode:
                    if settle(_InFlight(epoch, vals, snap, fm, esp),
                              drained=True):
                        break
                    epoch = harness.next_epoch()
                    continue
                # Lookahead: stage e+1's batches and (below) dispatch
                # e+1 BEFORE syncing e's metrics. The stop decision lags
                # one epoch, so the harness's epoch counter only
                # advances when the PREVIOUS epoch settles as
                # "continue" — an epoch that turns out to be the overrun
                # is never recorded anywhere.
                cand = epoch + 1 if epoch + 1 < harness.epochs else None
                if cand is not None:
                    prefetch.start(cand)
                if inflight is not None:
                    if settle(inflight, drained=False):
                        # Early stop with `epoch` speculatively in
                        # flight: roll the returned state back to the
                        # last RECORDED epoch's snapshot so downstream
                        # consumers (predict, walk-forward warm starts)
                        # see the same state the lock-step loop would
                        # have ended on.
                        overrun = epoch
                        esp.end(discarded=True)
                        telemetry.instant("lookahead_overrun", epoch=epoch)
                        if inflight.snap is not None:
                            state = inflight.snap
                        inflight = None
                        break
                    stepped = harness.next_epoch()
                    if stepped != epoch:  # pragma: no cover — invariant
                        raise RuntimeError(
                            f"pipeline epoch skew: dispatched {epoch}, "
                            f"harness advanced to {stepped}")
                inflight = _InFlight(epoch, vals, snap, fm, esp)
                probe = (time.perf_counter(), _all_ready(vals))
                epoch = cand
            if inflight is not None:
                settle(inflight, drained=True)
    finally:
        if prefetch is not None:
            prefetch.cancel()
    return state, overrun
