"""Training loop (L4) + single-seed experiment runner.

Parity target: the reference's session/`fit` training loop — optimizer, LR
schedule, early stopping, checkpointing (SURVEY.md §3 "Training loop";
call stack §4.1). TPU-native shape:

* ONE jitted train step: on-device window gather (data/windows.py) →
  flattened [D·Bf, W, F] forward (big MXU batches) → loss in [D, Bf]
  per-month layout → grad → optax update. Nothing but int32 index
  batches crosses host→device per step.
* ``lax.scan`` drives the RNN window axis inside the model (BASELINE.json:5).
* Early stopping on validation Spearman IC — the domain's canonical metric.
* Orbax checkpoints via train/checkpoint.py; metrics to JSONL.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import (Any, Callable, Dict, NamedTuple, Optional, Sequence,
                    Tuple, Union)

import jax
import jax.numpy as jnp
import numpy as np
import optax

from lfm_quant_tpu.config import RunConfig, model_kwargs
from lfm_quant_tpu.data.panel import Panel, PanelSplits
from lfm_quant_tpu.data.windows import (
    DateBatchSampler,
    WindowIndex,
    gather_targets,
    gather_windows_packed,
    resolve_gather_impl,
)
from lfm_quant_tpu.models import build_model
from lfm_quant_tpu.parallel import (DATA_AXIS, SEQ_AXIS, make_mesh,
                                    replicated, shard_batch)
from lfm_quant_tpu.ops import (
    finalize_loss,
    make_loss_parts,
    spearman_ic,
)
from lfm_quant_tpu.train.checkpoint import CheckpointManager
from lfm_quant_tpu.utils import telemetry
from lfm_quant_tpu.utils.logging import MetricsLogger
from lfm_quant_tpu.utils.profiling import StepTimer, timed_device_get


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: jax.Array
    # Raw uint32 base key for stochastic regularization (dropout). CONSTANT
    # through training — per-step keys are derived as fold_in(rng, step)
    # (+ shard index under shard_map), so resume-from-checkpoint replays
    # the exact dropout stream. Per-ensemble-member init keys make member
    # dropout streams independent.
    rng: jax.Array


def graft_params(state: TrainState, init_params, tx_init, commit):
    """Fresh-init state with ``init_params`` grafted in — the walk-forward
    warm start. The optimizer restarts from zero moments (a new fold is a
    new optimization problem), only the weights carry over; ``tx_init``
    rebuilds the opt state with the SAME tree structure the caller's
    ``init_state`` produces (plain for Trainer, vmapped for the seed-
    stacked ensemble) and ``commit`` re-places on the caller's mesh.
    Tree/shape mismatches get a clear error instead of a deep jit trace
    failure."""
    want = jax.tree.map(lambda a: (a.shape, a.dtype), state.params)
    got = jax.tree.map(lambda a: (a.shape, a.dtype), init_params)
    if want != got:
        raise ValueError(
            "init_params does not match this trainer's parameter "
            f"tree/shapes/dtypes — warm starts require the same model "
            f"config across folds (expected {want}, got {got})")
    params = jax.tree.map(jnp.asarray, init_params)
    return commit(TrainState(params, tx_init(params), state.step, state.rng))


def make_loss_fn(name: str) -> Callable:
    """Resolve a loss name to fn(outputs, targets, weights) → scalar.

    ``outputs`` is the model's head output: [D, Bf] for point heads,
    (mean, log_var) tuple for the heteroscedastic head (required by "nll").
    Derived from ``make_loss_parts`` so the scalar loss and the sharded
    num/den decomposition (train/loop.py psum assembly) cannot drift.
    """
    parts = make_loss_parts(name)
    return lambda out, y, w: finalize_loss(*parts(out, y, w))


def _point_forecast(out):
    """Point forecast from either head type (mean for heteroscedastic)."""
    return out[0] if isinstance(out, tuple) else out


def restore_state_dict(mgr: CheckpointManager,
                       abstract: Dict[str, Any]) -> Dict[str, Any]:
    """Restore a TrainState dict with legacy-checkpoint tolerance: states
    checkpointed before the ``rng`` field existed restore without it and
    take the freshly-initialized key (the dropout stream then differs
    from an unbroken run — harmless; pre-rng checkpoints trained without
    live dropout anyway)."""
    try:
        return mgr.restore(abstract)
    except Exception as e:
        if "rng" not in abstract:
            raise
        legacy = {k: v for k, v in abstract.items() if k != "rng"}
        try:
            restored = mgr.restore(legacy)
        except Exception:
            # The legacy tree fails too — the original failure was real
            # corruption, not the missing rng leaf; don't mask it.
            raise e
        restored["rng"] = abstract["rng"]
        return restored


def load_progress(run_dir: str) -> Dict[str, Any]:
    """Read the fit-progress sidecar used for crash resume."""
    with open(os.path.join(run_dir, "fit_progress.json")) as fh:
        return json.load(fh)


def save_progress(run_dir: Optional[str], **kw) -> None:
    """Atomic write: a preemption mid-dump must never leave a truncated
    sidecar (that would make the crash-resume feature itself unresumable)."""
    if run_dir:
        path = os.path.join(run_dir, "fit_progress.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(kw, fh)
        os.replace(tmp, path)


class FitHarness:
    """Shared fit scaffolding for Trainer and EnsembleTrainer: dual
    checkpoint lines (ckpt/latest every epoch for crash resume, ckpt/best
    on val-IC improvement for the final model), atomic progress sidecar,
    early stopping, and resume semantics (SURVEY.md §6 failure recovery).

    Usage:
        h = FitHarness(run_dir, epochs, patience, steps_per_epoch)
        state_dict = h.resume(state._asdict()) if resume else None
        while h.next_epoch() is not None: ... h.end_epoch(...)
        best = h.finalize(state._asdict())
    """

    def __init__(self, run_dir: Optional[str], epochs: int, patience: int,
                 steps_per_epoch: int):
        self.run_dir = run_dir
        self.epochs = epochs
        self.patience = patience
        self.steps_per_epoch = max(1, steps_per_epoch)
        self.latest_mgr = self.best_mgr = None
        if run_dir:
            self.latest_mgr = CheckpointManager(
                os.path.join(run_dir, "ckpt", "latest"), max_to_keep=2)
            self.best_mgr = CheckpointManager(
                os.path.join(run_dir, "ckpt", "best"), max_to_keep=1)
        self.best_ic, self.best_epoch, self.bad_epochs = -np.inf, -1, 0
        self.start_epoch = 0
        self._epoch = -1

    def resume(self, abstract_state_dict) -> Optional[Dict[str, Any]]:
        """Restore the latest checkpoint + loop counters. Returns the
        restored state dict, or None when nothing is checkpointed.

        The sidecar is only trusted where the DURABLE evidence backs it:
        a crash with async saves in flight can leave it ahead of either
        checkpoint line (it is written when the saves START), and a
        crash between a commit and the sidecar write leaves it behind.
        A sidecar out of step with the LATEST line in either direction
        falls back to step-derived counters (trusting a BEHIND sidecar
        would retrain the committed epoch on top of its own result); a
        sidecar claiming a best epoch the BEST line never committed
        falls back to the committed best (its IC recovered from the
        metrics stream via :meth:`_recover_best`) — the phantom best's
        params are unrecoverable, so pinning its IC would make
        ``finalize`` restore a checkpoint that never matched the
        reported best. A missing/corrupt sidecar degrades the same way
        instead of failing."""
        if not self.latest_mgr:
            return None
        step = self.latest_mgr.latest_step()
        if step is None:
            return None
        restored = restore_state_dict(self.latest_mgr, abstract_state_dict)
        try:
            prog = load_progress(self.run_dir)
            if (prog["epoch"] + 1) * self.steps_per_epoch != int(step):
                # Ahead: async save never committed. BEHIND: crash between
                # a commit and the sidecar write — trusting the sidecar
                # would retrain the committed epoch ON TOP of its own
                # result and skew the step↔epoch arithmetic for good.
                raise KeyError("progress sidecar out of step with "
                               "latest line")
            self.start_epoch = prog["epoch"] + 1
            claimed = ((prog["best_epoch"] + 1) * self.steps_per_epoch
                       if prog["best_epoch"] >= 0 else None)
            durable = self.best_mgr.latest_step() if self.best_mgr else None
            if claimed is not None and (durable is None
                                        or durable < claimed):
                raise KeyError("progress sidecar ahead of best line")
            self.best_ic = prog["best_ic"]
            self.best_epoch = prog["best_epoch"]
            self.bad_epochs = prog["bad_epochs"]
        except (FileNotFoundError, json.JSONDecodeError, KeyError,
                TypeError):
            self.start_epoch = int(step) // self.steps_per_epoch
            self._recover_best()
        self._epoch = self.start_epoch - 1
        return restored

    def _recover_best(self) -> None:
        """Rebuild best-line counters from DURABLE evidence only: the
        committed best checkpoint's step plus its logged val IC in
        metrics.jsonl (written before any save starts, so it always
        covers a committed epoch). Epochs whose best save never
        committed count as non-improving — their params are gone, so
        this is the best restorable contract (the resumed run may
        re-improve and re-save; it will never report a best_ic no
        checkpoint can back). A committed best whose IC is NOT
        recoverable (metrics stream missing/corrupt) keeps its epoch
        with best_ic=-inf: ``finalize`` can still restore it when no
        retrained epoch beats it, which strictly dominates forgetting
        the checkpoint exists. Fresh counters only when no best ever
        committed."""
        self.best_ic, self.best_epoch, self.bad_epochs = -np.inf, -1, 0
        durable = self.best_mgr.latest_step() if self.best_mgr else None
        if durable is None:
            return
        best_epoch = int(durable) // self.steps_per_epoch - 1
        best_ic = -np.inf
        try:
            with open(os.path.join(self.run_dir, "metrics.jsonl")) as fh:
                for line in fh:
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue  # a line truncated by the crash itself
                    if rec.get("epoch") == best_epoch and "val_ic" in rec:
                        best_ic = float(rec["val_ic"])
        except (OSError, ValueError):
            pass
        self.best_ic, self.best_epoch = best_ic, best_epoch
        self.bad_epochs = max(0, self.start_epoch - 1 - best_epoch)

    def next_epoch(self) -> Optional[int]:
        """The next epoch to train, or None when done — including a resumed
        run that had already early-stopped (bad_epochs >= patience must not
        restart training)."""
        nxt = self._epoch + 1 if self._epoch >= self.start_epoch - 1 else \
            self.start_epoch
        if nxt >= self.epochs or self.bad_epochs >= self.patience:
            return None
        self._epoch = nxt
        return nxt

    @property
    def last_epoch(self) -> int:
        """Epoch counter for reporting (start_epoch-1 if no epoch ran)."""
        return max(self._epoch, self.start_epoch - 1)

    def end_epoch(self, epoch: int, step: int, state_dict, val_ic: float
                  ) -> bool:
        """Record an epoch: update best, persist both checkpoint lines and
        the progress sidecar. Returns True when early stopping triggers.

        Both saves START asynchronously so the best and latest lines
        always overlap each other; with ``LFM_ASYNC_CKPT`` on (default)
        neither is waited for here — the caller hands in a host-fetched
        state copy (train/pipeline.py) and the writes drain behind the
        next epoch's compute, flushed only at :meth:`finalize`. With it
        off, one barrier per line at the end of this method restores the
        synchronous durability contract (still faster than the old
        serial save→wait→save→wait). A crashed async save loses at most
        the in-flight epoch: Orbax commits atomically and
        :meth:`resume` reconciles a sidecar that ran ahead."""
        from lfm_quant_tpu.train.reuse import async_ckpt_enabled

        saved_best = False
        if val_ic > self.best_ic:
            self.best_ic, self.best_epoch, self.bad_epochs = val_ic, epoch, 0
            if self.best_mgr:
                self.best_mgr.save(step, state_dict, wait=False)
                saved_best = True
        else:
            self.bad_epochs += 1
        if self.latest_mgr:
            self.latest_mgr.save(step, state_dict, wait=False)
            if not async_ckpt_enabled():
                # Sync reference path: both lines durable BEFORE the
                # sidecar records them (the pre-pipeline ordering) — a
                # crash can then never leave the sidecar claiming a
                # best/latest that no committed checkpoint backs.
                # timeout_s=0: this contract is "durable before
                # proceeding", which a bounded wait cannot honor (the
                # same carve-out as save(wait=True)).
                if saved_best:
                    self.best_mgr.wait(timeout_s=0)
                self.latest_mgr.wait(timeout_s=0)
            save_progress(self.run_dir, epoch=epoch,
                          best_ic=float(self.best_ic),
                          best_epoch=self.best_epoch,
                          bad_epochs=self.bad_epochs)
        return self.bad_epochs >= self.patience

    def preempt_flush(self) -> None:
        """SIGTERM-grace flush (train/preempt.py → pipeline driver):
        make everything recorded so far DURABLE before the process
        dies — both async checkpoint lines flushed and closed with
        BOUNDED waits (train/checkpoint.py, ``LFM_CKPT_WAIT_S``), so a
        wedged writer can never eat the whole grace window. The
        progress sidecar was already written by :meth:`end_epoch`; once
        the lines commit it is consistent, and a resume continues from
        exactly the last recorded epoch with identical history. If a
        wait times out (loud warning), the sidecar runs ahead of the
        uncommitted line and :meth:`resume`'s skew reconciliation takes
        over — degraded to the crash contract, never corrupt."""
        if not self.latest_mgr:
            return
        self.best_mgr.close()
        self.latest_mgr.close()

    def finalize(self, abstract_state_dict) -> Optional[Dict[str, Any]]:
        """Flush in-flight async saves, restore the best state (if any)
        and close the managers. The wait precedes the restore: the best
        checkpoint being read may still be committing."""
        best = None
        best_durable = True
        if self.latest_mgr:
            best_durable = self.best_mgr.wait()
            self.latest_mgr.wait()
        if (self.best_mgr and self.best_epoch >= 0
                and self.best_mgr.latest_step() is not None):
            if not best_durable:
                # Bounded wait timed out with the best save in flight:
                # latest_step() only reports COMMITTED steps, so the
                # restore below may hand back an OLDER best than the
                # recorded best_epoch — loud, never silent.
                import warnings

                warnings.warn(
                    f"best checkpoint line still uncommitted after the "
                    f"bounded wait (epoch {self.best_epoch} recorded) — "
                    "restoring the newest COMMITTED best instead, which "
                    "may be older", RuntimeWarning, stacklevel=2)
            best = restore_state_dict(self.best_mgr, abstract_state_dict)
        if self.latest_mgr:
            self.latest_mgr.close()
            self.best_mgr.close()
        return best


class TrainerPrograms:
    """The trace-relevant core of a Trainer: models, optimizer, and the
    jitted step/multi-step/forward/eval wrappers — hoisted out of
    per-instance construction into the module-level program cache
    (train/reuse.py) so a walk-forward sweep binds ONE set of
    executables across folds.

    Everything held here is a pure function of the cache key
    (``reuse.trainer_program_key``); nothing per-fold lives here — the
    panel, splits, samplers, run dir and TrainState all stay on the
    Trainer. That is the invariant that makes sharing safe: two
    Trainers with equal keys would have built byte-identical programs,
    so binding the first one's wrappers changes nothing but the compile
    count. Deliberately lightweight (no panel/device arrays) so cache
    entries never pin folds' worth of HBM or host memory.
    """

    def __init__(self, cfg: RunConfig, mesh: Any, n_seq: int,
                 steps_per_epoch: int, gather_impl: str,
                 eval_gather_impl: str, eval_gather_sharded: str, fp: int):
        from lfm_quant_tpu.train.reuse import ledger_jit

        self.cfg = cfg
        self.mesh = mesh
        self.window = cfg.data.window
        self._n_seq = n_seq
        self._gather_impl = gather_impl
        self._eval_gather_impl = eval_gather_impl
        self._eval_gather_sharded = eval_gather_sharded
        self._fp = fp
        self.loss_fn = make_loss_fn(cfg.optim.loss)
        self.loss_parts = make_loss_parts(cfg.optim.loss)
        # Geometry-bucket program twins, memoized per bundle (the zoo's
        # per-entry memo pattern): a busy program cache may evict the
        # trainbucket keys, but a trainer already bound to this bundle
        # keeps its warm bucket executables.
        self._bucket_programs: Dict[Tuple[int, int], "BucketPrograms"] = {}
        # Stochastic-regularization flag: when dropout is configured, the
        # train step threads a per-step rng + deterministic=False through
        # model.apply (eval stays deterministic). Without it the rng plumb
        # is skipped entirely, keeping the jitted graph unchanged.
        self._needs_rng = float(cfg.model.kwargs.get("dropout") or 0.0) > 0.0

        # Train model: the Pallas fused recurrence survives the mesh
        # because the train step runs inside shard_map (locally
        # un-partitioned per shard). The eval forward stays GSPMD-
        # partitioned, so under a mesh it gets a twin model on the XLA
        # scan — parameter trees are identical between scan impls
        # (models/rnn.py _GateKernel path aliasing), so params interchange.
        # Under sequence parallelism the train model is the seq_axis-aware
        # variant (checkpoint-compatible: no per-position params).
        kind, kwargs = model_kwargs(cfg, seq_axis=n_seq > 1)
        self.model = build_model(kind, **kwargs)
        if mesh is not None:
            ekind, ekwargs = model_kwargs(cfg, force_xla_scan=True)
            self.eval_model = build_model(ekind, **ekwargs)
        else:
            self.eval_model = self.model

        total_steps = max(1, steps_per_epoch * cfg.optim.epochs)
        schedule = optax.warmup_cosine_decay_schedule(
            0.0, cfg.optim.lr, min(cfg.optim.warmup_steps, total_steps // 2),
            total_steps, end_value=cfg.optim.lr * 0.1,
        )
        if cfg.optim.optimizer == "adamw":
            opt = optax.adamw(schedule, weight_decay=cfg.optim.weight_decay)
        elif cfg.optim.optimizer == "lamb":
            # Layerwise-adaptive Adam for large effective batches (the
            # pod-scale data axis): trust-ratio-scaled updates keep the
            # warmup-cosine schedule usable without per-batch-size lr
            # re-tuning (PAPERS.md, "Large-Batch Training for LSTM and
            # Beyond"). Same decoupled weight decay as the adamw path.
            opt = optax.lamb(schedule, weight_decay=cfg.optim.weight_decay)
        else:
            raise ValueError(
                f"optimizer must be adamw|lamb, got {cfg.optim.optimizer!r}")
        self.tx = optax.chain(
            optax.clip_by_global_norm(cfg.optim.grad_clip), opt)

        # The multi-step (whole-epoch) wrappers donate their TrainState:
        # fit() consumes states linearly, so XLA aliases params/opt_state
        # in place instead of double-buffering them in HBM across the
        # epoch-long dispatch (train/reuse.py multi_step_donate_argnums
        # has the safety argument; LFM_DONATE=0 is the kill switch). The
        # single-step wrappers stay un-donated — they are the numerical
        # A/B surface and tests re-dispatch one state on purpose.
        from lfm_quant_tpu.train.reuse import multi_step_donate_argnums

        donate = multi_step_donate_argnums()
        if mesh is None:
            self._jit_step = ledger_jit("step", self._step_impl)
            self._jit_multi_step = ledger_jit(
                "multi_step", self._multi_step_impl,
                donate_argnums=donate)
        else:
            # shard_map over the date axis: each shard gathers and runs the
            # model locally (Pallas kernels legal), with explicit psums for
            # the global loss/gradients — numerically the same weighted
            # means GSPMD computed, up to reduction order.
            self._jit_step = ledger_jit("step", self._shard_mapped(
                self._step_impl, steps_axis=False))
            self._jit_multi_step = ledger_jit(
                "multi_step",
                self._shard_mapped(self._multi_step_impl, steps_axis=True),
                donate_argnums=donate)
        self._jit_forward = ledger_jit(
            "forward", self._forward_impl,
            static_argnames=("variance",))
        # Batched MC-dropout: the eval forward vmapped over a stacked key
        # array, so K samples are ONE dispatch (and ONE D2H in predict)
        # instead of K serial dispatches each paying tunnel latency.
        self._jit_mc_forward = ledger_jit(
            "mc_forward", self._mc_forward_impl)
        # Forecast-only twin (scores_only): predict() consumes nothing
        # but the scores, so the serving sweep skips M wasted per-month
        # rank-IC sorts + MSE inside the dispatch — the single-seed
        # analog of the ensemble's _jit_predict.
        self._jit_predict = ledger_jit(
            "predict",
            lambda params, dev, fi, ti, w: self._forward_impl(
                params, dev, fi, ti, w, scores_only=True))
        # Month-sharded eval: under a data mesh the plain jitted forward
        # would replicate the whole sweep on every device; shard_map over
        # the stacked month axis makes eval/backtest scale with the data
        # axis like training does (n_data× at pod scale). MC-dropout
        # sampling keeps the plain path (per-chunk rng keys don't shard).
        self._eval_sharded = (mesh is not None
                              and mesh.shape[DATA_AXIS] > 1)
        self._jit_fwd_det = self._jit_fwd_var = None
        if self._eval_sharded:
            import functools

            from jax.sharding import PartitionSpec as P

            from lfm_quant_tpu.parallel.mesh import shard_map_compat

            sharded = functools.partial(
                shard_map_compat, mesh=mesh,
                in_specs=(P(), P(), P(DATA_AXIS), P(DATA_AXIS),
                          P(DATA_AXIS)),
                check_vma=False)
            self._jit_fwd_det = ledger_jit("fwd_det", sharded(
                functools.partial(self._forward_impl, axis=DATA_AXIS),
                out_specs=(P(DATA_AXIS), P(DATA_AXIS), P())))

            def fwd_var(params, dev, fi, ti, w):
                # axis marks this as a SHARDED dispatch (gather promotion
                # applies); the variance branch returns before the mse
                # psum, so the axis is never collectively reduced here.
                mean, var, _ = self._forward_impl(params, dev, fi, ti, w,
                                                  variance=True,
                                                  axis=DATA_AXIS)
                return mean, var

            self._jit_fwd_var = ledger_jit("fwd_var", sharded(
                fwd_var, out_specs=(P(DATA_AXIS), P(DATA_AXIS))))

    def _shard_mapped(self, impl, steps_axis: bool):
        """Wrap a step impl in shard_map over this program set's mesh.

        State and panel replicate (P()); index batches shard their date
        axis (and replicate over the seq axis when present — every seq
        shard sees the full batch and runs its window slice). out_specs
        are P() because the psum'd gradients make every shard's update
        identical (check_vma=False: the replication is mathematical, not
        provable by the varying-axes checker). With a live seq axis the
        step psums over BOTH batch axes: loss num/den each pick up the
        same ×n_seq duplication (the ratio is exact), and the per-shard
        window-slice gradients sum to the full-window gradient."""
        import functools

        from jax.sharding import PartitionSpec as P

        from lfm_quant_tpu.parallel.mesh import shard_map_compat

        axes = ((DATA_AXIS, SEQ_AXIS) if self._n_seq > 1 else (DATA_AXIS,))
        batch = P(None, DATA_AXIS) if steps_axis else P(DATA_AXIS)
        return shard_map_compat(
            functools.partial(impl, axis=axes),
            mesh=self.mesh,
            in_specs=(P(), P(), batch, batch, batch),
            out_specs=(P(), P()),
            check_vma=False,
        )

    # ---- jitted impls ------------------------------------------------

    def _apply(self, params, x, m, model=None, rng=None):
        """Flatten [D, Bf] batch dims → one big MXU batch, reapply shape.

        ``rng``: dropout key — training passes it when dropout is
        configured (deterministic=False); eval never does. Under sequence
        parallelism the STEP hands this the shard's pre-gathered
        sub-window (see ``_step_impl``); the seq-aware model's live-axis
        collectives (ring attention / distributed scan + psum pooling)
        make every shard return the identical full pooled output."""
        model = model or self.model
        lead = x.shape[:-2]
        xf = x.reshape((-1,) + x.shape[-2:])
        mf = m.reshape((-1,) + m.shape[-1:])
        if rng is not None:
            out = model.apply({"params": params}, xf, mf,
                              deterministic=False, rngs={"dropout": rng})
        else:
            out = model.apply({"params": params}, xf, mf)
        if isinstance(out, tuple):
            return tuple(o.reshape(lead) for o in out)
        return out.reshape(lead)

    def _gather(self, xm, firm_idx, time_idx, impl=None, window=None):
        """The resolved window gather (ops/pallas_gather.py DMA kernel or
        the XLA row gather). Both read the panel through the logical
        packed width ``fp`` — the panel may be lane-padded (Pallas).
        ``window`` overrides the lookback length (the sequence-parallel
        step gathers per-shard sub-windows)."""
        impl = impl or self._gather_impl
        window = window or self.window
        if impl == "pallas":
            from lfm_quant_tpu.ops.pallas_gather import gather_windows_pallas

            return gather_windows_pallas(
                xm, firm_idx, time_idx, window, fp=self._fp)
        # Full-universe widths chunk the firm axis so the [D, Bf, T, F]
        # row transient stays bounded (the Pallas DMA gather above never
        # materializes rows, so it needs no chunking).
        from lfm_quant_tpu.data.windows import FIRM_CHUNK

        chunk = FIRM_CHUNK if firm_idx.shape[-1] >= 2 * FIRM_CHUNK else None
        return gather_windows_packed(
            xm, firm_idx, time_idx, window, fp=self._fp,
            firm_chunk=chunk)

    def _grads_impl(self, state: TrainState, dev: dict, firm_idx, time_idx,
                    weight,
                    axis: Optional[Union[str, Tuple[str, ...]]] = None,
                    window: Optional[int] = None):
        """Loss + psum'd gradients of one batch — the optimizer-free
        half of :meth:`_step_impl`, shared with the stacked engine's
        per-run-operand hyper step (train/stacked.py): a config sweep
        computes gradients through exactly this code and applies them
        with per-run (lr, weight-decay) OPERANDS instead of the baked
        ``self.tx`` chain, so the two paths cannot drift.

        ``window`` overrides the gather's lookback length — the
        geometry-bucket programs (:class:`BucketPrograms`) bind their
        rung here so a short-history cohort scans W_b steps instead of
        the full window; None keeps the configured window. Bucketing is
        rejected under sequence parallelism upstream, so the seq-shard
        sub-window arithmetic below never sees an override."""
        step_rng = None
        if self._needs_rng:
            # Derived, never stored: resume replays the same stream; the
            # shard index decorrelates dropout masks across data shards.
            # (axis may be a tuple of names; dropout is rejected under a
            # live seq axis, so folding each name stays per-data-shard.)
            step_rng = jax.random.fold_in(state.rng, state.step)
            if axis is not None:
                names = (axis,) if isinstance(axis, str) else axis
                for nm in names:
                    step_rng = jax.random.fold_in(
                        step_rng, jax.lax.axis_index(nm))

        def loss_of(params):
            if self._n_seq > 1:
                # Gather only this seq shard's SUB-window: absolute window
                # positions [s·wl, (s+1)·wl) end at anchor − (W − (s+1)·wl),
                # so each shard moves 1/n_seq of the gather bytes and
                # holds 1/n_seq of the input transient. Young anchors
                # degrade exactly like the full gather (pre-history
                # positions mask False — pinned by test).
                wl = self.window // self._n_seq
                shift = (self.window
                         - (jax.lax.axis_index(SEQ_AXIS) + 1) * wl)
                x, m = self._gather(dev["xm"], firm_idx, time_idx - shift,
                                    window=wl)
            else:
                x, m = self._gather(dev["xm"], firm_idx, time_idx,
                                    window=window)
            y = gather_targets(dev["targets"], firm_idx, time_idx)
            out = self._apply(params, x, m, rng=step_rng)
            num, den = self.loss_parts(out, y, weight)
            if axis is not None:
                num = jax.lax.psum(num, axis)
                den = jax.lax.psum(den, axis)
            return finalize_loss(num, den)

        loss, grads = jax.value_and_grad(loss_of)(state.params)
        if axis is not None:
            grads = jax.lax.psum(grads, axis)
        return loss, grads

    def _step_impl(self, state: TrainState, dev: dict, firm_idx, time_idx,
                   weight,
                   axis: Optional[Union[str, Tuple[str, ...]]] = None,
                   window: Optional[int] = None):
        """One train step. ``axis`` names the mesh axis this step runs
        under inside shard_map (None = un-partitioned): the loss is a
        ratio of data-sums, so the global value needs one psum per part,
        and gradients psum across shards (replicated params).
        ``window``: the geometry-bucket lookback override (see
        :meth:`_grads_impl`)."""
        loss, grads = self._grads_impl(state, dev, firm_idx, time_idx,
                                       weight, axis=axis, window=window)
        updates, opt_state = self.tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        return TrainState(params, opt_state, state.step + 1, state.rng), {
            "loss": loss, "grad_norm": gnorm,
        }

    def _multi_step_impl(self, state: TrainState, dev: dict, fi, ti, w,
                         axis: Optional[Union[str, Tuple[str, ...]]] = None,
                         window: Optional[int] = None):
        """K training steps in ONE compiled dispatch: lax.scan over a
        [K, D, Bf] index stack. Per-step dispatch latency (25–30 ms on a
        tunneled device) would otherwise dwarf the ~ms of real compute per
        step; scanning an epoch inside jit removes it entirely."""
        def body(st, batch):
            return self._step_impl(st, dev, *batch, axis=axis,
                                   window=window)

        return jax.lax.scan(body, state, (fi, ti, w))

    def _mc_forward_impl(self, params, dev: dict, firm_idx, time_idx,
                         keys):
        """Batched MC-dropout eval forward: K samples in ONE dispatch.

        The window gather is SAMPLE-INVARIANT (every sample reads the
        same [M, bf] indices), so each chunk gathers once and only the
        model apply is vmapped over the stacked key axis — K× fewer
        gather bytes than vmapping the whole eval forward, and K× fewer
        dispatches than the per-sample loop it replaces. Key derivation
        matches the loop path exactly (per-sample key → per-chunk
        split), so the two paths draw identical dropout masks and
        ``predict`` replays are seed-stable on either.
        Returns stacked forecasts [K, M, bf].
        """
        M = firm_idx.shape[0]
        C = min(self.cfg.data.dates_per_batch, M)
        pad = (-M) % C
        if pad:
            firm_idx = jnp.concatenate([firm_idx, firm_idx[:pad]], axis=0)
            time_idx = jnp.concatenate([time_idx, time_idx[:pad]], axis=0)
        nc = firm_idx.shape[0] // C
        k_samples = keys.shape[0]
        # [K, nc] → [nc, K]: lax.map consumes the chunk axis first.
        chunk_keys = jnp.swapaxes(
            jax.vmap(lambda kk: jax.random.split(kk, nc))(keys), 0, 1)

        def chunk(args):
            fi, ti, kks = args
            x, m = self._gather(dev["xm"], fi, ti,
                                impl=self._eval_gather_impl)
            return jax.vmap(lambda kk: _point_forecast(self._apply(
                params, x, m, model=self.eval_model, rng=kk)))(kks)

        pred = jax.lax.map(chunk, (firm_idx.reshape(nc, C, -1),
                                   time_idx.reshape(nc, C), chunk_keys))
        # [nc, K, C, bf] → [K, nc·C, bf], padding sliced off.
        return jnp.moveaxis(pred, 0, 1).reshape(
            k_samples, nc * C, -1)[:, :M]

    def _forward_impl(self, params, dev: dict, firm_idx, time_idx, weight,
                      rng=None, variance: bool = False, axis=None,
                      scores_only: bool = False,
                      window: Optional[int] = None):
        """Eval forward: returns (pred [D,Bf], per-month IC [D], mse scalar).

        Chunked over the date axis with ``lax.map``: eval sweeps stack ALL
        months into one [M, bf] batch, and the fast gather materializes
        full firm histories ([chunk, bf, T, F]) — unchunked that would be
        T/W × the window bytes for every eval month at once.

        ``rng`` switches dropout LIVE (per-chunk keys) — the MC-dropout
        sampling path of :meth:`Trainer.predict`; None is the
        deterministic eval. ``variance`` (static) returns (mean, aleatoric
        variance, None) from a heteroscedastic head instead of
        (pred, IC, mse) — the uncertainty-aware-LFM prediction path
        (SURVEY.md §1 lineage). ``axis``: mesh axis name when running
        inside the month-sharded eval ``shard_map`` — the mse parts psum
        over it so the scalar replicates. ``scores_only`` (static) skips
        the per-month IC/MSE metrics like the sampling path does —
        prediction sweeps only consume the forecasts, and an S-seed
        ensemble predict would otherwise pay S × M wasted rank sorts in
        the dispatch. ``window``: the geometry-bucket lookback override
        (see :meth:`_grads_impl`) — bound by :class:`BucketPrograms`,
        never passed through the max-shape jitted entry points.
        """
        if variance and rng is not None:
            raise ValueError("variance + MC-dropout sampling not supported")
        M = firm_idx.shape[0]
        C = min(self.cfg.data.dates_per_batch, M)
        pad = (-M) % C
        if pad:
            firm_idx = jnp.concatenate([firm_idx, firm_idx[:pad]], axis=0)
            time_idx = jnp.concatenate([time_idx, time_idx[:pad]], axis=0)
            weight = jnp.concatenate(
                [weight, jnp.zeros_like(weight[:pad])], axis=0)
        nc = firm_idx.shape[0] // C
        chunks = [firm_idx.reshape(nc, C, -1), time_idx.reshape(nc, C),
                  weight.reshape(nc, C, -1)]
        if rng is not None:
            chunks.append(jax.random.split(rng, nc))

        def chunk(args):
            fi, ti, w, *key = args
            x, m = self._gather(dev["xm"], fi, ti,
                                impl=(self._eval_gather_sharded
                                      if axis is not None
                                      else self._eval_gather_impl),
                                window=window)
            out = self._apply(params, x, m, model=self.eval_model,
                              rng=key[0] if key else None)
            if variance:
                if not isinstance(out, tuple):
                    raise ValueError(
                        "variance=True needs a heteroscedastic head "
                        "(ModelConfig.heteroscedastic / loss='nll')")
                mean, log_var = out
                return mean, jnp.exp(log_var.astype(jnp.float32))
            pred = _point_forecast(out)
            if rng is not None or scores_only:
                # Sampling / forecast-only path: only the forecasts are
                # consumed — skip the per-month ranking/error metrics.
                return pred
            y = gather_targets(dev["targets"], fi, ti)
            ic = spearman_ic(pred, y, w)
            se = (w * (pred.astype(jnp.float32) - y) ** 2).sum(axis=-1)
            return pred, ic, se, w.sum(axis=-1)

        if variance:
            mean, var = jax.lax.map(chunk, tuple(chunks))
            return (mean.reshape(nc * C, -1)[:M],
                    var.reshape(nc * C, -1)[:M], None)
        if rng is not None or scores_only:
            pred = jax.lax.map(chunk, tuple(chunks))
            return pred.reshape(nc * C, -1)[:M], None, None
        pred, ic, se, ws = jax.lax.map(chunk, tuple(chunks))
        pred = pred.reshape(nc * C, -1)[:M]
        ic = ic.reshape(-1)[:M]
        se, ws = se.reshape(-1)[:M], ws.reshape(-1)[:M]
        se_sum, ws_sum = se.sum(), ws.sum()
        if axis is not None:
            se_sum = jax.lax.psum(se_sum, axis)
            ws_sum = jax.lax.psum(ws_sum, axis)
        mse = se_sum / jnp.maximum(ws_sum, 1e-12)
        return pred, ic, mse

    def bucket_programs(self, inner_key: Tuple,
                        bucket: Tuple[int, int]) -> "BucketPrograms":
        """The bucket's compiled program twins, through the program
        cache (``reuse.train_bucket_program_key``) — the exact pattern
        the serving zoo uses for its per-bucket scoring programs:
        cross-trainer reuse via the tagged key family, plus a
        per-bundle memo so eviction never forces a warm holder to
        rebuild. ``inner_key`` is the key THIS bundle was cached under
        (the caller's ``program_key`` — equal keys mean byte-identical
        bundles, so memoizing on the bundle is sound)."""
        bp = self._bucket_programs.get(bucket)
        if bp is None:
            from lfm_quant_tpu.train import reuse

            bp = reuse.get_programs(
                reuse.train_bucket_program_key(inner_key, bucket),
                lambda: BucketPrograms(self, bucket))
            self._bucket_programs[bucket] = bp
        return bp


class BucketPrograms:
    """Per-(lookback × width) jitted twins of a trainer's multi-step /
    eval-forward / predict programs (``LFM_BUCKETS``, DESIGN.md §16),
    cached under ``reuse.train_bucket_program_key``.

    The WIDTH half of the bucket never appears here — it arrives as the
    batch aval (jit's executable cache keys on it), exactly like the
    serve programs. The LOOKBACK half must be bound: the gather's
    window length is a static constant inside the traced program, so a
    W_b-rung scan is a genuinely different program from the full-window
    one. Everything else — impls, loss, optimizer, mesh wrapping — is
    the parent bundle's, which is what makes a bucketed batch's outputs
    BIT-identical to the same batch padded to max shape (masked steps
    hold RNN state exactly; weight-0 pad columns are exact no-ops in
    every loss/metric — the ``bucketed`` lane pins both). Holds only
    the parent bundle reference and jit wrappers — no panel or state
    (the lightweight-cache-entry invariant)."""

    def __init__(self, inner: TrainerPrograms, bucket: Tuple[int, int]):
        from lfm_quant_tpu.train.reuse import (ledger_jit,
                                               multi_step_donate_argnums)

        self.inner = inner
        self.bucket = bucket
        lookback, width = bucket
        tag = f"b{lookback}x{width}"
        donate = multi_step_donate_argnums()

        def multi(state, dev, fi, ti, w, axis=None):
            return inner._multi_step_impl(state, dev, fi, ti, w,
                                          axis=axis, window=lookback)

        if inner.mesh is None:
            self._jit_multi_step = ledger_jit(
                f"multi_step@{tag}", multi, donate_argnums=donate)
        else:
            self._jit_multi_step = ledger_jit(
                f"multi_step@{tag}",
                inner._shard_mapped(multi, steps_axis=True),
                donate_argnums=donate)

        def fwd(params, dev, fi, ti, w):
            return inner._forward_impl(params, dev, fi, ti, w,
                                       window=lookback)

        self._jit_forward = ledger_jit(f"forward@{tag}", fwd)

        def predict(params, dev, fi, ti, w):
            return inner._forward_impl(params, dev, fi, ti, w,
                                       scores_only=True, window=lookback)

        self._jit_predict = ledger_jit(f"predict@{tag}", predict)


#: rebind() sentinel: "keep the previous run_dir" (explicit None means
#: "drop it" — a fold that must not checkpoint).
_KEEP = object()


class Trainer:
    """Single-seed trainer: fit on splits.train, early-stop on splits.val.

    The ensemble trainer (train/ensemble.py) reuses the same jitted step
    vmapped over a leading seed axis. The jitted programs themselves live
    on a :class:`TrainerPrograms` bundle fetched through the cross-fold
    program cache (train/reuse.py) — two trainers with equal program
    keys (same mesh/model/optimizer/gather geometry) share executables,
    which is what makes a walk-forward sweep compile once.
    """

    def __init__(self, cfg: RunConfig, splits: PanelSplits,
                 run_dir: Optional[str] = None, echo: bool = False,
                 mesh: Any = "auto"):
        """``mesh``: "auto" builds the single-seed (1 × n_data_shards)
        data mesh; wrappers pass their own mesh (EnsembleTrainer's
        seed × data) or None, so model/gather/panel resolution happens
        exactly once against the mesh that will actually run the step
        (the ensemble then shares this trainer's device panel).
        """
        self._setup(cfg, splits, run_dir, echo, mesh)

    def rebind(self, cfg: Optional[RunConfig] = None,
               splits: Optional[PanelSplits] = None,
               run_dir: Any = _KEEP,
               echo: Optional[bool] = None) -> "Trainer":
        """Re-initialize this trainer for the next walk-forward fold:
        fresh sampler seeds and split boundaries, new run dir, TrainState
        dropped — WITHOUT rebuilding the jit wrappers (the program key is
        recomputed; an unchanged key keeps the exact same executables and
        device panel, a changed one fetches/builds through the cache like
        a fresh construction would). Like the other parameters, an
        OMITTED ``run_dir`` keeps the previous one (checkpointing must
        not silently vanish on a partial rebind); pass ``run_dir=None``
        explicitly to drop it. Returns self."""
        self._setup(cfg if cfg is not None else self.cfg,
                    splits if splits is not None else self.splits,
                    self.run_dir if run_dir is _KEEP else run_dir,
                    self.echo if echo is None else echo,
                    "auto")
        return self

    def _setup(self, cfg: RunConfig, splits: PanelSplits,
               run_dir: Optional[str], echo: bool, mesh: Any) -> None:
        from lfm_quant_tpu.data.windows import cached_device_panel
        from lfm_quant_tpu.train import reuse

        self.cfg = cfg
        self.splits = splits
        self.run_dir = run_dir
        self.echo = echo
        self.state = None
        d = cfg.data

        self.window = d.window
        # Recomputed here (not just in TrainerPrograms) because the mesh
        # validation below needs it before any program-cache lookup.
        self._needs_rng = float(cfg.model.kwargs.get("dropout") or 0.0) > 0.0

        # Data-parallel mesh (SURVEY.md §8 step 8): shard the DATE axis of
        # each batch so monthly cross-sections stay shard-local for
        # rank-IC. With ``n_seq_shards > 1`` the mesh gains an innermost
        # 'seq' axis — sequence/context parallelism for the train forward
        # (ring attention for the transformer, distributed associative
        # scan for the LRU); the two compose: batches shard dates over
        # 'data' and replicate over 'seq', where each shard runs its
        # window slice. Both axes degrade gracefully to fewer devices
        # than configured (data first — it reduces step memory; a
        # pod-trained config must stay loadable for eval/backtest on a
        # smaller host, where only the full-window eval model runs).
        self._n_seq = 1
        if mesh == "auto":
            n_data = max(1, min(cfg.n_data_shards, jax.device_count()))
            if cfg.n_seq_shards > 1:
                if self._needs_rng:
                    raise ValueError(
                        "dropout is unsupported under sequence parallelism "
                        "(shard-local masks would decorrelate; see "
                        "models/transformer.py)")
                from lfm_quant_tpu.parallel.mesh import resolve_seq_shards

                self._n_seq = resolve_seq_shards(
                    cfg.n_seq_shards, jax.device_count() // n_data)
                if self._n_seq > 1 and d.window % self._n_seq:
                    raise ValueError(
                        f"window={d.window} must divide by "
                        f"n_seq_shards={self._n_seq}")
            mesh = (make_mesh(1, n_data, n_seq=self._n_seq)
                    if n_data * self._n_seq > 1 else None)
        elif cfg.n_seq_shards > 1:
            # Wrapper-provided mesh (EnsembleTrainer): the wrapper owns
            # degradation and axis sizing — a mesh WITHOUT a seq axis (or
            # no mesh at all, e.g. eval on a small host) means seq
            # degraded to 1: train/eval with the plain full-window model.
            if mesh is not None and SEQ_AXIS in mesh.shape:
                if self._needs_rng:
                    raise ValueError(
                        "dropout is unsupported under sequence "
                        "parallelism (shard-local masks would "
                        "decorrelate; see models/transformer.py)")
                self._n_seq = mesh.shape[SEQ_AXIS]
                if self._n_seq > 1 and d.window % self._n_seq:
                    raise ValueError(
                        f"window={d.window} must divide by "
                        f"n_seq_shards={self._n_seq}")
        self.mesh = mesh
        # Test/introspection alias: the mesh carrying the live seq axis.
        self.seq_mesh = mesh if self._n_seq > 1 else None
        n_data = self.mesh.shape[DATA_AXIS] if self.mesh is not None else 1
        if d.dates_per_batch % n_data:
            raise ValueError(
                f"dates_per_batch={d.dates_per_batch} must be divisible by "
                f"n_data_shards={n_data}")

        self.train_sampler = DateBatchSampler(
            splits.panel, d.window, d.dates_per_batch, d.firms_per_date,
            seed=cfg.seed, min_valid_months=d.min_valid_months,
            date_range=splits.train_range, engine=d.sampler_engine,
        )
        self.val_sampler = DateBatchSampler(
            splits.panel, d.window, 1, d.firms_per_date,
            seed=cfg.seed, min_valid_months=d.min_valid_months,
            min_cross_section=1, date_range=splits.val_range,
        )
        # Compute-precision lane (LFM_PRECISION / RunConfig.precision,
        # DESIGN.md §17): ONE resolution feeds the gather choice, the
        # panel residency dtype and (via config.model_kwargs inside
        # TrainerPrograms) the models' compute dtype — master params,
        # Adam moments and every loss/IC reduction stay f32 regardless.
        from lfm_quant_tpu.config import compute_dtype

        self._compute_dtype = compute_dtype(cfg)
        # Gather implementation (Pallas DMA gather needs a lane-padded
        # panel, so it must be resolved before the device transfer). Under
        # a mesh the eval sweep keeps the XLA gather even though the
        # month-sharded path (_forward_eval) does run inside shard_map
        # where a pallas_call would be legal: the MC-dropout path still
        # runs un-sharded (GSPMD), and one shared eval gather impl keeps
        # the paths identical.
        self._gather_impl = resolve_gather_impl(
            d.gather_impl, self.mesh, splits.panel, d.window,
            bf16=self._compute_dtype is not None)
        if self._n_seq > 1:
            # Sequence-parallel steps gather only the shard's SUB-window
            # (window // n_seq months) — the Pallas DMA gather's aligned
            # spans are validated for the full window only, so the train
            # gather takes the XLA path under a seq axis.
            self._gather_impl = "xla"
        # Eval defaults to the XLA gather even where the DMA gather is
        # legal: the on-chip A/B (BENCH_ROWS.jsonl, 2026-07-31, c2) put
        # the XLA-gather eval at 48.0M fm/s vs 33.4M for the DMA gather
        # (+44% — the full-cross-section sweep is gather-bound in a way
        # the train step is not), and the XLA rows were measured LATER
        # in the session, so tunnel-state drift biases against them.
        # An EXPLICIT gather_impl="pallas" config still carries into
        # single-chip eval (the A/B override path); "auto" never does.
        self._eval_gather_impl = (
            self._gather_impl
            if d.gather_impl == "pallas" and self.mesh is None else "xla")
        # Sharded-eval gather promotion, flag-gated: inside the
        # month-sharded shard_map each shard is locally un-partitioned,
        # so the DMA gather is as legal there as in the train step.
        # LFM_EVAL_SHARDED_GATHER=pallas opts the sharded dispatches
        # (axis != None in _forward_impl) into it when the panel is
        # already lane-padded for the train gather; the GSPMD paths
        # (MC-dropout sampling, no-mesh eval) are untouched. The c2 A/B
        # above makes this promotion unlikely to pay — kept for the
        # mesh-resident re-measurement.
        self._eval_gather_sharded = self._eval_gather_impl
        if (os.environ.get("LFM_EVAL_SHARDED_GATHER") == "pallas"
                and self._gather_impl == "pallas"):
            self._eval_gather_sharded = "pallas"
        self._fp = splits.panel.n_features + 1  # logical packed width
        # ONE device-resident copy of the full panel serves training,
        # eval and inference (PanelSplits are anchor ranges, not slices)
        # — AND, through the residency cache, every other trainer/fold
        # bound to the same (panel, mesh, dtype, padding): a walk-forward
        # sweep transfers the panel exactly once.
        # Under the bf16 lane the resident packed panel is bf16: half
        # the panel HBM and half of every panel H2D, shared (through the
        # residency cache) by every trainer/fold/bucket/stack/serve
        # program bound to the same (panel, mesh, dtype, padding).
        self.dev = cached_device_panel(
            splits.panel, self.mesh,
            compute_dtype=self._compute_dtype,
            raw=False, lane_pad=self._gather_impl == "pallas")

        # Cold-process reuse: point XLA's persistent compilation cache at
        # the configured directory (no-op when unset). Idempotent, and
        # it must run before the first dispatch compiles.
        reuse.enable_persistent_cache(cfg.compilation_cache_dir)

        # Geometry-bucket mode (LFM_BUCKETS, DESIGN.md §16): batches
        # quantize to the sampler's (lookback × width) ladder instead of
        # one static max shape. Rejected under sequence parallelism (the
        # seq sub-window arithmetic assumes the full configured window).
        # The knob is NOT a program-cache key: buckets ride their own
        # tagged key family, and the base bundle stays shared with the
        # max-shape path (which is what the bit-parity tests dispatch
        # against).
        from lfm_quant_tpu.buckets import buckets_enabled

        self._bucketed = buckets_enabled()
        if self._bucketed and self._n_seq > 1:
            import warnings

            warnings.warn(
                "LFM_BUCKETS is unsupported under sequence parallelism "
                "(per-shard sub-windows assume the full lookback); "
                "training with max-shape padding", stacklevel=2)
            self._bucketed = False

        # Compiled-program bundle through the cross-fold cache: an equal
        # key binds a previous trainer's jit wrappers (zero re-tracing
        # for same-shape dispatches), a changed key builds fresh ones.
        # Bucketed epochs floor leftover dates per BUCKET, so their step
        # count (and with it the LR-schedule horizon baked into the
        # traced update — hence the key) is the bucketed count.
        steps_per_epoch = (self.train_sampler.bucketed_batches_per_epoch()
                           if self._bucketed
                           else self.train_sampler.batches_per_epoch())
        self._steps_per_epoch = steps_per_epoch
        self.program_key = reuse.trainer_program_key(
            cfg, self.mesh, self._n_seq, self._gather_impl,
            self._eval_gather_impl, self._eval_gather_sharded, self._fp,
            steps_per_epoch)
        self.programs = reuse.get_programs(
            self.program_key,
            lambda: TrainerPrograms(
                cfg, self.mesh, self._n_seq, steps_per_epoch,
                self._gather_impl, self._eval_gather_impl,
                self._eval_gather_sharded, self._fp))
        p = self.programs
        # Bind the bundle's objects (for a cache hit these are the donor
        # trainer's — byte-identical programs by key construction). The
        # donor's mesh becomes canonical so every consumer (batch
        # sharding, state commit, the compiled executables) agrees on
        # one object; it compares equal to the locally-resolved mesh.
        self.mesh = p.mesh
        self.seq_mesh = p.mesh if self._n_seq > 1 else None
        self.model, self.eval_model, self.tx = p.model, p.eval_model, p.tx
        self.loss_fn, self.loss_parts = p.loss_fn, p.loss_parts
        self._eval_sharded = p._eval_sharded
        # Bucketed eval sweeps stay off the month-sharded path: the
        # per-bucket month counts would each need padding to the data
        # axis, eroding exactly the padding the buckets remove. Under a
        # sharded eval mesh, val/predict keep max-shape geometry while
        # TRAIN batches still bucket.
        self._bucketed_eval = self._bucketed and not p._eval_sharded
        self._jit_step = p._jit_step
        self._jit_multi_step = p._jit_multi_step
        self._jit_forward = p._jit_forward
        self._jit_mc_forward = p._jit_mc_forward
        self._jit_predict = p._jit_predict
        self._jit_fwd_det = p._jit_fwd_det
        self._jit_fwd_var = p._jit_fwd_var

    # ---- program delegates -------------------------------------------
    # The un-jitted impls live on TrainerPrograms; these delegates keep
    # the historical Trainer surface (tests and EnsembleTrainer vmap
    # them) pointing at the shared bundle.

    def _apply(self, *args, **kwargs):
        return self.programs._apply(*args, **kwargs)

    def _gather(self, *args, **kwargs):
        return self.programs._gather(*args, **kwargs)

    def _step_impl(self, *args, **kwargs):
        return self.programs._step_impl(*args, **kwargs)

    def _multi_step_impl(self, *args, **kwargs):
        return self.programs._multi_step_impl(*args, **kwargs)

    def _forward_impl(self, *args, **kwargs):
        return self.programs._forward_impl(*args, **kwargs)

    # ---- public API --------------------------------------------------

    def _commit_state(self, state: TrainState) -> TrainState:
        """Re-place a state on the trainer's mesh (replicated). Needed
        after an Orbax restore: restored arrays arrive committed to one
        device, which conflicts with the mesh-replicated panel inside jit
        — true for the data mesh AND the sequence ('seq',) mesh."""
        mesh = self.mesh if self.mesh is not None else self.seq_mesh
        if mesh is None:
            return state
        return jax.device_put(state, replicated(mesh))

    def _warm_state(self, state: TrainState, init_params) -> TrainState:
        return graft_params(state, init_params, self.tx.init,
                            self._commit_state)

    def init_state(self, rng: Optional[jax.Array] = None) -> TrainState:
        if rng is None:
            rng = jax.random.key(self.cfg.seed)
        d = self.cfg.data
        x = jnp.zeros((2, d.window, self.splits.panel.n_features), jnp.float32)
        m = jnp.ones((2, d.window), bool)
        # Under sequence parallelism init with the plain full-window twin:
        # the seq model only traces inside shard_map (its psums need the
        # live axis), and the param trees are identical by contract.
        init_model = (self.eval_model if self.seq_mesh is not None
                      else self.model)
        params = init_model.init(rng, x, m)["params"]
        # Raw uint32 key data (checkpoint-friendly); distinct from the init
        # stream, and per-member under the ensemble's vmapped init.
        state_rng = jax.random.key_data(jax.random.fold_in(rng, 0x0D0))
        return TrainState(params, self.tx.init(params), jnp.asarray(0),
                          state_rng)

    def init_stacked_states(self, seeds: Sequence[int]) -> TrainState:
        """[F]-stacked fresh TrainStates, one independent draw per seed —
        the fold-vectorized walk-forward's init (train/foldstack.py).
        Entry k is bit-identical to what ``init_state()`` produces under
        ``cfg.seed = seeds[k]``: the same ``jax.random.key(seed)`` root,
        the same derived dropout key, the same vmapped optimizer-state
        tree the jitted step's structure contract relies on — so a
        stacked fold starts from exactly the parameters its sequential
        run would."""
        keys = jax.vmap(jax.random.key)(
            jnp.asarray(list(seeds), dtype=jnp.uint32))
        return jax.vmap(self.init_state)(keys)

    def _batch_args(self, b: WindowIndex, train: bool = False,
                    steps: bool = False):
        arrays = (jnp.asarray(b.firm_idx), jnp.asarray(b.time_idx),
                  jnp.asarray(b.weight))
        if train and self.mesh is not None:
            # Training batches shard dates across the mesh; XLA all-reduces
            # the resulting gradients (replicated params) automatically.
            return shard_batch(self.mesh, arrays, steps_axis=steps)
        return arrays

    def _eval_batch_args(self, b: WindowIndex):
        """Host-side prep for the month-sharded eval dispatch: months
        padded to the data-axis size with weight-0 repeats and the arrays
        placed on the mesh. Split out from :meth:`_forward_eval` so a
        benchmark loop can hoist this one-time prep (asarray + pad +
        device_put) OUT of its timed reps — per-rep host prep would tax
        the sharded number with tunnel RTT the replicated path doesn't
        pay."""
        M = b.weight.shape[0]
        fi = jnp.asarray(b.firm_idx)
        ti = jnp.asarray(b.time_idx)
        w = jnp.asarray(b.weight)
        n_data = self.mesh.shape[DATA_AXIS]
        pad = -M % n_data
        if pad:
            rep = lambda a: jnp.concatenate(
                [a] + [a[-1:]] * pad, axis=0)
            fi, ti = rep(fi), rep(ti)
            w = jnp.concatenate([w, jnp.zeros_like(w[-1:])
                                 .repeat(pad, axis=0)], axis=0)
        return shard_batch(self.mesh, (fi, ti, w))

    def _forward_eval(self, params, b: WindowIndex, variance: bool = False):
        """Deterministic eval dispatch for a stacked [M, bf] batch: the
        month-sharded path under a data mesh (months padded to the axis
        size with weight-0 repeats, outputs sliced back), else the plain
        jitted forward. Returns (pred, ic, mse) or (mean, var, None)."""
        M = b.weight.shape[0]
        if not self._eval_sharded:
            return self._jit_forward(params, self.dev, jnp.asarray(b.firm_idx),
                                     jnp.asarray(b.time_idx),
                                     jnp.asarray(b.weight),
                                     variance=variance)
        args = self._eval_batch_args(b)
        if variance:
            mean, var = self._jit_fwd_var(params, self.dev, *args)
            return mean[:M], var[:M], None
        pred, ic, mse = self._jit_fwd_det(params, self.dev, *args)
        return pred[:M], ic[:M], mse

    def evaluate(self, state_params, sampler=None) -> Dict[str, float]:
        """Validation sweep in ONE dispatch: all eval months stacked into a
        single [M, bf] batch (rows = months, so per-month IC comes out of
        the same [D, Bf] code path; month-sharded over the data mesh) —
        and ONE device→host sync: the per-month ICs and the mse scalar
        come back in a single ``jax.device_get`` (the old
        ``np.asarray(ic)`` + ``float(mse)`` pair paid dispatch-path
        latency twice)."""
        sampler = sampler or self.val_sampler
        with telemetry.span("eval", cat="eval"):
            b = sampler.stacked_cross_sections()
            _, ic, mse = self._forward_eval(state_params, b)
            counts = b.weight.sum(axis=1)
            ic, mse = timed_device_get((ic, mse))
        return {
            "ic": float(np.average(ic, weights=counts)),
            "mse": float(mse),
            "n_months": int(counts.size),
        }

    def fit(self, resume: bool = False, init_params=None) -> Dict[str, Any]:
        """Train with early stopping; ``resume=True`` continues from the
        latest per-epoch checkpoint after a crash/preemption (SURVEY.md §6
        "failure detection / recovery": Orbax resume-from-latest — two
        checkpoint lines are kept, ``ckpt/latest`` every epoch for recovery
        and ``ckpt/best`` on val-IC improvement for the final model).

        ``init_params``: start from these params instead of a fresh init —
        the walk-forward warm start (optimizer state and step counter are
        fresh either way; a crash resume takes precedence since the latest
        checkpoint already embodies the warm start).

        The epoch loop runs through the async pipeline driver
        (train/pipeline.py, ``LFM_ASYNC`` / ``LFM_ASYNC_CKPT`` knobs):
        each epoch is ONE multi-step dispatch with the validation sweep
        chained on the same stream, all scalars fetched in one
        ``jax.device_get``, the next epoch's batches prefetched and
        dispatched before this epoch's metrics sync, and checkpoints
        saved asynchronously from a host-fetched copy. The lock-step
        reference path (``LFM_ASYNC=0``) is numerically identical —
        including after an early stop that strands a speculative
        lookahead epoch: the driver rolls the state back to the last
        RECORDED epoch's snapshot, so predict/warm-start consumers see
        the same state in either mode (and with a run dir, finalize
        restores the best checkpoint on top, exactly as before)."""
        with telemetry.span("fit", cat="fit", kind="trainer") as sp:
            out = self._fit_impl(resume, init_params)
            sp.set(epochs_run=out["epochs_run"],
                   best_epoch=out["best_epoch"])
            return out

    def _fit_impl(self, resume: bool, init_params) -> Dict[str, Any]:
        from lfm_quant_tpu.train import pipeline

        cfg = self.cfg
        if cfg.optim.epochs < 1:
            raise ValueError(f"epochs must be >= 1, got {cfg.optim.epochs}")
        state = self.init_state()
        if init_params is not None:
            state = self._warm_state(state, init_params)
        harness = FitHarness(self.run_dir, cfg.optim.epochs,
                             cfg.optim.early_stop_patience,
                             self._steps_per_epoch)
        if resume:
            restored = harness.resume(state._asdict())
            if restored is not None:
                state = self._commit_state(TrainState(**restored))
        logger = MetricsLogger(self.run_dir, echo=self.echo)
        timer = StepTimer()
        history = []

        # Hoisted epoch-invariant val-sweep prep: the stacked eval batch
        # (and, under a mesh, its padded device placement) is identical
        # every epoch — building it per epoch was pure host overhead on
        # the critical path.
        if self._bucketed_eval:
            # Bucketed val sweep (LFM_BUCKETS): one hoisted batch + one
            # compiled forward per (lookback × width) bucket; per-month
            # ICs scatter back to the stacked month order through the
            # buckets' position arrays, so ``finish`` aggregates exactly
            # the values the max-shape sweep would produce (per-month
            # parity is the bit-identity contract; mse recombines as
            # Σ se / Σ ws via the host-known per-bucket weights).
            vparts = self.val_sampler.bucketed_cross_sections()
            n_val = sum(pos.size for _, _, pos in vparts)
            counts = np.zeros(n_val, np.float32)
            vhoist = []
            for bucket, b, pos in vparts:
                counts[pos] = b.weight.sum(axis=1)
                bp = self.programs.bucket_programs(self.program_key, bucket)
                vhoist.append((bp,
                               (jnp.asarray(b.firm_idx),
                                jnp.asarray(b.time_idx),
                                jnp.asarray(b.weight)),
                               jnp.asarray(pos), float(b.weight.sum())))
            w_total = max(sum(h[3] for h in vhoist), 1e-12)

            def val_dispatch(params):
                ic = jnp.zeros((n_val,), jnp.float32)
                mse = jnp.zeros((), jnp.float32)
                for bp, vargs, pos, wsum in vhoist:
                    _, ic_b, mse_b = bp._jit_forward(params, self.dev,
                                                     *vargs)
                    ic = ic.at[pos].set(ic_b.astype(jnp.float32))
                    mse = mse + mse_b.astype(jnp.float32) * (wsum / w_total)
                return ic, mse
        else:
            vb = self.val_sampler.stacked_cross_sections()
            counts = vb.weight.sum(axis=1)
            if self._eval_sharded:
                vargs = self._eval_batch_args(vb)
                n_val = vb.weight.shape[0]

                def val_dispatch(params):
                    _, ic, mse = self._jit_fwd_det(params, self.dev, *vargs)
                    return ic[:n_val], mse
            else:
                vargs = (jnp.asarray(vb.firm_idx), jnp.asarray(vb.time_idx),
                         jnp.asarray(vb.weight))

                def val_dispatch(params):
                    _, ic, mse = self._jit_forward(params, self.dev, *vargs)
                    return ic, mse

        if self._bucketed:
            # Bucketed epoch supply: per-bucket [K_b, D, w_b] stacks on
            # an epoch-invariant ladder, one donating multi-step dispatch
            # per bucket chained on the same stream (the state is
            # consumed linearly, so donation holds across the chain).
            geo = self.train_sampler.bucket_geometry()
            bprogs = {bucket: self.programs.bucket_programs(
                          self.program_key, bucket)
                      for bucket in geo.train_buckets}
            telemetry.instant(
                "bucket_geometry", cat="bucket",
                steps_per_epoch=self._steps_per_epoch,
                **geo.summary(cfg.data.dates_per_batch))
            k_total = float(max(1, self._steps_per_epoch))

            def build(epoch):
                with telemetry.span("sample", epoch=epoch):
                    parts = self.train_sampler.bucketed_epoch(epoch)
                    fm = disp = real = mx = 0.0
                    for (lb, w), b in parts:
                        sl = float(b.weight.sum())
                        k, dd = b.firm_idx.shape[:2]
                        fm += sl * lb
                        disp += k * dd * w * lb
                        real += sl * lb
                        mx += (k * dd * self.train_sampler.firms_per_date
                               * self.window)
                    # Padded-FLOP accounting (locked bumps — the build
                    # runs on the prefetch thread under LFM_ASYNC).
                    telemetry.COUNTERS.bump("bucket_dispatches",
                                            len(parts))
                    telemetry.COUNTERS.bump("bucket_cells_dispatched",
                                            int(disp))
                    telemetry.COUNTERS.bump("bucket_cells_real", int(real))
                    telemetry.COUNTERS.bump("bucket_cells_max_shape",
                                            int(mx))
                with telemetry.span("h2d", epoch=epoch):
                    args = [(bkt, self._batch_args(b, train=True,
                                                   steps=True))
                            for bkt, b in parts]
                return args, fm

            def dispatch(state, parts):
                loss = jnp.zeros((), jnp.float32)
                gnorm = jnp.zeros((), jnp.float32)
                for bucket, args in parts:
                    state, ms = bprogs[bucket]._jit_multi_step(
                        state, self.dev, *args)
                    loss = loss + ms["loss"].astype(jnp.float32).sum()
                    gnorm = gnorm + ms["grad_norm"].astype(jnp.float32).sum()
                ic, mse = val_dispatch(state.params)
                return state, {"loss": loss / k_total,
                               "grad_norm": gnorm / k_total,
                               "ic": ic, "mse": mse,
                               "step": jnp.copy(state.step)}
        else:
            def build(epoch):
                # Whole epoch as one [K, D, Bf] index stack; firm-months
                # are known on the host before any device work. The two
                # spans split host sampling from H2D staging (they emit
                # on the prefetch thread under LFM_ASYNC).
                with telemetry.span("sample", epoch=epoch):
                    b = self.train_sampler.stacked_epoch(epoch)
                    fm = float(b.weight.sum()) * self.window
                with telemetry.span("h2d", epoch=epoch):
                    args = self._batch_args(b, train=True, steps=True)
                return args, fm

            def dispatch(state, args):
                # Train epoch + chained validation sweep on one stream;
                # no host round-trip here — the driver fetches ``vals``
                # in a single device_get when the epoch settles.
                state, ms = self._jit_multi_step(state, self.dev, *args)
                ic, mse = val_dispatch(state.params)
                # step is COPIED out of the state: the lookahead dispatch
                # donates every state leaf, and a fetched scalar must not
                # alias a donated buffer.
                return state, {"loss": ms["loss"].mean(),
                               "grad_norm": ms["grad_norm"].mean(),
                               "ic": ic, "mse": mse,
                               "step": jnp.copy(state.step)}

        def finish(epoch, host, fm):
            val_ic = float(np.average(host["ic"], weights=counts))
            step = int(host["step"])
            rec = logger.log(
                step,
                epoch=epoch,
                train_loss=float(host["loss"]),
                grad_norm=float(host["grad_norm"]),
                val_ic=val_ic,
                val_mse=float(host["mse"]),
                firm_months_per_sec=timer.throughput(),
            )
            history.append(rec)
            return step, val_ic

        try:
            state, overrun = pipeline.run_fit_epochs(
                harness, state, build=build, dispatch=dispatch,
                finish=finish, timer=timer,
                checkpointing=self.run_dir is not None)
        except pipeline.preempt.Preempted:
            # SIGTERM grace stop: everything recorded is durable (the
            # driver ran preempt_flush); flush the metrics stream and
            # let the preemption propagate to the entry point (exit 75
            # → re-run with --resume continues with identical history).
            logger.close()
            raise

        # Restore best state for downstream prediction/backtest.
        best = harness.finalize(state._asdict())
        if best is not None:
            state = self._commit_state(TrainState(**best))
        logger.close()
        self.state = state
        return {
            "best_val_ic": harness.best_ic,
            "best_epoch": harness.best_epoch,
            "epochs_run": harness.last_epoch + 1,
            "steps": (harness.last_epoch + 1) * harness.steps_per_epoch,
            "firm_months_per_sec": timer.throughput(),
            "lookahead_overrun": overrun is not None,
            "history": history,
        }

    def predict(self, split: str = "test", mc_samples: int = 0,
                mc_seed: int = 0, date_range: Optional[Tuple[int, int]] = None,
                return_variance: bool = False, require_target: bool = True,
                mc_batched: Optional[bool] = None):
        """Forecasts for every eligible anchor in a split's date range.

        Returns (forecast [N, T] float32, pred_valid [N, T] bool) over the
        FULL panel shape, with pred_valid True only inside the split range —
        the backtest engine's input (SURVEY.md §4.3).

        ``return_variance=True`` (heteroscedastic models only, not
        combinable with ``mc_samples``) returns
        (forecast, aleatoric_variance [N, T], pred_valid) — the per-firm
        predicted noise level the uncertainty-aware aggregation consumes
        (``aggregate_ensemble(mode="mean_minus_total_std")``).

        ``mc_samples > 0`` switches to **MC-dropout sampling** (the
        uncertainty-aware LFM lineage's single-model alternative to deep
        ensembles, SURVEY.md §1 [BACKGROUND]): the eval forward runs with
        dropout live under K independent keys, returning stacked
        forecasts ``[K, N, T]`` shaped exactly like
        ``EnsembleTrainer.predict`` so ``aggregate_ensemble`` (mean /
        mean−λ·std) consumes either. Requires a model with dropout > 0.
        By default all K samples run as ONE vmapped dispatch with ONE
        device→host copy (the key array is the vmapped axis);
        ``mc_batched=False`` — or ``LFM_MC_BATCHED=0`` — keeps the
        per-sample dispatch loop (the A/B baseline, and the escape hatch
        for gathers whose batching rule can't ride an extra vmap axis).
        Both paths scatter the stacked ``[K, M, bf]`` result into the
        panel in a single vectorized assignment.

        ``date_range`` (month-INDEX pair, end-exclusive) overrides the
        split's anchor range — the walk-forward harness predicts each
        fold's bounded out-of-sample block with it.

        ``require_target=False`` forecasts LIVE anchors too — months whose
        realized outcome is not (yet) observable, which the default
        eligibility excludes. The forecast.py CLI's path: the last
        ``horizon`` months of the panel are exactly the rankings a
        production user trades on.
        """
        d = self.cfg.data
        panel = self.splits.panel
        if mc_samples > 0 and not self.cfg.model.kwargs.get("dropout", 0.0):
            raise ValueError(
                "mc_samples > 0 needs a model with dropout > 0 "
                "(ModelConfig.kwargs['dropout']); this run has none, so "
                "every sample would be identical")
        sampler = DateBatchSampler(
            panel, d.window, 1, d.firms_per_date, seed=0,
            min_valid_months=d.min_valid_months, min_cross_section=1,
            date_range=date_range or self.splits.range_of(split),
            require_target=require_target,
        )
        if (self._bucketed and not self._eval_sharded and mc_samples == 0
                and not return_variance):
            # Bucketed batch scoring (LFM_BUCKETS): one forecast-only
            # dispatch per (lookback × width) bucket, scattered straight
            # into the panel — results BIT-identical to the max-shape
            # sweep for the same params (pure inference; the ``bucketed``
            # lane pins it), with the thin months' pad columns and the
            # short-history cohort's dead scan steps compiled out.
            out = np.zeros((panel.n_firms, panel.n_months), np.float32)
            out_valid = np.zeros((panel.n_firms, panel.n_months), bool)
            for bucket, b, _pos in sampler.bucketed_cross_sections():
                bp = self.programs.bucket_programs(self.program_key, bucket)
                pred, _, _ = bp._jit_predict(
                    self.state.params, self.dev, jnp.asarray(b.firm_idx),
                    jnp.asarray(b.time_idx), jnp.asarray(b.weight))
                real = b.weight > 0
                rows = b.firm_idx[real]
                cols = np.broadcast_to(b.time_idx[:, None],
                                       b.firm_idx.shape)[real]
                out[rows, cols] = np.asarray(pred)[real]
                out_valid[rows, cols] = True
            return out, out_valid
        out_valid = np.zeros((panel.n_firms, panel.n_months), bool)
        b = sampler.stacked_cross_sections()
        real = b.weight > 0  # [M, bf]
        rows = b.firm_idx[real]
        cols = np.broadcast_to(b.time_idx[:, None], b.firm_idx.shape)[real]
        out_valid[rows, cols] = True

        if mc_samples > 0:
            if return_variance:
                raise ValueError(
                    "return_variance is not combinable with mc_samples — "
                    "MC sampling already carries the uncertainty")
            if mc_batched is None:
                mc_batched = os.environ.get("LFM_MC_BATCHED", "1") != "0"
            fi, ti, w = self._batch_args(b)
            key = jax.random.key(mc_seed)
            if mc_batched:
                # ONE dispatch: per-chunk gather shared by all samples,
                # model apply vmapped over the stacked key array (keys
                # derived exactly like the loop path, so replay is
                # seed-stable either way), ONE D2H of [K, M, bf].
                keys = jax.vmap(lambda i: jax.random.fold_in(key, i))(
                    jnp.arange(mc_samples))
                pred = np.asarray(self._jit_mc_forward(
                    self.state.params, self.dev, fi, ti, keys))
            else:
                # Fallback loop: one dispatch per sample (the 6-arg rng
                # signature of the shared eval forward), stacked on host.
                pred = np.stack([
                    np.asarray(self._jit_forward(
                        self.state.params, self.dev, fi, ti, w,
                        jax.random.fold_in(key, k))[0])
                    for k in range(mc_samples)])
            # Single vectorized scatter for the whole [K, M, bf] stack —
            # the per-sample fancy-indexing loop this replaces rewrote
            # rows/cols K times over.
            out = np.zeros((mc_samples, panel.n_firms, panel.n_months),
                           np.float32)
            out[:, rows, cols] = pred[:, real]
            return out, out_valid

        out = np.zeros((panel.n_firms, panel.n_months), np.float32)
        if return_variance:
            var_out = np.zeros_like(out)
            pred, var, _ = self._forward_eval(self.state.params, b,
                                              variance=True)
            out[rows, cols] = np.asarray(pred)[real]
            var_out[rows, cols] = np.asarray(var)[real]
            return out, var_out, out_valid
        if self._eval_sharded:
            # Month-sharded path keeps the shared det program (its psum
            # structure is part of the sharded executable).
            pred, _, _ = self._forward_eval(self.state.params, b)
        else:
            # Forecast-only dispatch: per-month metrics compiled out.
            pred, _, _ = self._jit_predict(
                self.state.params, self.dev, jnp.asarray(b.firm_idx),
                jnp.asarray(b.time_idx), jnp.asarray(b.weight))
        out[rows, cols] = np.asarray(pred)[real]
        return out, out_valid


def resolve_panel(d) -> Panel:
    """DataConfig → Panel: saved .npz dir, CSV/parquet (Compustat-style
    long format via data/compustat.py), or the synthetic generator —
    plus any configured derived feature columns (data/features.py)."""
    from lfm_quant_tpu.data.panel import load_panel, synthetic_panel

    if d.panel_path:
        if d.panel_path.endswith((".csv", ".parquet", ".pq")):
            from lfm_quant_tpu.data.compustat import load_compustat_csv

            panel = load_compustat_csv(d.panel_path, horizon=d.horizon,
                                       target_col=d.target_col)
        else:
            panel = load_panel(d.panel_path)
    else:
        panel = synthetic_panel(
            n_firms=d.n_firms, n_months=d.n_months, n_features=d.n_features,
            start_yyyymm=d.start_yyyymm, horizon=d.horizon, seed=d.panel_seed,
            het_noise=d.het_noise,
        )
    if getattr(d, "derived_features", ()):
        from lfm_quant_tpu.data.features import add_derived_features

        panel = add_derived_features(panel, d.derived_features)
    return panel


def default_split_dates(panel: Panel, d) -> Tuple[int, int]:
    """The default (train_end, val_end) boundaries for a DataConfig:
    the configured dates when set, else the 70%/85% panel quantiles —
    THE single copy of the policy every entry point (single fit,
    ensemble, loaders, config sweep) derives its splits from, so none
    can silently diverge from the fit it is compared against."""
    dates = panel.dates
    train_end = d.train_end or int(dates[int(len(dates) * 0.7)])
    val_end = d.val_end or int(dates[int(len(dates) * 0.85)])
    return train_end, val_end


def run_experiment(cfg: RunConfig, panel: Optional[Panel] = None,
                   echo: bool = False, resume: bool = False
                   ) -> Tuple[Dict[str, Any], "Trainer", PanelSplits]:
    """Config → panel → splits → train; returns (summary, trainer, splits)
    — the train.py call stack, SURVEY.md §4.1."""
    d = cfg.data
    if panel is None:
        panel = resolve_panel(d)
    train_end, val_end = default_split_dates(panel, d)
    splits = PanelSplits.by_date(panel, train_end, val_end,
                                 train_start=d.train_start)

    run_dir = os.path.join(cfg.out_dir, cfg.name, f"seed{cfg.seed}")
    trainer = Trainer(cfg, splits, run_dir=run_dir, echo=echo)
    summary = trainer.fit(resume=resume)
    summary["run_dir"] = run_dir
    summary["config"] = dataclasses.asdict(cfg)
    os.makedirs(run_dir, exist_ok=True)
    with open(os.path.join(run_dir, "config.json"), "w") as fh:
        fh.write(cfg.to_json())
    with open(os.path.join(run_dir, "summary.json"), "w") as fh:
        json.dump({k: v for k, v in summary.items() if k != "history"}, fh,
                  indent=2, default=str)
    return summary, trainer, splits


def load_trainer(run_dir: str, panel: Optional[Panel] = None):
    """Rebuild a Trainer from a run directory and restore its best
    checkpoint (the backtest.py call stack, SURVEY.md §4.3)."""
    with open(os.path.join(run_dir, "config.json")) as fh:
        cfg = RunConfig.from_json(fh.read())
    d = cfg.data
    if panel is None:
        panel = resolve_panel(d)
    train_end, val_end = default_split_dates(panel, d)
    splits = PanelSplits.by_date(panel, train_end, val_end,
                                 train_start=d.train_start)
    trainer = Trainer(cfg, splits, run_dir=run_dir)
    state = trainer.init_state()
    ckpt = CheckpointManager(os.path.join(run_dir, "ckpt", "best"))
    restored = restore_state_dict(ckpt, state._asdict())
    ckpt.close()
    trainer.state = trainer._commit_state(TrainState(**restored))
    return trainer, splits
