"""Multi-seed ensemble trainer (L5) — the reference's signature axis.

Parity target: the reference's multi-seed ensemble trainer — N independent
seeds of the same model, per-GPU replication under ``tf.distribute``
(SURVEY.md §3; BASELINE.json:5,11 — 64-seed LSTM ensemble on the full
panel). TPU-native re-expression (prescribed at BASELINE.json:5):

* Seeds become a LEADING AXIS of one stacked train state:
  ``params[s, ...], opt_state[s, ...]`` — built by ``vmap(init)`` over 64
  PRNG keys, stepped by ``vmap``-ing the single-seed train step. One XLA
  program trains all 64 members; on a v5e-64 the seed axis shards one
  member per chip over the mesh's 'seed' axis, composing with the 'data'
  axis for batch parallelism (SURVEY.md §8 step 9).
* Ensemble diversity: each member gets BOTH its own init key and its own
  data order — per-seed ``DateBatchSampler`` seeds (host-side index
  generation is cheap; the [S, D, Bf] index stack is the only per-step
  host→device traffic). This answers SURVEY.md §8's "hard part": per-seed
  PRNG folds, not one shared iterator.
* Checkpoints: ONE stacked PyTree (leading seed axis) via Orbax, so the
  whole ensemble restores in a single read (SURVEY.md §6).
* Early stopping on the ENSEMBLE-MEAN validation IC: members advance in
  lock-step (that is what makes the wall-clock target meaningful);
  per-member histories are logged for diagnosis.
"""

from __future__ import annotations

import dataclasses
import functools
import json
import os
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from lfm_quant_tpu.config import RunConfig
from lfm_quant_tpu.data.panel import Panel, PanelSplits
from lfm_quant_tpu.data.windows import DateBatchSampler
from lfm_quant_tpu.parallel import (
    DATA_AXIS,
    SEED_AXIS,
    SEQ_AXIS,
    make_mesh,
    shard_batch,
    state_sharding,
)
from lfm_quant_tpu.train.checkpoint import CheckpointManager
from lfm_quant_tpu.train.loop import (
    _KEEP,
    FitHarness,
    TrainState,
    Trainer,
    restore_state_dict,
)
from lfm_quant_tpu.utils import telemetry
from lfm_quant_tpu.utils.logging import MetricsLogger
from lfm_quant_tpu.utils.profiling import StepTimer


class EnsemblePrograms:
    """Seed-vmapped twin of ``TrainerPrograms`` (train/loop.py): the
    ensemble's jitted step/multi-step/forward wrappers, hoisted out of
    per-instance construction into the cross-fold program cache
    (reuse.ensemble_program_key = inner trainer key + seed-stack
    geometry). Holds only the inner program bundle and the vmapped
    wrappers — no panel, samplers, or TrainState — so cache entries stay
    lightweight and fold k+1's EnsembleTrainer binds fold k's
    executables."""

    def __init__(self, inner, mesh, n_seeds: int, seed_block: int):
        from lfm_quant_tpu.train.reuse import ledger_jit

        self.inner = inner  # TrainerPrograms
        self.mesh = mesh
        self.n_seeds = n_seeds
        self.seed_block = seed_block
        self._n_seq = inner._n_seq
        # Geometry-bucket twins (LFM_BUCKETS), memoized per bundle —
        # same pattern as TrainerPrograms._bucket_programs.
        self._bucket_programs: Dict[Tuple[int, int],
                                    "EnsembleBucketPrograms"] = {}

        # vmap the single-seed impls over the stacked state + index batch
        # (device panel broadcast, in_axes=None); under a mesh, shard_map
        # the vmapped step over (seed × data) — each shard trains its local
        # seed block on its local dates with Pallas kernels intact, psum
        # over 'data' only (seeds are independent).
        # Donate the stacked TrainState on the multi-step (whole-epoch)
        # wrapper: at 64-seed scale the un-donated dispatch double-buffers
        # seeds × (params + both Adam moments) in HBM — see
        # train/reuse.py multi_step_donate_argnums (LFM_DONATE=0 off).
        from lfm_quant_tpu.train.reuse import multi_step_donate_argnums

        donate = multi_step_donate_argnums()
        if mesh is None:
            self._vstep = jax.vmap(
                inner._step_impl, in_axes=(0, None, 0, 0, 0))
            self._jit_step = ledger_jit("ens_step", self._step_shards)
            self._jit_multi_step = ledger_jit(
                "ens_multi_step", self._multi_step_impl,
                donate_argnums=donate)
        else:
            # Batch psums cover the data axis and, when present, the seq
            # axis (per-shard sub-window gradients sum to the full-window
            # gradient; the loss num/den seq duplication cancels —
            # train/loop.py _shard_mapped has the argument).
            step_axes = ((DATA_AXIS, SEQ_AXIS) if self._n_seq > 1
                         else (DATA_AXIS,))
            self._vstep = jax.vmap(
                functools.partial(inner._step_impl, axis=step_axes),
                in_axes=(0, None, 0, 0, 0))
            self._jit_step = ledger_jit(
                "ens_step",
                self._shard_mapped(self._step_shards, steps_axis=False))
            self._jit_multi_step = ledger_jit(
                "ens_multi_step",
                self._shard_mapped(self._multi_step_impl, steps_axis=True),
                donate_argnums=donate)
        self._jit_forward = ledger_jit(
            "ens_forward",
            jax.vmap(inner._forward_impl, in_axes=(0, None, None, None, None)))
        # Forecast-only twin: predict() consumes nothing but the scores,
        # so the sweep skips S × M per-month rank-IC/MSE sorts inside the
        # dispatch (the one-dispatch analog of the batched MC path).
        self._jit_predict = ledger_jit(
            "ens_predict",
            jax.vmap(functools.partial(inner._forward_impl,
                                       scores_only=True),
                     in_axes=(0, None, None, None, None)))
        # Heteroscedastic twin: per-seed (mean, aleatoric variance) for
        # the uncertainty-aware aggregation (mean_minus_total_std).
        self._jit_forward_var = ledger_jit(
            "ens_forward_var",
            jax.vmap(functools.partial(inner._forward_impl, variance=True),
                     in_axes=(0, None, None, None, None)))

    def _step_shards(self, state, dev, fi, ti, w):
        """One ensemble step over the LOCAL seed stack (the whole stack
        off-mesh; the shard's block under shard_map).

        With ``seed_block`` set, the local stack is stepped in blocks via
        ``lax.scan`` (train/stacked.py ``scan_in_blocks`` — the shared
        microbatching the stacked-run engine applies one axis up with
        ``LFM_STACK_BLOCK``) — peak activation memory drops from
        all-local-seeds × per-seed to seed_block × per-seed (params/opt
        stay resident either way), which is what lets a 64-seed c5 train
        on a single chip when the vmapped backward doesn't fit HBM.
        Seeds are independent, so blocking is numerically a pure
        re-batching. Construction validates divisibility, so the
        helper's silent non-divisor fallback is unreachable here."""
        from lfm_quant_tpu.train.stacked import scan_in_blocks

        return scan_in_blocks(
            lambda st, f, t, ww: self._vstep(st, dev, f, t, ww),
            self.seed_block, (state, fi, ti, w))

    def _shard_mapped(self, impl, steps_axis: bool):
        """shard_map an ensemble step over (seed × data): the stacked
        state shards its leading seed axis; [.., S, D, Bf] index batches
        shard seed and date axes; the panel replicates. out_specs mark the
        state seed-sharded and (implicitly) data-replicated — true because
        the psum'd gradients make every data-shard's update identical
        (check_vma=False: replication is mathematical, not provable)."""
        from jax.sharding import PartitionSpec as P

        from lfm_quant_tpu.parallel.mesh import shard_map_compat

        batch = (P(None, SEED_AXIS, DATA_AXIS) if steps_axis
                 else P(SEED_AXIS, DATA_AXIS))
        metrics = P(None, SEED_AXIS) if steps_axis else P(SEED_AXIS)
        return shard_map_compat(
            impl,
            mesh=self.mesh,
            in_specs=(P(SEED_AXIS), P(), batch, batch, batch),
            out_specs=(P(SEED_AXIS), metrics),
            check_vma=False,
        )

    def _multi_step_impl(self, state: TrainState, dev: dict, fi, ti, w):
        """K vmapped ensemble steps in one dispatch: lax.scan over a
        [K, S, D, Bf] index stack (see Trainer._multi_step_impl)."""
        def body(st, batch):
            return self._step_shards(st, dev, *batch)

        return jax.lax.scan(body, state, (fi, ti, w))

    def bucket_programs(self, inner_key: Tuple,
                        bucket: Tuple[int, int]) -> "EnsembleBucketPrograms":
        """The bucket's seed-vmapped program twins through the program
        cache (``reuse.train_bucket_program_key`` over the ENSEMBLE
        key, so single-seed and ensemble bucket programs can never
        collide) — see ``TrainerPrograms.bucket_programs``."""
        bp = self._bucket_programs.get(bucket)
        if bp is None:
            from lfm_quant_tpu.train import reuse

            bp = reuse.get_programs(
                reuse.train_bucket_program_key(inner_key, bucket),
                lambda: EnsembleBucketPrograms(self, bucket))
            self._bucket_programs[bucket] = bp
        return bp


class EnsembleBucketPrograms:
    """Per-(lookback × width) seed-vmapped twins of the ensemble's
    multi-step / forward / predict programs (``LFM_BUCKETS``) — the
    ensemble analog of ``train/loop.py BucketPrograms``: the lookback
    rung is bound into the gather as a static constant, the width rides
    on the batch aval, everything else is the parent bundles' shared
    impls (bit-parity with max-shape padding, per seed). Bucketing is
    rejected under sequence parallelism upstream, so the step axis here
    is at most 'data'."""

    def __init__(self, ens: EnsemblePrograms, bucket: Tuple[int, int]):
        from lfm_quant_tpu.train.reuse import (ledger_jit,
                                               multi_step_donate_argnums)
        from lfm_quant_tpu.train.stacked import scan_in_blocks

        inner = ens.inner
        self.bucket = bucket
        lookback, width = bucket
        tag = f"b{lookback}x{width}"
        donate = multi_step_donate_argnums()
        step_kw = {"window": lookback}
        if ens.mesh is not None:
            step_kw["axis"] = (DATA_AXIS,)
        vstep = jax.vmap(functools.partial(inner._step_impl, **step_kw),
                         in_axes=(0, None, 0, 0, 0))

        def multi(state, dev, fi, ti, w):
            def body(st, batch):
                f, t, ww = batch
                return scan_in_blocks(
                    lambda s_, f_, t_, w_: vstep(s_, dev, f_, t_, w_),
                    ens.seed_block, (st, f, t, ww))

            return jax.lax.scan(body, state, (fi, ti, w))

        if ens.mesh is None:
            self._jit_multi_step = ledger_jit(
                f"ens_multi_step@{tag}", multi, donate_argnums=donate)
        else:
            self._jit_multi_step = ledger_jit(
                f"ens_multi_step@{tag}",
                ens._shard_mapped(multi, steps_axis=True),
                donate_argnums=donate)
        self._jit_forward = ledger_jit(
            f"ens_forward@{tag}",
            jax.vmap(functools.partial(inner._forward_impl,
                                       window=lookback),
                     in_axes=(0, None, None, None, None)))
        self._jit_predict = ledger_jit(
            f"ens_predict@{tag}",
            jax.vmap(functools.partial(inner._forward_impl,
                                       scores_only=True, window=lookback),
                     in_axes=(0, None, None, None, None)))


class EnsembleTrainer:
    """Trains ``cfg.n_seeds`` members as one vmapped, seed-sharded
    program. Like the single-seed Trainer, the jitted wrappers live on a
    cached :class:`EnsemblePrograms` bundle so walk-forward folds (and
    ``rebind``) reuse executables instead of recompiling."""

    def __init__(self, cfg: RunConfig, splits: PanelSplits,
                 run_dir: Optional[str] = None, echo: bool = False):
        self._setup(cfg, splits, run_dir, echo)

    def rebind(self, cfg: Optional[RunConfig] = None,
               splits: Optional[PanelSplits] = None,
               run_dir: Any = _KEEP,
               echo: Optional[bool] = None) -> "EnsembleTrainer":
        """Re-initialize for the next walk-forward fold: fresh per-seed
        sampler orders, new split boundaries/run dir, stacked TrainState
        dropped — without rebuilding the vmapped jit wrappers when the
        program key is unchanged (see Trainer.rebind; an omitted
        ``run_dir`` keeps the previous one, explicit None drops it).
        Returns self."""
        self._setup(cfg if cfg is not None else self.cfg,
                    splits if splits is not None else self.splits,
                    self.run_dir if run_dir is _KEEP else run_dir,
                    self.echo if echo is None else echo)
        return self

    def _setup(self, cfg: RunConfig, splits: PanelSplits,
               run_dir: Optional[str], echo: bool) -> None:
        from lfm_quant_tpu.train import reuse

        if cfg.n_seeds < 2:
            raise ValueError("EnsembleTrainer needs n_seeds >= 2")
        self.cfg = cfg
        self.splits = splits
        self.run_dir = run_dir
        self.echo = echo
        self.state = None
        self.n_seeds = cfg.n_seeds

        # Mesh FIRST: seed axis as large as divides both n_seeds and the
        # device count; data axis from config when devices remain; then a
        # seq axis from what's left (n_seq_shards > 1 — the full
        # seed × data × seq composition; each degrades gracefully). The
        # inner Trainer then resolves model / gather / panel exactly once
        # against this mesh (no post-hoc attribute surgery).
        n_dev = jax.device_count()
        n_seed_mesh = 1
        for cand in range(min(self.n_seeds, n_dev), 0, -1):
            if self.n_seeds % cand == 0 and n_dev % cand == 0:
                n_seed_mesh = cand
                break
        n_data = max(1, min(cfg.n_data_shards, n_dev // n_seed_mesh))
        self._n_seq = 1
        if cfg.n_seq_shards > 1:
            # Seeds are the workload's signature axis; seq takes only the
            # devices left over (degrading to 1 = plain full-window
            # training — the shared contract in resolve_seq_shards).
            from lfm_quant_tpu.parallel.mesh import resolve_seq_shards

            self._n_seq = resolve_seq_shards(
                cfg.n_seq_shards, n_dev // (n_seed_mesh * n_data))
        self.mesh = (
            make_mesh(n_seed_mesh, n_data, n_seq=self._n_seq)
            if n_seed_mesh * n_data * self._n_seq > 1 else None
        )

        self.seed_block = int(getattr(cfg, "seed_block", 0) or 0)
        if self.seed_block < 0:
            raise ValueError(f"seed_block must be >= 0, got {self.seed_block}")
        local_seeds = self.n_seeds // n_seed_mesh
        # A block >= the per-shard count is a no-op (the step degrades to
        # the unblocked vmap), NOT an error: a config tuned for one chip
        # (e.g. seed_block=8 at 64 local seeds) must stay loadable on a
        # wider seed mesh where local_seeds shrinks below the block.
        if (0 < self.seed_block < local_seeds
                and local_seeds % self.seed_block):
            raise ValueError(
                f"seed_block={self.seed_block} must divide the per-shard "
                f"seed count {local_seeds} (n_seeds={self.n_seeds} over a "
                f"{n_seed_mesh}-wide seed mesh)")

        # The single-seed Trainer provides the model, loss, optimizer,
        # jit-free step/forward impls that we vmap, AND the HBM-resident
        # panel (ONE copy serves ensemble + inner: PanelSplits are anchor
        # ranges over a shared panel, not slices). Under the mesh its
        # train model keeps the Pallas kernels (the step runs inside
        # shard_map below) while its eval model/gather are GSPMD-safe.
        self.inner = Trainer(cfg, splits, run_dir=None, mesh=self.mesh)
        self.window = self.inner.window
        self.dev = self.inner.dev
        # Precision lane (DESIGN.md §17): the seed stack rides the inner
        # trainer's resolution — one bf16 resident panel shared by all
        # seeds, f32 master params per member (vmapped init preserves
        # leaf dtypes), f32 moments, f32 IC/loss reductions.
        self._compute_dtype = self.inner._compute_dtype
        # Geometry-bucket mode rides the inner trainer's resolution
        # (LFM_BUCKETS; rejected under a live seq axis there). The
        # ensemble's GSPMD eval forward has no month-sharded variant, so
        # no extra eval gating is needed here.
        self._bucketed = self.inner._bucketed

        d = cfg.data
        self.samplers = [
            DateBatchSampler(
                splits.panel, d.window, d.dates_per_batch, d.firms_per_date,
                seed=cfg.seed + s, min_valid_months=d.min_valid_months,
                date_range=splits.train_range, engine=d.sampler_engine,
            )
            for s in range(self.n_seeds)
        ]
        self.val_sampler = self.inner.val_sampler

        # Vmapped/jitted wrappers through the cross-fold program cache:
        # key = inner trainer key + seed-stack geometry. A hit binds the
        # previous fold's executables; a changed n_seeds/seed_block (or
        # any inner-key change) builds fresh — never stale reuse.
        self.program_key = reuse.ensemble_program_key(
            self.inner.program_key, self.mesh, self.n_seeds,
            self.seed_block)
        self.programs = reuse.get_programs(
            self.program_key,
            lambda: EnsemblePrograms(self.inner.programs, self.mesh,
                                     self.n_seeds, self.seed_block))
        p = self.programs
        self.mesh = p.mesh  # canonical (donor's; compares equal)
        self._jit_step = p._jit_step
        self._jit_multi_step = p._jit_multi_step
        self._jit_forward = p._jit_forward
        self._jit_predict = p._jit_predict
        self._jit_forward_var = p._jit_forward_var

    # ---- program delegates (back-compat; see Trainer's) --------------

    @property
    def _vstep(self):
        return self.programs._vstep

    def _step_shards(self, *args, **kwargs):
        return self.programs._step_shards(*args, **kwargs)

    def _multi_step_impl(self, *args, **kwargs):
        return self.programs._multi_step_impl(*args, **kwargs)

    # ---- state -------------------------------------------------------

    def init_state(self) -> TrainState:
        keys = jax.random.split(jax.random.key(self.cfg.seed), self.n_seeds)
        state = jax.vmap(self.inner.init_state)(keys)
        return self._commit_state(state)

    def init_stacked_states(self, seeds) -> TrainState:
        """[F, S]-stacked fresh ensemble TrainStates for the
        fold-vectorized walk-forward (train/foldstack.py): fold k's seed
        block is bit-identical to ``init_state()`` under
        ``cfg.seed = seeds[k]`` — the same root-key split into
        ``n_seeds`` member keys, vmapped twice (members inside, folds
        outside). Left UNCOMMITTED: the fold-stack driver places the
        stacked state on its own fold mesh."""
        import jax.numpy as jnp

        def one_fold(seed):
            keys = jax.random.split(jax.random.key(seed), self.n_seeds)
            return jax.vmap(self.inner.init_state)(keys)

        return jax.vmap(one_fold)(
            jnp.asarray(list(seeds), dtype=jnp.uint32))

    def _commit_state(self, state: TrainState) -> TrainState:
        """Place a stacked state on the mesh (seed axis sharded). Needed
        after Orbax restores, whose arrays arrive committed to one device
        and would conflict with the mesh-placed panel inside jit."""
        if self.mesh is None:
            return state
        shardings = state_sharding(self.mesh, state, stacked=True)
        return jax.device_put(state, shardings)

    def _stacked_batch(self, iterators) -> Optional[Tuple]:
        """Stack one [S, D, Bf] index batch from the per-seed samplers."""
        batches = []
        for it in iterators:
            b = next(it, None)
            if b is None:
                return None
            batches.append(b)
        fi = np.stack([b.firm_idx for b in batches])
        ti = np.stack([b.time_idx for b in batches])
        w = np.stack([b.weight for b in batches])
        arrays = (jnp.asarray(fi), jnp.asarray(ti), jnp.asarray(w))
        if self.mesh is not None:
            arrays = shard_batch(self.mesh, arrays, with_seed_axis=True)
        return arrays

    def _build_epoch(self, epoch: Optional[int]) -> Tuple[Tuple, float]:
        """One whole epoch for all seeds — [K, S, D, Bf] index stacks
        (K = steps, truncated to the shortest member epoch) — plus the
        epoch's firm-month count, computed from the HOST stacks before
        the device transfer so throughput accounting never forces a
        device→host sync. Thread-safe for explicit epochs (the async
        pipeline's prefetch thread builds and stages here)."""
        with telemetry.span("sample", epoch=epoch):
            per_seed = [s.stacked_epoch(epoch) for s in self.samplers]
            k = min(b.firm_idx.shape[0] for b in per_seed)
            fi = np.stack([b.firm_idx[:k] for b in per_seed], axis=1)
            ti = np.stack([b.time_idx[:k] for b in per_seed], axis=1)
            w = np.stack([b.weight[:k] for b in per_seed], axis=1)
            fm = float(w.sum()) * self.window
        with telemetry.span("h2d", epoch=epoch):
            arrays = (jnp.asarray(fi), jnp.asarray(ti), jnp.asarray(w))
            if self.mesh is not None:
                arrays = shard_batch(self.mesh, arrays, with_seed_axis=True,
                                     steps_axis=True)
        return arrays, fm

    def _stacked_epoch(self, epoch: Optional[int] = None) -> Tuple:
        """Back-compat surface (tests/bench): the stacked device arrays
        of :meth:`_build_epoch` without the firm-month count."""
        return self._build_epoch(epoch)[0]

    def _build_bucketed_epoch(self, epoch: Optional[int]):
        """Bucketed twin of :meth:`_build_epoch`: per bucket, a
        ``[K_b, S, D, w]`` stack from the per-seed samplers. Bucket
        geometry is eligibility-derived and therefore SEED-INVARIANT, so
        every member contributes the same bucket structure (asserted) —
        only the within-bucket shuffles differ, preserving per-member
        data-order independence."""
        from lfm_quant_tpu.utils.telemetry import COUNTERS

        with telemetry.span("sample", epoch=epoch):
            per_seed = [s.bucketed_epoch(epoch) for s in self.samplers]
            keys = [k for k, _ in per_seed[0]]
            assert all([k for k, _ in ps] == keys for ps in per_seed), \
                "per-seed bucket geometry diverged"
            host = []
            fm = disp = real = mx = 0.0
            cap = self.samplers[0].firms_per_date
            for i, (lb, w) in enumerate(keys):
                fi = np.stack([ps[i][1].firm_idx for ps in per_seed], axis=1)
                ti = np.stack([ps[i][1].time_idx for ps in per_seed], axis=1)
                wt = np.stack([ps[i][1].weight for ps in per_seed], axis=1)
                sl = float(wt.sum())
                k, s, dd = fi.shape[:3]
                fm += sl * lb
                disp += k * s * dd * w * lb
                real += sl * lb
                mx += k * s * dd * cap * self.window
                host.append(((lb, w), (fi, ti, wt)))
            COUNTERS.bump("bucket_dispatches", len(host))
            COUNTERS.bump("bucket_cells_dispatched", int(disp))
            COUNTERS.bump("bucket_cells_real", int(real))
            COUNTERS.bump("bucket_cells_max_shape", int(mx))
        with telemetry.span("h2d", epoch=epoch):
            parts = []
            for bucket, (fi, ti, wt) in host:
                arrays = (jnp.asarray(fi), jnp.asarray(ti), jnp.asarray(wt))
                if self.mesh is not None:
                    arrays = shard_batch(self.mesh, arrays,
                                         with_seed_axis=True,
                                         steps_axis=True)
                parts.append((bucket, arrays))
        return parts, fm

    # ---- training ----------------------------------------------------

    def evaluate(self, params_stacked) -> Dict[str, Any]:
        """Per-member and ensemble-mean val IC in ONE vmapped dispatch
        (and one device→host sync, counted by the pipeline observability
        counters)."""
        from lfm_quant_tpu.utils.profiling import timed_device_get

        with telemetry.span("eval", cat="eval"):
            b = self.val_sampler.stacked_cross_sections()
            fi, ti, w = self.inner._batch_args(b)
            _, ic, _ = self._jit_forward(params_stacked, self.dev, fi, ti, w)
            ics = timed_device_get(ic)  # [S, M]
        counts = b.weight.sum(axis=1)  # [M]
        per_seed = (ics * counts).sum(axis=1) / counts.sum()
        return {"ic_per_seed": per_seed, "ic_mean": float(per_seed.mean()),
                "ic_std": float(per_seed.std())}

    def fit(self, resume: bool = False, init_params=None) -> Dict[str, Any]:
        """Lock-step ensemble training with crash resume (ckpt/latest every
        epoch) and best-model tracking (ckpt/best) — see Trainer.fit.
        Runs through the same async epoch-pipeline driver
        (train/pipeline.py, ``LFM_ASYNC`` / ``LFM_ASYNC_CKPT``): one
        fused train+eval dispatch chain and ONE device_get per epoch,
        next epoch's [K, S, D, Bf] stacks staged on a background thread
        and dispatched before this epoch's metrics sync, checkpoints
        saved asynchronously from a host-fetched copy of the stacked
        state.

        ``init_params``: seed-stacked [S, ...] params to start from (the
        walk-forward warm start); optimizer state restarts fresh."""
        with telemetry.span("fit", cat="fit", kind="ensemble",
                            n_seeds=self.n_seeds) as sp:
            out = self._fit_impl(resume, init_params)
            sp.set(epochs_run=out["epochs_run"],
                   best_epoch=out["best_epoch"])
            return out

    def _fit_impl(self, resume: bool, init_params) -> Dict[str, Any]:
        from lfm_quant_tpu.train import pipeline

        cfg = self.cfg
        if cfg.optim.epochs < 1:
            raise ValueError(f"epochs must be >= 1, got {cfg.optim.epochs}")
        state = self.init_state()
        if init_params is not None:
            from lfm_quant_tpu.train.loop import graft_params

            # vmapped tx.init keeps the opt-state tree IDENTICAL to
            # init_state's (per-seed count leaves etc.), which the jitted
            # step's structure contract relies on.
            state = graft_params(state, init_params,
                                 jax.vmap(self.inner.tx.init),
                                 self._commit_state)
        harness = FitHarness(self.run_dir, cfg.optim.epochs,
                             cfg.optim.early_stop_patience,
                             self.samplers[0].bucketed_batches_per_epoch()
                             if self._bucketed else
                             min(s.batches_per_epoch() for s in self.samplers))
        if resume:
            restored = harness.resume(state._asdict())
            if restored is not None:
                state = self._commit_state(TrainState(**restored))
        logger = MetricsLogger(self.run_dir, echo=self.echo)
        timer = StepTimer()
        history = []

        # Epoch-invariant val-sweep prep, hoisted off the critical path.
        if self._bucketed:
            # Bucketed val sweep + bucketed epoch supply (LFM_BUCKETS):
            # per-bucket dispatches on one stream; per-month per-seed ICs
            # scatter back to the stacked month order so ``finish``
            # aggregates exactly what the max-shape sweep produces.
            vparts = self.val_sampler.bucketed_cross_sections()
            n_val = sum(pos.size for _, _, pos in vparts)
            counts = np.zeros(n_val, np.float32)
            vhoist = []
            for bucket, b, pos in vparts:
                counts[pos] = b.weight.sum(axis=1)
                bp = self.programs.bucket_programs(self.program_key, bucket)
                vhoist.append((bp, self.inner._batch_args(b),
                               jnp.asarray(pos)))
            geo = self.samplers[0].bucket_geometry()
            bprogs = {bucket: self.programs.bucket_programs(
                          self.program_key, bucket)
                      for bucket in geo.train_buckets}
            telemetry.instant(
                "bucket_geometry", cat="bucket", n_seeds=self.n_seeds,
                steps_per_epoch=harness.steps_per_epoch,
                **geo.summary(cfg.data.dates_per_batch))
            k_total = float(max(1, harness.steps_per_epoch) * self.n_seeds)

            def build(epoch):
                return self._build_bucketed_epoch(epoch)

            def dispatch(state, parts):
                loss = jnp.zeros((), jnp.float32)
                for bucket, arrays in parts:
                    state, ms = bprogs[bucket]._jit_multi_step(
                        state, self.dev, *arrays)
                    loss = loss + ms["loss"].astype(jnp.float32).sum()
                ic = jnp.zeros((self.n_seeds, n_val), jnp.float32)
                for bp, va, pos in vhoist:
                    _, ic_b, _ = bp._jit_forward(state.params, self.dev,
                                                 *va)
                    ic = ic.at[:, pos].set(ic_b.astype(jnp.float32))
                return state, {"loss": loss / k_total, "ic": ic,
                               "step": state.step[0]}
        else:
            vb = self.val_sampler.stacked_cross_sections()
            vargs = self.inner._batch_args(vb)
            counts = vb.weight.sum(axis=1)  # [M]

            def build(epoch):
                return self._build_epoch(epoch)

            def dispatch(state, arrays):
                # Whole epoch × all seeds + the vmapped val sweep chained
                # on one stream; scalars fetched by the driver in one
                # call.
                state, ms = self._jit_multi_step(state, self.dev, *arrays)
                _, ic, _ = self._jit_forward(state.params, self.dev, *vargs)
                return state, {"loss": ms["loss"].mean(), "ic": ic,
                               "step": state.step[0]}

        def finish(epoch, host, fm):
            per_seed = (host["ic"] * counts).sum(axis=1) / counts.sum()
            val_ic = float(per_seed.mean())
            step = int(host["step"])
            rec = logger.log(
                step,
                epoch=epoch,
                train_loss=float(host["loss"]),
                val_ic=val_ic,
                val_ic_std=float(per_seed.std()),
                firm_months_per_sec=timer.throughput(),
            )
            history.append(rec)
            return step, val_ic

        try:
            state, overrun = pipeline.run_fit_epochs(
                harness, state, build=build, dispatch=dispatch,
                finish=finish, timer=timer,
                checkpointing=self.run_dir is not None)
        except pipeline.preempt.Preempted:
            # SIGTERM grace stop: recorded epochs are durable (the
            # driver flushed the checkpoint lines); flush metrics and
            # propagate — same contract as the single-seed trainer.
            logger.close()
            raise

        best = harness.finalize(state._asdict())
        if best is not None:
            state = self._commit_state(TrainState(**best))
        logger.close()
        self.state = state
        return {
            "best_val_ic": harness.best_ic,
            "best_epoch": harness.best_epoch,
            "epochs_run": harness.last_epoch + 1,
            "n_seeds": self.n_seeds,
            "firm_months_per_sec": timer.throughput(),
            "lookahead_overrun": overrun is not None,
            "history": history,
        }

    # ---- inference -----------------------------------------------------

    def predict(self, split: str = "test",
                date_range: Optional[Tuple[int, int]] = None,
                return_variance: bool = False, require_target: bool = True):
        """Stacked forecasts [S, N, T] + shared validity [N, T] over the
        split's anchor range (or an explicit month-index ``date_range`` —
        the walk-forward fold window), for the backtest's ensemble
        aggregation (SURVEY.md §4.3).

        ``return_variance=True`` (heteroscedastic members) additionally
        returns per-seed aleatoric variances [S, N, T]:
        (forecasts, variances, valid) — consumed by
        ``aggregate_ensemble(mode="mean_minus_total_std")``.

        ``require_target=False`` includes LIVE anchors (no observable
        outcome yet) — see Trainer.predict / the forecast.py CLI.
        """
        d = self.cfg.data
        panel = self.splits.panel
        sampler = DateBatchSampler(
            panel, d.window, 1, d.firms_per_date, seed=0,
            min_valid_months=d.min_valid_months, min_cross_section=1,
            date_range=date_range or self.splits.range_of(split),
            require_target=require_target,
        )
        out = np.zeros((self.n_seeds, panel.n_firms, panel.n_months), np.float32)
        out_valid = np.zeros((panel.n_firms, panel.n_months), bool)
        if self._bucketed and not return_variance:
            # Bucketed batch scoring (LFM_BUCKETS): per-bucket vmapped
            # forecast-only dispatches, scattered straight into the
            # panel — bit-identical to the max-shape sweep for the same
            # stacked params (see Trainer.predict's bucketed path).
            for bucket, b, _pos in sampler.bucketed_cross_sections():
                bp = self.programs.bucket_programs(self.program_key, bucket)
                fi, ti, w = self.inner._batch_args(b)
                pred, _, _ = bp._jit_predict(self.state.params, self.dev,
                                             fi, ti, w)
                pred = np.asarray(pred)  # [S, M_b, w]
                real = b.weight > 0
                rows = b.firm_idx[real]
                cols = np.broadcast_to(b.time_idx[:, None],
                                       b.firm_idx.shape)[real]
                out[:, rows, cols] = pred[:, real]
                out_valid[rows, cols] = True
            return out, out_valid
        b = sampler.stacked_cross_sections()
        fi, ti, w = self.inner._batch_args(b)
        if return_variance:
            pred, var, _ = self._jit_forward_var(
                self.state.params, self.dev, fi, ti, w)
        else:
            # Forecast-only dispatch: ONE vmapped forward for all seeds
            # with the per-month metrics compiled out, ONE D2H below.
            pred, _, _ = self._jit_predict(
                self.state.params, self.dev, fi, ti, w)
        pred = np.asarray(pred)  # [S, M, bf]
        real = b.weight > 0  # [M, bf]
        rows = b.firm_idx[real]
        cols = np.broadcast_to(b.time_idx[:, None], b.firm_idx.shape)[real]
        out[:, rows, cols] = pred[:, real]
        out_valid[rows, cols] = True
        if return_variance:
            var_out = np.zeros_like(out)
            var_out[:, rows, cols] = np.asarray(var)[:, real]
            return out, var_out, out_valid
        return out, out_valid


def run_ensemble_experiment(cfg: RunConfig, panel: Optional[Panel] = None,
                            echo: bool = False, resume: bool = False):
    """Config → panel → splits → vmapped ensemble training → summary."""
    from lfm_quant_tpu.train.loop import default_split_dates, resolve_panel

    d = cfg.data
    if panel is None:
        panel = resolve_panel(d)
    train_end, val_end = default_split_dates(panel, d)
    splits = PanelSplits.by_date(panel, train_end, val_end,
                                 train_start=d.train_start)

    run_dir = os.path.join(cfg.out_dir, cfg.name, "ensemble")
    trainer = EnsembleTrainer(cfg, splits, run_dir=run_dir, echo=echo)
    summary = trainer.fit(resume=resume)
    summary["run_dir"] = run_dir
    summary["config"] = dataclasses.asdict(cfg)
    os.makedirs(run_dir, exist_ok=True)
    with open(os.path.join(run_dir, "config.json"), "w") as fh:
        fh.write(cfg.to_json())
    from lfm_quant_tpu.train.forecast import mark_ensemble_run_dir
    mark_ensemble_run_dir(run_dir, True)
    with open(os.path.join(run_dir, "summary.json"), "w") as fh:
        json.dump({k: v for k, v in summary.items() if k != "history"}, fh,
                  indent=2, default=str)
    return summary, trainer, splits


def load_ensemble(run_dir: str, panel: Optional[Panel] = None):
    """Rebuild an EnsembleTrainer from a run dir + restore the stacked
    checkpoint (backtest.py ensemble path)."""
    from lfm_quant_tpu.train.loop import default_split_dates, resolve_panel

    with open(os.path.join(run_dir, "config.json")) as fh:
        cfg = RunConfig.from_json(fh.read())
    d = cfg.data
    if panel is None:
        panel = resolve_panel(d)
    train_end, val_end = default_split_dates(panel, d)
    splits = PanelSplits.by_date(panel, train_end, val_end,
                                 train_start=d.train_start)
    trainer = EnsembleTrainer(cfg, splits, run_dir=run_dir)
    state = trainer.init_state()
    ckpt = CheckpointManager(os.path.join(run_dir, "ckpt", "best"))
    restored = restore_state_dict(ckpt, state._asdict())
    ckpt.close()
    trainer.state = trainer._commit_state(TrainState(**restored))
    return trainer, splits
