"""Shared run-dir → aggregated-forecast dispatch for the two forecast
consumers: ``backtest.py`` (historical anchors, scored against realized
outcomes) and ``forecast.py`` (live anchors, ``require_target=False``).
One copy of the ensemble/MC-dropout/heteroscedastic branching and its
validation rules — the CLIs were growing drifting duplicates
(round-4 advisor finding)."""

from __future__ import annotations

import os
from typing import Callable, Optional


def _raise_system_exit(msg: str):
    raise SystemExit(msg)


def is_ensemble_run_dir(run_dir: str) -> bool:
    """Cheap ensemble.flag stat — lets CLIs validate flag combinations
    (e.g. --mc-samples against an ensemble) BEFORE load_forecaster
    restores every seed checkpoint, which takes minutes on a real
    ensemble run dir."""
    return os.path.exists(os.path.join(run_dir, "ensemble.flag"))


def mark_ensemble_run_dir(run_dir: str, ensemble: bool) -> None:
    """Write (or remove) the ensemble marker — the ONE writer for every
    run-dir producer, so the flag is both created and CLEARED when a dir
    is reused by the other trainer kind (a stale flag would route
    load_forecaster to the wrong restore)."""
    path = os.path.join(run_dir, "ensemble.flag")
    if ensemble:
        with open(path, "w") as fh:
            fh.write("stacked-seed-axis checkpoint\n")
    elif os.path.exists(path):
        os.unlink(path)


def load_forecaster(run_dir: str):
    """Load a run dir's trained model (single seed or ensemble —
    auto-detected via the ``ensemble.flag`` marker).

    Returns ``(model, splits, is_ensemble)`` where ``model`` is a
    ``Trainer`` or ``EnsembleTrainer`` with its best checkpoint restored.
    Loading is separate from forecasting so callers can inspect the panel
    (date ranges, live block) before choosing what to predict."""
    is_ensemble = is_ensemble_run_dir(run_dir)
    if is_ensemble:
        from lfm_quant_tpu.train.ensemble import load_ensemble

        model, splits = load_ensemble(run_dir)
    else:
        from lfm_quant_tpu.train.loop import load_trainer

        model, splits = load_trainer(run_dir)
    return model, splits, is_ensemble


def run_forecast(
    model,
    is_ensemble: bool,
    mode: str = "mean",
    risk_lambda: float = 1.0,
    mc_samples: int = 0,
    error: Optional[Callable[[str], None]] = None,
    **predict_kw,
):
    """Aggregated forecast from a loaded model.

    ``predict_kw`` flows into ``predict()``: ``split=`` for the backtest
    path, ``date_range=``/``require_target=False`` for the live path.
    ``error`` reports invalid flag combinations (argparse's ``ap.error``
    from the CLIs; defaults to raising SystemExit) — it must not return.

    Returns ``(forecast [N, T], valid [N, T])``.
    """
    from lfm_quant_tpu.backtest.engine import aggregate_ensemble

    error = error or _raise_system_exit
    if is_ensemble:
        if mc_samples > 0:
            error("--mc-samples applies to single-model run dirs only; "
                  "this is a seed ensemble — its uncertainty comes from "
                  "the seeds (use --mode mean_minus_std directly)")
        if mode == "mean_minus_total_std":
            stacked, avar, valid = model.predict(return_variance=True,
                                                 **predict_kw)
            return aggregate_ensemble(stacked, valid, mode, risk_lambda,
                                      aleatoric_var=avar)
        stacked, valid = model.predict(**predict_kw)
        return aggregate_ensemble(stacked, valid, mode, risk_lambda)

    if mc_samples > 0:
        if mode == "mean_minus_total_std":
            error("--mode mean_minus_total_std is not combinable with "
                  "--mc-samples (dropout samples carry no aleatoric "
                  "head variance); use --mode mean_minus_std")
        stacked, valid = model.predict(mc_samples=mc_samples, **predict_kw)
        return aggregate_ensemble(stacked, valid, mode, risk_lambda)
    if mode == "mean_minus_total_std":
        # Single heteroscedastic model: no epistemic seed axis — the
        # penalty reduces to the aleatoric head alone.
        fc, avar, valid = model.predict(return_variance=True, **predict_kw)
        return aggregate_ensemble(fc[None], valid, mode, risk_lambda,
                                  aleatoric_var=avar[None])
    if mode != "mean":
        error(f"--mode {mode} needs stacked forecasts: an ensemble run "
              "dir or --mc-samples")
    forecast, valid = model.predict(**predict_kw)
    return forecast, valid
