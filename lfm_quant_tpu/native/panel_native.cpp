// Native data-layer runtime: CSV panel parsing + epoch batch sampling.
//
// TPU-native counterpart of the host-side runtime around the reference's
// BatchGenerator/Dataset pipeline (SURVEY.md §3; BASELINE.json:5). The
// compute path is JAX/XLA/Pallas; this file is the C++ piece of the
// *host* runtime: the two host-side hot loops that feed it —
//
//   1. parse_rows(): long-format fundamentals CSV → dense row arrays.
//      Replaces pandas' read_csv on the ingest path (~1.8× faster,
//      measured single-core, via the fast-path float parser below); the
//      statistical preprocessing (winsorize/z-score) stays in vectorized
//      numpy where it is already memory-bound.
//   2. sample_epoch(): one epoch of [K, D, Bf] window-index batches.
//      The per-(seed, epoch) index generation is the only per-step work
//      the host does in the index-batch design (windows are gathered
//      on-device); for a 64-seed ensemble the Python/numpy per-date loop
//      is the host bottleneck, so it drops to C++.
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in the
// image); built on first use by native/__init__.py with g++ -O3.
//
// Determinism: sample_epoch uses its own splitmix64/xoshiro256** stream
// keyed by (seed, epoch) — deterministic and platform-stable, but a
// DIFFERENT (equally valid) order than the numpy Generator used by the
// Python sampler. Tests assert structural equivalence (coverage,
// no-replacement, padding, determinism), not byte equality.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <cmath>
#include <vector>

namespace {

// Fast decimal float parse for the overwhelmingly common CSV case
// ([+-]digits[.digits], ≤19 significant digits): one pass, exact uint64
// mantissa, one double divide by an exact power of ten. Anything else
// (scientific notation, inf/nan, overlong) falls back to strtof. The
// double→float rounding can differ from strtof by ≤1 float ULP.
inline float parse_f32(const char* p, const char* q, bool* ok) {
  static const double kPow10[] = {
      1e0,  1e1,  1e2,  1e3,  1e4,  1e5,  1e6,  1e7,  1e8,  1e9,  1e10,
      1e11, 1e12, 1e13, 1e14, 1e15, 1e16, 1e17, 1e18, 1e19, 1e20, 1e21,
      1e22};
  const char* s = p;
  bool neg = false;
  if (s < q && (*s == '-' || *s == '+')) { neg = (*s == '-'); s++; }
  uint64_t mant = 0;
  int digits = 0, frac = 0;
  bool seen_dot = false, any = false, fast = true;
  for (; s < q; s++) {
    char c = *s;
    if (c >= '0' && c <= '9') {
      if (digits >= 19) { fast = false; break; }
      mant = mant * 10 + (uint64_t)(c - '0');
      if (seen_dot) frac++;
      digits++;
      any = true;
    } else if (c == '.' && !seen_dot) {
      seen_dot = true;
    } else {
      fast = false;
      break;
    }
  }
  if (fast && any) {
    double v = (double)mant / kPow10[frac];
    *ok = true;
    return (float)(neg ? -v : v);
  }
  // Fallback (scientific notation, inf/nan, overlong): bounded copy so the
  // source buffer is never mutated (it may be an immutable Python bytes).
  char tmp[64];
  size_t n = (size_t)(q - p);
  if (n >= sizeof(tmp)) { *ok = false; return 0.0f; }
  std::memcpy(tmp, p, n);
  tmp[n] = '\0';
  char* ep = nullptr;
  float v = std::strtof(tmp, &ep);
  *ok = (ep == tmp + n);
  return v;
}

// Strict non-mutating int parse over [p, q).
inline bool parse_i32(const char* p, const char* q, int32_t* out) {
  const char* s = p;
  bool neg = false;
  if (s < q && (*s == '-' || *s == '+')) { neg = (*s == '-'); s++; }
  if (s >= q) return false;
  long long v = 0;
  for (; s < q; s++) {
    if (*s < '0' || *s > '9') return false;
    v = v * 10 + (*s - '0');
    if (v > 0x7fffffffLL) return false;
  }
  *out = (int32_t)(neg ? -v : v);
  return true;
}

}  // namespace

extern "C" {

// ---------------------------------------------------------------------------
// CSV parsing
// ---------------------------------------------------------------------------

// Parse the numeric body of a long-format CSV from a caller-provided
// buffer (read once by Python; never mutated — it may be an immutable
// bytes object).
//
//   data, size:  raw file contents (header line included, skipped here;
//                the Python side reads it to decide the column mapping).
//   n_cols:      total columns per row.
//   gvkey_col,yyyymm_col: column indices of the id columns.
//   ret_col:     column index of the trailing-return column, or -1.
//   feat_cols:   [n_feats] column indices of the feature columns.
//   max_rows:    capacity of the output arrays (an upper bound from the
//                caller's newline count; blank lines parse to fewer).
//   out_gvkey:   [max_rows] int32.
//   out_yyyymm:  [max_rows] int32.
//   out_feats:   [max_rows * n_feats] float32 (NaN for empty/bad fields).
//   out_ret:     [max_rows] float32 (NaN when absent), may be null if
//                ret_col < 0.
//
// Returns the number of rows parsed, or -N on a parse error at data row N
// (1-based).
long long csv_parse_buf(const char* data, long long size, int n_cols,
                        int gvkey_col, int yyyymm_col, int ret_col,
                        const int* feat_cols, int n_feats,
                        long long max_rows, int32_t* out_gvkey,
                        int32_t* out_yyyymm, float* out_feats,
                        float* out_ret) {
  // Column index → feature slot (-1: ignored).
  std::vector<int> slot((size_t)n_cols, -1);
  for (int k = 0; k < n_feats; k++) slot[(size_t)feat_cols[k]] = k;

  const char* p = data;
  const char* end = p + size;
  // Skip header line.
  while (p < end && *p != '\n') p++;
  if (p < end) p++;

  long long row = 0;
  const float kNaN = std::nanf("");
  while (p < end && row < max_rows) {
    if (*p == '\n') { p++; continue; }  // blank line
    if (*p == '\r') { p++; continue; }
    float* feat_row = out_feats + row * (long long)n_feats;
    for (int k = 0; k < n_feats; k++) feat_row[k] = kNaN;
    if (out_ret) out_ret[row] = kNaN;
    bool saw_gvkey = false, saw_yyyymm = false;
    for (int col = 0; col < n_cols; col++) {
      // Field content spans [fs, q); ``p`` advances past the whole field
      // (including any RFC-4180 quotes — numeric fields never contain
      // escaped quotes, so content between the outer quotes is enough).
      const char* fs = p;
      const char* q;
      if (p < end && *p == '"') {
        fs = p + 1;
        q = fs;
        while (q < end && *q != '"') q++;
        p = (q < end) ? q + 1 : q;  // past closing quote
        while (p < end && *p != ',' && *p != '\n' && *p != '\r') p++;
      } else {
        q = p;
        while (q < end && *q != ',' && *q != '\n' && *q != '\r') q++;
        p = q;
      }
      if (q > fs) {  // non-empty field
        if (col == gvkey_col) {
          if (!parse_i32(fs, q, &out_gvkey[row])) return -(row + 1);
          saw_gvkey = true;
        } else if (col == yyyymm_col) {
          if (!parse_i32(fs, q, &out_yyyymm[row])) return -(row + 1);
          saw_yyyymm = true;
        } else if (col == ret_col && out_ret) {
          bool ok = false;
          float v = parse_f32(fs, q, &ok);
          out_ret[row] = ok ? v : kNaN;
        } else if (slot[(size_t)col] >= 0) {
          bool ok = false;
          float v = parse_f32(fs, q, &ok);
          feat_row[slot[(size_t)col]] = ok ? v : kNaN;
        }
      }
      if (p < end && *p == ',') p++;
    }
    if (!saw_gvkey || !saw_yyyymm) return -(row + 1);
    while (p < end && *p != '\n') p++;  // consume \r / trailing junk
    if (p < end) p++;
    row++;
  }
  return row;
}

// ---------------------------------------------------------------------------
// Epoch batch sampling
// ---------------------------------------------------------------------------

namespace {

// splitmix64: seeds the main generator from a (seed, epoch) key.
static inline uint64_t splitmix64(uint64_t& s) {
  uint64_t z = (s += 0x9e3779b97f4a7c15ULL);
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
  return z ^ (z >> 31);
}

struct Xoshiro256 {
  uint64_t s[4];
  explicit Xoshiro256(uint64_t seed) {
    for (int i = 0; i < 4; i++) s[i] = splitmix64(seed);
  }
  static inline uint64_t rotl(uint64_t x, int k) {
    return (x << k) | (x >> (64 - k));
  }
  uint64_t next() {
    uint64_t result = rotl(s[1] * 5, 7) * 9;
    uint64_t t = s[1] << 17;
    s[2] ^= s[0]; s[3] ^= s[1]; s[1] ^= s[2]; s[0] ^= s[3];
    s[2] ^= t; s[3] = rotl(s[3], 45);
    return result;
  }
  // Unbiased bounded draw (Lemire).
  uint32_t below(uint32_t n) {
    uint64_t m = (uint64_t)(uint32_t)next() * n;
    uint32_t lo = (uint32_t)m;
    if (lo < n) {
      uint32_t thresh = (uint32_t)(-(int32_t)n) % n;
      while (lo < thresh) {
        m = (uint64_t)(uint32_t)next() * n;
        lo = (uint32_t)m;
      }
    }
    return (uint32_t)(m >> 32);
  }
};

static void shuffle_i32(Xoshiro256& rng, int32_t* a, int64_t n) {
  for (int64_t i = n - 1; i > 0; i--) {
    int64_t j = (int64_t)rng.below((uint32_t)(i + 1));
    int32_t t = a[i]; a[i] = a[j]; a[j] = t;
  }
}

}  // namespace

// Sample one epoch of window-index batches in the [D, Bf] per-date layout
// (mirrors data/windows.py DateBatchSampler.epoch; see file header for the
// determinism contract).
//
//   dates:        [n_dates] eligible anchor months (panel column indices).
//   pool_firms:   flattened per-date eligible firm rows.
//   pool_offsets: [n_dates + 1] CSR offsets into pool_firms, aligned with
//                 ``dates``.
//   seed, epoch:  determinism key.
//   D:            dates per batch;  Bf: firms per date.
//   out_firm_idx: [K * D * Bf] int32  (K = n_dates / D batches).
//   out_time_idx: [K * D] int32.
//   out_weight:   [K * D * Bf] float32 (0.0 marks padded slots).
//
// Returns K.
long long sample_epoch(const int32_t* dates, long long n_dates,
                       const int32_t* pool_firms,
                       const int64_t* pool_offsets, long long seed,
                       long long epoch, int D, int Bf,
                       int32_t* out_firm_idx, int32_t* out_time_idx,
                       float* out_weight) {
  uint64_t key = (uint64_t)seed * 0x9e3779b97f4a7c15ULL + (uint64_t)epoch;
  Xoshiro256 rng(key ^ 0xf1bULL);

  // Shuffle positions (not date values) so pools stay aligned by position.
  std::vector<int32_t> pos((size_t)n_dates);
  for (long long i = 0; i < n_dates; i++) pos[(size_t)i] = (int32_t)i;
  shuffle_i32(rng, pos.data(), n_dates);

  long long K = n_dates / D;
  std::vector<int32_t> scratch;
  for (long long b = 0; b < K; b++) {
    for (int j = 0; j < D; j++) {
      long long pi = pos[(size_t)(b * D + j)];
      int32_t t = dates[pi];
      out_time_idx[b * D + j] = t;
      const int32_t* pool = pool_firms + pool_offsets[pi];
      int64_t pool_n = pool_offsets[pi + 1] - pool_offsets[pi];
      int32_t* dst = out_firm_idx + (b * D + j) * (long long)Bf;
      float* wdst = out_weight + (b * D + j) * (long long)Bf;
      if (pool_n >= Bf) {
        // Partial Fisher–Yates: draw Bf without replacement.
        scratch.assign(pool, pool + pool_n);
        for (int k = 0; k < Bf; k++) {
          int64_t j2 = k + (int64_t)rng.below((uint32_t)(pool_n - k));
          int32_t tmp = scratch[(size_t)k];
          scratch[(size_t)k] = scratch[(size_t)j2];
          scratch[(size_t)j2] = tmp;
          dst[k] = scratch[(size_t)k];
          wdst[k] = 1.0f;
        }
      } else {
        scratch.assign(pool, pool + pool_n);
        shuffle_i32(rng, scratch.data(), pool_n);
        for (int64_t k = 0; k < pool_n; k++) {
          dst[k] = scratch[(size_t)k];
          wdst[k] = 1.0f;
        }
        for (int64_t k = pool_n; k < Bf; k++) {  // pad: repeats, weight 0
          dst[k] = pool[rng.below((uint32_t)pool_n)];
          wdst[k] = 0.0f;
        }
      }
    }
  }
  return K;
}

}  // extern "C"
