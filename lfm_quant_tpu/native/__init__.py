"""Native (C++) host-runtime components, bound via ctypes.

The TPU compute path is JAX/XLA/Pallas; this package is the native side of
the HOST runtime around it (SURVEY.md §3: the reference's own native layer
is stock TF kernels — our framework instead puts the host-side hot loops
in C++): fast CSV panel ingest and epoch batch sampling (see
panel_native.cpp).

Build model: compiled on first use with ``g++ -O3 -shared`` (deliberately
no ``-march=native``: the cached .so may be loaded by other hosts on a
shared filesystem — see ``_build``) into this directory (cached; rebuilt
when the source is newer). Every
consumer must degrade gracefully: :func:`get_lib` returns ``None`` when no
toolchain is available, and callers fall back to the pure-Python path.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sys
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "panel_native.cpp")
_SO = os.path.join(_DIR, "_panel_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> bool:
    # Per-process temp name: concurrent first-use builds (multi-host launch
    # on a shared FS, pytest-xdist) must not interleave linker output in one
    # file; each writes its own and the os.replace rename is atomic.
    tmp = f"{_SO}.{os.getpid()}.tmp"
    # No -march=native: the .so may be cached on a shared filesystem and
    # loaded by hosts with older CPUs (SIGILL is not a graceful fallback).
    cmd = ["g++", "-O3", "-std=c++17", "-fPIC", "-shared", _SRC, "-o", tmp]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=180)
    except (OSError, subprocess.TimeoutExpired) as e:
        print(f"lfm_quant_tpu.native: build skipped ({e})", file=sys.stderr)
        return False
    if proc.returncode != 0:
        print(f"lfm_quant_tpu.native: g++ failed:\n{proc.stderr[:2000]}",
              file=sys.stderr)
        return False
    os.replace(tmp, _SO)
    return True


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    f32p = ctypes.POINTER(ctypes.c_float)
    lib.csv_parse_buf.argtypes = [
        ctypes.c_char_p, ctypes.c_longlong, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, i32p, ctypes.c_int, ctypes.c_longlong,
        i32p, i32p, f32p, f32p,
    ]
    lib.csv_parse_buf.restype = ctypes.c_longlong
    lib.sample_epoch.argtypes = [
        i32p, ctypes.c_longlong, i32p, i64p, ctypes.c_longlong,
        ctypes.c_longlong, ctypes.c_int, ctypes.c_int, i32p, i32p, f32p,
    ]
    lib.sample_epoch.restype = ctypes.c_longlong
    return lib


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, building it on first call; None when
    unavailable (no toolchain / build error) — callers must fall back."""
    global _lib, _build_failed
    if _lib is not None:
        return _lib
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        fresh = (os.path.exists(_SO)
                 and os.path.getmtime(_SO) >= os.path.getmtime(_SRC))
        if not fresh and not _build():
            _build_failed = True
            return None
        try:
            _lib = _bind(ctypes.CDLL(_SO))
        except OSError as e:
            print(f"lfm_quant_tpu.native: load failed ({e})", file=sys.stderr)
            _build_failed = True
            return None
    return _lib


def available() -> bool:
    return get_lib() is not None
