"""MLP factor model — parity with the reference's ``mlp_model``
(SURVEY.md §3; BASELINE.json:5,7 — the 5-feature toy-panel config runs here).
"""

from __future__ import annotations

from typing import Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp

from lfm_quant_tpu.models.heads import ForecastHead


class MLPModel(nn.Module):
    """Feed-forward model over the flattened (masked) lookback window.

    The window is flattened to ``W*F`` inputs (masked steps contribute
    zeros) plus one scalar valid-fraction input so the net can distinguish
    "zero feature" from "missing month". With ``window_input=False`` only
    the anchor month's features are used — the classic cross-sectional MLP.
    """

    hidden: Sequence[int] = (64, 32)
    window_input: bool = True
    heteroscedastic: bool = False
    dropout: float = 0.0
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x, m, deterministic: bool = True):
        x = x.astype(self.dtype) if self.dtype is not None else x
        mf = m.astype(x.dtype)
        if self.window_input:
            z = (x * mf[..., None]).reshape(*x.shape[:-2], -1)
            frac = mf.mean(axis=-1, keepdims=True)
            z = jnp.concatenate([z, frac], axis=-1)
        else:
            z = x[..., -1, :] * mf[..., -1:]
        for i, h in enumerate(self.hidden):
            z = nn.Dense(h, dtype=self.dtype, name=f"dense_{i}")(z)
            z = nn.gelu(z)
            if self.dropout > 0.0:
                z = nn.Dropout(self.dropout, deterministic=deterministic)(z)
        return ForecastHead(
            heteroscedastic=self.heteroscedastic, dtype=self.dtype, name="head"
        )(z)
