"""Linear Recurrent Unit factor model — the time-PARALLEL recurrence.

Beyond-reference model family (the reference ships MLP/LSTM/GRU only —
SURVEY.md §3), motivated by the retrieved throughput literature
(PAPERS.md: "Parallelizing Linear Recurrent Neural Nets Over Sequence
Length", "Parallelizing Legendre Memory Unit Training"): a *linear*
diagonal recurrence has an associative step, so the whole T-step history
folds in O(log T) depth via ``lax.associative_scan`` instead of the
LSTM/GRU's irreducibly serial T-step chain. On TPU that turns the
recurrence from the latency-bound tail of the step into a few elementwise
VPU passes, and every remaining FLOP is a big ``[B·T, ·]`` GEMM the MXU
tiles perfectly — no Pallas kernel needed, XLA alone reaches high MFU.

The cell is the LRU of the linear-RNN line of work: per layer a complex
diagonal state ``h_t = λ ⊙ h_{t-1} + γ ⊙ (B x_t)`` with
``λ = exp(-exp(ν) + i·exp(θ))`` (stable by construction: |λ| < 1),
input normalization ``γ = sqrt(1 - |λ|²)``, readout
``y_t = Re(C h_t) + d ⊙ x_t``, GELU + residual + LayerNorm between
layers. Complex arithmetic is carried as explicit (re, im) pairs — TPUs
have no native complex type, and the pairs keep every array bf16/f32.

Masking matches the RNN contract exactly (invalid months HOLD state):
``h_t = a_t ⊙ h_{t-1} + m_t·γ⊙(B x_t)`` with ``a_t = m_t·λ + (1-m_t)``
— still a first-order linear recurrence, so the same associative combine
``(a₂,b₂)∘(a₁,b₁) = (a₁a₂, a₂b₁ + b₂)`` applies and the scan stays
parallel. The last step's state is therefore the state at the last
*valid* month, and the readout mirrors models/rnn.py (anchor-last
windows, ``z = y[..., -1, :]``).

Numerics: the scan runs in f32 (elementwise — VPU-cheap) regardless of
compute dtype; the B/C projections and head run in the model dtype
(bf16 on TPU). Params are fp32 throughout.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from lfm_quant_tpu.models.heads import ForecastHead


def _combine(x, y):
    """First-order-recurrence composition: apply x (earlier) then y
    (later). Elements are (a_re, a_im, b_re, b_im) meaning h ↦ a·h + b;
    the composition is a = xa·ya, b = ya·xb + yb (complex arithmetic on
    explicit re/im pairs)."""
    xar, xai, xbr, xbi = x
    yar, yai, ybr, ybi = y
    ar = xar * yar - xai * yai
    ai = xar * yai + xai * yar
    br = yar * xbr - yai * xbi + ybr
    bi = yar * xbi + yai * xbr + ybi
    return ar, ai, br, bi


def _linear_scan(a_re, a_im, b_re, b_im):
    """Masked linear recurrence via associative_scan over the time axis.

    All inputs [..., T, N] f32. Returns (h_re, h_im) with
    ``h_t = a_t·h_{t-1} + b_t`` (h_0 = 0), computed in O(log T) depth.
    """
    _, _, h_re, h_im = jax.lax.associative_scan(
        _combine, (a_re, a_im, b_re, b_im), axis=-2)
    return h_re, h_im


def _distributed_linear_scan(a_re, a_im, b_re, b_im, axis: str):
    """Sequence-parallel linear recurrence — the long-context mode.

    Must run inside ``shard_map`` with the TIME axis of all four inputs
    sharded over mesh axis ``axis`` (T_local = T / S per shard). Three
    phases, the classic scan decomposition laid onto the mesh:

    1. local inclusive scan (O(log T_local) depth, no communication);
    2. ONE ``all_gather`` of each shard's aggregate transform — the
       (A, B) pair folding its whole local block — S·N numbers per
       batch row, tiny next to the activations; every shard then folds
       the exclusive prefix of earlier shards' aggregates in S steps
       (S = mesh axis size, compile-time constant);
    3. local correction ``h_t ← h_t + cumA_t ⊙ h_in`` where ``h_in`` is
       the state entering this shard — elementwise, no communication.

    Contrast with ring attention (parallel/ring.py): no rotation, no
    O(S) pipeline — the linear recurrence's associativity collapses the
    cross-shard dependency into one collective.
    """
    # ONE scan yields both the running state (b outputs) and the
    # cumulative complex product of a (a outputs) — the latter drives
    # the prefix correction below.
    cA_re, cA_im, h_re, h_im = jax.lax.associative_scan(
        _combine, (a_re, a_im, b_re, b_im), axis=-2)

    S = jax.lax.psum(1, axis)  # static under shard_map
    if S == 1:
        return h_re, h_im
    agg = (cA_re[..., -1, :], cA_im[..., -1, :],
           h_re[..., -1, :], h_im[..., -1, :])
    # Gather every shard's aggregate as [S, ...] via one-hot + psum
    # rather than all_gather: psum is the collective with the cleanest
    # AD story under shard_map, and the aggregates are S·N scalars per
    # batch row — the broadcast costs nothing.
    me = jax.lax.axis_index(axis)
    onehot = (jnp.arange(S) == me).astype(agg[0].dtype)
    gathered = tuple(
        jax.lax.psum(onehot.reshape((S,) + (1,) * v.ndim) * v[None], axis)
        for v in agg)
    cur = (jnp.ones_like(agg[0]), jnp.zeros_like(agg[1]),
           jnp.zeros_like(agg[2]), jnp.zeros_like(agg[3]))
    prefixes = []
    for s in range(S):
        prefixes.append(cur)
        cur = _combine(cur, tuple(v[s] for v in gathered))
    stacked = tuple(jnp.stack([p[i] for p in prefixes]) for i in range(4))
    hin_re = jnp.take(stacked[2], me, axis=0)
    hin_im = jnp.take(stacked[3], me, axis=0)
    hin_re, hin_im = hin_re[..., None, :], hin_im[..., None, :]
    return (h_re + cA_re * hin_re - cA_im * hin_im,
            h_im + cA_re * hin_im + cA_im * hin_re)


class LRULayer(nn.Module):
    """One LRU mixing layer: x [..., T, H] → y [..., T, H] (same width)."""

    hidden: int           # model width H (input/output)
    state_dim: int = 128  # complex state size N
    r_min: float = 0.9    # |λ| init ring (long-memory end near 1)
    r_max: float = 0.999
    max_phase: float = math.pi / 2  # θ init range — 60-step windows
    dtype: Optional[jnp.dtype] = None
    seq_axis: Optional[str] = None  # mesh axis name for sharded time

    @nn.compact
    def __call__(self, x, m):
        N = self.state_dim
        compute = self.dtype or jnp.float32

        def nu_init(key, shape, _=None):
            u = jax.random.uniform(key, shape)
            mag2 = self.r_min ** 2 + u * (self.r_max ** 2 - self.r_min ** 2)
            return jnp.log(-0.5 * jnp.log(mag2)).astype(jnp.float32)

        def theta_init(key, shape, _=None):
            u = jax.random.uniform(key, shape)
            return jnp.log(self.max_phase * u + 1e-4).astype(jnp.float32)

        nu_log = self.param("nu_log", nu_init, (N,))
        theta_log = self.param("theta_log", theta_init, (N,))
        mag = jnp.exp(-jnp.exp(nu_log))               # |λ| in (0, 1)
        phase = jnp.exp(theta_log)
        lam_re = mag * jnp.cos(phase)
        lam_im = mag * jnp.sin(phase)
        gamma = jnp.sqrt(jnp.maximum(1.0 - mag ** 2, 1e-6))

        # Input projection Bx (complex, MXU): ONE H→2N GEMM in bf16,
        # split into (re, im) — half the dispatches of separate re/im
        # Denses, identical parameterization (the halves concatenate).
        bx = nn.Dense(2 * N, use_bias=False, dtype=compute, name="b")(x)
        bx_re, bx_im = jnp.split(bx, 2, axis=-1)

        # Per-step recurrence coefficients with mask-holds-state blended
        # in: a_t = m·λ + (1-m); b_t = m·γ⊙Bx_t. f32 for the scan.
        keep = m[..., None].astype(jnp.float32)       # [..., T, 1]
        a_re = keep * lam_re + (1.0 - keep)
        a_im = keep * lam_im
        b_re = keep * gamma * bx_re.astype(jnp.float32)
        b_im = keep * gamma * bx_im.astype(jnp.float32)
        if self.seq_axis is not None:
            h_re, h_im = _distributed_linear_scan(
                a_re, a_im, b_re, b_im, self.seq_axis)
        else:
            h_re, h_im = _linear_scan(a_re, a_im, b_re, b_im)

        # Readout y = Re(C h) + d ⊙ x as ONE 2N→H GEMM over the
        # concatenated (re, im) state — the -Im(C) sign folds into the
        # learned kernel, so the parameterization is unchanged.
        hcat = jnp.concatenate(
            [h_re.astype(compute), h_im.astype(compute)], axis=-1)
        y = nn.Dense(self.hidden, use_bias=True, dtype=compute,
                     name="c")(hcat)
        d = self.param("d_skip", nn.initializers.ones_init(),
                       (self.hidden,), jnp.float32)
        return y + d.astype(compute) * x


class LRUModel(nn.Module):
    """Stacked LRU blocks over the lookback window → forecast head.

    Same calling convention as every model in the registry:
    ``apply({'params': p}, x [B, W, F], m [B, W]) → [B] fp32`` (or
    (mean, log_var) when ``heteroscedastic``). Depth-wise each block is
    pre-norm: ``x + GELU(LRU(LN(x)))`` — the residual keeps the anchor
    month's information intact through depth.
    """

    hidden: int = 128
    state_dim: int = 128
    layers: int = 2
    head_hidden: Sequence[int] = ()
    heteroscedastic: bool = False
    dtype: Optional[jnp.dtype] = None
    # Sequence-parallel mode: run inside shard_map with the window axis
    # of (x, m) sharded over this mesh axis (parallel/ring.py
    # ``sequence_parallel_apply`` — same contract as TransformerModel).
    # No per-position params, so checkpoints interchange with seq_axis
    # None.
    seq_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x, m, deterministic: bool = True):
        del deterministic  # no dropout in this trunk
        compute = self.dtype or jnp.float32
        # Zero masked-step features: the scan already ignores them, but
        # the residual stream (embed + d-skip) is position-wise and the
        # readout reads position -1 — without this, an INVALID anchor
        # month would leak its (garbage) features into the forecast,
        # breaking the RNN mask contract ("a function of valid history
        # only"). With it, an invalid anchor reduces to the held scan
        # state plus a constant embed-bias offset.
        x = x * m[..., None].astype(x.dtype)
        h = nn.Dense(self.hidden, dtype=self.dtype, name="embed")(
            x.astype(compute))
        for layer in range(self.layers):
            z = nn.LayerNorm(dtype=self.dtype, name=f"norm_{layer}")(h)
            z = LRULayer(
                hidden=self.hidden, state_dim=self.state_dim,
                dtype=self.dtype, seq_axis=self.seq_axis,
                name=f"lru_{layer}",
            )(z, m)
            h = h + nn.gelu(z)
        # Anchor-last windows + mask-holds-state: the last step carries
        # the last valid month's state (models/rnn.py readout parity).
        z = h[..., -1, :]
        if self.seq_axis is not None:
            # The global last position lives on the LAST shard; replicate
            # its readout so every shard returns the identical forecast
            # (sequence_parallel_apply's out_specs=P() contract).
            n_shard = jax.lax.psum(1, self.seq_axis)
            me = jax.lax.axis_index(self.seq_axis)
            z = jax.lax.psum(
                jnp.where(me == n_shard - 1, z, jnp.zeros_like(z)),
                self.seq_axis)
        return ForecastHead(
            hidden=self.head_hidden,
            heteroscedastic=self.heteroscedastic,
            dtype=self.dtype,
            name="head",
        )(z)
