"""Factor models (L3): MLP, LSTM, GRU, transformer encoder, LRU.

Parity targets: the reference's ``mlp_model`` and ``rnn_model`` (LSTM/GRU)
plus the transformer-encoder ladder config (SURVEY.md §3; BASELINE.json:5,10).
All models share one calling convention:

    pred = model.apply({'params': p}, x, m)        # point forecast
    x: [B, W, F] float windows, m: [B, W] bool step-validity
    pred: [B] float32 — or (mean, log_var) [B] pairs when
    ``heteroscedastic=True`` (uncertainty head, lineage of the 2020
    uncertainty-aware LFM paper — SURVEY.md §1 [BACKGROUND]).

TPU-first choices: recurrent cells use one fused gate matmul per step
(MXU-shaped), driven by ``lax.scan`` over the window axis (prescribed at
BASELINE.json:5); compute dtype is bf16 with fp32 params and fp32 head
output; masking holds carried state through invalid months so ragged
histories never contaminate the forecast.
"""

from lfm_quant_tpu.models.lru import LRUModel
from lfm_quant_tpu.models.mlp import MLPModel
from lfm_quant_tpu.models.rnn import GRUModel, LSTMModel, RNNModel
from lfm_quant_tpu.models.transformer import TransformerModel

MODEL_REGISTRY = {
    "mlp": MLPModel,
    "lstm": LSTMModel,
    "gru": GRUModel,
    "transformer": TransformerModel,
    "lru": LRUModel,
}


def build_model(kind: str, **kwargs):
    """Construct a model by registry name (config system entry point)."""
    try:
        cls = MODEL_REGISTRY[kind]
    except KeyError:
        raise ValueError(
            f"unknown model kind {kind!r}; available: {sorted(MODEL_REGISTRY)}"
        ) from None
    return cls(**kwargs)


__all__ = [
    "LRUModel",
    "MLPModel",
    "LSTMModel",
    "GRUModel",
    "RNNModel",
    "TransformerModel",
    "MODEL_REGISTRY",
    "build_model",
]
