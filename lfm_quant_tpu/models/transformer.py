"""Transformer-encoder factor model — parity with ladder config 4
(BASELINE.json:10 — "Transformer encoder over fundamentals (replace RNN),
mixed bf16").

Each month of the lookback window is a token. At W=60 tokens full attention
is trivially cheap (SURVEY.md §6: no sequence parallelism needed at this
scale), so the default encoder is a standard pre-norm stack; key-padding
masking handles ragged histories. bf16 compute / fp32 params via ``dtype``.

Long-context mode (``seq_axis``): for windows that outgrow one chip (daily
bars, high-frequency panels), set ``seq_axis="seq"`` and run the model
inside ``shard_map`` with the WINDOW axis sharded over that mesh axis
(``parallel/ring.py:sequence_parallel_apply``). Attention becomes ring
attention (K/V blocks rotating over ICI via ppermute), the position
embedding is sliced per shard, and pooling psums across shards — the
parameter tree is IDENTICAL to the plain model, so the same checkpoint
serves both modes.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from lfm_quant_tpu.models.heads import ForecastHead, masked_mean_pool


class RingSelfAttention(nn.Module):
    """Self-attention over a sequence-sharded token axis (ring K/V).

    Parameter-compatible with ``nn.MultiHeadDotProductAttention`` (same
    query/key/value/out DenseGeneral tree), so plain and sequence-parallel
    encoders interchange checkpoints.
    """

    num_heads: int
    axis_name: str
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, z, kv_mask):
        from lfm_quant_tpu.parallel.ring import ring_attention

        dim = z.shape[-1]
        if dim % self.num_heads:
            raise ValueError(f"dim {dim} not divisible by {self.num_heads}")
        head_dim = dim // self.num_heads
        proj = functools.partial(
            nn.DenseGeneral, features=(self.num_heads, head_dim),
            dtype=self.dtype)
        # [B, Wl, H, Dh] → [B, H, Wl, Dh]
        q, k, v = (proj(name=n)(z).swapaxes(-3, -2)
                   for n in ("query", "key", "value"))
        out = ring_attention(q, k, v, kv_mask, axis_name=self.axis_name)
        out = out.swapaxes(-3, -2)  # [B, Wl, H, Dh]
        return nn.DenseGeneral(features=dim, axis=(-2, -1), dtype=self.dtype,
                               name="out")(out)


class EncoderBlock(nn.Module):
    dim: int
    heads: int
    mlp_ratio: int = 4
    dropout: float = 0.0
    dtype: Optional[jnp.dtype] = None
    seq_axis: Optional[str] = None

    @nn.compact
    def __call__(self, z, m, deterministic: bool = True):
        y = nn.LayerNorm(dtype=self.dtype, name="ln1")(z)
        if self.seq_axis is not None:
            y = RingSelfAttention(
                num_heads=self.heads, axis_name=self.seq_axis,
                dtype=self.dtype, name="attn",
            )(y, m)
        else:
            w = z.shape[-2]
            # Key-padding mask: queries may be anything (pooling ignores
            # invalid outputs); keys must be valid months.
            attn_mask = jnp.broadcast_to(
                m[..., None, None, :], (*m.shape[:-1], 1, w, w))
            y = nn.MultiHeadDotProductAttention(
                num_heads=self.heads,
                dtype=self.dtype,
                dropout_rate=self.dropout,
                deterministic=deterministic,
                name="attn",
            )(y, y, mask=attn_mask)
        z = z + y
        y = nn.LayerNorm(dtype=self.dtype, name="ln2")(z)
        y = nn.Dense(self.dim * self.mlp_ratio, dtype=self.dtype, name="mlp_in")(y)
        y = nn.gelu(y)
        y = nn.Dense(self.dim, dtype=self.dtype, name="mlp_out")(y)
        return z + y


class TransformerModel(nn.Module):
    """Pre-norm encoder over month-tokens with masked mean pooling.

    ``seq_axis=None``: plain single-device attention over the full window.
    ``seq_axis="seq"``: sequence-parallel — MUST run inside shard_map with
    the window axis of (x, m) sharded over that mesh axis; the position
    table stays global-length (identical params) and is sliced per shard.
    """

    dim: int = 64
    depth: int = 2
    heads: int = 4
    mlp_ratio: int = 4
    head_hidden: Sequence[int] = ()
    heteroscedastic: bool = False
    dropout: float = 0.0
    dtype: Optional[jnp.dtype] = None
    seq_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x, m, deterministic: bool = True):
        if self.seq_axis is not None and self.dropout > 0.0:
            raise ValueError(
                "dropout is not implemented for the sequence-parallel "
                "encoder (RingSelfAttention) — it would silently train "
                "differently from the plain mode; set dropout=0.0 with "
                "seq_axis")
        w = x.shape[-2]  # LOCAL window length under seq sharding
        compute_dtype = self.dtype or jnp.float32
        z = nn.Dense(self.dim, dtype=self.dtype, name="embed")(
            x.astype(compute_dtype)
        )
        if self.seq_axis is not None:
            n_shard = jax.lax.psum(1, self.seq_axis)  # static
            pos = self.param(
                "pos_emb", nn.initializers.normal(0.02),
                (w * n_shard, self.dim), jnp.float32)
            shard = jax.lax.axis_index(self.seq_axis)
            pos = jax.lax.dynamic_slice_in_dim(pos, shard * w, w, axis=0)
        else:
            pos = self.param(
                "pos_emb", nn.initializers.normal(0.02), (w, self.dim),
                jnp.float32)
        z = z + pos.astype(z.dtype)
        for i in range(self.depth):
            z = EncoderBlock(
                dim=self.dim,
                heads=self.heads,
                mlp_ratio=self.mlp_ratio,
                dropout=self.dropout,
                dtype=self.dtype,
                seq_axis=self.seq_axis,
                name=f"block_{i}",
            )(z, m, deterministic=deterministic)
        z = nn.LayerNorm(dtype=self.dtype, name="ln_f")(z)
        if self.seq_axis is not None:
            mf = m.astype(z.dtype)[..., None]
            num = jax.lax.psum((z * mf).sum(axis=-2), self.seq_axis)
            den = jax.lax.psum(mf.sum(axis=-2), self.seq_axis)
            pooled = num / jnp.maximum(den, 1.0)
        else:
            pooled = masked_mean_pool(z, m)
        return ForecastHead(
            hidden=self.head_hidden,
            heteroscedastic=self.heteroscedastic,
            dtype=self.dtype,
            name="head",
        )(pooled)
