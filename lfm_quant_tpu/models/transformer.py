"""Transformer-encoder factor model — parity with ladder config 4
(BASELINE.json:10 — "Transformer encoder over fundamentals (replace RNN),
mixed bf16").

Each month of the lookback window is a token. At W=60 tokens full attention
is trivially cheap (SURVEY.md §6: no sequence parallelism needed at this
scale), so the encoder is a standard pre-norm stack; key-padding masking
handles ragged histories. bf16 compute / fp32 params via ``dtype``.
"""

from __future__ import annotations

from typing import Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp

from lfm_quant_tpu.models.heads import ForecastHead, masked_mean_pool


class EncoderBlock(nn.Module):
    dim: int
    heads: int
    mlp_ratio: int = 4
    dropout: float = 0.0
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, z, attn_mask, deterministic: bool = True):
        y = nn.LayerNorm(dtype=self.dtype, name="ln1")(z)
        y = nn.MultiHeadDotProductAttention(
            num_heads=self.heads,
            dtype=self.dtype,
            dropout_rate=self.dropout,
            deterministic=deterministic,
            name="attn",
        )(y, y, mask=attn_mask)
        z = z + y
        y = nn.LayerNorm(dtype=self.dtype, name="ln2")(z)
        y = nn.Dense(self.dim * self.mlp_ratio, dtype=self.dtype, name="mlp_in")(y)
        y = nn.gelu(y)
        y = nn.Dense(self.dim, dtype=self.dtype, name="mlp_out")(y)
        return z + y


class TransformerModel(nn.Module):
    """Pre-norm encoder over month-tokens with masked mean pooling."""

    dim: int = 64
    depth: int = 2
    heads: int = 4
    mlp_ratio: int = 4
    head_hidden: Sequence[int] = ()
    heteroscedastic: bool = False
    dropout: float = 0.0
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x, m, deterministic: bool = True):
        w = x.shape[-2]
        compute_dtype = self.dtype or jnp.float32
        z = nn.Dense(self.dim, dtype=self.dtype, name="embed")(
            x.astype(compute_dtype)
        )
        pos = self.param(
            "pos_emb", nn.initializers.normal(0.02), (w, self.dim), jnp.float32
        )
        z = z + pos.astype(z.dtype)
        # Key-padding mask: queries may be anything (pooling ignores invalid
        # outputs); keys must be valid months. [..., 1(heads), W(q), W(kv)]
        attn_mask = jnp.broadcast_to(
            m[..., None, None, :], (*m.shape[:-1], 1, w, w)
        )
        for i in range(self.depth):
            z = EncoderBlock(
                dim=self.dim,
                heads=self.heads,
                mlp_ratio=self.mlp_ratio,
                dropout=self.dropout,
                dtype=self.dtype,
                name=f"block_{i}",
            )(z, attn_mask, deterministic=deterministic)
        z = nn.LayerNorm(dtype=self.dtype, name="ln_f")(z)
        pooled = masked_mean_pool(z, m)
        return ForecastHead(
            hidden=self.head_hidden,
            heteroscedastic=self.heteroscedastic,
            dtype=self.dtype,
            name="head",
        )(pooled)
