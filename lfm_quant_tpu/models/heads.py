"""Shared forecast head: features → point forecast or (mean, log_var)."""

from __future__ import annotations

from typing import Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp


class ForecastHead(nn.Module):
    """MLP head over pooled features.

    Emits fp32 regardless of compute dtype — losses and the backtest always
    see full precision (bf16 in the trunk, fp32 at the boundary is the
    standard TPU mixed-precision recipe).
    """

    hidden: Sequence[int] = ()
    heteroscedastic: bool = False
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, z):
        for i, h in enumerate(self.hidden):
            z = nn.Dense(h, dtype=self.dtype, name=f"hidden_{i}")(z)
            z = nn.gelu(z)
        out_dim = 2 if self.heteroscedastic else 1
        y = nn.Dense(out_dim, dtype=jnp.float32, name="out")(z)
        y = y.astype(jnp.float32)
        if self.heteroscedastic:
            mean, log_var = y[..., 0], y[..., 1]
            # Soft-clamp log-variance for stable NLL early in training.
            log_var = 8.0 * jnp.tanh(log_var / 8.0)
            return mean, log_var
        return y[..., 0]


def masked_mean_pool(z, m):
    """Mean over valid steps: z [..., W, D], m [..., W] → [..., D]."""
    m = m.astype(z.dtype)[..., None]
    denom = jnp.maximum(m.sum(axis=-2), 1.0)
    return (z * m).sum(axis=-2) / denom
