"""Recurrent factor models — parity with the reference's ``rnn_model``
(LSTM/GRU variants; SURVEY.md §3, BASELINE.json:5,8,9).

TPU-first design:

* **Hoisted input projection** (the cuDNN RNN decomposition): the input
  contribution to every gate, ``x_t @ W_x + b`` for all T steps, is ONE
  large ``[B, T, H] @ [H, gates·H]`` GEMM computed outside the scan — a
  shape the MXU tiles perfectly. Only the irreducibly-serial recurrent
  matmul ``h @ W_h`` stays inside the scan, so the serial critical path
  does half the matmul work of a fused ``[x, h]`` cell.
* The time axis is driven by ``lax.scan`` via ``nn.scan`` (prescribed at
  BASELINE.json:5) — compiled once, no Python unrolling.
* The GRU uses the reset-after-projection (cuDNN v2) variant,
  ``n = tanh(x·Wxn + r ⊙ (h·Whn))``, precisely because it lets the x-side
  of all three gates hoist out of the scan; the classic v1 variant
  (reset-before-projection) would force a second in-scan matmul.
* Masking: invalid months HOLD the carried state (h, c unchanged), so a
  firm's forecast is a function of its valid history only; with left-padded
  short histories the initial zero state simply persists until the first
  valid month.
* bf16 compute / fp32 params: pass ``dtype=jnp.bfloat16``.
"""

from __future__ import annotations

from typing import Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp

from lfm_quant_tpu.models.heads import ForecastHead


class LSTMRecurrence(nn.Module):
    """Recurrent-only LSTM step (input contribution precomputed).

    carry = (h, c); input = (xw_t, m_t) where ``xw_t = x_t @ W_x + b`` is
    the hoisted [..., 4H] ifgo input projection and m_t carries a trailing
    singleton dim ([..., 1]) so the scan treats xw and m uniformly on
    axis -2; returns h_t as the per-step output.
    """

    hidden: int
    forget_bias: float = 1.0
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, carry, inp):
        h, c = carry
        xw, m = inp
        gates = xw.astype(h.dtype) + nn.Dense(
            4 * self.hidden, use_bias=False, dtype=self.dtype, name="h_proj"
        )(h)
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        c_new = nn.sigmoid(f + self.forget_bias) * c + nn.sigmoid(i) * jnp.tanh(g)
        h_new = nn.sigmoid(o) * jnp.tanh(c_new)
        keep = m.astype(h.dtype)
        h = keep * h_new + (1.0 - keep) * h
        c = keep * c_new + (1.0 - keep) * c
        return (h, c), h


class GRURecurrence(nn.Module):
    """Recurrent-only GRU step, reset-after-projection (cuDNN v2) variant."""

    hidden: int
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, carry, inp):
        (h,) = carry
        xw, m = inp
        hw = nn.Dense(
            3 * self.hidden, use_bias=False, dtype=self.dtype, name="h_proj"
        )(h)
        xz, xr, xn = jnp.split(xw.astype(h.dtype), 3, axis=-1)
        hz, hr, hn = jnp.split(hw, 3, axis=-1)
        z = nn.sigmoid(xz + hz)
        r = nn.sigmoid(xr + hr)
        n = jnp.tanh(xn + r * hn)
        h_new = (1.0 - z) * n + z * h
        keep = m.astype(h.dtype)
        h = keep * h_new + (1.0 - keep) * h
        return (h,), h


# cell name → (recurrence module, gate multiplier, carry arity)
_CELLS = {"lstm": (LSTMRecurrence, 4, 2), "gru": (GRURecurrence, 3, 1)}


class _GateKernel(nn.Module):
    """Recurrent gate weights for the Pallas scan, declared at the SAME
    parameter path as the ``nn.scan`` recurrence (``<cell>_<n>/h_proj/
    kernel``) so checkpoints are interchangeable between ``scan_impl``
    values. The identity matmul through the Dense returns the kernel matrix
    itself (cast to the compute dtype) — [H, H]·[H, G·H] is noise next to
    the recurrence it feeds.
    """

    features: int
    hidden: int
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self):
        eye = jnp.eye(self.hidden, dtype=self.dtype or jnp.float32)
        return nn.Dense(
            self.features, use_bias=False, dtype=self.dtype, name="h_proj"
        )(eye)


class _DenseParams(nn.Module):
    """Raw input-projection weights for the fused Pallas scan, declared at
    the SAME parameter path and with the same initializers/param dtype as
    the XLA path's ``nn.Dense`` (``<cell>_<n>_xproj/{kernel, bias}``) so
    checkpoints are interchangeable between ``scan_impl`` values. Returned
    raw (fp32): the fused kernel casts to the compute dtype itself.
    """

    in_features: int
    features: int

    @nn.compact
    def __call__(self):
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (self.in_features, self.features), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros_init(),
                          (self.features,), jnp.float32)
        return kernel, bias


class RNNModel(nn.Module):
    """Stacked masked RNN over the lookback window → forecast head.

    ``cell``: "lstm" | "gru".  Input projection lifts F → hidden once; each
    layer then hoists its gate input projection (``gates·H`` wide) out of
    the scan as a single big GEMM, leaving one ``[.., H] @ [H, gates·H]``
    matmul on the serial path per step.
    """

    cell: str = "lstm"
    hidden: int = 128
    layers: int = 1
    head_hidden: Sequence[int] = ()
    heteroscedastic: bool = False
    dtype: Optional[jnp.dtype] = None
    # "xla": nn.scan/lax.scan (default; GSPMD-partitionable). "pallas": the
    # fused single-kernel recurrence (ops/pallas_rnn.py) — h/c resident in
    # VMEM across all T steps; opaque to GSPMD, so use it single-device or
    # inside shard_map. "pallas_fused": additionally computes the gate
    # input projection in-kernel, streaming the H-wide layer input instead
    # of the G·H-wide hoisted projection (~3x less HBM traffic on the
    # recurrence path); identical parameter tree.
    scan_impl: str = "xla"
    # Batch rows per Pallas grid block (None = rnn_scan's default); the
    # tuning knob scripts/sweep_rnn_blocks.py measures.
    scan_block_b: Optional[int] = None

    @nn.compact
    def __call__(self, x, m, deterministic: bool = True):
        if self.cell not in _CELLS:
            raise ValueError(f"cell must be one of {sorted(_CELLS)}")
        rec_cls, gate_mult, carry_n = _CELLS[self.cell]
        compute_dtype = self.dtype or jnp.float32
        batch_shape = x.shape[:-2]
        h = nn.Dense(self.hidden, dtype=self.dtype, name="embed")(
            x.astype(compute_dtype)
        )
        mexp = m[..., None].astype(compute_dtype)  # [..., W, 1]: scan axis -2
        zeros = jnp.zeros((*batch_shape, self.hidden), compute_dtype)
        if self.scan_impl not in ("xla", "pallas", "pallas_fused"):
            raise ValueError(
                "scan_impl must be 'xla', 'pallas' or 'pallas_fused', "
                f"got {self.scan_impl!r}")
        for layer in range(self.layers):
            if self.scan_impl == "pallas_fused":
                from lfm_quant_tpu.ops.pallas_rnn import rnn_scan_fused

                wx, xb = _DenseParams(
                    self.hidden, gate_mult * self.hidden,
                    name=f"{self.cell}_{layer}_xproj",
                )()
                wh = _GateKernel(
                    gate_mult * self.hidden, self.hidden, dtype=self.dtype,
                    name=f"{self.cell}_{layer}",
                )()
                W = h.shape[-2]
                h = rnn_scan_fused(
                    self.cell,
                    h.reshape((-1, W, self.hidden)),
                    wx.astype(compute_dtype),
                    xb.astype(compute_dtype),
                    wh,
                    m.reshape((-1, W)),
                    block_b=self.scan_block_b,
                ).reshape(h.shape[:-1] + (self.hidden,))
                continue
            # Hoisted input projection: all T steps in one GEMM.
            xw = nn.Dense(
                gate_mult * self.hidden, dtype=self.dtype,
                name=f"{self.cell}_{layer}_xproj",
            )(h)
            if self.scan_impl == "pallas":
                from lfm_quant_tpu.ops.pallas_rnn import rnn_scan

                wh = _GateKernel(
                    gate_mult * self.hidden, self.hidden, dtype=self.dtype,
                    name=f"{self.cell}_{layer}",
                )()
                W = xw.shape[-2]
                h = rnn_scan(
                    self.cell,
                    xw.reshape((-1, W, xw.shape[-1])),
                    wh,
                    m.reshape((-1, W)),
                    block_b=self.scan_block_b,
                ).reshape(xw.shape[:-1] + (self.hidden,))
                continue
            scan = nn.scan(
                rec_cls,
                variable_broadcast="params",
                split_rngs={"params": False},
                in_axes=-2,   # time axis of (xw, m) inputs
                out_axes=-2,
            )(hidden=self.hidden, dtype=self.dtype, name=f"{self.cell}_{layer}")
            carry = (zeros,) * carry_n
            _, h = scan(carry, (xw, mexp))
        # Masked steps held state, so the last step's output is the state at
        # the last *valid* month.
        z = h[..., -1, :]
        return ForecastHead(
            hidden=self.head_hidden,
            heteroscedastic=self.heteroscedastic,
            dtype=self.dtype,
            name="head",
        )(z)


def LSTMModel(**kw) -> RNNModel:
    return RNNModel(cell="lstm", **kw)


def GRUModel(**kw) -> RNNModel:
    return RNNModel(cell="gru", **kw)
