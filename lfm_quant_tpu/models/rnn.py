"""Recurrent factor models — parity with the reference's ``rnn_model``
(LSTM/GRU variants; SURVEY.md §3, BASELINE.json:5,8,9).

TPU-first design:

* Each cell step is ONE fused gate matmul ``[x, h] @ W → 4H (LSTM) / 3H
  (GRU)`` so the MXU sees a single large GEMM per step instead of eight
  small ones (the GRU needs a second small matmul for the candidate because
  the reset gate is applied to ``h`` *before* its projection).
* The time axis is driven by ``lax.scan`` via ``nn.scan`` (prescribed at
  BASELINE.json:5) — compiled once, no Python unrolling.
* Masking: invalid months HOLD the carried state (h, c unchanged), so a
  firm's forecast is a function of its valid history only; with left-padded
  short histories the initial zero state simply persists until the first
  valid month.
* bf16 compute / fp32 params: pass ``dtype=jnp.bfloat16``.
"""

from __future__ import annotations

from typing import Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp

from lfm_quant_tpu.models.heads import ForecastHead


class LSTMCellFused(nn.Module):
    """LSTM cell with a single fused ifgo matmul and state-hold masking.

    carry = (h, c), input = (x_t, m_t) where m_t carries a trailing
    singleton dim ([..., 1]) so the scan treats x and m uniformly on axis -2;
    returns h_t as the per-step output.
    """

    hidden: int
    forget_bias: float = 1.0
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, carry, xm):
        h, c = carry
        x, m = xm
        x = x.astype(h.dtype)
        z = jnp.concatenate([x, h], axis=-1)
        gates = nn.Dense(4 * self.hidden, dtype=self.dtype, name="ifgo")(z)
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        c_new = nn.sigmoid(f + self.forget_bias) * c + nn.sigmoid(i) * jnp.tanh(g)
        h_new = nn.sigmoid(o) * jnp.tanh(c_new)
        keep = m.astype(h.dtype)
        h = keep * h_new + (1.0 - keep) * h
        c = keep * c_new + (1.0 - keep) * c
        return (h, c), h


class GRUCellFused(nn.Module):
    """GRU cell: fused z/r matmul + candidate matmul, state-hold masking."""

    hidden: int
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, carry, xm):
        (h,) = carry
        x, m = xm
        x = x.astype(h.dtype)
        zin = jnp.concatenate([x, h], axis=-1)
        zr = nn.Dense(2 * self.hidden, dtype=self.dtype, name="zr")(zin)
        z, r = jnp.split(zr, 2, axis=-1)
        z, r = nn.sigmoid(z), nn.sigmoid(r)
        cand_in = jnp.concatenate([x, r * h], axis=-1)
        n = jnp.tanh(nn.Dense(self.hidden, dtype=self.dtype, name="cand")(cand_in))
        h_new = (1.0 - z) * n + z * h
        keep = m.astype(h.dtype)
        h = keep * h_new + (1.0 - keep) * h
        return (h,), h


_CELLS = {"lstm": LSTMCellFused, "gru": GRUCellFused}


class RNNModel(nn.Module):
    """Stacked masked RNN over the lookback window → forecast head.

    ``cell``: "lstm" | "gru".  Input projection lifts F → hidden once so
    every scan step's fused matmul is (hidden + hidden) × gates — a square,
    MXU-friendly shape even when F is tiny (5–20 in the ladder configs).
    """

    cell: str = "lstm"
    hidden: int = 128
    layers: int = 1
    head_hidden: Sequence[int] = ()
    heteroscedastic: bool = False
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x, m, deterministic: bool = True):
        if self.cell not in _CELLS:
            raise ValueError(f"cell must be one of {sorted(_CELLS)}")
        compute_dtype = self.dtype or jnp.float32
        batch_shape = x.shape[:-2]
        h = nn.Dense(self.hidden, dtype=self.dtype, name="embed")(
            x.astype(compute_dtype)
        )
        mexp = m[..., None].astype(compute_dtype)  # [..., W, 1]: scan axis -2
        zeros = jnp.zeros((*batch_shape, self.hidden), compute_dtype)
        cell_cls = _CELLS[self.cell]
        for layer in range(self.layers):
            scan = nn.scan(
                cell_cls,
                variable_broadcast="params",
                split_rngs={"params": False},
                in_axes=-2,   # time axis of (x, m) inputs
                out_axes=-2,
            )(hidden=self.hidden, dtype=self.dtype, name=f"{self.cell}_{layer}")
            carry = (zeros, zeros) if self.cell == "lstm" else (zeros,)
            _, h = scan(carry, (h, mexp))
        # Masked steps held state, so the last step's output is the state at
        # the last *valid* month.
        z = h[..., -1, :]
        return ForecastHead(
            hidden=self.head_hidden,
            heteroscedastic=self.heteroscedastic,
            dtype=self.dtype,
            name="head",
        )(z)


def LSTMModel(**kw) -> RNNModel:
    return RNNModel(cell="lstm", **kw)


def GRUModel(**kw) -> RNNModel:
    return RNNModel(cell="gru", **kw)
