"""Recurrent factor models — parity with the reference's ``rnn_model``
(LSTM/GRU variants; SURVEY.md §3, BASELINE.json:5,8,9).

TPU-first design:

* **Hoisted input projection** (the cuDNN RNN decomposition): the input
  contribution to every gate, ``x_t @ W_x + b`` for all T steps, is ONE
  large ``[B, T, H] @ [H, gates·H]`` GEMM computed outside the scan — a
  shape the MXU tiles perfectly. Only the irreducibly-serial recurrent
  matmul ``h @ W_h`` stays inside the scan, so the serial critical path
  does half the matmul work of a fused ``[x, h]`` cell.
* The time axis is driven by ``lax.scan`` via ``nn.scan`` (prescribed at
  BASELINE.json:5) — compiled once, no Python unrolling.
* The GRU uses the reset-after-projection (cuDNN v2) variant,
  ``n = tanh(x·Wxn + r ⊙ (h·Whn))``, precisely because it lets the x-side
  of all three gates hoist out of the scan; the classic v1 variant
  (reset-before-projection) would force a second in-scan matmul.
* Masking: invalid months HOLD the carried state (h, c unchanged), so a
  firm's forecast is a function of its valid history only; with left-padded
  short histories the initial zero state simply persists until the first
  valid month.
* bf16 compute / fp32 params: pass ``dtype=jnp.bfloat16``.
"""

from __future__ import annotations

from typing import Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp

from lfm_quant_tpu.models.heads import ForecastHead


class LowRankDense(nn.Module):
    """``W ≈ U @ V`` factorized projection — the "F-LSTM" factorization
    trick (PAPERS.md "Factorization tricks for LSTM networks"): params and
    FLOPs drop from ``in·out`` to ``rank·(in + out)``, worthwhile when
    ``rank < in·out/(in+out)``."""

    features: int
    rank: int
    use_bias: bool = True
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x):
        u = nn.Dense(self.rank, use_bias=False, dtype=self.dtype,
                     name="u")(x)
        return nn.Dense(self.features, use_bias=self.use_bias,
                        dtype=self.dtype, name="v")(u)


class GroupedDense(nn.Module):
    """Block-diagonal projection — the "G-LSTM" grouping trick
    (PAPERS.md): the feature axis splits into ``n_groups`` independent
    slices, each with its own ``[in/g, out/g]`` kernel (params and FLOPs
    ÷ g). Output stays in GROUP-MAJOR order; every consumer in this
    module keeps that layout, so the head simply learns it."""

    features: int
    n_groups: int
    use_bias: bool = True
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x):
        g = self.n_groups
        gin, gout = x.shape[-1] // g, self.features // g
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (g, gin, gout), jnp.float32)
        xg = x.reshape(x.shape[:-1] + (g, gin))
        dtype = self.dtype or x.dtype
        y = jnp.einsum("...gi,gio->...go", xg.astype(dtype),
                       kernel.astype(dtype))
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros_init(),
                              (g, gout), jnp.float32)
            y = y + bias.astype(dtype)
        return y.reshape(x.shape[:-1] + (self.features,))


def _proj(features, factor_rank, n_groups, dtype, use_bias, name):
    """A projection in its dense, low-rank, or grouped form — ONE dispatch
    shared by the in-scan recurrent projection and the hoisted input
    projection, so the two addends can never desynchronize layouts."""
    if factor_rank:
        return LowRankDense(features, factor_rank, use_bias=use_bias,
                            dtype=dtype, name=name)
    if n_groups > 1:
        return GroupedDense(features, n_groups, use_bias=use_bias,
                            dtype=dtype, name=name)
    return nn.Dense(features, use_bias=use_bias, dtype=dtype, name=name)


def _hproj(hidden, gate_mult, factor_rank, n_groups, dtype):
    """The in-scan recurrent projection — always named ``h_proj`` so the
    variants stay siblings in the param tree."""
    return _proj(gate_mult * hidden, factor_rank, n_groups, dtype,
                 use_bias=False, name="h_proj")


def _split_gates(gates, n_gates, n_groups, hidden):
    """Gate slices from a projection output. Grouped layouts interleave
    (group-major): ``[..., g, n_gates, H/g]`` — each gate is the
    concatenation of its per-group slices, matching the group-major h."""
    if n_groups == 1:
        return jnp.split(gates, n_gates, axis=-1)
    lead = gates.shape[:-1]
    gg = gates.reshape(lead + (n_groups, n_gates, hidden // n_groups))
    return [gg[..., i, :].reshape(lead + (hidden,)) for i in range(n_gates)]


class LSTMRecurrence(nn.Module):
    """Recurrent-only LSTM step (input contribution precomputed).

    carry = (h, c); input = (xw_t, m_t) where ``xw_t = x_t @ W_x + b`` is
    the hoisted [..., 4H] ifgo input projection and m_t carries a trailing
    singleton dim ([..., 1]) so the scan treats xw and m uniformly on
    axis -2; returns h_t as the per-step output.

    ``factor_rank``/``n_groups``: the PAPERS.md factorization tricks —
    low-rank (F-LSTM) or block-diagonal (G-LSTM) recurrent projection.
    The hoisted input projection must use the matching layout (RNNModel
    arranges this).
    """

    hidden: int
    forget_bias: float = 1.0
    dtype: Optional[jnp.dtype] = None
    factor_rank: Optional[int] = None
    n_groups: int = 1

    @nn.compact
    def __call__(self, carry, inp):
        h, c = carry
        xw, m = inp
        gates = xw.astype(h.dtype) + _hproj(
            self.hidden, 4, self.factor_rank, self.n_groups, self.dtype)(h)
        i, f, g, o = _split_gates(gates, 4, self.n_groups, self.hidden)
        c_new = nn.sigmoid(f + self.forget_bias) * c + nn.sigmoid(i) * jnp.tanh(g)
        h_new = nn.sigmoid(o) * jnp.tanh(c_new)
        keep = m.astype(h.dtype)
        h = keep * h_new + (1.0 - keep) * h
        c = keep * c_new + (1.0 - keep) * c
        return (h, c), h


class GRURecurrence(nn.Module):
    """Recurrent-only GRU step, reset-after-projection (cuDNN v2) variant.

    ``factor_rank``/``n_groups``: as in LSTMRecurrence.
    """

    hidden: int
    dtype: Optional[jnp.dtype] = None
    factor_rank: Optional[int] = None
    n_groups: int = 1

    @nn.compact
    def __call__(self, carry, inp):
        (h,) = carry
        xw, m = inp
        hw = _hproj(self.hidden, 3, self.factor_rank, self.n_groups,
                    self.dtype)(h)
        xz, xr, xn = _split_gates(xw.astype(h.dtype), 3, self.n_groups,
                                  self.hidden)
        hz, hr, hn = _split_gates(hw, 3, self.n_groups, self.hidden)
        z = nn.sigmoid(xz + hz)
        r = nn.sigmoid(xr + hr)
        n = jnp.tanh(xn + r * hn)
        h_new = (1.0 - z) * n + z * h
        keep = m.astype(h.dtype)
        h = keep * h_new + (1.0 - keep) * h
        return (h,), h


# cell name → (recurrence module, gate multiplier, carry arity)
_CELLS = {"lstm": (LSTMRecurrence, 4, 2), "gru": (GRURecurrence, 3, 1)}


class _GateKernel(nn.Module):
    """Recurrent gate weights for the Pallas scan, declared at the SAME
    parameter path as the ``nn.scan`` recurrence (``<cell>_<n>/h_proj/
    kernel``) so checkpoints are interchangeable between ``scan_impl``
    values. The identity matmul through the Dense returns the kernel matrix
    itself (cast to the compute dtype) — [H, H]·[H, G·H] is noise next to
    the recurrence it feeds.
    """

    features: int
    hidden: int
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self):
        eye = jnp.eye(self.hidden, dtype=self.dtype or jnp.float32)
        return nn.Dense(
            self.features, use_bias=False, dtype=self.dtype, name="h_proj"
        )(eye)


class _DenseParams(nn.Module):
    """Raw input-projection weights for the fused Pallas scan, declared at
    the SAME parameter path and with the same initializers/param dtype as
    the XLA path's ``nn.Dense`` (``<cell>_<n>_xproj/{kernel, bias}``) so
    checkpoints are interchangeable between ``scan_impl`` values. Returned
    raw (fp32): the fused kernel casts to the compute dtype itself.
    """

    in_features: int
    features: int

    @nn.compact
    def __call__(self):
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (self.in_features, self.features), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros_init(),
                          (self.features,), jnp.float32)
        return kernel, bias


class RNNModel(nn.Module):
    """Stacked masked RNN over the lookback window → forecast head.

    ``cell``: "lstm" | "gru".  Input projection lifts F → hidden once; each
    layer then hoists its gate input projection (``gates·H`` wide) out of
    the scan as a single big GEMM, leaving one ``[.., H] @ [H, gates·H]``
    matmul on the serial path per step.
    """

    cell: str = "lstm"
    hidden: int = 128
    layers: int = 1
    head_hidden: Sequence[int] = ()
    heteroscedastic: bool = False
    dtype: Optional[jnp.dtype] = None
    # "xla": nn.scan/lax.scan (default; GSPMD-partitionable). "pallas": the
    # fused single-kernel recurrence (ops/pallas_rnn.py) — h/c resident in
    # VMEM across all T steps; opaque to GSPMD, so use it single-device or
    # inside shard_map. "pallas_fused": additionally computes the gate
    # input projection in-kernel, streaming the H-wide layer input instead
    # of the G·H-wide hoisted projection (~3x less HBM traffic on the
    # recurrence path); identical parameter tree.
    scan_impl: str = "xla"
    # Batch rows per Pallas grid block (None = rnn_scan's default); the
    # tuning knob scripts/sweep_rnn_blocks.py measures.
    scan_block_b: Optional[int] = None
    # Eval-only override of scan_block_b (None = use scan_block_b). The
    # deterministic forward has no backward pass, so its VMEM budget per
    # block is ~3× lighter — it can ride wider blocks than training can
    # afford, and the eval sweep is exactly the per-step-overhead-bound
    # shape wider blocks help (eval MFU ≈ train/3 at equal bb, ledger
    # 2026-07-31 c2 rows; DESIGN.md §9). Selected on `deterministic`,
    # which is already a static jit argument — no extra recompiles.
    eval_scan_block_b: Optional[int] = None
    # PAPERS.md factorization tricks (mutually exclusive; XLA scan only —
    # the Pallas kernels' VMEM/MXU layout assumes dense [H, G·H] weights):
    # factor_rank → low-rank U·V projections (F-LSTM); n_groups → block-
    # diagonal group projections (G-LSTM), hidden % n_groups == 0.
    factor_rank: Optional[int] = None
    n_groups: int = 1

    @nn.compact
    def __call__(self, x, m, deterministic: bool = True):
        if self.cell not in _CELLS:
            raise ValueError(f"cell must be one of {sorted(_CELLS)}")
        rec_cls, gate_mult, carry_n = _CELLS[self.cell]
        factored = bool(self.factor_rank) or self.n_groups > 1
        if self.n_groups < 1:
            raise ValueError(f"n_groups must be >= 1, got {self.n_groups}")
        if self.factor_rank is not None and self.factor_rank < 1:
            raise ValueError(
                f"factor_rank must be >= 1, got {self.factor_rank}")
        if self.factor_rank and self.n_groups > 1:
            raise ValueError(
                "factor_rank and n_groups are alternative factorizations "
                "— set at most one")
        if self.n_groups > 1 and self.hidden % self.n_groups:
            raise ValueError(
                f"hidden={self.hidden} must divide evenly into "
                f"n_groups={self.n_groups}")
        if factored and self.scan_impl != "xla":
            raise ValueError(
                "factor_rank/n_groups need scan_impl='xla': the Pallas "
                "recurrence kernels assume dense gate weights (config "
                "auto-resolution routes factorized models to the XLA "
                "scan; don't force a pallas impl on one)")
        compute_dtype = self.dtype or jnp.float32
        # Select on `is not None`, not truthiness: an EXPLICIT
        # eval_scan_block_b=0 means "pin the kernel default for eval"
        # independently of whatever scan_block_b the train step tuned —
        # a falsy-`or` fallback would silently re-route eval through the
        # train block size.
        block_b = (self.eval_scan_block_b
                   if deterministic and self.eval_scan_block_b is not None
                   else self.scan_block_b)
        batch_shape = x.shape[:-2]
        h = nn.Dense(self.hidden, dtype=self.dtype, name="embed")(
            x.astype(compute_dtype)
        )
        mexp = m[..., None].astype(compute_dtype)  # [..., W, 1]: scan axis -2
        zeros = jnp.zeros((*batch_shape, self.hidden), compute_dtype)
        if self.scan_impl not in ("xla", "pallas", "pallas_fused"):
            raise ValueError(
                "scan_impl must be 'xla', 'pallas' or 'pallas_fused', "
                f"got {self.scan_impl!r}")
        for layer in range(self.layers):
            if self.scan_impl == "pallas_fused":
                from lfm_quant_tpu.ops.pallas_rnn import rnn_scan_fused

                wx, xb = _DenseParams(
                    self.hidden, gate_mult * self.hidden,
                    name=f"{self.cell}_{layer}_xproj",
                )()
                wh = _GateKernel(
                    gate_mult * self.hidden, self.hidden, dtype=self.dtype,
                    name=f"{self.cell}_{layer}",
                )()
                W = h.shape[-2]
                h = rnn_scan_fused(
                    self.cell,
                    h.reshape((-1, W, self.hidden)),
                    wx.astype(compute_dtype),
                    xb.astype(compute_dtype),
                    wh,
                    m.reshape((-1, W)),
                    block_b=block_b,
                ).reshape(h.shape[:-1] + (self.hidden,))
                continue
            # Hoisted input projection: all T steps in one GEMM — in the
            # same (dense/low-rank/grouped) layout as the in-scan gate
            # projection so the two addends share gate ordering.
            xw = _proj(gate_mult * self.hidden, self.factor_rank,
                       self.n_groups, self.dtype, use_bias=True,
                       name=f"{self.cell}_{layer}_xproj")(h)
            if self.scan_impl == "pallas":
                from lfm_quant_tpu.ops.pallas_rnn import rnn_scan

                wh = _GateKernel(
                    gate_mult * self.hidden, self.hidden, dtype=self.dtype,
                    name=f"{self.cell}_{layer}",
                )()
                W = xw.shape[-2]
                h = rnn_scan(
                    self.cell,
                    xw.reshape((-1, W, xw.shape[-1])),
                    wh,
                    m.reshape((-1, W)),
                    block_b=block_b,
                ).reshape(xw.shape[:-1] + (self.hidden,))
                continue
            scan = nn.scan(
                rec_cls,
                variable_broadcast="params",
                split_rngs={"params": False},
                in_axes=-2,   # time axis of (xw, m) inputs
                out_axes=-2,
            )(hidden=self.hidden, dtype=self.dtype,
              factor_rank=self.factor_rank, n_groups=self.n_groups,
              name=f"{self.cell}_{layer}")
            carry = (zeros,) * carry_n
            _, h = scan(carry, (xw, mexp))
        # Masked steps held state, so the last step's output is the state at
        # the last *valid* month.
        z = h[..., -1, :]
        return ForecastHead(
            hidden=self.head_hidden,
            heteroscedastic=self.heteroscedastic,
            dtype=self.dtype,
            name="head",
        )(z)


def LSTMModel(**kw) -> RNNModel:
    return RNNModel(cell="lstm", **kw)


def GRUModel(**kw) -> RNNModel:
    return RNNModel(cell="gru", **kw)
