"""Request micro-batcher: coalesce concurrent queries into one dispatch.

The serving hot path is the PR 2 lesson applied to request traffic:
months are independent given the params, so N concurrent requests for
the same universe are ONE ``[rows, width]`` scoring dispatch, not N
serial ones — each row is one request's padded cross-section, exactly
the ``[M, bf]`` layout the batch eval sweep dispatches. The batcher
thread pops the queue, coalesces same-(universe, width-bucket) requests
for at most ``max_wait_ms`` (or until ``max_rows``), pads to the
request-shape bucket (``serve/buckets.py``) and dispatches through the
zoo entry's cached bucket program. Steady state therefore pays zero jit
traces (every bucket was warmed), zero panel H2D (the panel is
resident), and one small H2D (int32 indices + f32 weights) + one D2H
(f32 scores) per BATCH.

Observability (PR 4 registry): every request is an async
``serve_request`` span begun at submit and ended at completion carrying
``latency_ms`` (the number ``stats()``/bench/trace_report all roll up —
one measurement, three consumers, no drift); every dispatch is a sync
``serve_batch`` span carrying rows/occupancy/queue depth; counters
``serve_requests`` / ``serve_batches`` / ``serve_rows`` /
``serve_rows_real`` / ``serve_queue_peak`` feed the run record.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, Dict, List, NamedTuple, Optional

import numpy as np

from lfm_quant_tpu.serve.buckets import bucket_rows, bucket_width
from lfm_quant_tpu.serve.zoo import ModelZoo
from lfm_quant_tpu.utils import telemetry


class ScoreResponse(NamedTuple):
    """One served query: the month's eligible firms and their scores.

    ``firm_idx`` are panel rows (int32) in pool order; ``scores`` the
    matching float32 forecasts — the ranking signal a client trades on.
    ``generation`` tags which zoo generation served it (every response
    is entirely one generation's — the no-torn-request contract).
    """

    universe: str
    month: int
    generation: int
    firm_idx: np.ndarray
    scores: np.ndarray
    latency_ms: float


class _Request:
    __slots__ = ("universe", "month", "width", "future", "t_submit",
                 "span")

    def __init__(self, universe: str, month: int, width: int,
                 future: Future, span):
        self.universe = universe
        self.month = month
        self.width = width
        self.future = future
        self.t_submit = time.perf_counter()
        self.span = span


class MicroBatcher:
    """The queue + batcher thread. One instance per ScoringService."""

    def __init__(self, zoo: ModelZoo, max_rows: int, max_wait_ms: float,
                 latency_window: int = 65536):
        self.zoo = zoo
        self.max_rows = max(1, int(max_rows))
        self.max_wait_s = max(0.0, float(max_wait_ms)) / 1e3
        self._queue: "deque[_Request]" = deque()
        self._cv = threading.Condition()
        self._stop = False
        self._stats_lock = threading.Lock()
        self._lat_ms: "deque[float]" = deque(maxlen=max(1, latency_window))
        self._rows = 0
        self._rows_real = 0
        self._batches = 0
        self._requests = 0
        self._errors = 0
        self._rejects = 0
        self._queue_peak = 0
        self._thread = threading.Thread(target=self._loop,
                                        name="serve-batcher", daemon=True)
        self._thread.start()

    # ---- client side -------------------------------------------------

    def submit(self, universe: str, month: int) -> Future:
        """Enqueue one scoring query; the Future resolves to a
        :class:`ScoreResponse` (or raises the routing/validation error).
        Validation that only needs the ROUTING table happens here so a
        bad request fails fast without occupying the batcher."""
        future: Future = Future()
        try:
            entry = self.zoo.current(universe)  # KeyError → unregistered
            t = entry.month_col(month)
            n_firms = entry.pool_size(t)  # memoized — no pool copy here
            width = bucket_width(n_firms)
        except Exception as e:  # noqa: BLE001 — routed to the caller
            future.set_exception(e)
            return future
        span = telemetry.begin_async("serve_request", cat="serve",
                                     universe=universe, month=int(month),
                                     n_firms=int(n_firms))
        req = _Request(universe, int(month), width, future, span)
        with self._cv:
            if self._stop:
                span.end(error="closed")
                future.set_exception(
                    RuntimeError("scoring service is closed"))
                return future
            self._queue.append(req)
            depth = len(self._queue)
            self._cv.notify()
        telemetry.COUNTERS.bump("serve_requests")
        telemetry.COUNTERS.peak("serve_queue_peak", depth)
        with self._stats_lock:
            if depth > self._queue_peak:
                self._queue_peak = depth
        return future

    # ---- batcher thread ----------------------------------------------

    def _loop(self) -> None:
        while True:
            batch = self._next_batch()
            if batch is None:
                return
            try:
                self._dispatch(batch)
            except Exception as e:  # noqa: BLE001 — the loop must survive
                with self._stats_lock:
                    self._errors += 1
                for r in batch:
                    if not r.future.done():
                        r.future.set_exception(e)
                    r.span.end(error=type(e).__name__)

    def _next_batch(self) -> Optional[List[_Request]]:
        """Pop the head request, then coalesce same-(universe, width)
        requests until ``max_rows`` or the ``max_wait_ms`` window closes.
        Non-matching requests stay queued in order for the next batch."""
        with self._cv:
            while not self._queue:
                if self._stop:
                    return None
                self._cv.wait(0.05)
            first = self._queue.popleft()
            key = (first.universe, first.width)
            batch = [first]
            deadline = time.perf_counter() + self.max_wait_s
            while len(batch) < self.max_rows:
                matched = False
                for i, r in enumerate(self._queue):
                    if (r.universe, r.width) == key:
                        del self._queue[i]
                        batch.append(r)
                        matched = True
                        break
                if matched:
                    continue
                remaining = deadline - time.perf_counter()
                if remaining <= 0 or self._stop:
                    break
                self._cv.wait(remaining)
                if not self._queue and self._stop:
                    break
            telemetry.COUNTERS.set("serve_queue_depth", len(self._queue))
            return batch

    def _dispatch(self, batch: List[_Request]) -> None:
        universe = batch[0].universe
        with self.zoo.lease(universe) as entry:
            # Per-request validation against the LEASED entry: a request
            # validated at submit against an older generation can be
            # stale by dispatch (a refresh changed the serveable set).
            # Only the stale request fails — its coalesced neighbors
            # must not be poisoned by someone else's KeyError.
            live: List[_Request] = []
            pools = []
            for r in batch:
                try:
                    t = entry.month_col(r.month)
                    pool = entry.pool(t)
                except Exception as e:  # noqa: BLE001 — per-request fate
                    r.span.end(error=type(e).__name__)
                    r.future.set_exception(e)
                    with self._stats_lock:
                        self._rejects += 1
                    continue
                live.append(r)
                pools.append((t, pool))
            batch = live
            if not batch:
                return
            rows = bucket_rows(len(batch), self.max_rows)
            # Re-derive the width from the LEASED entry's pools — the
            # truth this response is built from. Deliberately NOT
            # max()ed with the submit-time bucket: a generation swap
            # between submit and dispatch can change pool sizes either
            # way, and only the width derived from the leased pools is
            # guaranteed to be in the LEASED entry's warmed ladder (a
            # stale submit-time width could force a compile on the
            # serving hot path).
            width = bucket_width(max(p.size for _, p in pools))
            fi = np.zeros((rows, width), np.int32)
            ti = np.zeros((rows,), np.int32)
            w = np.zeros((rows, width), np.float32)
            for i, (t, pool) in enumerate(pools):
                fi[i, :pool.size] = pool
                fi[i, pool.size:] = pool[-1] if pool.size else 0
                ti[i] = t
                w[i, :pool.size] = 1.0
            # Padded rows repeat row 0 at weight 0 (same scheme as the
            # eval sweep's thin dates — shapes static, outputs masked).
            for i in range(len(batch), rows):
                fi[i], ti[i] = fi[0], ti[0]
            occupancy = len(batch) / rows
            with telemetry.span("serve_batch", cat="serve",
                                universe=universe, generation=entry.generation,
                                rows=rows, rows_real=len(batch),
                                width=width, occupancy=round(occupancy, 4),
                                queue_depth=len(self._queue)):
                with entry.lease_panel() as dev:
                    programs = entry.programs_for((rows, width))
                    out = np.asarray(programs(entry.params, dev, fi, ti, w))
            t_done = time.perf_counter()
            gen = entry.generation
        lats = []
        for i, r in enumerate(batch):
            pool = pools[i][1]
            lat = round((t_done - r.t_submit) * 1e3, 3)
            lats.append(lat)
            r.span.end(latency_ms=lat, generation=gen)
            r.future.set_result(ScoreResponse(
                universe=universe, month=r.month, generation=gen,
                firm_idx=pool, scores=out[i, :pool.size].copy(),
                latency_ms=lat))
        telemetry.COUNTERS.bump("serve_batches")
        telemetry.COUNTERS.bump("serve_rows", rows)
        telemetry.COUNTERS.bump("serve_rows_real", len(batch))
        with self._stats_lock:
            self._lat_ms.extend(lats)
            self._rows += rows
            self._rows_real += len(batch)
            self._batches += 1
            self._requests += len(batch)

    # ---- stats / lifecycle -------------------------------------------

    def stats(self) -> Dict[str, Any]:
        from lfm_quant_tpu.serve.stats import latency_summary

        with self._stats_lock:
            lat = list(self._lat_ms)
            rows, real = self._rows, self._rows_real
            out: Dict[str, Any] = {
                "completed": self._requests,
                "batches": self._batches,
                "dispatch_errors": self._errors,
                "rejected": self._rejects,
                # THIS batcher's peak (the process-global
                # serve_queue_peak counter spans every instance and is
                # never reset — it feeds the run record, not stats).
                "queue_peak": self._queue_peak,
            }
        out.update(latency_summary(lat))
        # The rolling window bounds memory on long-lived services; past
        # its size the percentiles cover only the newest requests while
        # trace_report covers every span — the flag marks when the
        # "stats == trace_report" cross-check stops being exact.
        out["latency_truncated"] = out["completed"] > len(lat)
        out["mean_occupancy"] = round(real / rows, 4) if rows else None
        out["rows"] = rows
        out["rows_real"] = real
        return out

    def reset_stats(self) -> None:
        """Zero the rolling stats window (latencies, occupancy, peaks) —
        bench draws the line between warmup and the measured steady
        state with this, so the reported percentiles cover exactly the
        timed window."""
        with self._stats_lock:
            self._lat_ms.clear()
            self._rows = self._rows_real = 0
            self._batches = self._requests = 0
            self._errors = self._rejects = 0
            self._queue_peak = 0

    def close(self) -> None:
        """Stop the batcher thread; drain the queue by failing pending
        requests loudly (a silent drop would hang clients forever)."""
        with self._cv:
            self._stop = True
            pending = list(self._queue)
            self._queue.clear()
            self._cv.notify_all()
        for r in pending:
            if not r.future.done():
                r.future.set_exception(
                    RuntimeError("scoring service closed with the "
                                 "request still queued"))
            r.span.end(error="closed")
        self._thread.join(timeout=10.0)
