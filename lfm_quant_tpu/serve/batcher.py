"""Request micro-batcher: coalesce concurrent queries into one dispatch.

The serving hot path is the PR 2 lesson applied to request traffic:
months are independent given the params, so N concurrent requests for
the same universe are ONE ``[rows, width]`` scoring dispatch, not N
serial ones — each row is one request's padded cross-section, exactly
the ``[M, bf]`` layout the batch eval sweep dispatches. The batcher
thread pops the queue, coalesces same-(universe, width-bucket) requests
for at most ``max_wait_ms`` (or until ``max_rows``), pads to the
request-shape bucket (``serve/buckets.py``) and dispatches through the
zoo entry's cached bucket program. Steady state therefore pays zero jit
traces (every bucket was warmed), zero panel H2D (the panel is
resident), and one small H2D (int32 indices + f32 weights) + one D2H
(f32 scores) per BATCH.

Graceful degradation (DESIGN.md §18 — the chaos-hardened layer; every
path below is drivable on demand via the ``serve_dispatch``/``zoo_lease``
fault sites in utils/faults.py):

* **Bounded admission** — the queue is capped at ``LFM_SERVE_QUEUE_MAX``;
  a submit over the cap is SHED in O(1) (:class:`ShedError`, HTTP 429)
  instead of growing an unbounded backlog where every request times out.
* **Deadlines** — each request carries a deadline (explicit per call,
  else ``LFM_SERVE_DEADLINE_MS``; ``ScoringService.score`` propagates
  its client timeout). Expired or client-cancelled requests are dropped
  BEFORE dispatch (:class:`DeadlineError`, HTTP 504) — a client that
  gave up at 60 s no longer costs a device dispatch.
* **Bounded jittered retry** — a TRANSIENT dispatch failure
  (serve/errors.py ``is_transient``) re-dispatches the surviving batch
  up to ``LFM_SERVE_RETRIES`` times with capped exponential backoff;
  deadlines are re-checked before every retry.
* **Circuit breaker** — ``LFM_SERVE_BREAKER`` consecutive exhausted
  dispatch failures OPEN the circuit: submits fast-fail
  (:class:`CircuitOpenError`, HTTP 503 + retry-after) for
  ``LFM_SERVE_BREAKER_COOLDOWN_MS``, then a half-open probe admits
  traffic again — one success closes the circuit, one failure re-opens
  it. State transitions emit ``circuit_open``/``circuit_half_open``/
  ``circuit_closed`` instants and the ``circuit_state`` gauge
  (0 closed / 1 half-open / 2 open).
* **Thread-death guard** — if the batcher thread dies OUTSIDE the
  per-batch failure path (e.g. ``_next_batch`` raising), every pending
  future is failed loudly (:class:`BatcherDeadError`), subsequent
  submits fail fast, and :meth:`health` reports unready — the pre-chaos
  behavior was every client hanging until its own timeout.

Observability (PR 4 registry): every request is an async
``serve_request`` span begun at submit and ended at completion carrying
``latency_ms`` (the number ``stats()``/bench/trace_report all roll up —
one measurement, three consumers, no drift); every dispatch is a sync
``serve_batch`` span carrying rows/occupancy/queue depth; counters
``serve_requests`` / ``serve_batches`` / ``serve_rows`` /
``serve_rows_real`` / ``serve_queue_peak`` plus the degradation set
``serve_shed`` / ``serve_deadline_drops`` / ``serve_retries`` /
``serve_breaker_opens`` / ``circuit_state`` feed the run record
(rendered by ``scripts/trace_report.py``'s serve section).
"""

from __future__ import annotations

import heapq
import random
import threading
import time
import uuid
import warnings
from collections import deque
from concurrent.futures import Future
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from lfm_quant_tpu.serve.buckets import (
    breaker_cooldown_ms_default,
    breaker_threshold_default,
    bucket_rows,
    bucket_width,
    deadline_ms_default,
    queue_max_default,
    retries_default,
)
from lfm_quant_tpu.serve.errors import (
    BatcherDeadError,
    CircuitOpenError,
    DeadlineError,
    ShedError,
    is_transient,
)
from lfm_quant_tpu.serve.zoo import ModelZoo
from lfm_quant_tpu.utils import faults, flight, metrics, telemetry


def new_request_id() -> str:
    """A fresh 32-hex trace id (W3C ``traceparent`` trace-id width, so
    an id minted here propagates cleanly into any tracing fabric)."""
    return uuid.uuid4().hex


def backoff_sleep(attempt: int) -> None:
    """Capped exponential backoff with full jitter, bounded at 50 ms
    so a retry burst can never stall its caller past a deadline's
    resolution. ONE owner for the formula: the batcher's dispatch
    retry and the fleet router's member failover both sleep through
    here (serve/fleet.py)."""
    time.sleep(min(0.05, 0.002 * (2 ** (attempt - 1)))
               * (0.5 + random.random()))


def clean_request_id(rid: Optional[str]) -> Optional[str]:
    """Sanitize an INBOUND id (header-sourced — hostile by default):
    keep it opaque but bounded and log-line-safe. None/empty → None
    (the caller mints one)."""
    if not rid:
        return None
    # Pre-truncate BEFORE the per-character filter: header values can
    # be tens of KB, and the filter must not scan all of it per request.
    rid = "".join(c for c in str(rid).strip()[:256]
                  if c.isalnum() or c in "-_.")[:64]
    return rid or None


class ScoreResponse(NamedTuple):
    """One served query: the month's eligible firms and their scores.

    ``firm_idx`` are panel rows (int32) in pool order; ``scores`` the
    matching float32 forecasts — the ranking signal a client trades on.
    ``generation`` tags which zoo generation served it (every response
    is entirely one generation's — the no-torn-request contract).
    """

    universe: str
    month: int
    generation: int
    firm_idx: np.ndarray
    scores: np.ndarray
    latency_ms: float
    #: Request-scoped trace id (DESIGN.md §21): minted at submit or
    #: propagated from the caller's X-Request-Id / traceparent header —
    #: the same id the serve_request span, the access log, the slow-
    #: trace tracker and the histogram exemplars all carry.
    request_id: str = ""
    #: The per-request phase breakdown (ms): queue_ms (submit → joined
    #: a batch), batch_ms (coalescing-window wait), dispatch_ms (the
    #: successful device attempt), retry_ms (failed attempts+backoff),
    #: retries (count). Recorded O(1) from perf_counter stamps.
    phases: Optional[Dict[str, Any]] = None


class _Request:
    __slots__ = ("universe", "month", "width", "future", "t_submit",
                 "span", "deadline", "rid", "t_batched", "t_dispatch0",
                 "t_dispatch", "retries")

    def __init__(self, universe: str, month: int, width: int,
                 future: Future, span, deadline: Optional[float],
                 rid: str):
        self.universe = universe
        self.month = month
        self.width = width
        self.future = future
        self.t_submit = time.perf_counter()
        self.span = span
        self.deadline = deadline  # absolute perf_counter seconds, or None
        self.rid = rid
        # Phase stamps (perf_counter): set as the request moves through
        # the pipeline — queue pop, first dispatch attempt, last
        # dispatch attempt. O(1) per request, no allocation.
        self.t_batched: Optional[float] = None
        self.t_dispatch0: Optional[float] = None
        self.t_dispatch: Optional[float] = None
        self.retries = 0

    def phase_breakdown(self, t_done: float) -> Dict[str, Any]:
        """The queue/batch/dispatch/retry split of this request's
        latency, in ms (DESIGN.md §21). Stamps missing on early-failed
        requests degrade to the last known boundary, so the phases
        always sum to ~latency."""
        tb = self.t_batched if self.t_batched is not None else t_done
        td0 = self.t_dispatch0 if self.t_dispatch0 is not None else t_done
        td = self.t_dispatch if self.t_dispatch is not None else td0
        return {
            "queue_ms": round(max(0.0, tb - self.t_submit) * 1e3, 3),
            "batch_ms": round(max(0.0, td0 - tb) * 1e3, 3),
            "retry_ms": round(max(0.0, td - td0) * 1e3, 3),
            "dispatch_ms": round(max(0.0, t_done - td) * 1e3, 3),
            "retries": self.retries,
        }


class MicroBatcher:
    """The queue + batcher thread. One instance per ScoringService."""

    #: How many slowest request traces the rolling tracker keeps (the
    #: incident bundles' ``slow_requests.json`` and the trace_report
    #: waterfall's depth). Bounded heap: O(log K) per completion.
    SLOW_TRACES_K = 16

    def __init__(self, zoo: ModelZoo, max_rows: int, max_wait_ms: float,
                 latency_window: int = 65536,
                 queue_max: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 retries: Optional[int] = None,
                 breaker_threshold: Optional[int] = None,
                 breaker_cooldown_ms: Optional[float] = None):
        self.zoo = zoo
        self.max_rows = max(1, int(max_rows))
        self.max_wait_s = max(0.0, float(max_wait_ms)) / 1e3
        # Degradation knobs: explicit ctor values win (tests/bench),
        # else the LFM_SERVE_* env defaults (serve/buckets.py).
        self.queue_max = int(queue_max if queue_max is not None
                             else queue_max_default())
        self.default_deadline_s = float(
            deadline_ms if deadline_ms is not None
            else deadline_ms_default()) / 1e3
        self.retries = max(0, int(retries if retries is not None
                                  else retries_default()))
        self._breaker_threshold = int(
            breaker_threshold if breaker_threshold is not None
            else breaker_threshold_default())
        self._breaker_cooldown_s = max(0.0, float(
            breaker_cooldown_ms if breaker_cooldown_ms is not None
            else breaker_cooldown_ms_default())) / 1e3
        self._queue: "deque[_Request]" = deque()
        self._cv = threading.Condition()
        self._stop = False
        # Breaker / death state (guarded by _cv; _dead is also read
        # lock-free on the submit fast path — a benign GIL-atomic read).
        self._circuit = "closed"  # closed | half_open | open
        self._fail_streak = 0
        self._open_until = 0.0
        self._dead: Optional[BaseException] = None
        self._stats_lock = threading.Lock()
        self._lat_ms: "deque[float]" = deque(maxlen=max(1, latency_window))
        # The K slowest completed request traces since the last stats
        # reset (a bounded min-heap — O(log K) per completion, keyed on
        # latency with a monotone tiebreak so trace dicts never
        # compare): the incident bundles' slow-request evidence and the
        # trace_report waterfall's cross-check anchor.
        self._slow: List[Tuple[float, int, Dict[str, Any]]] = []
        self._slow_seq = 0
        # Incident hook (serve/incident.py): set by ScoringService; a
        # breaker OPEN transition triggers an automatic capture. Plain
        # attribute read on the failure path — never on the hot path.
        self.incidents: Optional[Any] = None
        self._rows = 0
        self._rows_real = 0
        self._batches = 0
        self._requests = 0
        self._errors = 0
        self._rejects = 0
        self._queue_peak = 0
        self._shed = 0
        self._deadline_drops = 0
        self._retry_count = 0
        self._breaker_opens = 0
        self._thread = threading.Thread(target=self._loop,
                                        name="serve-batcher", daemon=True)
        self._thread.start()

    # ---- client side -------------------------------------------------

    def submit(self, universe: str, month: int,
               deadline_ms: Optional[float] = None,
               request_id: Optional[str] = None) -> Future:
        """Enqueue one scoring query; the Future resolves to a
        :class:`ScoreResponse` (or raises the routing/validation/
        degradation error). Validation that only needs the ROUTING
        table happens here so a bad request fails fast without
        occupying the batcher; admission control (dead batcher, open
        circuit, full queue) fails fast the same way. ``deadline_ms``
        (else ``LFM_SERVE_DEADLINE_MS``; 0/None = none) bounds how long
        the request may wait — past it the batcher DROPS it before
        dispatch. ``request_id`` propagates a caller-supplied trace id
        (the front door's X-Request-Id / traceparent header); None
        mints a fresh one — either way the id rides the span, the
        response, the access log and the exemplars (DESIGN.md §21)."""
        rid = clean_request_id(request_id) or new_request_id()
        future: Future = Future()
        dead = self._dead
        if dead is not None:
            future.set_exception(BatcherDeadError(dead))
            return future
        now = time.perf_counter()
        with self._cv:
            state, ticked = self._circuit_tick_locked(now)
            open_until = self._open_until
        if ticked:
            self._emit_half_open()
        if state == "open":
            telemetry.COUNTERS.bump("serve_circuit_rejects")
            metrics.METRICS.mark("serve_err")
            future.set_exception(CircuitOpenError(open_until - now))
            return future
        try:
            entry = self.zoo.current(universe)  # KeyError → unregistered
            t = entry.month_col(month)
            n_firms = entry.pool_size(t)  # memoized — no pool copy here
            width = bucket_width(n_firms)
        except Exception as e:  # noqa: BLE001 — routed to the caller
            future.set_exception(e)
            return future
        if deadline_ms is None:
            deadline_ms = self.default_deadline_s * 1e3
        deadline = (now + deadline_ms / 1e3
                    if deadline_ms and deadline_ms > 0 else None)
        span = telemetry.begin_async("serve_request", cat="serve",
                                     universe=universe, month=int(month),
                                     n_firms=int(n_firms),
                                     request_id=rid)
        req = _Request(universe, int(month), width, future, span, deadline,
                       rid)
        shed = False
        with self._cv:
            if self._dead is not None:
                span.end(error="unready")
                future.set_exception(BatcherDeadError(self._dead))
                return future
            if self._stop:
                span.end(error="closed")
                future.set_exception(
                    RuntimeError("scoring service is closed"))
                return future
            if 0 < self.queue_max <= len(self._queue):
                shed = True
            else:
                self._queue.append(req)
                depth = len(self._queue)
                self._cv.notify()
        if shed:
            span.end(error="shed")
            telemetry.COUNTERS.bump("serve_shed")
            metrics.METRICS.mark("serve_err")  # availability budget
            # Dedicated shed ring: the incident layer's shed-rate-spike
            # trigger reads it (serve/monitor.py) — serve_err blends
            # sheds with dispatch errors and deadline drops.
            metrics.METRICS.mark("serve_shed")
            flight.record("shed", universe=universe, month=int(month),
                          request_id=rid, queue_max=self.queue_max)
            with self._stats_lock:
                self._shed += 1
            future.set_exception(ShedError(self.queue_max))
            return future
        telemetry.COUNTERS.bump("serve_requests")
        telemetry.COUNTERS.peak("serve_queue_peak", depth)
        with self._stats_lock:
            if depth > self._queue_peak:
                self._queue_peak = depth
        return future

    # ---- circuit breaker ---------------------------------------------

    def _circuit_tick_locked(self, now: float) -> Tuple[str, bool]:
        """Advance the breaker clock (caller holds ``_cv``): an OPEN
        circuit whose cooldown elapsed becomes HALF-OPEN — admission
        resumes and the next dispatch outcome decides. Returns
        ``(state, transitioned)``; the CALLER emits the transition
        telemetry after releasing the lock (the zoo.lease convention —
        an instant's trace write must never run under the admission
        lock every submit contends on)."""
        if self._circuit == "open" and now >= self._open_until:
            self._circuit = "half_open"
            return "half_open", True
        return self._circuit, False

    @staticmethod
    def _emit_half_open() -> None:
        telemetry.COUNTERS.set("circuit_state", 1)
        telemetry.instant("circuit_half_open", cat="serve")

    def _dispatch_ok(self) -> None:
        with self._cv:
            self._fail_streak = 0
            reclosed = self._circuit != "closed"
            self._circuit = "closed"
        if reclosed:
            telemetry.COUNTERS.set("circuit_state", 0)
            telemetry.instant("circuit_closed", cat="serve")

    def _dispatch_fail(self) -> None:
        """One exhausted dispatch (retries included) failed: advance the
        streak; at the threshold — or instantly in half-open (the probe
        failed) — OPEN the circuit for the cooldown."""
        opened = False
        with self._cv:
            self._fail_streak += 1
            streak = self._fail_streak
            if self._breaker_threshold > 0 and (
                    self._circuit == "half_open"
                    or streak >= self._breaker_threshold):
                opened = self._circuit != "open"
                self._circuit = "open"
                self._open_until = (time.perf_counter()
                                    + self._breaker_cooldown_s)
        if opened:
            telemetry.COUNTERS.set("circuit_state", 2)
            telemetry.COUNTERS.bump("serve_breaker_opens")
            telemetry.instant("circuit_open", cat="serve", streak=streak)
            with self._stats_lock:
                self._breaker_opens += 1
            # Automatic incident capture (DESIGN.md §21): the breaker
            # opening IS the degradation moment — snapshot the evidence
            # (flight ring, scrape, slow traces) before it scrolls
            # away. The capture runs on its own thread; this is one
            # attribute read + a rate-limited trigger call.
            inc = self.incidents
            if inc is not None:
                inc.trigger("breaker_open", streak=streak)

    # ---- batcher thread ----------------------------------------------

    def _loop(self) -> None:
        try:
            while True:
                batch = self._next_batch()
                if batch is None:
                    return
                try:
                    self._dispatch(batch)
                except Exception as e:  # noqa: BLE001 — the loop survives
                    with self._stats_lock:
                        self._errors += 1
                    failed = 0
                    for r in batch:
                        if not r.future.done():
                            r.future.set_exception(e)
                            failed += 1
                        r.span.end(error=type(e).__name__)
                    if failed:
                        metrics.METRICS.mark("serve_err", float(failed))
        except BaseException as e:  # noqa: BLE001 — death guard
            # The loop died OUTSIDE the per-batch failure path (e.g.
            # _next_batch raising): without this guard every pending and
            # future submit hangs until client timeout.
            self._die(e)
            raise

    def _die(self, exc: BaseException) -> None:
        """Batcher-thread death: fail pending futures LOUDLY, mark the
        service unready (submits fast-fail, /healthz goes 503)."""
        with self._cv:
            self._dead = exc
            pending = list(self._queue)
            self._queue.clear()
            self._cv.notify_all()
        telemetry.COUNTERS.set("serve_batcher_dead", 1)
        telemetry.instant("batcher_died", cat="serve",
                          error=type(exc).__name__)
        warnings.warn(
            f"serve batcher thread died: {type(exc).__name__}: {exc} — "
            f"failing {len(pending)} pending request(s); the service is "
            "unready until restarted", RuntimeWarning, stacklevel=2)
        for r in pending:
            if not r.future.done():
                r.future.set_exception(BatcherDeadError(exc))
            r.span.end(error="batcher_dead")

    def _next_batch(self) -> Optional[List[_Request]]:
        """Pop the head request, then coalesce same-(universe, width)
        requests until ``max_rows`` or the ``max_wait_ms`` window closes.
        Non-matching requests stay queued in order for the next batch."""
        with self._cv:
            while not self._queue:
                if self._stop:
                    return None
                self._cv.wait(0.05)
            first = self._queue.popleft()
            first.t_batched = time.perf_counter()
            key = (first.universe, first.width)
            batch = [first]
            deadline = first.t_batched + self.max_wait_s
            while len(batch) < self.max_rows:
                matched = False
                for i, r in enumerate(self._queue):
                    if (r.universe, r.width) == key:
                        del self._queue[i]
                        r.t_batched = time.perf_counter()
                        batch.append(r)
                        matched = True
                        break
                if matched:
                    continue
                remaining = deadline - time.perf_counter()
                if remaining <= 0 or self._stop:
                    break
                self._cv.wait(remaining)
                if not self._queue and self._stop:
                    break
            telemetry.COUNTERS.set("serve_queue_depth", len(self._queue))
            return batch

    def _reap(self, batch: List[_Request]) -> List[_Request]:
        """Drop expired / client-abandoned requests BEFORE they cost a
        device dispatch (the deadline contract — run again before every
        retry, since backoff consumes deadline budget too)."""
        now = time.perf_counter()
        live: List[_Request] = []
        dropped = 0
        for r in batch:
            if r.future.cancelled():
                r.span.end(error="abandoned")
                dropped += 1
                continue
            if r.future.done():
                continue  # already routed (validation failure)
            if r.deadline is not None and now >= r.deadline:
                r.span.end(error="deadline", **r.phase_breakdown(now))
                flight.record("deadline_drop", universe=r.universe,
                              month=r.month, request_id=r.rid,
                              overdue_ms=round((now - r.deadline) * 1e3, 3))
                r.future.set_exception(
                    DeadlineError(r.universe, r.month, now - r.deadline))
                dropped += 1
                continue
            live.append(r)
        if dropped:
            telemetry.COUNTERS.bump("serve_deadline_drops", dropped)
            metrics.METRICS.mark("serve_err", float(dropped))
            with self._stats_lock:
                self._deadline_drops += dropped
        return live

    def _dispatch(self, batch: List[_Request]) -> None:
        """Dispatch with bounded jittered retry: a TRANSIENT failure
        (serve/errors.py ``is_transient`` — injected faults and
        retryable runtime statuses) re-dispatches the SURVIVING batch
        (deadlines re-checked) up to ``self.retries`` times; permanent
        failures and exhaustion fail the batch and feed the breaker."""
        universe = batch[0].universe
        attempt = 0
        while True:
            batch = self._reap(batch)
            if not batch:
                return
            try:
                self._dispatch_once(universe, batch)
                return
            except Exception as e:  # noqa: BLE001 — classified below
                batch = [r for r in batch if not r.future.done()]
                if (not is_transient(e) or attempt >= self.retries
                        or self._stop):
                    self._dispatch_fail()
                    flight.record("dispatch_fail", universe=universe,
                                  rows=len(batch), attempt=attempt,
                                  error=type(e).__name__)
                    raise
                attempt += 1
                for r in batch:
                    r.retries += 1
                telemetry.COUNTERS.bump("serve_retries")
                flight.record("retry", universe=universe,
                              rows=len(batch), attempt=attempt,
                              error=type(e).__name__)
                with self._stats_lock:
                    self._retry_count += 1
                backoff_sleep(attempt)

    def _dispatch_once(self, universe: str, batch: List[_Request]) -> None:
        # Phase stamps (O(1) per request): first attempt fixes the end
        # of the coalescing wait, the last attempt anchors dispatch_ms
        # — the gap between the two is retry_ms (failed attempts plus
        # backoff). Stamped BEFORE the fault site: an injected failure
        # is part of the attempt it fails.
        t_attempt = time.perf_counter()
        for r in batch:
            if r.t_dispatch0 is None:
                r.t_dispatch0 = t_attempt
            r.t_dispatch = t_attempt
        faults.check("serve_dispatch", universe=universe,
                     rows=len(batch))
        with self.zoo.lease(universe) as entry:
            # Per-request validation against the LEASED entry: a request
            # validated at submit against an older generation can be
            # stale by dispatch (a refresh changed the serveable set).
            # Only the stale request fails — its coalesced neighbors
            # must not be poisoned by someone else's KeyError.
            live: List[_Request] = []
            pools = []
            for r in batch:
                try:
                    t = entry.month_col(r.month)
                    pool = entry.pool(t)
                except Exception as e:  # noqa: BLE001 — per-request fate
                    r.span.end(error=type(e).__name__)
                    r.future.set_exception(e)
                    with self._stats_lock:
                        self._rejects += 1
                    continue
                live.append(r)
                pools.append((t, pool))
            batch = live
            if not batch:
                return
            rows = bucket_rows(len(batch), self.max_rows)
            # Re-derive the width from the LEASED entry's pools — the
            # truth this response is built from. Deliberately NOT
            # max()ed with the submit-time bucket: a generation swap
            # between submit and dispatch can change pool sizes either
            # way, and only the width derived from the leased pools is
            # guaranteed to be in the LEASED entry's warmed ladder (a
            # stale submit-time width could force a compile on the
            # serving hot path).
            width = bucket_width(max(p.size for _, p in pools))
            fi = np.zeros((rows, width), np.int32)
            ti = np.zeros((rows,), np.int32)
            w = np.zeros((rows, width), np.float32)
            for i, (t, pool) in enumerate(pools):
                fi[i, :pool.size] = pool
                fi[i, pool.size:] = pool[-1] if pool.size else 0
                ti[i] = t
                w[i, :pool.size] = 1.0
            # Padded rows repeat row 0 at weight 0 (same scheme as the
            # eval sweep's thin dates — shapes static, outputs masked).
            for i in range(len(batch), rows):
                fi[i], ti[i] = fi[0], ti[0]
            occupancy = len(batch) / rows
            with telemetry.span("serve_batch", cat="serve",
                                universe=universe, generation=entry.generation,
                                rows=rows, rows_real=len(batch),
                                width=width, occupancy=round(occupancy, 4),
                                queue_depth=len(self._queue)):
                with entry.lease_panel() as dev:
                    programs = entry.programs_for((rows, width))
                    out = np.asarray(programs(entry.params, dev, fi, ti, w))
            # Success bookkeeping BEFORE the futures resolve: a client
            # woken by its result must observe the breaker already
            # reset/closed (health() right after a successful probe).
            self._dispatch_ok()
            t_done = time.perf_counter()
            gen = entry.generation
        lats = []
        score_slices = []
        slow_items = []
        for i, r in enumerate(batch):
            pool = pools[i][1]
            lat = round((t_done - r.t_submit) * 1e3, 3)
            lats.append(lat)
            scores = out[i, :pool.size].copy()
            score_slices.append(scores)
            # The per-request causal trail (DESIGN.md §21): where the
            # latency went — queue, coalescing window, retries,
            # dispatch — echoed in the span (trace_report's waterfall),
            # the response (the client/access log) and the slow-trace
            # tracker (incident bundles).
            phases = r.phase_breakdown(t_done)
            phases["width"] = width  # the bucket that served it
            slow_items.append({
                "request_id": r.rid, "universe": universe,
                "month": r.month, "rows": rows,
                "generation": gen, "latency_ms": lat, **phases})
            r.span.end(latency_ms=lat, generation=gen,
                       request_id=r.rid, **phases)
            r.future.set_result(ScoreResponse(
                universe=universe, month=r.month, generation=gen,
                firm_idx=pool, scores=scores, latency_ms=lat,
                request_id=r.rid, phases=phases))
        # Live metrics plane (utils/metrics.py, DESIGN.md §19): O(1)
        # per event, lock-guarded inside each instrument, exact no-op
        # under LFM_METRICS=0. Latency attributed per (universe,
        # width-bucket) — the Khomenko-style request stream means a
        # bucket-ladder regression must be visible per bucket, not
        # blended away — plus the SLO rings and the drift sketch (the
        # served scores are already host arrays; nothing here touches
        # the device).
        if metrics.enabled():
            m = metrics.METRICS
            # One label-set resolution per BATCH, then bare records —
            # and no numpy anywhere in this block: numpy calls release
            # the GIL, and a GIL release on this thread under
            # closed-loop contention costs a scheduling quantum.
            hist = m.histogram("serve_latency_ms",
                               universe=universe, width=width)
            for r, lat in zip(batch, lats):
                # Exemplar wiring (DESIGN.md §21): each bucket keeps
                # the last trace id that landed in it — O(1), no
                # allocation growth — so a p99 bucket in a scrape
                # points at a REAL request whose phase breakdown is in
                # the slow-trace tracker / span record.
                hist.record(lat, exemplar=r.rid)
            m.mark("serve_ok", float(len(batch)))
            slo_ms = metrics.slo_p99_ms_default()
            if slo_ms > 0:
                bad = sum(1 for lat in lats if lat > slo_ms)
                if bad:
                    m.mark("serve_slo_lat_bad", float(bad))
            # The response-path copies, not views of `out`: a lazy
            # sketch entry pins its base array until a fold, and 256
            # pending views of full (rows × width) batch outputs is
            # tens of MB at large width. The fold re-copies to f64, so
            # sharing with the (read-mostly) client response is safe.
            entry.record_scores(score_slices)
        telemetry.COUNTERS.bump("serve_batches")
        telemetry.COUNTERS.bump("serve_rows", rows)
        telemetry.COUNTERS.bump("serve_rows_real", len(batch))
        flight.record("dispatch", universe=universe, rows=rows,
                      rows_real=len(batch), width=width, generation=gen,
                      ms=round((t_done - t_attempt) * 1e3, 3))
        with self._stats_lock:
            self._lat_ms.extend(lats)
            self._rows += rows
            self._rows_real += len(batch)
            self._batches += 1
            self._requests += len(batch)
            for item in slow_items:
                self._slow_seq += 1
                entry = (item["latency_ms"], self._slow_seq, item)
                if len(self._slow) < self.SLOW_TRACES_K:
                    heapq.heappush(self._slow, entry)
                elif entry[0] > self._slow[0][0]:
                    heapq.heapreplace(self._slow, entry)

    # ---- stats / health / lifecycle ----------------------------------

    def slow_traces(self) -> List[Dict[str, Any]]:
        """The K slowest completed request traces since the last stats
        reset, slowest first — request id, routing, and the full
        queue/batch/retry/dispatch phase breakdown each (the incident
        bundles' slow-request evidence, DESIGN.md §21)."""
        with self._stats_lock:
            items = [dict(item) for _, _, item in self._slow]
        return sorted(items, key=lambda d: -d["latency_ms"])

    def queue_depth(self) -> int:
        """Current queue depth (gauge read: a single ``len`` is
        GIL-atomic; staleness by one in-flight submit is the documented
        worst case for cross-thread gauge readers)."""
        return len(self._queue)

    def circuit_state_code(self) -> int:
        """The ``circuit_state`` gauge encoding (DESIGN.md §18 +
        §19): 0 closed, 1 half-open, 2 open, 3 batcher dead."""
        if self._dead is not None:
            return 3
        return {"closed": 0, "half_open": 1, "open": 2}.get(
            self._circuit, 0)

    def health(self) -> Dict[str, Any]:
        """Readiness, with the reason when degraded: a dead batcher
        thread or an OPEN circuit is NOT ready (the /healthz 503 path);
        half-open is ready-but-probing. ``retry_after_s`` carries the
        remaining cooldown when open."""
        dead = self._dead
        if dead is not None:
            return {"ok": False, "circuit": "dead",
                    "reason": ("batcher thread dead: "
                               f"{type(dead).__name__}: {dead}")}
        now = time.perf_counter()
        with self._cv:
            if self._stop:
                return {"ok": False, "circuit": self._circuit,
                        "reason": "service closed"}
            state, ticked = self._circuit_tick_locked(now)
            retry = max(0.0, self._open_until - now)
        if ticked:
            self._emit_half_open()
        if state == "open":
            return {"ok": False, "circuit": state,
                    "reason": ("circuit open (consecutive dispatch "
                               "failures); fast-failing until the "
                               "half-open probe"),
                    "retry_after_s": round(retry, 3)}
        return {"ok": True, "circuit": state}

    def stats(self) -> Dict[str, Any]:
        from lfm_quant_tpu.serve.stats import latency_summary

        with self._stats_lock:
            lat = list(self._lat_ms)
            rows, real = self._rows, self._rows_real
            out: Dict[str, Any] = {
                "completed": self._requests,
                "batches": self._batches,
                "dispatch_errors": self._errors,
                "rejected": self._rejects,
                "shed": self._shed,
                "deadline_drops": self._deadline_drops,
                "retries": self._retry_count,
                "breaker_opens": self._breaker_opens,
                # THIS batcher's peak (the process-global
                # serve_queue_peak counter spans every instance and is
                # never reset — it feeds the run record, not stats).
                "queue_peak": self._queue_peak,
            }
        out["circuit"] = ("dead" if self._dead is not None
                          else self._circuit)
        out.update(latency_summary(lat))
        # The rolling window bounds memory on long-lived services; past
        # its size the percentiles cover only the newest requests while
        # trace_report covers every span — the flag marks when the
        # "stats == trace_report" cross-check stops being exact.
        out["latency_truncated"] = out["completed"] > len(lat)
        out["mean_occupancy"] = round(real / rows, 4) if rows else None
        out["rows"] = rows
        out["rows_real"] = real
        return out

    def carry_stats(self, other: "MicroBatcher") -> None:
        """Adopt a predecessor batcher's rolling stats — the
        in-process ``restart_batcher`` recovery (DESIGN.md §20) swaps
        the thread, not the observability: completed counts, latency
        window, degradation tallies and peaks carry over so ``stats()``
        stays continuous across the restart. Both stats locks are
        taken in sequence, never nested (the predecessor is already
        closed — nothing concurrently mutates it)."""
        with other._stats_lock:
            lat = list(other._lat_ms)
            slow = [item for _, _, item in other._slow]
            snap = (other._rows, other._rows_real, other._batches,
                    other._requests, other._errors, other._rejects,
                    other._queue_peak, other._shed,
                    other._deadline_drops, other._retry_count,
                    other._breaker_opens)
        with self._stats_lock:
            self._lat_ms.extend(lat)
            for item in slow:
                self._slow_seq += 1
                entry = (item["latency_ms"], self._slow_seq, item)
                if len(self._slow) < self.SLOW_TRACES_K:
                    heapq.heappush(self._slow, entry)
                elif entry[0] > self._slow[0][0]:
                    heapq.heapreplace(self._slow, entry)
            (rows, real, batches, requests, errors, rejects, peak,
             shed, drops, retries, opens) = snap
            self._rows += rows
            self._rows_real += real
            self._batches += batches
            self._requests += requests
            self._errors += errors
            self._rejects += rejects
            self._queue_peak = max(self._queue_peak, peak)
            self._shed += shed
            self._deadline_drops += drops
            self._retry_count += retries
            self._breaker_opens += opens

    def reset_stats(self) -> None:
        """Zero the rolling stats window (latencies, occupancy, peaks,
        degradation tallies) — bench draws the line between warmup and
        the measured steady state with this, so the reported
        percentiles cover exactly the timed window. Circuit STATE is
        not reset — it is live machinery, not a statistic."""
        with self._stats_lock:
            self._lat_ms.clear()
            self._slow.clear()
            self._rows = self._rows_real = 0
            self._batches = self._requests = 0
            self._errors = self._rejects = 0
            self._queue_peak = 0
            self._shed = self._deadline_drops = 0
            self._retry_count = self._breaker_opens = 0

    def close(self) -> None:
        """Stop the batcher thread; drain the queue by failing pending
        requests loudly (a silent drop would hang clients forever)."""
        with self._cv:
            self._stop = True
            pending = list(self._queue)
            self._queue.clear()
            self._cv.notify_all()
        for r in pending:
            if not r.future.done():
                r.future.set_exception(
                    RuntimeError("scoring service closed with the "
                                 "request still queued"))
            r.span.end(error="closed")
        self._thread.join(timeout=10.0)
