"""HBM-resident model zoo: many (universe × generation) served models.

Each :class:`ZooEntry` is one servable model generation: a fitted
``Trainer`` whose compiled programs and device-resident panel came
through the PR 1 reuse caches (``train/reuse.py`` program cache,
``data/windows.py cached_device_panel``), plus the serving-side pools
(which firms are scoreable for which months, ``require_target=False``
so LIVE months — the ones a production user actually trades on — are
servable) and the per-bucket scoring programs.

Lifecycle invariants, all lock-guarded and refcount-safe:

* **Lease, don't grab** — the batcher scores through ``zoo.lease()``,
  which pins the entry for the dispatch. Publish/evict NEVER tears a
  leased entry: it is atomically unlinked from the routing table (new
  requests route to the new generation / miss) and decommissioned only
  when the last lease drains.
* **Atomic generation swap** — :meth:`ModelZoo.publish` replaces the
  current generation in one lock region; every request is served
  entirely by one generation (no torn reads), and the old generation's
  HBM is reclaimed once its in-flight dispatches finish.
* **LRU eviction** — capacity is counted in resident universes
  (``LFM_SERVE_ZOO``); the least-recently-leased universe is evicted
  when a registration overflows it. Eviction releases the panel's
  device residency through ``invalidate_panel`` — whose own
  refcount/deferred-drop machinery (``data/windows.py``) makes that
  safe under an in-flight dispatch — unless another resident entry
  still shares the panel object.
"""

from __future__ import annotations

import contextlib
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional

import numpy as np

from lfm_quant_tpu.serve.buckets import BucketKey, width_ladder
from lfm_quant_tpu.utils import telemetry


class ServePrograms:
    """The compiled scoring program of ONE request-shape bucket, cached
    in the cross-fold program cache under ``reuse.serve_program_key``:
    a jitted forecast-only forward (the inner ``TrainerPrograms``
    impl with per-month metrics compiled out) plus the weight mask that
    zeroes padded slots — the same masking ``_aggregate_modes`` applies,
    so served scores are bit-identical to the batch scoring path's.
    Holds only the inner program bundle and the bucket geometry, no
    panel or state (the same lightweight-entry invariant every cached
    bundle keeps)."""

    def __init__(self, inner: Any, bucket: BucketKey):
        import jax.numpy as jnp

        from lfm_quant_tpu.train.reuse import ledger_jit

        self.inner = inner
        self.bucket = bucket
        # A deserialized AOT executable (train/reuse.py aot_load — the
        # durable store's deploy artifact, DESIGN.md §20). When set, the
        # scoring path dispatches it DIRECTLY: zero traces, zero XLA
        # compiles, the restored process's warm ladder. None on the
        # normal (publish-side) path.
        self._aot = None

        def score(params, dev, fi, ti, w):
            pred, _, _ = inner._forward_impl(params, dev, fi, ti, w,
                                             scores_only=True)
            return jnp.where(w > 0, pred.astype(jnp.float32), 0.0)

        rows, width = bucket
        self._jit_score = ledger_jit(f"serve_score_r{rows}x{width}", score)

    def __call__(self, params, dev, fi, ti, w):
        if self._aot is not None:
            try:
                return self._aot(params, dev, fi, ti, w)
            except Exception as e:  # noqa: BLE001 — loud counted fallback
                # A loaded executable that rejects live arguments
                # (sharding/layout drift the load-time probe missed)
                # falls back to the jit path ONCE, loudly — serving
                # wrong shapes is impossible (Compiled validates), but
                # serving nothing is not an option.
                import warnings

                self._aot = None
                telemetry.COUNTERS.bump("serve_aot_call_fallbacks")
                warnings.warn(
                    f"AOT executable for bucket {self.bucket} rejected a "
                    f"dispatch ({type(e).__name__}: {e}) — falling back "
                    "to the jit path (one recompile)",
                    RuntimeWarning, stacklevel=2)
        return self._jit_score(params, dev, fi, ti, w)

    # ---- serialized-executable artifact (DESIGN.md §20) ----------------

    def aot_export(self, params, dev, fi, ti, w) -> Optional[bytes]:
        """Serialize this bucket's compiled executable for the given
        argument avals (train/reuse.py ``aot_serialize``) — the durable
        store calls this at publish so a restore can skip the compile.
        None when the jax build/backend cannot export."""
        from lfm_quant_tpu.train import reuse

        return reuse.aot_serialize(self._jit_score, (params, dev, fi, ti, w))

    def load_aot(self, data: bytes) -> bool:
        """Adopt a serialized executable (restore path). Returns True
        when it deserialized; False → caller counts the fallback and
        the next dispatch traces/compiles normally."""
        from lfm_quant_tpu.train import reuse

        loaded = reuse.aot_load(data)
        if loaded is None:
            return False
        self._aot = loaded
        return True


class ZooEntry:
    """One servable (universe, generation) model resident in HBM."""

    def __init__(self, universe: str, generation: int, trainer: Any):
        import jax.numpy as jnp

        from lfm_quant_tpu.data.windows import DateBatchSampler

        if trainer.state is None:
            raise ValueError(
                f"universe {universe!r}: trainer has no state — fit() it "
                "(or set trainer.state = trainer.init_state()) before "
                "registering; the zoo serves params, it does not train")
        self.universe = universe
        self.generation = int(generation)
        self.trainer = trainer
        self.cfg = trainer.cfg
        self.panel = trainer.splits.panel
        # Tagged routing key: distinct (universe, generation) pairs can
        # never collide by construction (no string concatenation).
        self.key = ("zoo", ("universe", universe),
                    ("generation", self.generation))
        d = self.cfg.data
        # Serving pools over the WHOLE panel, live months included: the
        # last `horizon` months have no realized target by construction
        # and are exactly what production queries rank.
        self._sampler = DateBatchSampler(
            self.panel, d.window, 1, d.firms_per_date, seed=0,
            min_valid_months=d.min_valid_months, min_cross_section=1,
            require_target=False)
        months = self._sampler.months_with_anchors()
        self._month_index: Dict[int, int] = {
            int(self.panel.dates[t]): int(t) for t in months}
        self._pool_sizes = {int(t): self._sampler.cross_section(int(t)).size
                            for t in months}
        # Precision lane: bind the TRAINER'S resolved compute dtype
        # (config.compute_dtype at its construction) rather than
        # re-resolving the env knob here — the entry's panel lease must
        # key-match the resident panel the trainer's programs were
        # traced against even if LFM_PRECISION flips mid-process. Under
        # the bf16 lane that lease is a bf16 panel: half the
        # per-universe HBM, so a zoo of fixed capacity holds twice the
        # universes' panels per chip (DESIGN.md §17).
        self._compute_dtype = getattr(
            trainer, "_compute_dtype",
            jnp.bfloat16 if self.cfg.model.bf16 else None)
        self._lane_pad = trainer._gather_impl == "pallas"
        # Per-bucket scoring programs, memoized HERE as well as in the
        # reuse LRU: an entry must keep its executables warm even if a
        # busy cache evicts the serve keys (evicted bundles keep working
        # for holders of a reference — train/reuse.py contract).
        self._programs: Dict[BucketKey, ServePrograms] = {}
        # Score-drift sketches (utils/metrics.py ScoreSketch, DESIGN.md
        # §19): the REFERENCE is stamped at publish from the
        # generation's batch-scored months; the LIVE twin streams from
        # served responses. None until the service stamps them (metrics
        # off, or a pre-metrics register path).
        self.ref_sketch = None
        self.live_sketch = None
        # Zoo bookkeeping (guarded by the zoo's lock).
        self.refs = 0
        self.doomed = False

    # ---- serveable geometry -----------------------------------------

    def serveable_months(self) -> List[int]:
        """YYYYMM months with a non-empty scoreable cross-section."""
        return sorted(self._month_index)

    def month_col(self, yyyymm: int) -> int:
        """Panel column of a serveable YYYYMM month (KeyError detail
        names the universe — the error a client sees)."""
        try:
            return self._month_index[int(yyyymm)]
        except KeyError:
            raise KeyError(
                f"month {yyyymm} is not serveable for universe "
                f"{self.universe!r} (no eligible cross-section)") from None

    def pool(self, t: int) -> np.ndarray:
        return self._sampler.cross_section(t)

    def pool_size(self, t: int) -> int:
        """Memoized pool size — the submit hot path only needs the
        width bucket, not an O(n_firms) pool copy per request."""
        return self._pool_sizes.get(int(t), 0)

    def widths(self) -> List[int]:
        """Every cross-section bucket this universe's months occupy."""
        return width_ladder(self._pool_sizes.values())

    # ---- dispatch resources -----------------------------------------

    def lease_panel(self):
        """Pin the entry's device panel for a dispatch (refcount-safe
        against a concurrent invalidate — data/windows.py)."""
        from lfm_quant_tpu.data.windows import lease_device_panel

        return lease_device_panel(
            self.panel, self.trainer.mesh,
            compute_dtype=self._compute_dtype, raw=False,
            lane_pad=self._lane_pad)

    def programs_for(self, bucket: BucketKey) -> ServePrograms:
        """The bucket's scoring program, through the reuse program cache
        (warm generations of the same universe geometry share it)."""
        sp = self._programs.get(bucket)
        if sp is None:
            from lfm_quant_tpu.train import reuse

            inner = self.trainer.programs
            sp = reuse.get_programs(
                reuse.serve_program_key(self.trainer.program_key, bucket),
                lambda: ServePrograms(inner, bucket))
            self._programs[bucket] = sp
        return sp

    def adopt_programs(self, donor: "ZooEntry") -> None:
        """Inherit a predecessor generation's warm bucket programs when
        the inner program key is unchanged (the refresh path). The
        donor's programs are RE-SEEDED into the reuse cache through
        ``get_programs`` with a builder that returns the existing
        bundle — so even if LRU pressure (many universes × buckets)
        evicted the serve keys since the donor warmed them,
        re-admission re-caches the compiled objects instead of
        rebuilding fresh jit wrappers that would re-trace on first
        dispatch. This is what keeps a refresh recompile-free under a
        full zoo, not just an idle one."""
        if donor.trainer.program_key != self.trainer.program_key:
            return  # changed geometry: genuinely new programs
        from lfm_quant_tpu.train import reuse

        for bucket, sp in donor._programs.items():
            self._programs[bucket] = reuse.get_programs(
                reuse.serve_program_key(self.trainer.program_key, bucket),
                lambda sp=sp: sp)

    # ---- score-drift sketches (DESIGN.md §19) ------------------------

    def stamp_reference(self, sketch) -> None:
        """Attach the publish-time reference sketch and its empty live
        twin (same bin edges, so the two are always comparable)."""
        self.ref_sketch = sketch
        self.live_sketch = sketch.live_twin()

    def record_scores(self, scores) -> None:
        """Stream served scores into the live sketch: the batcher's
        per-dispatch call, O(1) on its critical path (lazy appends —
        readers fold; a numpy histogram here would release the GIL
        mid-batch and measurably tax closed-loop throughput).
        ``scores`` is one array or a list of per-request arrays. Exact
        no-op when no reference was stamped or ``LFM_METRICS=0``."""
        from lfm_quant_tpu.utils import metrics

        if self.live_sketch is None or not metrics.enabled():
            return
        if isinstance(scores, (list, tuple)):
            for a in scores:
                self.live_sketch.record_lazy(a)
        else:
            self.live_sketch.record_lazy(scores)

    def drift_psi(self, min_scores: int = 1):
        """PSI of the live served-score distribution against the
        publish-time reference; None until sketches exist and the live
        one holds at least ``min_scores`` scores."""
        if self.ref_sketch is None or self.live_sketch is None:
            return None
        if self.live_sketch.size() < max(1, int(min_scores)):
            return None
        return self.ref_sketch.psi(self.live_sketch)

    # ---- resident-footprint metadata ---------------------------------

    def param_bytes(self) -> int:
        """Resident parameter bytes from array METADATA (shape × dtype
        — jax exposes ``nbytes`` without a device fetch; the metrics
        path must never originate one)."""
        import jax

        return int(sum(getattr(leaf, "nbytes", 0)
                       for leaf in jax.tree.leaves(self.trainer.state)))

    def panel_bytes(self) -> int:
        """Resident panel bytes at the entry's compute dtype: host-side
        array sizes with features scaled by the lane's itemsize (the
        bf16 lane halves the feature block — DESIGN.md §17); masks/
        targets/returns ride at their host width."""
        p = self.panel
        feat = int(p.features.nbytes)
        if self._compute_dtype is not None:
            import numpy as np

            factor = (np.dtype(self._compute_dtype).itemsize
                      / p.features.dtype.itemsize)
            feat = int(feat * factor)
        aux = sum(int(a.nbytes) for a in
                  (p.targets, p.valid, p.target_valid, p.returns)
                  if getattr(a, "nbytes", None) is not None)
        return feat + aux

    @property
    def params(self):
        return self.trainer.state.params


class ModelZoo:
    """The routing table: universe name → current resident generation."""

    def __init__(self, capacity: int):
        self.capacity = max(1, int(capacity))
        self._lock = threading.RLock()
        self._entries: "OrderedDict[str, ZooEntry]" = OrderedDict()

    # ---- introspection ----------------------------------------------

    def universes(self) -> List[str]:
        with self._lock:
            return list(self._entries)

    def current(self, universe: str) -> ZooEntry:
        with self._lock:
            entry = self._entries.get(universe)
            if entry is None:
                raise KeyError(
                    f"universe {universe!r} is not registered "
                    f"(resident: {list(self._entries)})")
            return entry

    def generation(self, universe: str) -> int:
        return self.current(universe).generation

    def snapshot(self) -> Dict[str, Any]:
        """One-lock routing-table snapshot: ``{universes: {name: gen},
        size, capacity}``. The per-field accessors above each take the
        lock separately, so a caller iterating them can observe a TORN
        view across a concurrent publish/eviction — consumers that
        report state (``ScoringService.snapshot()``, the monitor's
        gauge collection) read through here instead."""
        with self._lock:
            return {
                "universes": {u: e.generation
                              for u, e in self._entries.items()},
                "size": len(self._entries),
                "capacity": self.capacity,
            }

    def __len__(self) -> int:
        return len(self._entries)

    # ---- lease / publish / evict ------------------------------------

    @contextlib.contextmanager
    def lease(self, universe: str):
        """Pin the universe's CURRENT entry for one dispatch. The entry
        stays fully servable for the whole block even if a publish or
        eviction unlinks it concurrently — decommission waits for the
        last lease."""
        from lfm_quant_tpu.utils import faults

        # Chaos lane: an injectable lease failure (utils/faults.py) —
        # checked OUTSIDE the zoo lock so the telemetry instant never
        # emits under it. Exact no-op when LFM_FAULTS is unset.
        faults.check("zoo_lease", universe=universe)
        with self._lock:
            entry = self._entries.get(universe)
            if entry is None:
                raise KeyError(f"universe {universe!r} is not registered "
                               f"(resident: {list(self._entries)})")
            self._entries.move_to_end(universe)  # LRU recency
            entry.refs += 1
        try:
            yield entry
        finally:
            with self._lock:
                entry.refs -= 1
                dead = entry.doomed and entry.refs == 0
            if dead:
                self._decommission(entry)

    def publish(self, entry: ZooEntry) -> Optional[ZooEntry]:
        """Atomically make ``entry`` the served generation for its
        universe. Returns the replaced entry (already unlinked; its HBM
        drains when its last lease does). Registering a NEW universe
        over capacity LRU-evicts the least-recently-leased one."""
        evicted: List[ZooEntry] = []
        with self._lock:
            old = self._entries.get(entry.universe)
            if old is not None and old.generation >= entry.generation:
                raise ValueError(
                    f"universe {entry.universe!r}: generation "
                    f"{entry.generation} does not advance the served "
                    f"generation {old.generation} — refresh must publish "
                    "monotonically")
            self._entries[entry.universe] = entry
            self._entries.move_to_end(entry.universe)
            if old is not None:
                old.doomed = True
                if old.refs == 0:
                    evicted.append(old)
            while len(self._entries) > self.capacity:
                _, lru = self._entries.popitem(last=False)
                telemetry.COUNTERS.bump("serve_zoo_evictions")
                lru.doomed = True
                if lru.refs == 0:
                    evicted.append(lru)
        for e in evicted:
            self._decommission(e)
        if old is not None:
            telemetry.instant("zoo_swap", cat="serve",
                              universe=entry.universe,
                              generation=entry.generation)
        return old

    def drop(self, universe: str) -> None:
        """Explicitly unregister a universe (tests/operator)."""
        with self._lock:
            entry = self._entries.pop(universe, None)
            if entry is None:
                return
            entry.doomed = True
            dead = entry.refs == 0
        if dead:
            self._decommission(entry)

    def _decommission(self, entry: ZooEntry) -> None:
        """Release a dead entry's device residency. The panel is
        invalidated only when NO resident entry still shares the panel
        object (a refresh generation over the same panel must not evict
        the arrays its successor is serving from); invalidation itself
        is lease-deferred in data/windows.py, so even a racing dispatch
        is safe."""
        from lfm_quant_tpu.data.windows import invalidate_panel

        with self._lock:
            shared = any(e.panel is entry.panel
                         for e in self._entries.values())
        if not shared:
            invalidate_panel(entry.panel)
        entry._programs.clear()
        telemetry.COUNTERS.bump("serve_zoo_decommissions")
