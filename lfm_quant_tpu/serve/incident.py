"""Automatic incident capture: a self-contained bundle per degradation.

When the always-on service degrades — the PR 10 breaker opens, a PR 11
SLO starts burning, the drift gate vetoes a publish, a PR 12 restore
quarantines a snapshot, the shed rate spikes — the operator previously
got a gauge flip and nothing else: by the time anyone scrapes, the
evidence of the seconds BEFORE the degradation is gone. This module
turns each of those existing signals into a trigger that writes one
rate-limited, self-contained **incident bundle** to disk (DESIGN.md
§21):

    <dir>/incidents/inc_<NNN>_<trigger>/
        flight.jsonl        the black-box flight-recorder ring
                            (utils/flight.py — the causal timeline)
        metrics.prom        one full /metrics scrape at capture time
        snapshot.json       the one-lock ScoringService.snapshot()
                            view (stats + health + SLO/drift detail)
        slow_requests.json  the K slowest recent request traces, each
                            with the queue/batch/retry/dispatch phase
                            breakdown (the histogram-exemplar targets)
        exemplars.json      per-bucket latency exemplars (trace ids)
        incident.json       trigger, context, host/build identity
                            (telemetry.build_info()) — written LAST,
                            fsync'd: its presence marks a complete
                            bundle (readers skip half-written ones)

Triggers (all EXISTING signals — this module adds no new detection):

* ``breaker_open``  — the circuit breaker transitioned to OPEN
  (serve/batcher.py ``_dispatch_fail``);
* ``slo_burn``      — an SLO objective is burning in every window
  (serve/monitor.py ``collect``, i.e. at scrape/snapshot time);
* ``shed_spike``    — sheds exceed :data:`SHED_SPIKE_FRACTION` of the
  last 60 s of traffic (monitor ``collect``, the ``serve_shed`` ring);
* ``drift_veto``    — the knob-gated publish gate fired
  (serve/monitor.py ``check_publish_gate``);
* ``quarantine``    — a durable snapshot failed restore verification
  (serve/persist.py ``_quarantine``).

Rate limiting: one bundle per trigger kind per
``LFM_INCIDENT_COOLDOWN_S`` (default 300 s) — a flapping breaker under
sustained overload must not turn the run dir into a bundle farm;
suppressed triggers still count (``incidents_suppressed``).

Where bundles land: ``LFM_INCIDENT_DIR`` if set, else the active
telemetry run dir, else capture is disabled (no run dir and no
explicit destination means nobody asked for evidence on this host —
the trigger is a no-op beyond a counter bump).

Capture runs on a daemon thread: the triggering code path (the batcher
thread that just opened the circuit, the scrape handler that noticed a
burn) pays one rate-limit check; file writes, the scrape render and
the locked snapshot happen off it. Captures are serialized (one at a
time) and re-entrancy-guarded — a capture's OWN scrape calling
``collect()`` can notice the same burning SLO; it must not recurse.

Non-interference: no code path here touches a device; everything reads
locked host-side snapshots. With no triggers firing the layer costs
nothing on the request path (the breaker hook is one attribute read on
the failure path only).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

#: Shed-spike trigger: sheds over the last 60 s exceeding this fraction
#: of that window's traffic (with at least MIN_EVENTS of volume —
#: 3 sheds out of 4 requests is startup noise, not an incident).
SHED_SPIKE_FRACTION = 0.10
SHED_SPIKE_MIN_EVENTS = 20

#: The rate/SLO window the shed-spike trigger evaluates over (seconds).
SHED_SPIKE_WINDOW_S = 60.0


def incident_dir_default() -> str:
    """``LFM_INCIDENT_DIR``: explicit bundle destination; empty/unset
    defers to the active telemetry run dir (and disables capture when
    neither exists)."""
    return os.environ.get("LFM_INCIDENT_DIR", "").strip()


def incident_cooldown_default() -> float:
    """``LFM_INCIDENT_COOLDOWN_S``: minimum seconds between bundles of
    the SAME trigger kind (default 300; <= 0 disables capture
    entirely — the loud-off switch)."""
    return float(os.environ.get("LFM_INCIDENT_COOLDOWN_S", "300"))


def _atomic_json(path: str, obj: Any, fsync: bool = False) -> None:
    """Write ``obj`` as JSON via temp file + rename (readers never see
    a torn file); non-finite floats nulled (the spans.jsonl policy)."""
    from lfm_quant_tpu.utils.logging import _finite

    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(_finite(obj), fh, indent=2, default=str)
        if fsync:
            fh.flush()
            os.fsync(fh.fileno())
    os.replace(tmp, path)


class IncidentManager:
    """One per :class:`~lfm_quant_tpu.serve.service.ScoringService`:
    holds the trigger cooldowns and writes the bundles. Construction is
    cheap and unconditional — whether capture is ACTIVE is re-resolved
    per trigger (the run dir can attach after the service starts)."""

    def __init__(self, service: Any, incident_dir: Optional[str] = None,
                 cooldown_s: Optional[float] = None):
        self._service = service
        self._dir = incident_dir  # explicit ctor dir wins; None = env
        self._cooldown_s = cooldown_s
        self._lock = threading.Lock()
        self._last: Dict[str, float] = {}
        self._seq = 0
        # Captures in flight (guarded by _lock, incremented at trigger
        # ACCEPT — before the thread starts — so the window where a
        # second trigger could slip past is closed): >0 means a
        # capture is running and any further trigger is dropped
        # WITHOUT consuming its cooldown (it may fire once the capture
        # finishes). This both serializes captures (two bundles
        # writing concurrently would race the gauge clear+rebuild in
        # collect()) and breaks the recursion where a capture's own
        # scrape re-notices the burning SLO.
        self._active = 0
        self._threads: List[threading.Thread] = []
        self.captured = 0
        self.suppressed = 0

    # ---- config resolution -------------------------------------------

    def cooldown_s(self) -> float:
        return (self._cooldown_s if self._cooldown_s is not None
                else incident_cooldown_default())

    def resolve_dir(self) -> Optional[str]:
        """The bundle destination, re-resolved per trigger: explicit
        ctor dir, else ``LFM_INCIDENT_DIR``, else the active telemetry
        run dir, else None (capture disabled)."""
        if self._dir:
            return self._dir
        env = incident_dir_default()
        if env:
            return env
        from lfm_quant_tpu.utils import telemetry

        run = telemetry.active_run()
        return run.run_dir if run is not None else None

    # ---- trigger / capture -------------------------------------------

    def trigger(self, trigger: str, sync: bool = False,
                **ctx: Any) -> bool:
        """Fire a trigger: rate-limit check, then capture on a daemon
        thread (``sync=True`` captures inline — tests and operator
        tooling). Returns True when a capture was started. Never
        raises — incident capture must not be able to take down the
        path that noticed the incident."""
        try:
            return self._trigger(trigger, sync, ctx)
        except Exception as e:  # noqa: BLE001 — capture is best-effort
            import warnings

            warnings.warn(f"incident capture failed for {trigger!r}: "
                          f"{type(e).__name__}: {e}", RuntimeWarning,
                          stacklevel=2)
            return False

    def _trigger(self, trigger: str, sync: bool,
                 ctx: Dict[str, Any]) -> bool:
        from lfm_quant_tpu.utils import telemetry

        cooldown = self.cooldown_s()
        if cooldown <= 0:
            return False
        out_dir = self.resolve_dir()
        if out_dir is None:
            return False
        now = time.monotonic()
        with self._lock:
            if self._active > 0:
                # A capture is already running (possibly THIS trigger
                # re-noticed by the capture's own scrape): drop without
                # consuming the cooldown.
                return False
            last = self._last.get(trigger)
            if last is not None and now - last < cooldown:
                self.suppressed += 1
                telemetry.COUNTERS.bump("incidents_suppressed")
                return False
            self._last[trigger] = now
            # The bundle name must be fresh ON DISK, not just fresh in
            # this process: a restarted service pointing at the same
            # persistent LFM_INCIDENT_DIR would otherwise restart at
            # inc_001 and silently overwrite the previous process's
            # evidence — often the most interesting bundle (the crash).
            while True:
                self._seq += 1
                seq = self._seq
                bundle = os.path.join(out_dir, "incidents",
                                      f"inc_{seq:03d}_{trigger}")
                if not os.path.exists(bundle):
                    break
            self._active += 1
        telemetry.COUNTERS.bump("incidents_triggered")
        telemetry.instant("incident_trigger", cat="incident",
                          trigger=trigger, seq=seq, **ctx)
        if sync:
            self._capture(bundle, trigger, ctx)
            return True
        t = threading.Thread(target=self._capture,
                             args=(bundle, trigger, ctx),
                             name=f"incident-{trigger}", daemon=True)
        with self._lock:
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)
        try:
            t.start()
        except BaseException:
            # _capture never ran, so its finally can't release the
            # in-flight slot — release it here or capture deadlocks off.
            with self._lock:
                self._active -= 1
            raise
        return True

    def _capture(self, bundle_dir: str, trigger: str,
                 ctx: Dict[str, Any]) -> None:
        from lfm_quant_tpu.utils import flight, metrics, telemetry

        t0 = time.perf_counter()
        try:
            os.makedirs(bundle_dir, exist_ok=True)
            svc = self._service
            files: Dict[str, Optional[str]] = {}
            # Every artifact below is individually guarded: a partial
            # bundle with incident.json naming what failed beats a
            # half-written directory a crashed capture orphans (readers
            # key completeness on incident.json, written LAST).
            # 1. The flight-recorder ring — the causal timeline of the
            #    seconds before the trigger (crash-safe dump).
            n_events = 0
            try:
                n_events = flight.dump(os.path.join(bundle_dir,
                                                    "flight.jsonl"))
                files["flight.jsonl"] = f"{n_events} events"
            except Exception as e:  # noqa: BLE001 — partial > nothing
                files["flight.jsonl"] = f"failed: {type(e).__name__}: {e}"
            # 2. One /metrics scrape, rendered from ONE counter
            #    snapshot that incident.json below also records
            #    verbatim — so the scrape's lfm_*_total lines and the
            #    manifest's counters_at_capture agree EXACTLY, which is
            #    what lets trace_report catch a torn/forged scrape.
            #    The monitor's collect() runs first (gauges + the SLO/
            #    shed trigger checks — the _capturing guard keeps a
            #    burning SLO it notices from recursing into another
            #    capture).
            counters_now: Dict[str, Any] = {}
            try:
                svc.monitor.collect()
                counters_now = {
                    k: v for k, v in
                    telemetry.COUNTERS.snapshot().items()
                    if isinstance(v, (int, float))}
                with open(os.path.join(bundle_dir, "metrics.prom"),
                          "w") as fh:
                    fh.write(metrics.render_prometheus(
                        metrics.METRICS, counters=counters_now))
                files["metrics.prom"] = "ok"
            except Exception as e:  # noqa: BLE001 — partial > nothing
                files["metrics.prom"] = f"failed: {type(e).__name__}: {e}"
            # Run-scoped counter deltas: the registry is process-
            # LIFETIME (a long-lived service carries counts from before
            # this run dir attached), so the bundle stamps totals MINUS
            # the run's starting snapshot — the anchor trace_report's
            # 1% discipline compares against the span-derived counts
            # (a mid-run capture can only have seen AT MOST what the
            # full run ends with).
            run = telemetry.active_run()
            counters_since_run = None
            if run is not None and counters_now:
                c0 = run.counters_at_start()
                counters_since_run = {
                    k: v - c0.get(k, 0) for k, v in counters_now.items()
                    if isinstance(c0.get(k, 0), (int, float))
                    and v != c0.get(k, 0)}
            # 3. The one-lock service snapshot (stats + health detail).
            try:
                _atomic_json(os.path.join(bundle_dir, "snapshot.json"),
                             svc.snapshot())
                files["snapshot.json"] = "ok"
            except Exception as e:  # noqa: BLE001
                files["snapshot.json"] = f"failed: {type(e).__name__}: {e}"
            # 4. The K slowest recent request traces (phase breakdowns)
            #    — what the histogram exemplars point at.
            slow: List[Dict[str, Any]] = []
            try:
                slow = svc.batcher.slow_traces()
                _atomic_json(os.path.join(bundle_dir,
                                          "slow_requests.json"), slow)
                files["slow_requests.json"] = f"{len(slow)} traces"
            except Exception as e:  # noqa: BLE001
                files["slow_requests.json"] = \
                    f"failed: {type(e).__name__}: {e}"
            # 5. The per-bucket latency exemplars (trace ids).
            try:
                _atomic_json(os.path.join(bundle_dir, "exemplars.json"),
                             metrics.METRICS.exemplar_snapshot(
                                 "serve_latency_ms"))
                files["exemplars.json"] = "ok"
            except Exception as e:  # noqa: BLE001
                files["exemplars.json"] = \
                    f"failed: {type(e).__name__}: {e}"
            # 6. The manifest — LAST, fsync'd: a complete incident.json
            #    marks a complete bundle.
            _atomic_json(os.path.join(bundle_dir, "incident.json"), {
                "schema_version": 1,
                "trigger": trigger,
                "context": ctx,
                "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                "ts_unix": time.time(),
                "cooldown_s": self.cooldown_s(),
                "capture_wall_s": round(time.perf_counter() - t0, 4),
                "flight": flight.recorder().stats()
                if flight.recorder() else {"capacity": 0},
                "slow_traces": len(slow),
                "files": files,
                # The SAME snapshot the scrape above rendered (exact
                # agreement = the scrape-integrity anchor) + the run-
                # scoped deltas (the spans-discipline anchor).
                "counters_at_capture": counters_now,
                "counters_since_run": counters_since_run,
                # Host/process identity (ROADMAP item 2 groundwork): a
                # fleet aggregator collecting bundles must know which
                # member, build and backend produced each one.
                "host": telemetry.build_info(),
            }, fsync=True)
            with self._lock:
                self.captured += 1
            telemetry.COUNTERS.bump("incidents_captured")
            telemetry.instant("incident_captured", cat="incident",
                              trigger=trigger, path=bundle_dir,
                              events=n_events, slow=len(slow))
            import warnings

            warnings.warn(
                f"incident captured ({trigger}): {bundle_dir} — "
                f"{n_events} flight events, {len(slow)} slow traces",
                RuntimeWarning, stacklevel=2)
        finally:
            with self._lock:
                self._active -= 1

    # ---- introspection / lifecycle -----------------------------------

    def wait(self, timeout: float = 10.0) -> None:
        """Join outstanding capture threads (tests, shutdown)."""
        with self._lock:
            threads = list(self._threads)
        deadline = time.monotonic() + timeout
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"captured": self.captured,
                    "suppressed": self.suppressed,
                    "cooldown_s": self.cooldown_s(),
                    "dir": self.resolve_dir(),
                    "triggers_seen": sorted(self._last)}


def find_bundles(root: str) -> List[str]:
    """Complete incident bundles under ``root`` (a run dir or an
    explicit incident dir), oldest first — a bundle is complete iff its
    ``incident.json`` exists (written last, fsync'd)."""
    base = os.path.join(root, "incidents")
    if not os.path.isdir(base):
        return []
    out = []
    for name in sorted(os.listdir(base)):
        path = os.path.join(base, name)
        if os.path.isfile(os.path.join(path, "incident.json")):
            out.append(path)
    return out
