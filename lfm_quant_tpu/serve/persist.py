"""Durable serving state: journaled zoo snapshots, crash-consistent
publish, zero-cold-start restart (DESIGN.md §20).

Everything the always-on service holds — zoo generations, drift
reference sketches, the warmed bucket-program ladder — is process
memory: before this module a crash, eviction, or the documented
``BatcherDeadError`` "unready until restarted" path lost it all and
paid a full retrain plus the warmup trace ladder before the first
correct response. :class:`ZooStore` makes restart a recovery tool:

* **Snapshots** — every published generation is written durably: an
  Orbax param checkpoint (through ``train/checkpoint.py``'s atomic
  commit machinery) plus a params checksum, the universe's panel as a
  content-addressed ``.npz``, the run config and split boundaries, the
  publish-time drift reference sketch
  (``utils/metrics.py ScoreSketch.to_state``), a stamped parity-probe
  score vector (one serveable month, bit-exact float32), and — where
  the jax build supports AOT export (``train/reuse.py aot_serialize``)
  — the serialized lowered executable of every warmed bucket program.
* **Write-ahead journal + atomic manifest** — a publish appends a
  ``begin`` intent to ``journal.jsonl`` (fsync'd), stages every
  artifact, then commits by atomically replacing ``manifest.json``
  (temp file + fsync + rename + directory fsync) and appending
  ``commit``. The manifest is the single commit point: a crash at ANY
  instant — enforced by the ``zoo_persist``/``manifest_write`` fault
  sites (utils/faults.py, ``kind=sigkill`` for a real
  SIGKILL-mid-publish subprocess test) — leaves either the old or the
  new manifest, never a torn one. Orphaned artifacts from a crashed
  commit are resolved by :meth:`ZooStore.sweep` at the next startup
  (journal replay + unreferenced-file scan).
* **Restore** (:meth:`ZooStore.restore_into`) — re-registers every
  universe from the manifest, newest committed generation first, and
  VERIFIES before serving: params checksum, then one stamped month
  scored through the restored generation must be bit-equal to the
  publish-time probe. A corrupt or mismatched snapshot is quarantined
  (renamed ``*.quarantined.*``) with a loud warning and the restore
  falls back to the next-older committed generation — or to nothing
  (fresh retrain) — rather than ever serving wrong numbers. Drift
  references are re-stamped from the serialized sketches (zero new
  traces), and the warm ladder is rebuilt through the loaded
  executables (zero compiles) with a loud counted fallback to
  recompile (softened by the persistent compilation cache,
  ``LFM_COMPILATION_CACHE``) on deserialize/topology/fingerprint
  mismatch.
* **Retention** — ``LFM_ZOO_KEEP_GENERATIONS`` (default 2) newest
  generations per universe stay in the manifest; superseded ones are
  pruned under the same journal discipline (dropped from the manifest
  FIRST, their directories deleted after the commit).

Single-writer contract: one serving process owns a store directory at
a time (the same contract the zoo itself has). ``LFM_ZOO_PERSIST``
unset/``0`` is an EXACT no-op — the service holds no store and no
serving or training path changes by a single instruction.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import shutil
import tempfile
import threading
import time
import warnings
import weakref
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from lfm_quant_tpu.serve.buckets import bucket_rows, bucket_width, rows_ladder
from lfm_quant_tpu.serve.errors import SnapshotIntegrityError
from lfm_quant_tpu.utils import faults, telemetry

#: Manifest schema version. A manifest from a NEWER schema is rejected
#: loudly at restore (quarantine + fresh-start fallback) — a
#: half-understood manifest must never half-restore.
SCHEMA_VERSION = 1


def persist_dir_default() -> Optional[str]:
    """``LFM_ZOO_PERSIST``: the durable store directory. Unset, empty
    or ``"0"`` disables persistence entirely (the exact-no-op
    contract); any other value is the directory path."""
    v = os.environ.get("LFM_ZOO_PERSIST", "")
    return None if v in ("", "0") else v


def persist_enabled() -> bool:
    """Whether durable zoo persistence is configured (the manifest
    knob probe)."""
    return persist_dir_default() is not None


def keep_generations_default() -> int:
    """``LFM_ZOO_KEEP_GENERATIONS``: committed generations retained
    per universe (default 2 — the serving one plus one rollback);
    older snapshots are pruned under the journal discipline."""
    return max(1, int(os.environ.get("LFM_ZOO_KEEP_GENERATIONS", "2")))


# ---- pure helpers --------------------------------------------------------


def params_checksum(params: Any) -> str:
    """sha256 over the parameter pytree's leaves (shape, dtype and raw
    bytes, in tree-flatten order) — the restore-side integrity gate's
    first rung. Host-side only; device leaves are fetched once."""
    import jax

    h = hashlib.sha256()
    for leaf in jax.tree.leaves(params):
        a = np.asarray(leaf)
        h.update(str(a.shape).encode())
        h.update(str(a.dtype).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def program_fingerprint(program_key: Tuple) -> str:
    """Identity of a compiled-program family ACROSS processes: the
    reuse program key (mesh/model/optimizer/gather/precision-qualified
    already) plus the jax/jaxlib versions, backend and device count.
    An AOT executable artifact only loads when this matches — anything
    else is the loud counted recompile fallback."""
    import jax
    import jaxlib

    return hashlib.sha256(repr(
        (program_key, jax.__version__, jaxlib.__version__,
         jax.default_backend(), jax.device_count())).encode()).hexdigest()


def _fsync_dir(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def score_single_month(entry: Any, month: int, max_rows: int) -> np.ndarray:
    """Score ONE month through the entry's bucket programs — the
    row-bucket-1 member of the warmed ladder, so this dispatch is
    compile-free on a warmed entry. The publish-time parity probe and
    the restore-time verification both run through here, so the two
    vectors are produced by the same code path (bit-equality is then a
    statement about the snapshot, not about two scoring forks)."""
    t = entry.month_col(int(month))
    pool = entry.pool(t)
    if pool.size == 0:
        raise ValueError(f"probe month {month} has an empty pool")
    width = bucket_width(pool.size)
    rows = bucket_rows(1, max_rows)
    fi = np.zeros((rows, width), np.int32)
    ti = np.full((rows,), t, np.int32)
    w = np.zeros((rows, width), np.float32)
    fi[0, :pool.size] = pool
    fi[0, pool.size:] = pool[-1]
    w[0, :pool.size] = 1.0
    for i in range(1, rows):
        fi[i], ti[i] = fi[0], ti[0]
    with entry.lease_panel() as dev:
        out = np.asarray(entry.programs_for((rows, width))(
            entry.params, dev, fi, ti, w))
    return out[0, :pool.size].copy()


def _panel_npz_bytes(panel: Any) -> bytes:
    """Serialize a Panel to deterministic .npz bytes (same arrays →
    same bytes → same content address)."""
    buf = io.BytesIO()
    arrays = {
        "features": panel.features, "targets": panel.targets,
        "target_valid": panel.target_valid, "valid": panel.valid,
        "returns": panel.returns, "dates": panel.dates,
        "firm_ids": panel.firm_ids,
        "feature_names": np.asarray(list(panel.feature_names), dtype=str),
        "horizon": np.asarray(panel.horizon, np.int64),
    }
    if panel.ret_valid is not None:
        arrays["ret_valid"] = panel.ret_valid
    np.savez(buf, **arrays)
    return buf.getvalue()


def _panel_from_npz(path: str) -> Any:
    from lfm_quant_tpu.data.panel import Panel

    with np.load(path, allow_pickle=False) as z:
        return Panel(
            features=z["features"], targets=z["targets"],
            target_valid=z["target_valid"], valid=z["valid"],
            returns=z["returns"], dates=z["dates"],
            firm_ids=z["firm_ids"],
            feature_names=[str(s) for s in z["feature_names"]],
            horizon=int(z["horizon"]),
            ret_valid=z["ret_valid"] if "ret_valid" in z.files else None,
        )


#: Trainer kinds a snapshot may record (restore constructs the same
#: class; anything else is rejected loudly at restore).
_TRAINER_KINDS = ("Trainer", "EnsembleTrainer")


def _build_trainer(kind: str, cfg: Any, splits: Any) -> Any:
    if kind == "Trainer":
        from lfm_quant_tpu.train.loop import Trainer

        return Trainer(cfg, splits, run_dir=None)
    if kind == "EnsembleTrainer":
        from lfm_quant_tpu.train.ensemble import EnsembleTrainer

        return EnsembleTrainer(cfg, splits, run_dir=None)
    raise ValueError(
        f"snapshot records unsupported trainer kind {kind!r} "
        f"(supported: {', '.join(_TRAINER_KINDS)})")




class ZooStore:
    """One durable store directory for one serving process.

    Layout::

        <root>/manifest.json            # THE commit point
        <root>/journal.jsonl            # write-ahead intents (begin/commit)
        <root>/tmp/                     # atomic-write staging (swept)
        <root>/universes/<u>/panel_<hash12>.npz
        <root>/universes/<u>/gen_<g>/params/   # Orbax checkpoint
        <root>/universes/<u>/gen_<g>/probe.npz
        <root>/execs/<fp16>_<rows>x<width>.bin # content-addressed by
                                               # (program fingerprint,
                                               # bucket) — shared
                                               # across generations
    """

    def __init__(self, root: str, keep: Optional[int] = None,
                 readonly: bool = False):
        self.root = os.path.abspath(root)
        # Clamped like the env default: keep=0 would make the prune
        # slice `gens[:-0]` silently empty — retention off forever.
        self.keep = (max(1, int(keep)) if keep is not None
                     else keep_generations_default())
        # Read-only attach (serve/fleet.py, DESIGN.md §22): the store
        # as a DEPLOY ARTIFACT many fleet members bootstrap from
        # CONCURRENTLY. The single-writer contract is per store
        # directory; a read-only attach holds it trivially — no attach
        # sweep, no journal/tmp mutation, no quarantine renames (N
        # concurrent readers racing each other's sweeps corrupted
        # exactly the files they were attaching to), and
        # record_publish refuses.
        self.readonly = bool(readonly)
        self.tmp_dir = os.path.join(self.root, "tmp")
        self.journal_path = os.path.join(self.root, "journal.jsonl")
        self.manifest_path = os.path.join(self.root, "manifest.json")
        if not self.readonly:
            os.makedirs(self.tmp_dir, exist_ok=True)
            os.makedirs(os.path.join(self.root, "universes"),
                        exist_ok=True)
            os.makedirs(os.path.join(self.root, "execs"), exist_ok=True)
        # The manifest is read-modify-written by every publish;
        # register() and refresh() can run on different threads of one
        # service (the single-WRITER contract is per store directory,
        # i.e. per process — not per thread). One commit at a time, or
        # a racing pair could commit a manifest missing the other's
        # generation record and the next sweep would reclaim that
        # committed snapshot as unreferenced.
        self._commit_lock = threading.Lock()
        # Incident hook (serve/incident.py, DESIGN.md §21): set by the
        # owning ScoringService — a quarantine verdict triggers an
        # automatic evidence bundle. Plain attribute, None when the
        # store is used standalone (tests, tooling).
        self.incidents: Optional[Any] = None
        # Same-panel publishes (a refresh over unchanged data) skip the
        # full re-serialize + re-hash: id-keyed memo, weakref-validated
        # so a recycled id after GC can never alias a different panel.
        self._panel_memo: Dict[int, Tuple[Any, str]] = {}
        # STARTUP sweep: attaching the store IS the process start —
        # without this, a persist-only service (no --restore) would
        # accumulate crashed-publish debris and journal lines across
        # every crash-restart cycle, with nothing ever reclaiming them.
        # quarantine=False: attach must never rename a corrupt manifest
        # aside (that is restore's/publish's LOUD decision — an attach
        # that quarantined would let a subsequent publish commit a
        # fresh manifest that disowns other universes' snapshots).
        if not self.readonly:
            self.sweep(quarantine=False)

    # ---- low-level durability primitives -----------------------------

    def _atomic_write(self, path: str, data: bytes) -> None:
        """temp file (in <root>/tmp, same filesystem) + fsync + atomic
        rename + directory fsync: after this returns, ``path`` durably
        holds ``data``; before the rename, ``path`` durably holds its
        previous content. There is no instant at which a reader (or a
        crash) can observe a torn file."""
        fd, tmp = tempfile.mkstemp(dir=self.tmp_dir,
                                   prefix=os.path.basename(path) + ".")
        try:
            with os.fdopen(fd, "wb") as fh:
                fh.write(data)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        _fsync_dir(os.path.dirname(path))

    def _journal(self, rec: Dict[str, Any]) -> None:
        """Append one fsync'd intent line. The journal is an
        append-only WAL: ``begin`` before any artifact is staged,
        ``commit`` after the manifest rename — a ``begin`` without its
        ``commit`` marks a crashed publish whose staged artifacts
        :meth:`sweep` reclaims."""
        with open(self.journal_path, "a") as fh:
            fh.write(json.dumps(rec) + "\n")
            fh.flush()
            os.fsync(fh.fileno())

    def _quarantine(self, path: str, reason: str) -> None:
        """Move a failed artifact aside (never delete — it is the
        operator's evidence), loudly. A READ-ONLY attach (a fleet
        member on a shared deploy artifact) reports the verdict with
        the same counters/warning but renames NOTHING — concurrent
        readers must not mutate each other's artifact, and the
        quarantine decision belongs to the store's single writer."""
        if self.readonly:
            telemetry.COUNTERS.bump("persist_quarantines")
            telemetry.instant("restore_quarantine", cat="serve",
                              path=os.path.relpath(path, self.root),
                              reason=reason[:200], readonly=True)
            inc = self.incidents
            if inc is not None:
                inc.trigger("quarantine",
                            path=os.path.relpath(path, self.root),
                            reason=reason[:200])
            warnings.warn(
                f"durable zoo: QUARANTINE verdict (read-only attach, "
                f"not renamed) {os.path.relpath(path, self.root)}: "
                f"{reason}", RuntimeWarning, stacklevel=3)
            return
        dst = f"{path}.quarantined.{int(time.time() * 1e3)}"
        try:
            os.replace(path, dst)
        except OSError as e:
            warnings.warn(
                f"durable zoo: could not quarantine {path} ({e}) — "
                f"original verification failure: {reason}",
                RuntimeWarning, stacklevel=3)
            return
        telemetry.COUNTERS.bump("persist_quarantines")
        telemetry.instant("restore_quarantine", cat="serve",
                          path=os.path.relpath(dst, self.root),
                          reason=reason[:200])
        # A quarantine is an incident trigger (DESIGN.md §21): durable
        # state failed verification — capture the evidence bundle
        # (rate-limited; never raises back into the restore ladder).
        inc = self.incidents
        if inc is not None:
            inc.trigger("quarantine",
                        path=os.path.relpath(dst, self.root),
                        reason=reason[:200])
        warnings.warn(
            f"durable zoo: QUARANTINED {os.path.relpath(path, self.root)} "
            f"→ {os.path.basename(dst)}: {reason}",
            RuntimeWarning, stacklevel=3)

    # ---- manifest ----------------------------------------------------

    def load_manifest(self, quarantine: bool = True
                      ) -> Optional[Dict[str, Any]]:
        """The committed manifest, or None when absent — or when it is
        corrupt/truncated or from a FUTURE schema, in which case it is
        quarantined with a loud warning (fresh-start fallback; never
        half-parsed). ``quarantine=False`` reports None WITHOUT
        renaming/warning — the attach-time sweep's read-only mode."""
        if not os.path.exists(self.manifest_path):
            return None
        try:
            with open(self.manifest_path) as fh:
                m = json.load(fh)
            if not isinstance(m, dict):
                raise ValueError(f"manifest root is {type(m).__name__}, "
                                 "not an object")
            schema = int(m.get("schema_version", -1))
        except (OSError, ValueError, TypeError) as e:
            if quarantine:
                self._quarantine(
                    self.manifest_path,
                    f"corrupt manifest: {type(e).__name__}: {e}")
            return None
        if schema != SCHEMA_VERSION:
            if quarantine:
                self._quarantine(
                    self.manifest_path,
                    f"manifest schema_version {schema} != supported "
                    f"{SCHEMA_VERSION} (a newer writer owns this store; "
                    "refusing to half-parse it)")
            return None
        return m

    def _commit_manifest(self, manifest: Dict[str, Any]) -> None:
        """The commit point: ``manifest_write`` fault checks bracket
        the atomic rename (even call index before, odd after), so a
        scheduled crash — including ``kind=sigkill`` — lands on either
        side of it."""
        data = json.dumps(manifest, indent=1, sort_keys=True).encode()
        faults.check("manifest_write", phase="pre_rename")
        self._atomic_write(self.manifest_path, data)
        faults.check("manifest_write", phase="post_rename")

    # ---- publish -----------------------------------------------------

    def record_publish(self, entry: Any, max_rows: int,
                       probe_month: Optional[int] = None) -> Dict[str, Any]:
        """Durably record ``entry`` (a warmed, about-to-publish
        ZooEntry) as its universe's newest committed generation.
        Called by the service BEFORE the in-memory ``zoo.publish`` —
        crash after this commit restores the NEW generation, crash
        before it restores the OLD one; there is no third outcome.
        Returns the generation record written."""
        if self.readonly:
            raise RuntimeError(
                "durable zoo: this store is attached READ-ONLY (a fleet "
                "member bootstrapping from the deploy artifact) — "
                "publishes belong to the store's single writer")
        universe, gen = entry.universe, entry.generation
        # ONE commit at a time: the manifest read-modify-write below
        # must not interleave with another thread's (register and
        # refresh may run concurrently on one service — a racing pair
        # could commit a manifest missing the other's record, whose
        # snapshot the next sweep would then reclaim as unreferenced).
        with self._commit_lock:
            return self._record_publish_locked(entry, universe, gen,
                                               max_rows, probe_month)

    def _record_publish_locked(self, entry: Any, universe: str, gen: int,
                               max_rows: int,
                               probe_month: Optional[int]
                               ) -> Dict[str, Any]:
        import jax

        # Fail FAST on an unreadable committed manifest — before any
        # artifact is staged, and WITHOUT quarantining it (renaming it
        # here would make the refusal one-shot: the next publish would
        # see no manifest and commit a fresh one that disowns every
        # other universe's committed snapshots, which the next sweep
        # would then reclaim — the exact data loss this guard exists
        # to prevent). Quarantine stays the restore path's loud
        # decision; every publish keeps refusing until the operator
        # resolves the corrupt manifest.
        had_manifest = os.path.exists(self.manifest_path)
        manifest = self.load_manifest(quarantine=False)
        if manifest is None and had_manifest:
            raise RuntimeError(
                "durable zoo: refusing to publish over an unreadable "
                f"manifest ({self.manifest_path} is corrupt or from a "
                "newer schema) — committing a fresh manifest would "
                "disown other universes' committed snapshots; resolve "
                "(restore quarantines it loudly, or remove it by hand) "
                "first")
        manifest = manifest or {"schema_version": SCHEMA_VERSION,
                                "universes": {}}
        udir = os.path.join(self.root, "universes", universe)
        gdir_rel = os.path.join("universes", universe, f"gen_{gen:05d}")
        gdir = os.path.join(self.root, gdir_rel)
        replaced_rel: Optional[str] = None
        if os.path.exists(gdir):
            # The canonical name is taken: either a crashed earlier
            # attempt (uncommitted — sweep debt) or a COMMITTED
            # snapshot of the same generation number being
            # re-published (a cold re-register over an existing
            # store). Never touch it before the commit point — the
            # manifest may reference it, and destroying it mid-staging
            # would put a crash inside a window where the committed
            # manifest names a gutted snapshot. Stage under a unique
            # name instead; the superseded dir is GC'd after commit.
            replaced_rel = gdir_rel
            gdir_rel = f"{gdir_rel}.r{int(time.time() * 1e3)}"
            gdir = os.path.join(self.root, gdir_rel)
        with telemetry.span("zoo_persist_commit", cat="serve",
                            universe=universe, generation=gen) as sp:
            self._journal({"op": "publish", "universe": universe,
                           "generation": gen, "dir": gdir_rel,
                           "state": "begin", "ts": time.time()})
            # Chaos site: a crash anywhere in the staging below leaves
            # a dangling `begin` + partial artifacts that sweep()
            # reclaims; the manifest still names only committed state.
            faults.check("zoo_persist", universe=universe, generation=gen)
            os.makedirs(udir, exist_ok=True)
            os.makedirs(gdir)

            # Panel: content-addressed per universe (a refresh over the
            # same panel reuses the file; new data writes a new one).
            # Same-OBJECT publishes skip the full re-serialize + hash
            # via the weakref-validated memo — a refresh over unchanged
            # data pays an exists() check, not a panel copy.
            memo = self._panel_memo.get(id(entry.panel))
            psha = (memo[1] if memo is not None
                    and memo[0]() is entry.panel else None)
            panel_rel = (os.path.join("universes", universe,
                                      f"panel_{psha[:12]}.npz")
                         if psha else None)
            if psha is None or not os.path.exists(
                    os.path.join(self.root, panel_rel)):
                pbytes = _panel_npz_bytes(entry.panel)
                psha = hashlib.sha256(pbytes).hexdigest()
                # Prune dead weakrefs on insert — a monthly-refresh
                # service must not grow one stale entry per publish.
                self._panel_memo = {k: v for k, v in
                                    self._panel_memo.items()
                                    if v[0]() is not None}
                self._panel_memo[id(entry.panel)] = (
                    weakref.ref(entry.panel), psha)
                panel_rel = os.path.join("universes", universe,
                                         f"panel_{psha[:12]}.npz")
                panel_path = os.path.join(self.root, panel_rel)
                if not os.path.exists(panel_path):
                    self._atomic_write(panel_path, pbytes)

            # Params: host-fetched once — the checksum and the Orbax
            # snapshot must cover the same bytes.
            host_params = jax.device_get(entry.params)
            from lfm_quant_tpu.train.checkpoint import CheckpointManager

            mgr = CheckpointManager(os.path.join(gdir, "params"),
                                    max_to_keep=1)
            mgr.save(int(gen) + 1, {"params": host_params}, wait=True)
            mgr.close()

            # Parity probe: one stamped serveable month, float32
            # bit-exact — restore must reproduce it exactly through the
            # same scoring path before the generation may serve.
            months = entry.serveable_months()
            month = int(probe_month if probe_month is not None
                        else months[len(months) // 2])
            probe_scores = score_single_month(entry, month, max_rows)
            pbuf = io.BytesIO()
            np.savez(pbuf, month=np.asarray(month, np.int64),
                     firm_idx=entry.pool(entry.month_col(month)),
                     scores=probe_scores.astype(np.float32))
            self._atomic_write(os.path.join(gdir, "probe.npz"),
                               pbuf.getvalue())

            # Serialized lowered executables, where supported: the
            # zero-compile restore artifact (counted; absence is the
            # documented recompile fallback, not an error). Blobs are
            # param-INDEPENDENT (params arrive as runtime arguments),
            # so they are content-addressed by (program fingerprint,
            # bucket) and shared across generations — a same-key
            # refresh pays an exists() check per bucket, not a
            # lower+compile+serialize, and the store holds one copy.
            trainer = entry.trainer
            fp = program_fingerprint(trainer.program_key)
            execs = self._export_execs(entry, fp, max_rows)

            splits = trainer.splits
            import dataclasses

            rec: Dict[str, Any] = {
                "generation": int(gen),
                "dir": gdir_rel,
                "trainer": type(trainer).__name__,
                "cfg": dataclasses.asdict(entry.cfg),
                "splits": {
                    "train_end_idx": int(splits.train_end_idx),
                    "val_end_idx": int(splits.val_end_idx),
                    "train_start_idx": int(splits.train_start_idx)},
                "panel_file": panel_rel,
                "panel_sha256": psha,
                "params_sha256": params_checksum(host_params),
                "probe": {"month": month, "file":
                          os.path.join(gdir_rel, "probe.npz")},
                "ref_sketch": (entry.ref_sketch.to_state()
                               if entry.ref_sketch is not None else None),
                "buckets": [[int(r), int(w)] for r in rows_ladder(max_rows)
                            for w in entry.widths()],
                "max_rows": int(max_rows),
                "program_fingerprint": fp,
                "execs": execs,
                "saved_at": time.time(),
            }

            import jaxlib

            manifest["schema_version"] = SCHEMA_VERSION
            manifest["saved_at"] = time.time()
            manifest["jax"] = {"version": jax.__version__,
                               "jaxlib": jaxlib.__version__,
                               "backend": jax.default_backend(),
                               "device_count": jax.device_count()}
            uni = manifest["universes"].setdefault(universe, {})
            # Records this commit supersedes: an earlier snapshot of
            # the SAME generation number (its dir may be the canonical
            # name — already tracked as replaced_rel — or a previous
            # restage's unique name, which nothing else would reclaim
            # at commit time).
            superseded = [g for g in uni.get("generations", [])
                          if g.get("generation") == int(gen)]
            gens: List[Dict[str, Any]] = [
                g for g in uni.get("generations", [])
                if g.get("generation") != int(gen)]
            gens.append(rec)
            gens.sort(key=lambda g: g["generation"])
            pruned = gens[:-self.keep] if len(gens) > self.keep else []
            uni["generations"] = gens[len(pruned):]

            # COMMIT. Everything before this line is invisible to a
            # restore; everything after it is cleanup of state the
            # manifest no longer references.
            self._commit_manifest(manifest)
            self._journal({"op": "publish", "universe": universe,
                           "generation": gen, "state": "commit",
                           "ts": time.time()})
            self._gc(universe, manifest, pruned)
            # Superseded same-number snapshots: committed out of the
            # manifest just now, safe to reclaim (kept only if some
            # other record still references them).
            kept_dirs = {g["dir"] for g in
                         manifest["universes"][universe]["generations"]}
            stale = {g["dir"] for g in superseded} | (
                {replaced_rel} if replaced_rel is not None else set())
            for rel in stale - kept_dirs:
                shutil.rmtree(os.path.join(self.root, rel),
                              ignore_errors=True)
            telemetry.COUNTERS.bump("persist_commits")
            sp.set(execs=len(execs), pruned=len(pruned))
        return rec

    def _export_execs(self, entry: Any, fp: str,
                      max_rows: int) -> Dict[str, str]:
        from lfm_quant_tpu.train import reuse

        execs: Dict[str, str] = {}
        if not reuse.aot_supported():
            return execs
        todo = []
        for width in entry.widths():
            for rows in rows_ladder(max_rows):
                rel = os.path.join("execs", f"{fp[:16]}_{rows}x{width}.bin")
                if os.path.exists(os.path.join(self.root, rel)):
                    # Content hit: an earlier generation (or universe)
                    # with the same program fingerprint already
                    # exported this bucket's executable.
                    execs[f"{rows}x{width}"] = rel
                    telemetry.COUNTERS.bump("persist_execs_reused")
                else:
                    todo.append((rows, width, rel))
        if not todo:
            return execs
        with entry.lease_panel() as dev:
            for rows, width, rel in todo:
                fi = np.zeros((rows, width), np.int32)
                ti = np.zeros((rows,), np.int32)
                w = np.zeros((rows, width), np.float32)
                sp = entry.programs_for((rows, width))
                blob = sp.aot_export(entry.params, dev, fi, ti, w)
                if blob is None:
                    continue  # aot_serialize warned + counted
                self._atomic_write(os.path.join(self.root, rel), blob)
                execs[f"{rows}x{width}"] = rel
                telemetry.COUNTERS.bump("persist_execs_exported")
        return execs

    # ---- retention / GC / sweep --------------------------------------

    def _gc(self, universe: str, manifest: Dict[str, Any],
            pruned: List[Dict[str, Any]]) -> None:
        """Delete the just-pruned generations' artifacts (the manifest
        already committed without them) plus any panel file the kept
        generations no longer reference. Failures warn — GC debt is
        reclaimed by the next sweep, never worth failing a publish."""
        kept = manifest["universes"].get(universe, {}).get("generations", [])
        kept_panels = {g["panel_file"] for g in kept}
        for g in pruned:
            d = os.path.join(self.root, g["dir"])
            try:
                if os.path.isdir(d):
                    shutil.rmtree(d)
                telemetry.COUNTERS.bump("persist_gc_pruned")
            except OSError as e:
                warnings.warn(f"durable zoo GC: could not prune {d}: {e}",
                              RuntimeWarning, stacklevel=2)
            pf = g.get("panel_file")
            if pf and pf not in kept_panels:
                try:
                    os.unlink(os.path.join(self.root, pf))
                except OSError:
                    pass

    def sweep(self, quarantine: bool = True) -> Dict[str, int]:
        """Startup recovery: replay the journal (a ``begin`` without
        its ``commit`` is a crashed publish whose staged artifacts must
        go), drop everything in ``tmp/``, and remove any artifact the
        committed manifest does not reference. Idempotent;
        single-writer (runs at store attach — ``quarantine=False``,
        read-only toward a corrupt manifest — and again at restore,
        where a corrupt manifest IS quarantined loudly)."""
        return self._sweep_impl(quarantine)[0]

    def _sweep_impl(self, quarantine: bool
                    ) -> Tuple[Dict[str, int], Optional[Dict[str, Any]]]:
        """Sweep + the manifest it loaded (one parse serves both the
        sweep and the restore that follows it)."""
        if self.readonly:
            # A read-only attach sweeps NOTHING (concurrent readers on
            # one deploy artifact; cleanup belongs to the writer) —
            # just load the manifest without the quarantine rename.
            return ({"journal_replays": 0, "orphans": 0},
                    self.load_manifest(quarantine=False))
        replays = 0
        begun: Dict[Tuple[str, int], str] = {}
        for line in self._read_journal():
            if line.get("op") != "publish":
                continue
            key = (line.get("universe"), line.get("generation"))
            if line.get("state") == "begin":
                begun[key] = line.get("dir", "")
            elif line.get("state") == "commit":
                begun.pop(key, None)
        manifest = self.load_manifest(quarantine=quarantine)
        if manifest is None:
            # No VALID committed reference set: the store is fresh, or
            # the manifest is unreadable (corrupt / future schema —
            # quarantined just now when this is the restore-path
            # sweep). Either way the snapshots on disk cannot be told
            # apart from committed state, and deleting the operator's
            # evidence on the strength of a manifest we could not read
            # would turn a recoverable incident into data loss. Clean
            # only tmp/; the journal is kept as evidence too.
            orphans = 0
            for item in os.listdir(self.tmp_dir):
                try:
                    p = os.path.join(self.tmp_dir, item)
                    os.unlink(p) if os.path.isfile(p) else shutil.rmtree(p)
                    orphans += 1
                except OSError:
                    pass
            return {"journal_replays": 0, "orphans": orphans}, None
        referenced: set = set()
        for uni in manifest.get("universes", {}).values():
            for g in uni.get("generations", []):
                referenced.add(g["dir"])
                referenced.add(g["panel_file"])
                referenced.update((g.get("execs") or {}).values())
        orphans = 0
        for key, rel in begun.items():
            # Dangling begin: the crashed publish. Its dir is only
            # removed when the manifest does not reference it (the
            # crash may have landed AFTER the commit point but before
            # the journal's commit line — then the manifest owns it).
            replays += 1
            if rel and rel not in referenced:
                d = os.path.join(self.root, rel)
                if os.path.isdir(d):
                    shutil.rmtree(d, ignore_errors=True)
                    orphans += 1
        # Unreferenced-artifact scan (covers pre-journal debris and GC
        # failures). Quarantined artifacts are operator evidence — kept.
        ubase = os.path.join(self.root, "universes")
        for uname in sorted(os.listdir(ubase)) if os.path.isdir(ubase) \
                else []:
            udir = os.path.join(ubase, uname)
            if not os.path.isdir(udir):
                continue
            for item in sorted(os.listdir(udir)):
                rel = os.path.join("universes", uname, item)
                if ".quarantined." in item or rel in referenced:
                    continue
                path = os.path.join(udir, item)
                if item.startswith("gen_") and os.path.isdir(path):
                    shutil.rmtree(path, ignore_errors=True)
                    orphans += 1
                elif item.startswith("panel_") and item.endswith(".npz"):
                    try:
                        os.unlink(path)
                        orphans += 1
                    except OSError:
                        pass
        # Content-addressed executable blobs no kept generation
        # references (all their referents pruned/superseded).
        edir = os.path.join(self.root, "execs")
        for item in (sorted(os.listdir(edir))
                     if os.path.isdir(edir) else []):
            rel = os.path.join("execs", item)
            if ".quarantined." in item or rel in referenced:
                continue
            try:
                os.unlink(os.path.join(edir, item))
                orphans += 1
            except OSError:
                pass
        for item in os.listdir(self.tmp_dir):
            try:
                p = os.path.join(self.tmp_dir, item)
                os.unlink(p) if os.path.isfile(p) else shutil.rmtree(p)
                orphans += 1
            except OSError:
                pass
        # The journal's information is now fully folded into the
        # manifest + filesystem — truncate it (atomically) so it cannot
        # grow without bound across restarts.
        if os.path.exists(self.journal_path):
            self._atomic_write(self.journal_path, b"")
        if replays or orphans:
            telemetry.COUNTERS.bump("persist_journal_replays", replays)
            telemetry.COUNTERS.bump("persist_sweep_orphans", orphans)
            telemetry.instant("persist_sweep", cat="serve",
                              journal_replays=replays, orphans=orphans)
        return {"journal_replays": replays, "orphans": orphans}, manifest

    def _read_journal(self) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        if not os.path.exists(self.journal_path):
            return out
        with open(self.journal_path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue  # the torn final line of a crashed append
        return out

    def probe_record(self, universe: str,
                     generation: Optional[int] = None
                     ) -> Optional[Dict[str, Any]]:
        """The committed parity probe of a universe's generation
        (newest by default): ``{generation, month, firm_idx, scores}``
        from the snapshot's ``probe.npz``, or None when absent/
        unreadable. Read-only — the fleet join gate scores this month
        through a CANDIDATE member and compares bit-equal (DESIGN.md
        §22: the §20 publish-time probe IS the promotion criterion,
        verified actively rather than trusted from a self-report)."""
        manifest = self.load_manifest(quarantine=False) or {}
        gens = (manifest.get("universes", {}).get(universe)
                or {}).get("generations", [])
        if generation is None:
            rec = max(gens, key=lambda g: int(g["generation"]),
                      default=None)
        else:
            rec = next((g for g in gens
                        if int(g["generation"]) == int(generation)),
                       None)
        if rec is None:
            return None
        try:
            with np.load(os.path.join(self.root, rec["dir"],
                                      "probe.npz"),
                         allow_pickle=False) as z:
                return {"generation": int(rec["generation"]),
                        "month": int(z["month"]),
                        "firm_idx": z["firm_idx"].copy(),
                        "scores": z["scores"].copy()}
        except (OSError, KeyError, ValueError):
            return None

    # ---- restore -----------------------------------------------------

    def restore_into(self, service: Any, warm: bool = True,
                     only_newer: bool = False) -> List[Dict[str, Any]]:
        """Re-register every committed universe into ``service``'s zoo,
        newest generation first with older-generation fallback, each
        verified (checksum + bit-exact parity probe) before it may
        serve. Returns one info dict per restored universe; a universe
        whose every committed generation fails verification restores
        NOTHING (loud warning — the fresh-retrain fallback) rather
        than serving wrong numbers.

        ``only_newer`` is the fleet-sync mode (DESIGN.md §22): only
        generations STRICTLY beyond what the service already serves are
        considered — the journaled manifest generation is the publish
        fence a fleet member catches up to; universes already at the
        fence are silently untouched (the zoo's monotonic-publish
        invariant stays intact)."""
        t0 = time.perf_counter()
        out: List[Dict[str, Any]] = []
        with telemetry.span("zoo_restore", cat="serve") as sp:
            # One parse serves both: the sweep's reference scan and the
            # restore loop below read the same loaded manifest (a
            # corrupt one was quarantined by the sweep — loudly).
            swept, manifest = self._sweep_impl(quarantine=True)
            if not manifest:
                sp.set(universes=0, **swept)
                return out
            for universe in sorted(manifest.get("universes", {})):
                gens = manifest["universes"][universe].get("generations", [])
                if only_newer:
                    try:
                        served = int(service.zoo.generation(universe))
                    except KeyError:
                        served = -1
                    gens = [g for g in gens
                            if int(g["generation"]) > served]
                    if not gens:
                        continue  # already at (or past) the fence
                restored = None
                for rec in sorted(gens, key=lambda g: -g["generation"]):
                    try:
                        restored = self._restore_generation(
                            service, universe, rec, warm=warm)
                        break
                    except Exception as e:  # noqa: BLE001 — ladder rung
                        # Quarantine is reserved for an EXPLICIT
                        # corruption verdict: a SnapshotIntegrityError
                        # raised by a verification rung, minus the two
                        # flags (artifact already quarantined itself —
                        # a shared panel file; or an environmental
                        # failure — a transient device fault must not
                        # condemn a possibly-healthy snapshot). Any
                        # UNDIAGNOSED exception fails this attempt
                        # loudly and falls back — it is not evidence
                        # against the snapshot, and the restore as a
                        # whole must never crash over one generation.
                        verdict = isinstance(e, SnapshotIntegrityError)
                        if verdict and not (
                                e.artifact_quarantined
                                or e.skip_quarantine):
                            self._quarantine(
                                os.path.join(self.root, rec["dir"]),
                                str(e))
                        elif not verdict:
                            warnings.warn(
                                f"durable zoo: {universe}/gen"
                                f"{rec.get('generation')}: restore "
                                f"attempt failed ({type(e).__name__}: "
                                f"{e}) — snapshot NOT quarantined "
                                "(undiagnosed failure, not corruption "
                                "evidence); falling back",
                                RuntimeWarning, stacklevel=2)
                        telemetry.COUNTERS.bump(
                            "restore_integrity_failures")
                        continue
                if restored is None:
                    warnings.warn(
                        f"durable zoo: universe {universe!r} restored "
                        "NOTHING (every committed generation failed "
                        "verification) — degrading to fresh retrain "
                        "rather than serving wrong numbers",
                        RuntimeWarning, stacklevel=2)
                    continue
                out.append(restored)
            wall = time.perf_counter() - t0
            sp.set(universes=len(out), wall_s=round(wall, 3),
                   execs_loaded=sum(r["execs_loaded"] for r in out),
                   execs_recompiled=sum(r["execs_recompiled"]
                                        for r in out),
                   **swept)
        return out

    def _restore_generation(self, service: Any, universe: str,
                            rec: Dict[str, Any], warm: bool
                            ) -> Dict[str, Any]:
        """One generation's verify-then-serve ladder. Any rung failing
        raises :class:`SnapshotIntegrityError` (caller quarantines and
        falls back)."""
        import jax
        import jax.numpy as jnp

        from lfm_quant_tpu.config import RunConfig
        from lfm_quant_tpu.data.panel import PanelSplits
        from lfm_quant_tpu.serve.zoo import ZooEntry
        from lfm_quant_tpu.train.checkpoint import CheckpointManager
        from lfm_quant_tpu.train.loop import restore_state_dict
        from lfm_quant_tpu.utils import metrics

        t0 = time.perf_counter()
        gen = int(rec["generation"])
        gdir = os.path.join(self.root, rec["dir"])

        # 1. Panel: content hash must match the manifest. A corrupt or
        # missing panel is an ARTIFACT failure, not a generation
        # failure: the corrupt file itself is quarantined and the
        # healthy gen dir is left in place (several generations may
        # share one content-addressed panel — renaming their dirs over
        # a panel fault would cascade one flipped bit into the loss of
        # the universe's whole restore chain).
        panel_path = os.path.join(self.root, rec["panel_file"])
        try:
            with open(panel_path, "rb") as fh:
                pbytes = fh.read()
        except OSError as e:
            err = SnapshotIntegrityError(
                f"{universe}/gen{gen}: panel file missing ({e})")
            err.artifact_quarantined = True  # nothing else to rename
            raise err
        if hashlib.sha256(pbytes).hexdigest() != rec["panel_sha256"]:
            reason = (f"{universe}/gen{gen}: panel content hash mismatch "
                      f"({rec['panel_file']})")
            self._quarantine(panel_path, reason)
            err = SnapshotIntegrityError(reason)
            err.artifact_quarantined = True
            raise err
        panel = _panel_from_npz(panel_path)

        # 2. Trainer rebuilt from the recorded config + boundaries —
        # same program key ⇒ same compiled-program family.
        try:
            cfg = RunConfig.from_json(json.dumps(rec["cfg"]))
            splits = PanelSplits(
                panel=panel,
                train_end_idx=rec["splits"]["train_end_idx"],
                val_end_idx=rec["splits"]["val_end_idx"],
                train_start_idx=rec["splits"]["train_start_idx"])
            trainer = _build_trainer(rec.get("trainer", "Trainer"),
                                     cfg, splits)
        except (KeyError, TypeError, ValueError) as e:
            raise SnapshotIntegrityError(
                f"{universe}/gen{gen}: recorded config/splits do not "
                f"rebuild a trainer ({type(e).__name__}: {e})")

        # 3. Params: Orbax restore + checksum gate.
        st = trainer.init_state()
        mgr = CheckpointManager(os.path.join(gdir, "params"), max_to_keep=1)
        try:
            restored = restore_state_dict(
                mgr, {"params": jax.tree.map(np.asarray, st.params)})
        except Exception as e:  # noqa: BLE001 — integrity rung
            raise SnapshotIntegrityError(
                f"{universe}/gen{gen}: params checkpoint unreadable "
                f"({type(e).__name__}: {e})")
        finally:
            mgr.close()
        params = restored["params"]
        if params_checksum(params) != rec["params_sha256"]:
            raise SnapshotIntegrityError(
                f"{universe}/gen{gen}: params checksum mismatch — the "
                "snapshot does not hold the bytes the manifest stamped")
        commit = getattr(trainer, "_commit_state", lambda s: s)
        trainer.state = commit(st._replace(
            params=jax.tree.map(jnp.asarray, params)))

        entry = ZooEntry(universe, gen, trainer)

        # 4. Serialized executables deserialized (no dispatch yet —
        # and no counter bumps until the probe gate passes, so a
        # quarantined generation never inflates the restore
        # accounting), with the loud counted fallback.
        loaded = fallback = 0
        fp_now = program_fingerprint(trainer.program_key)
        fp_match = fp_now == rec.get("program_fingerprint")
        execs = rec.get("execs") or {}
        if execs and not fp_match:
            warnings.warn(
                f"durable zoo: {universe}/gen{gen}: program fingerprint "
                "mismatch (jax version / backend / topology / program "
                "key changed since publish) — serialized executables "
                "skipped, warm ladder recompiles (persistent "
                "compilation cache softens this when configured)",
                RuntimeWarning, stacklevel=2)
        if fp_match:
            for bkey, rel in sorted(execs.items()):
                rows_s, _, width_s = bkey.partition("x")
                bucket = (int(rows_s), int(width_s))
                try:
                    with open(os.path.join(self.root, rel), "rb") as fh:
                        blob = fh.read()
                except OSError:
                    blob = None
                if blob is not None and \
                        entry.programs_for(bucket).load_aot(blob):
                    loaded += 1
                else:
                    fallback += 1
                    warnings.warn(
                        f"durable zoo: {universe}/gen{gen}: serialized "
                        f"executable for bucket {bucket} failed to "
                        "deserialize — that bucket recompiles",
                        RuntimeWarning, stacklevel=2)

        # 5. The parity-probe gate, BEFORE the full warm ladder: a
        # snapshot that cannot reproduce its publish-time numbers must
        # not cost the whole ladder's warmup (the probe itself touches
        # only the (1, width) bucket — one load or one compile).
        try:
            with np.load(os.path.join(gdir, "probe.npz"),
                         allow_pickle=False) as z:
                p_month = int(z["month"])
                p_pool = z["firm_idx"]
                p_scores = z["scores"]
        except (OSError, KeyError, ValueError) as e:
            raise SnapshotIntegrityError(
                f"{universe}/gen{gen}: probe artifact unreadable "
                f"({type(e).__name__}: {e})")
        try:
            live_pool = entry.pool(entry.month_col(p_month))
            live = score_single_month(entry, p_month, service.max_rows)
        except KeyError:
            # The stamped month is no longer serveable on the rebuilt
            # entry: snapshot and code genuinely disagree — quarantine.
            raise SnapshotIntegrityError(
                f"{universe}/gen{gen}: probe month {p_month} is not "
                "serveable on the rebuilt entry — snapshot and code "
                "disagree about the universe's geometry")
        except Exception as e:  # noqa: BLE001 — environmental, not corrupt
            # The probe could not RUN (a transient device fault, an
            # active chaos schedule, OOM): that is an environmental
            # failure, not evidence against the snapshot — fail this
            # attempt WITHOUT condemning a possibly-healthy snapshot.
            err = SnapshotIntegrityError(
                f"{universe}/gen{gen}: parity probe could not run "
                f"({type(e).__name__}: {e}) — snapshot NOT quarantined "
                "(environmental failure, retry the restore)")
            err.skip_quarantine = True
            raise err from e
        if not np.array_equal(live_pool, p_pool) or \
                not np.array_equal(live.astype(np.float32), p_scores):
            raise SnapshotIntegrityError(
                f"{universe}/gen{gen}: parity probe mismatch — month "
                f"{p_month} scored through the restored generation is "
                "NOT bit-equal to the publish-time probe")
        telemetry.COUNTERS.bump("restore_probe_ok")

        # 6+. The snapshot VERIFIED: any failure past this line is
        # environmental (warmup fault, device hiccup) — the attempt
        # fails but the bit-verified snapshot is never condemned.
        try:
            # The verified generation pays its warm ladder (and only
            # now do the exec counters record). Honest accounting:
            # every warmed bucket WITHOUT a loaded executable compiles
            # here — a missing artifact (export failed at publish), a
            # failed load (already counted), a fingerprint mismatch,
            # or a restore-side ladder wider than the published one
            # all end in the same jit trace.
            if warm:
                n_buckets = service.warmup_entry(entry)
                extra = max(0, n_buckets - loaded) - fallback
                if extra > 0:
                    fallback += extra
            if loaded:
                telemetry.COUNTERS.bump("restore_execs_loaded", loaded)
            if fallback:
                telemetry.COUNTERS.bump("restore_execs_recompiled",
                                        fallback)

            # 7. Drift reference re-stamped from the serialized sketch
            # — zero re-scoring, zero new traces. NON-FATAL: the
            # params/scores verified bit-equal; a malformed sketch
            # state costs the drift gauge, not the generation.
            if rec.get("ref_sketch") and metrics.enabled():
                try:
                    entry.stamp_reference(
                        metrics.ScoreSketch.from_state(
                            rec["ref_sketch"]))
                except (KeyError, TypeError, ValueError) as e:
                    warnings.warn(
                        f"durable zoo: {universe}/gen{gen}: drift "
                        f"reference sketch unreadable ({e}) — serving "
                        "WITHOUT a drift reference for this generation",
                        RuntimeWarning, stacklevel=2)

            service.zoo.publish(entry)
        except Exception as e:  # noqa: BLE001 — environmental, not corrupt
            err = SnapshotIntegrityError(
                f"{universe}/gen{gen}: post-verification restore step "
                f"failed ({type(e).__name__}: {e}) — snapshot NOT "
                "quarantined (it verified bit-equal; the failure is "
                "environmental)")
            err.skip_quarantine = True
            raise err from e
        wall = time.perf_counter() - t0
        info = {"universe": universe, "generation": gen,
                "execs_loaded": loaded, "execs_recompiled": fallback,
                "probe": "bit_equal", "wall_s": round(wall, 3)}
        telemetry.instant("restore_generation", cat="serve", **info)
        return info
