"""Always-on scoring service (L7): the batch stack turned online.

The walk-forward stack trains and scores as batch programs; this
package serves the same compiled programs to live traffic:

  buckets.py — request-shape quantization (padded cross-section / row
               buckets folded into the program-cache key, Khomenko-style
               sequence bucketing) so arbitrary queries never re-trace
  zoo.py     — HBM-resident model zoo: (universe × generation) entries
               through the PR 1 program/panel caches, refcount-safe LRU
               eviction and atomic generation swap
  batcher.py — micro-batcher coalescing concurrent queries into one
               bucketed dispatch of the compiled scoring core, with
               per-request latency spans + queue/occupancy counters
               through the PR 4 telemetry registry
  service.py — the front-end: register / warmup / score / submit /
               refresh (warm single-fold retrain + swap) / restore /
               restart_batcher / stats
  persist.py — durable serving state (DESIGN.md §20): write-ahead-
               journaled zoo snapshots (Orbax params + checksum, panel,
               drift sketch, parity probe, serialized executables),
               crash-consistent atomic manifest commit, verified
               zero-cold-start restore with quarantine fallback
  incident.py — automatic incident capture (DESIGN.md §21): the
               existing degradation signals (breaker open, SLO burn,
               drift veto, snapshot quarantine, shed spike) each write
               one rate-limited self-contained evidence bundle —
               flight-recorder ring, /metrics scrape, one-lock
               snapshot, slowest request traces, host identity
  stats.py   — pure-python latency percentiles shared with bench and
               mirrored in scripts/trace_report.py
  fleet.py   — fleet-ready serving (DESIGN.md §22): FleetCoordinator
               (member registry, consistent (universe, generation) →
               member routing with replication, store-manifest publish
               fence, store-bootstrapped join/promotion gate) +
               FleetRouter (health-aware failover front door — an
               open-circuit or dead member is a reroute, not an
               error) + the subprocess member entry
               (``python -m lfm_quant_tpu.serve.fleet``)

Entry point: ``serve.py`` at the repo root. Knobs: ``LFM_SERVE_ZOO``,
``LFM_SERVE_MAX_ROWS``, ``LFM_SERVE_MAX_WAIT_MS``, ``LFM_ZOO_PERSIST``,
``LFM_ZOO_KEEP_GENERATIONS``, ``LFM_FLIGHT``, ``LFM_INCIDENT_DIR``,
``LFM_INCIDENT_COOLDOWN_S``, ``LFM_ACCESS_LOG``, ``LFM_FLEET`` (+ the
``LFM_FLEET_*`` routing knobs).
"""

from lfm_quant_tpu.serve.batcher import MicroBatcher, ScoreResponse
from lfm_quant_tpu.serve.fleet import (
    FleetCoordinator,
    FleetRouter,
    HttpMember,
    LocalMember,
    MemberJoinRefused,
)
from lfm_quant_tpu.serve.incident import IncidentManager
from lfm_quant_tpu.serve.persist import ZooStore
from lfm_quant_tpu.serve.service import ScoringService
from lfm_quant_tpu.serve.zoo import ModelZoo, ServePrograms, ZooEntry

__all__ = [
    "FleetCoordinator",
    "FleetRouter",
    "HttpMember",
    "IncidentManager",
    "LocalMember",
    "MemberJoinRefused",
    "MicroBatcher",
    "ModelZoo",
    "ScoreResponse",
    "ScoringService",
    "ServePrograms",
    "ZooEntry",
    "ZooStore",
]
