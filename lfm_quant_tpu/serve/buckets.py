"""Request-shape buckets: quantize arbitrary queries to compiled shapes.

A scoring request's natural shape is ragged twice over — the month's
eligible cross-section (hundreds to thousands of firms, different every
month) and the number of requests the micro-batcher happens to coalesce.
Dispatching those raw shapes into jit would re-trace (and XLA-recompile)
on nearly every query. The fix is the sequence-bucketing idea of
Khomenko et al. 1708.05604 applied to the serving path: round both axes
UP to a power-of-two bucket, pad with weight-0 slots (exactly the
padding discipline the eval sweep already uses), and fold the bucket
into the program-cache key (``train/reuse.py serve_program_key``). The
bucket ladder is finite and known at warmup, so every program the
service can ever dispatch is compiled before the first real request —
steady state pays ZERO jit traces by construction, measured by the
``reuse`` counters. The third ragged axis — the model's lookback window
— is a per-universe constant and already lives in the inner trainer
program key (``cfg.data.window``), so distinct lookbacks are distinct
compiled programs the same way.

Padding waste is bounded by construction: a power-of-two ladder wastes
< 2× slots worst-case, and weight-0 slots cost only FLOPs, not
correctness (the forward masks them; responses slice them off).
"""

from __future__ import annotations

import os
from typing import Tuple

# The pow2 ladder arithmetic is shared with the training-side geometry
# buckets (PR 8): lfm_quant_tpu/buckets.py is the single source, this
# module re-exports the serving half so existing imports keep working
# and the two paths can never drift.
from lfm_quant_tpu.buckets import (  # noqa: F401 — re-exports
    MIN_WIDTH,
    bucket_width,
    next_pow2,
    rows_ladder,
    width_ladder,
)


def bucket_rows(n_requests: int, max_rows: int) -> int:
    """Row (coalesced-request) bucket: next power of two, capped at the
    batcher's ``max_rows`` (the cap is itself a ladder member)."""
    if n_requests < 1:
        raise ValueError(f"bucket_rows needs >= 1 request, got {n_requests}")
    return min(next_pow2(n_requests), next_pow2(max_rows))


def max_rows_default() -> int:
    """``LFM_SERVE_MAX_ROWS``: the micro-batch row cap (default 8)."""
    return max(1, int(os.environ.get("LFM_SERVE_MAX_ROWS", "8")))


def max_wait_ms_default() -> float:
    """``LFM_SERVE_MAX_WAIT_MS``: how long the batcher holds a batch
    open for more same-bucket requests (default 2 ms — latency floor
    traded against occupancy)."""
    return float(os.environ.get("LFM_SERVE_MAX_WAIT_MS", "2"))


def zoo_capacity_default() -> int:
    """``LFM_SERVE_ZOO``: resident (universe) entries before LRU
    eviction (default 8)."""
    return max(1, int(os.environ.get("LFM_SERVE_ZOO", "8")))


# ---- degradation knobs (DESIGN.md §18) -----------------------------------
# Operational defaults for the graceful-degradation layer, resolved here
# beside the other LFM_SERVE_* knobs so the batcher has one place to
# read and the knob checker one place to find.


def queue_max_default() -> int:
    """``LFM_SERVE_QUEUE_MAX``: bounded admission — a submit that finds
    this many requests already queued is SHED (429-path ShedError)
    instead of growing the queue without bound (default 256; <= 0
    disables the bound — the pre-chaos behavior)."""
    return int(os.environ.get("LFM_SERVE_QUEUE_MAX", "256"))


def deadline_ms_default() -> float:
    """``LFM_SERVE_DEADLINE_MS``: default per-request deadline in ms
    (0, the default, = none). A request whose deadline expires before
    dispatch is dropped (504-path DeadlineError) WITHOUT costing a
    device dispatch; ``score(timeout=...)`` propagates the client
    timeout as the deadline regardless of this knob."""
    return float(os.environ.get("LFM_SERVE_DEADLINE_MS", "0"))


def retries_default() -> int:
    """``LFM_SERVE_RETRIES``: bounded jittered retries of the surviving
    batch on a TRANSIENT dispatch failure (serve/errors.py
    ``is_transient``; default 2 — i.e. up to 3 attempts)."""
    return max(0, int(os.environ.get("LFM_SERVE_RETRIES", "2")))


def breaker_threshold_default() -> int:
    """``LFM_SERVE_BREAKER``: consecutive exhausted dispatch failures
    that OPEN the circuit breaker (default 4; <= 0 disables it)."""
    return int(os.environ.get("LFM_SERVE_BREAKER", "4"))


def breaker_cooldown_ms_default() -> float:
    """``LFM_SERVE_BREAKER_COOLDOWN_MS``: how long an OPEN circuit
    fast-fails (503 + retry-after) before admitting a half-open probe
    (default 250 ms)."""
    return float(os.environ.get("LFM_SERVE_BREAKER_COOLDOWN_MS", "250"))


BucketKey = Tuple[int, int]  # (rows, cross-section width)
