"""Fleet-ready serving: coordinator-scoped state, health-aware failover
routing, store-bootstrapped member join (DESIGN.md §22).

PR 6–13 built a production-shaped single process: one zoo, one batcher,
one host's HBM — durable (§20), observable (§19/§21), chaos-hardened
(§18), but a dead process was still a total outage. This module is the
fleet layer over those exact primitives, the Khomenko-style bucketed
data-parallel serving pattern extended from one batcher to many
members:

* **Coordinator-scoped state** — :class:`FleetCoordinator` owns what
  used to be per-process module state seen fleet-wide: the member
  registry, the consistent (universe, generation) → member routing
  table (rendezvous hashing with ``LFM_FLEET_REPLICAS``-way replication
  of hot universes, per-universe overridable), and the publish FENCE —
  the durable store's journaled manifest generation per universe, the
  single source of truth a publish propagates from. Each member remains
  a whole :class:`~lfm_quant_tpu.serve.service.ScoringService` (its own
  program cache, panel residency and zoo — per-process state stays
  per-process; the coordinator scopes the ROUTING over it), so today's
  single-process deploy is exactly the degenerate one-member fleet
  (:meth:`FleetCoordinator.local`).
* **Health-aware failover routing** — :class:`FleetRouter` is the fleet
  front door: it consumes each member's PR 10/11 health surface
  (breaker state, ``/healthz`` readiness + retry-after, SLO-burn
  detail) through a TTL-cached probe, routes around members that are
  OUT (dead, open-circuit, unready) and soft-deprioritizes members
  whose SLO is burning, retries a failed member call on the next
  replica with the serve/errors.py transient taxonomy and the
  batcher's capped-jittered backoff (bounded by ``LFM_FLEET_RETRIES``),
  and readmits an OUT member only through a half-open probe: after
  ``LFM_FLEET_COOLDOWN_MS`` exactly ONE live request is routed to it —
  success readmits, failure re-opens. A member crash is therefore a
  reroute, not an error: every member restored from the same store
  artifact serves BIT-EQUAL scores (the §20 parity probe is the
  promotion criterion), so a failover response is the same bytes the
  dead member would have sent.
* **Store-bootstrapped join** — a new member bootstraps from the
  durable store deploy artifact alone (``member_main``: restore →
  verify → serve), and :meth:`FleetCoordinator.add_member` is the
  promotion gate: the member's join report must show every restored
  generation probe-verified ``bit_equal`` and generation-matched to
  the store fence (behind-fence members get one ``sync()`` to catch
  up). A member that fails the gate is REFUSED — never routed to. An
  atomic generation publish propagates fleet-wide through the same
  fence: :meth:`FleetCoordinator.sync_members` tells every member to
  pull newer-than-served generations from the store (journal
  generation as the fence; ``ScoringService.sync_from_store``).

Everything runs on one machine as N subprocess members behind the
router (``serve.py --fleet N`` / ``LFM_FLEET=N``; ``spawn_member``
launches ``python -m lfm_quant_tpu.serve.fleet`` children), which makes
the whole layer drivable under the chaos harness today and is the
deployment shape for the v5e pod later. With ``LFM_FLEET`` unset
nothing here runs: the single-process serve path is byte-for-byte the
pre-fleet one (measured non-interference, tests/test_fleet.py).

Observability: the router bumps ``fleet_requests`` / ``fleet_reroutes``
/ ``fleet_failovers`` / ``fleet_member_out`` / ``fleet_probes`` /
``fleet_readmissions`` / ``fleet_joins`` / ``fleet_refusals`` /
``fleet_unroutable`` counters and emits matching ``fleet_*`` instants
(the per-member health timeline ``scripts/trace_report.py`` renders);
fleet ``/metrics`` is the router registry plus every remote member's
scrape relabeled with ``member="name"``, and fleet ``/healthz`` is the
aggregation of one health probe per member.
"""

from __future__ import annotations

import json
import os
import threading
import time
from hashlib import sha256
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from lfm_quant_tpu.serve.batcher import (
    ScoreResponse,
    backoff_sleep,
    clean_request_id,
    new_request_id,
)
from lfm_quant_tpu.serve.errors import (
    DeadlineError,
    DriftVetoError,
    MemberUnavailableError,
)
from lfm_quant_tpu.utils import telemetry

# ---- knobs (LFM_FLEET_*) --------------------------------------------------


def fleet_members_default() -> int:
    """``LFM_FLEET``: subprocess member count for the ``serve.py``
    fleet mode (unset/0 = single-process serving, the exact-no-op
    default — no router, no coordinator, no subprocesses)."""
    try:
        return max(0, int(os.environ.get("LFM_FLEET", "0")))
    except ValueError:
        raise ValueError(
            f"LFM_FLEET must be an integer member count, got "
            f"{os.environ.get('LFM_FLEET')!r}")


def fleet_enabled() -> bool:
    """Whether fleet serving is configured (the manifest knob probe)."""
    return fleet_members_default() > 0


def replicas_default() -> int:
    """``LFM_FLEET_REPLICAS``: how many members serve each universe
    (default 2, capped at the member count; hot universes can be
    widened per-universe via ``FleetCoordinator.set_replicas``)."""
    return max(1, int(os.environ.get("LFM_FLEET_REPLICAS", "2")))


def retries_default() -> int:
    """``LFM_FLEET_RETRIES``: bounded per-request MEMBER retries — how
    many additional members a request may fail over to after its first
    attempt (default 2, i.e. up to 3 member attempts)."""
    return max(0, int(os.environ.get("LFM_FLEET_RETRIES", "2")))


def breaker_default() -> int:
    """``LFM_FLEET_BREAKER``: consecutive failed calls that take a
    member OUT of the routing set (default 2; 1 = first failure)."""
    return max(1, int(os.environ.get("LFM_FLEET_BREAKER", "2")))


def cooldown_ms_default() -> float:
    """``LFM_FLEET_COOLDOWN_MS``: how long an OUT member is skipped
    before the half-open readmission probe (default 1000 ms; a member
    whose /healthz carried a longer ``retry_after_s`` keeps that)."""
    return max(0.0, float(os.environ.get("LFM_FLEET_COOLDOWN_MS", "1000")))


def health_ttl_ms_default() -> float:
    """``LFM_FLEET_HEALTH_TTL_MS``: how long a member health probe is
    trusted before the router re-consults ``/healthz`` (default 500 ms
    — bounds both staleness and probe traffic)."""
    return max(0.0, float(os.environ.get("LFM_FLEET_HEALTH_TTL_MS", "500")))


def member_timeout_ms_default() -> float:
    """``LFM_FLEET_TIMEOUT_MS``: per-member call timeout (default
    15000 ms; the client's own deadline caps it per attempt)."""
    return max(1.0, float(os.environ.get("LFM_FLEET_TIMEOUT_MS", "15000")))


# ---- member-level failure taxonomy ---------------------------------------


class MemberCallError(RuntimeError):
    """A member-LEVEL failure of one call: connection refused/reset,
    timeout, or an HTTP 5xx/429 from the member's front door. Marked
    ``transient`` because another replica can serve the same request
    (serve/errors.py ``is_transient`` reads the attribute)."""

    transient = True

    def __init__(self, member: str, detail: str,
                 status: Optional[int] = None):
        super().__init__(f"member {member!r}: {detail}")
        self.member = member
        self.status = status


def member_retryable(exc: BaseException) -> bool:
    """The ROUTER's failover classification, one level above the
    batcher's: may another member serve this request? Client/data
    errors that would fail identically everywhere (unknown universe or
    month, malformed values, an expired client deadline, a drift veto)
    are NOT — they propagate. Everything else (shed, open circuit,
    dead batcher, transient faults, connection failures, undiagnosed
    member-side errors) IS: all members serve the same store artifact
    bit-equally, so a retry elsewhere is the same answer."""
    if isinstance(exc, (KeyError, ValueError, TypeError,
                        DeadlineError, DriftVetoError)):
        return False
    return True


# ---- member adapters ------------------------------------------------------


class LocalMember:
    """An in-process :class:`ScoringService` as a fleet member — the
    degenerate one-member fleet IS today's deploy behind this adapter,
    and multi-member single-process fleets are the unit-test vehicle
    for the routing/failover machinery."""

    remote = False

    def __init__(self, name: str, service: Any):
        self.name = name
        self.service = service

    def score(self, universe: str, month: int,
              timeout_s: Optional[float] = None,
              request_id: Optional[str] = None) -> ScoreResponse:
        return self.service.score(universe, month, timeout=timeout_s,
                                  request_id=request_id)

    def health(self, timeout_s: Optional[float] = None
               ) -> Dict[str, Any]:
        return self.service.health()  # in-process: no wire to bound

    def snapshot(self) -> Dict[str, Any]:
        return self.service.snapshot()

    def metrics_text(self) -> str:
        return self.service.metrics_text()

    def universes(self) -> Dict[str, int]:
        return dict(self.service.zoo.snapshot()["universes"])

    def serveable_months(self, universe: str) -> List[int]:
        return self.service.serveable_months(universe)

    def sync(self) -> List[Dict[str, Any]]:
        return self.service.sync_from_store()

    def join_report(self) -> Dict[str, Any]:
        return {
            "member": self.name,
            "build": telemetry.build_info(),
            "universes": self.universes(),
            "restore": getattr(self.service, "last_restore", None),
            "restore_compiles": getattr(
                self.service, "last_restore_compiles", None),
        }

    def close(self) -> None:
        self.service.close()


class HttpMember:
    """A subprocess (or remote-host) member reached over its HTTP front
    door (``serve.py make_http_server`` — the same one front door every
    deploy shape shares). Every failure of the wire or of the member's
    degradation layer surfaces as :class:`MemberCallError` (transient:
    the router fails over); routing/validation errors the member
    answered with 404 surface as ``KeyError`` (the client's error on
    every member, not this member's)."""

    remote = True

    def __init__(self, name: str, base_url: str,
                 timeout_s: Optional[float] = None,
                 pid: Optional[int] = None):
        self.name = name
        self.base_url = base_url.rstrip("/")
        self.timeout_s = (member_timeout_ms_default() / 1e3
                          if timeout_s is None else float(timeout_s))
        self.pid = pid
        self._months: Dict[str, List[int]] = {}

    def _get(self, path: str, timeout_s: Optional[float] = None,
             headers: Optional[Dict[str, str]] = None
             ) -> Tuple[int, bytes]:
        import urllib.error
        import urllib.request

        req = urllib.request.Request(self.base_url + path,
                                     headers=headers or {})
        try:
            with urllib.request.urlopen(
                    req, timeout=timeout_s or self.timeout_s) as resp:
                return resp.status, resp.read()
        except urllib.error.HTTPError as e:
            # The member ANSWERED with a failure status: read the body
            # (its error taxonomy) so the caller can classify.
            return e.code, e.read()
        except Exception as e:  # noqa: BLE001 — wire-level failure
            raise MemberCallError(
                self.name, f"{type(e).__name__}: {e}") from e

    def _get_json(self, path: str, timeout_s: Optional[float] = None,
                  headers: Optional[Dict[str, str]] = None
                  ) -> Tuple[int, Any]:
        status, body = self._get(path, timeout_s, headers)
        try:
            return status, json.loads(body.decode())
        except (ValueError, UnicodeDecodeError) as e:
            raise MemberCallError(
                self.name, f"unparseable response ({e}) on {path}",
                status=status) from e

    def score(self, universe: str, month: int,
              timeout_s: Optional[float] = None,
              request_id: Optional[str] = None) -> ScoreResponse:
        headers = {"X-Request-Id": request_id} if request_id else {}
        status, payload = self._get_json(
            f"/score?universe={universe}&month={int(month)}",
            timeout_s=timeout_s, headers=headers)
        if status == 404:
            raise KeyError(str(payload.get("error") or
                               f"{universe!r}/{month} not serveable"))
        if status == 504:
            # The member ANSWERED that the request's deadline expired:
            # same taxonomy as a LocalMember's DeadlineError —
            # non-retryable (the client gave up; re-running it on every
            # replica would punish healthy-but-congested members), and
            # it must not feed the member breaker.
            raise DeadlineError(universe, int(month), 0.0)
        if status != 200:
            raise MemberCallError(
                self.name,
                f"HTTP {status}: {payload.get('error')}", status=status)
        # float32 → JSON float → float32 is exact (float64 represents
        # every float32), so bit-equality SURVIVES the wire — the
        # failover correctness contract rests on this.
        return ScoreResponse(
            universe=payload["universe"], month=int(payload["month"]),
            generation=int(payload["generation"]),
            firm_idx=np.asarray(payload["firm_idx"], np.int32),
            scores=np.asarray(payload["scores"], np.float32),
            latency_ms=float(payload.get("latency_ms") or 0.0),
            request_id=str(payload.get("request_id") or ""),
            phases=payload.get("phases"))

    def health(self, timeout_s: Optional[float] = None
               ) -> Dict[str, Any]:
        status, payload = self._get_json("/healthz",
                                         timeout_s=timeout_s)
        if not isinstance(payload, dict):
            raise MemberCallError(self.name, "malformed /healthz body",
                                  status=status)
        return payload

    def snapshot(self) -> Dict[str, Any]:
        _, stats = self._get_json("/stats")
        return {"stats": stats, "health": self.health()}

    def metrics_text(self) -> str:
        status, body = self._get("/metrics")
        if status != 200:
            raise MemberCallError(self.name, f"/metrics HTTP {status}",
                                  status=status)
        return body.decode()

    def join_report(self) -> Dict[str, Any]:
        status, payload = self._get_json("/fleet")
        if status != 200 or not isinstance(payload, dict):
            raise MemberCallError(self.name, f"/fleet HTTP {status}",
                                  status=status)
        payload.setdefault("member", self.name)
        months = payload.get("months")
        if isinstance(months, dict):
            self._months = {u: [int(m) for m in ms]
                            for u, ms in months.items()}
        return payload

    def universes(self) -> Dict[str, int]:
        _, stats = self._get_json("/stats")
        return {u: int(g) for u, g in (stats.get("universes")
                                       or {}).items()}

    def serveable_months(self, universe: str) -> List[int]:
        if universe not in self._months:
            self.join_report()
        if universe not in self._months:
            raise KeyError(f"universe {universe!r} is not served by "
                           f"member {self.name!r}")
        return list(self._months[universe])

    def sync(self) -> List[Dict[str, Any]]:
        status, payload = self._get_json("/sync")
        if status != 200:
            raise MemberCallError(self.name, f"/sync HTTP {status}: "
                                             f"{payload.get('error')}",
                                  status=status)
        # A sync can change the serveable-month coverage (a newer
        # generation's panel): the memoized months are stale now.
        self._months = {}
        return payload.get("synced", [])

    def close(self) -> None:
        pass  # the spawner owns the process lifecycle


# ---- the coordinator ------------------------------------------------------


class MemberJoinRefused(RuntimeError):
    """The join/promotion gate refused a member: its restore report is
    missing, probe-unverified, or behind the store fence even after a
    sync. A refused member is never entered into routing."""


class _MemberSlot:
    """One member's coordinator-side state (registry entry + the
    router's health/breaker machine). Guarded by the coordinator lock;
    the router mutates it through the coordinator's helpers."""

    __slots__ = ("name", "member", "state", "fail_streak", "out_until",
                 "probing", "universes", "health_cache", "health_ts",
                 "health_inflight", "degraded", "served", "failures",
                 "last_error", "info")

    def __init__(self, name: str, member: Any):
        self.name = name
        self.member = member
        self.state = "in"          # in | out
        self.fail_streak = 0
        self.out_until = 0.0       # perf_counter seconds
        self.probing = False       # half-open: ONE probe in flight
        self.universes: Dict[str, int] = {}
        self.health_cache: Optional[Dict[str, Any]] = None
        self.health_ts = -1e18     # perf_counter of last health probe
        self.health_inflight = False  # single-flight health refresh
        self.degraded = False      # SLO burning → soft-deprioritized
        self.served = 0
        self.failures = 0
        self.last_error: Optional[str] = None
        self.info: Dict[str, Any] = {}


def _hrw(key: str, member: str) -> int:
    """Rendezvous (highest-random-weight) score: deterministic in the
    (key, member) pair alone, so the routing table is identical on
    every router instance and across member registration orders."""
    return int.from_bytes(
        sha256(f"{key}|{member}".encode()).digest()[:8], "big")


class FleetCoordinator:
    """The fleet's shared state, promoted out of per-process modules:
    member registry, consistent (universe, generation) → member routing
    with replication, the store-manifest publish fence, and the
    join/promotion gate (module docstring). Thread-safe; owns no
    network I/O on the routing hot path (routing is pure hashing over
    the registry snapshot)."""

    def __init__(self, store: Any = None, replicas: Optional[int] = None):
        self.store = store
        self._default_replicas = (replicas_default() if replicas is None
                                  else max(1, int(replicas)))
        self._replica_overrides: Dict[str, int] = {}
        self._lock = threading.RLock()
        self._slots: "Dict[str, _MemberSlot]" = {}
        # fence() memo keyed on the manifest file's stat (see fence()).
        self._fence_cache: Optional[Tuple[Any, Dict[str, int]]] = None

    @classmethod
    def local(cls, service: Any, name: str = "m0",
              replicas: Optional[int] = None) -> "FleetCoordinator":
        """The degenerate one-member fleet: today's single-process
        deploy wrapped as a member. No store, no verification — the
        service IS the authority it would be verified against."""
        coord = cls(store=getattr(service, "store", None),
                    replicas=replicas)
        coord.add_member(LocalMember(name, service), verify=False)
        return coord

    # ---- registry / join gate ---------------------------------------

    def add_member(self, member: Any, verify: bool = True
                   ) -> Dict[str, Any]:
        """Admit a member — the fleet's PROMOTION gate (DESIGN.md §22).
        With ``verify`` (the default for store-bootstrapped joins) the
        member's join report must show every restored generation
        verified ``bit_equal`` against its publish-time parity probe,
        and every served generation matching the store fence (a member
        behind the fence gets ONE ``sync()`` to catch up, then must
        match). A member that fails the gate raises
        :class:`MemberJoinRefused` and is NEVER entered into routing.
        Returns the accepted join report."""
        name = member.name
        try:
            rep = member.join_report()
        except Exception as e:  # noqa: BLE001 — refusal, not a crash
            self._refuse(name, f"join report unavailable "
                               f"({type(e).__name__}: {e})")
        unis = {u: int(g) for u, g in (rep.get("universes") or {}).items()}
        if verify:
            restore = rep.get("restore")
            if restore is not None:
                bad = [r for r in restore
                       if r.get("probe") != "bit_equal"]
                if bad:
                    self._refuse(
                        name, "restore report carries unverified "
                        f"generations: {[r.get('universe') for r in bad]}"
                        " (probe != bit_equal)")
            fence = self.fence()
            behind = {u for u, g in fence.items()
                      if unis.get(u, -1) < g}
            if behind:
                # One chance to catch up through the store (the fence
                # is the journal generation — sync pulls only newer).
                try:
                    member.sync()
                    unis = {u: int(g)
                            for u, g in member.universes().items()}
                except Exception as e:  # noqa: BLE001 — refusal below
                    self._refuse(name, f"behind fence {sorted(behind)} "
                                       f"and sync failed "
                                       f"({type(e).__name__}: {e})")
                behind = {u for u, g in fence.items()
                          if unis.get(u, -1) < g}
            if behind:
                self._refuse(
                    name, f"still behind the publish fence after sync: "
                          f"{sorted(behind)}")
            # ACTIVE parity verification — the promotion criterion
            # proper (DESIGN.md §22): score each fenced universe's
            # publish-time probe month THROUGH the candidate and
            # compare bit-equal against the store's committed probe.
            # Self-reported verdicts alone would admit a member that
            # never restored (restore=None) but serves its own,
            # different params; the active probe trusts nothing.
            # Skipped per-universe only when the store holds no probe
            # artifact (then the report checks above are all the
            # evidence there is).
            if self.store is not None:
                for u in sorted(set(fence) & set(unis)):
                    pr = self.store.probe_record(u)
                    if pr is None:
                        continue
                    try:
                        live = member.score(
                            u, pr["month"],
                            timeout_s=member_timeout_ms_default() / 1e3)
                    except Exception as e:  # noqa: BLE001 — refusal below
                        self._refuse(
                            name, f"parity probe for {u!r} could not "
                                  f"run ({type(e).__name__}: {e})")
                    if not (np.array_equal(live.firm_idx,
                                           pr["firm_idx"])
                            and np.array_equal(
                                live.scores.astype(np.float32),
                                pr["scores"])):
                        self._refuse(
                            name, f"parity probe mismatch for {u!r}: "
                                  f"month {pr['month']} scored through "
                                  "the member is not bit-equal to the "
                                  "store's publish-time probe")
        slot = _MemberSlot(name, member)
        slot.universes = unis
        slot.info = {
            "host": (rep.get("build") or {}).get("host"),
            "pid": ((rep.get("build") or {}).get("pid")
                    or getattr(member, "pid", None)),
            "restore_compiles": rep.get("restore_compiles"),
        }
        with self._lock:
            self._slots[name] = slot
        telemetry.COUNTERS.bump("fleet_joins")
        telemetry.instant("fleet_member_joined", cat="fleet",
                          member=name, universes=sorted(unis),
                          restore_compiles=rep.get("restore_compiles"),
                          host=slot.info.get("host"),
                          pid=slot.info.get("pid"))
        return rep

    def _refuse(self, name: str, reason: str) -> None:
        telemetry.COUNTERS.bump("fleet_refusals")
        telemetry.instant("fleet_member_refused", cat="fleet",
                          member=name, reason=reason)
        raise MemberJoinRefused(
            f"member {name!r} refused at the join gate: {reason}")

    def remove_member(self, name: str) -> None:
        with self._lock:
            self._slots.pop(name, None)

    def members(self) -> List[str]:
        with self._lock:
            return sorted(self._slots)

    def member(self, name: str) -> Any:
        with self._lock:
            return self._slots[name].member

    def slot(self, name: str) -> _MemberSlot:
        with self._lock:
            return self._slots[name]

    # ---- replication / routing --------------------------------------

    def set_replicas(self, universe: str, n: int) -> None:
        """Per-universe replication override — widen a HOT universe's
        replica set beyond ``LFM_FLEET_REPLICAS`` (capped at the member
        count at route time)."""
        with self._lock:
            self._replica_overrides[universe] = max(1, int(n))

    def replicas(self, universe: str) -> int:
        with self._lock:
            return self._replica_overrides.get(universe,
                                               self._default_replicas)

    def route(self, universe: str, month: Optional[int] = None
              ) -> List[str]:
        """The consistent routing decision: member names in attempt
        order. Rendezvous hashing ranks the members that HOLD the
        universe; the top ``replicas(universe)`` are its replica set
        (requests spread across it deterministically by month);
        members outside the replica set trail as last-resort
        candidates — availability beats placement when every replica
        is out. Deterministic in (universe, month, member names) alone:
        registration order and caller identity never change it."""
        with self._lock:
            holders = [n for n, s in self._slots.items()
                       if universe in s.universes]
        if not holders:
            raise KeyError(
                f"universe {universe!r} is not served by any fleet "
                f"member (members: {self.members()})")
        ranked = sorted(holders, key=lambda n: _hrw(universe, n),
                        reverse=True)
        r = max(1, min(self.replicas(universe), len(ranked)))
        replica_set, rest = ranked[:r], ranked[r:]
        if month is not None and len(replica_set) > 1:
            start = _hrw(universe, str(int(month))) % len(replica_set)
            replica_set = replica_set[start:] + replica_set[:start]
        return replica_set + rest

    # ---- the publish fence ------------------------------------------

    def fence(self) -> Dict[str, int]:
        """Universe → committed generation, from the durable store's
        journaled manifest (the single atomic commit point every
        publish goes through — DESIGN.md §20 — and therefore the one
        fence a fleet-wide publish propagates from). Cached on the
        manifest file's (mtime, size) stat — every publish rewrites
        the manifest via atomic rename, so a changed stat IS a changed
        fence, and the observability surfaces that read the fence per
        snapshot never re-parse an unchanged manifest. Without a
        store: the max generation any member serves (a storeless
        fleet has no durable fence, only the observed one)."""
        if self.store is not None:
            try:
                st = os.stat(self.store.manifest_path)
                stamp: Any = (st.st_mtime_ns, st.st_size)
            except OSError:
                stamp = None
            with self._lock:
                if self._fence_cache is not None \
                        and self._fence_cache[0] == stamp:
                    return dict(self._fence_cache[1])
            manifest = self.store.load_manifest(quarantine=False) or {}
            out: Dict[str, int] = {}
            for u, rec in (manifest.get("universes") or {}).items():
                gens = [int(g["generation"])
                        for g in rec.get("generations", [])]
                if gens:
                    out[u] = max(gens)
            with self._lock:
                self._fence_cache = (stamp, dict(out))
            return out
        out = {}
        with self._lock:
            for s in self._slots.values():
                for u, g in s.universes.items():
                    out[u] = max(out.get(u, -1), int(g))
        return out

    def sync_members(self) -> Dict[str, Any]:
        """Propagate the published fence fleet-wide: every member whose
        served generation is behind pulls the newer generations from
        the store (``/sync`` → ``ScoringService.sync_from_store`` —
        verified exactly like a join). Returns per-member outcomes; a
        member whose sync FAILS is taken out of routing (it would
        serve a stale generation)."""
        fence = self.fence()
        out: Dict[str, Any] = {"fence": fence, "members": {}}
        with self._lock:
            slots = list(self._slots.values())
        for slot in slots:
            behind = {u for u, g in fence.items()
                      if slot.universes.get(u, -1) < g}
            if not behind:
                out["members"][slot.name] = {"synced": 0,
                                             "up_to_date": True}
                continue
            try:
                synced = slot.member.sync()
                unis = {u: int(g)
                        for u, g in slot.member.universes().items()}
                with self._lock:
                    slot.universes = unis
                still = {u for u, g in fence.items()
                         if unis.get(u, -1) < g}
                if still:
                    raise MemberCallError(
                        slot.name,
                        f"still behind the fence after sync: "
                        f"{sorted(still)}")
                out["members"][slot.name] = {
                    "synced": len(synced), "up_to_date": True}
                # A successful sync IS an end-to-end verification (the
                # member restored AND probe-verified the pulled
                # generations): a member previously out for a failed
                # sync is readmitted by it.
                with self._lock:
                    readmit = slot.state == "out"
                    if readmit:
                        slot.state = "in"
                        slot.probing = False
                        slot.fail_streak = 0
                if readmit:
                    telemetry.COUNTERS.bump("fleet_readmissions")
                    telemetry.instant("fleet_member_readmitted",
                                      cat="fleet", member=slot.name,
                                      via="sync")
                telemetry.instant("fleet_member_synced", cat="fleet",
                                  member=slot.name,
                                  generations=len(synced))
            except Exception as e:  # noqa: BLE001 — stale member goes out
                with self._lock:
                    slot.state = "out"
                    slot.out_until = time.perf_counter() + 86400.0
                    slot.last_error = f"{type(e).__name__}: {e}"
                telemetry.COUNTERS.bump("fleet_member_out")
                telemetry.instant("fleet_member_out", cat="fleet",
                                  member=slot.name, reason="sync_failed",
                                  error=type(e).__name__)
                out["members"][slot.name] = {
                    "synced": 0, "up_to_date": False,
                    "error": f"{type(e).__name__}: {e}"}
        return out

    # ---- views -------------------------------------------------------

    def universes(self) -> List[str]:
        out = set()
        with self._lock:
            for s in self._slots.values():
                out.update(s.universes)
        return sorted(out)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "members": {
                    n: {"state": s.state, "probing": s.probing,
                        "degraded": s.degraded,
                        "served": s.served, "failures": s.failures,
                        "fail_streak": s.fail_streak,
                        "universes": dict(s.universes),
                        "last_error": s.last_error,
                        **{k: v for k, v in s.info.items()
                           if v is not None}}
                    for n, s in self._slots.items()},
                "replicas_default": self._default_replicas,
                "replica_overrides": dict(self._replica_overrides),
            }

    def close(self) -> None:
        with self._lock:
            slots = list(self._slots.values())
            self._slots.clear()
        for s in slots:
            try:
                s.member.close()
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass


# ---- the router -----------------------------------------------------------


class FleetRouter:
    """The fleet front door (module docstring): health-aware failover
    routing over a :class:`FleetCoordinator`. Duck-typed against the
    single-process ``ScoringService`` surface the HTTP front door and
    the demo driver consume (``score`` / ``snapshot`` / ``stats`` /
    ``health`` / ``metrics_text`` / ``serveable_months``), so
    ``serve.py make_http_server(router, port)`` serves a fleet with
    the SAME error taxonomy single-process clients see — member-level
    failures surface as :class:`MemberUnavailableError` (503 +
    retry-after) when every candidate is exhausted."""

    #: Health-refresh probe timeout (seconds): bounded and SHORT — a
    #: wedged member's /healthz must never hold a scoring request for
    #: the full member-call budget.
    HEALTH_PROBE_TIMEOUT_S = 2.0

    def __init__(self, coordinator: FleetCoordinator,
                 retries: Optional[int] = None,
                 breaker: Optional[int] = None,
                 cooldown_ms: Optional[float] = None,
                 health_ttl_ms: Optional[float] = None,
                 member_timeout_ms: Optional[float] = None):
        self.coord = coordinator
        self.retries = retries_default() if retries is None \
            else max(0, int(retries))
        self.breaker = breaker_default() if breaker is None \
            else max(1, int(breaker))
        self.cooldown_s = (cooldown_ms_default() if cooldown_ms is None
                           else max(0.0, float(cooldown_ms))) / 1e3
        self.health_ttl_s = (health_ttl_ms_default()
                             if health_ttl_ms is None
                             else max(0.0, float(health_ttl_ms))) / 1e3
        self.member_timeout_s = (member_timeout_ms_default()
                                 if member_timeout_ms is None
                                 else float(member_timeout_ms)) / 1e3
        self._stats_lock = threading.Lock()
        self._lat_ms: List[float] = []
        self._requests = 0
        self._rerouted = 0
        self._failovers = 0
        self._unroutable = 0

    # ---- member state machine ---------------------------------------

    def _admit(self, slot: _MemberSlot, now: float) -> str:
        """May this request try the member? ``yes`` | ``probe`` (the
        half-open readmission probe — exactly one in flight) | ``no``.
        Health-surface consumption happens here: a stale health cache
        is refreshed from the member's ``/healthz`` (TTL-bounded), an
        unready member goes OUT with its own advertised retry-after as
        the cooldown, and a burning SLO marks the member degraded
        (soft-deprioritized by the candidate ordering, not refused)."""
        with self.coord._lock:
            if slot.state == "out":
                if now >= slot.out_until and not slot.probing:
                    slot.probing = True
                    probe = True
                else:
                    return "no"
            else:
                probe = False
            fresh = (now - slot.health_ts) <= self.health_ttl_s
            refresh = not probe and not fresh \
                and not slot.health_inflight
            if refresh:
                slot.health_inflight = True  # single-flight
        if probe:
            telemetry.COUNTERS.bump("fleet_probes")
            telemetry.instant("fleet_member_probe", cat="fleet",
                              member=slot.name)
            return "probe"
        if not refresh:
            # Fresh cache — or another thread is already refreshing it
            # (single-flight: act on the last known verdict instead of
            # stacking probes on a possibly-wedged member).
            h = slot.health_cache
            return "yes" if (h is None or h.get("ok", True)) else "no"
        # TTL expired: consult the member's health surface (breaker
        # state, readiness, SLO detail) — the PR 10/11 primitives
        # aggregated fleet-wide. SHORT probe timeout: a wedged member
        # must cost this request a bounded probe, never the full
        # member-call budget.
        try:
            h = slot.member.health(
                timeout_s=min(self.HEALTH_PROBE_TIMEOUT_S,
                              self.member_timeout_s))
        except Exception as e:  # noqa: BLE001 — an unreachable member is out
            self._member_failed(slot, e, probing=False,
                                reason="health_unreachable")
            return "no"
        finally:
            with self.coord._lock:
                slot.health_inflight = False
        with self.coord._lock:
            slot.health_cache = h
            slot.health_ts = now
            slot.degraded = bool((h.get("slo") or {}).get("burning"))
        if not h.get("ok", True):
            self._mark_out(
                slot, reason=f"unready:{h.get('circuit', '?')}",
                cooldown_s=max(self.cooldown_s,
                               float(h.get("retry_after_s") or 0.0)))
            return "no"
        return "yes"

    def _mark_out(self, slot: _MemberSlot, reason: str,
                  cooldown_s: Optional[float] = None) -> None:
        with self.coord._lock:
            was_in = slot.state != "out"
            slot.state = "out"
            slot.probing = False
            slot.out_until = (time.perf_counter()
                              + (self.cooldown_s if cooldown_s is None
                                 else cooldown_s))
        if was_in:
            telemetry.COUNTERS.bump("fleet_member_out")
            telemetry.instant("fleet_member_out", cat="fleet",
                              member=slot.name, reason=reason)

    def _member_failed(self, slot: _MemberSlot, exc: BaseException,
                       probing: bool, reason: str = "call_failed"
                       ) -> None:
        with self.coord._lock:
            slot.fail_streak += 1
            slot.failures += 1
            slot.last_error = f"{type(exc).__name__}: {exc}"
            streak = slot.fail_streak
        if probing:
            # The half-open probe failed: straight back out for a full
            # cooldown (the batcher's breaker discipline, one level
            # up). This IS an out-transition — counter and instant
            # together, so the timeline and the scrape totals agree
            # (_mark_out itself is silent here: state was never "in").
            self._mark_out(slot, reason="probe_failed")
            telemetry.COUNTERS.bump("fleet_member_out")
            telemetry.instant("fleet_member_out", cat="fleet",
                              member=slot.name, reason="probe_failed",
                              error=type(exc).__name__)
        elif streak >= self.breaker:
            self._mark_out(slot, reason=reason,
                           cooldown_s=None)

    def _member_ok(self, slot: _MemberSlot, probing: bool) -> None:
        with self.coord._lock:
            slot.fail_streak = 0
            slot.served += 1
            readmitted = probing or slot.state == "out"
            slot.state = "in"
            slot.probing = False
            if readmitted:
                # The live probe just proved the member healthy: drop
                # any stale ok=False health cache, or a cooldown
                # shorter than the TTL would re-veto the member it
                # just readmitted until the TTL ran out.
                slot.health_cache = None
                slot.health_ts = -1e18
        if readmitted:
            telemetry.COUNTERS.bump("fleet_readmissions")
            telemetry.instant("fleet_member_readmitted", cat="fleet",
                              member=slot.name)

    # ---- the request path -------------------------------------------

    def score(self, universe: str, month: int,
              timeout: Optional[float] = 60.0,
              request_id: Optional[str] = None) -> ScoreResponse:
        """Route one scoring request: walk the coordinator's candidate
        order (replica set spread by month, then the last-resort tail),
        skipping OUT members, admitting at most one half-open probe,
        failing over on member-level errors with the batcher's capped
        jittered backoff, bounded at ``retries`` extra member attempts.
        Client/data errors propagate unretried; exhaustion raises
        :class:`MemberUnavailableError` (503 + retry-after)."""
        rid = clean_request_id(request_id) or new_request_id()
        t0 = time.perf_counter()
        deadline = None if timeout is None else t0 + timeout
        candidates = self.coord.route(universe, month)
        # Soft SLO-aware ordering: burning members drop behind healthy
        # ones WITHIN their tier — the replica set stays ahead of the
        # last-resort tail (a degraded replica still beats a member
        # outside the universe's placement).
        r = max(1, min(self.coord.replicas(universe), len(candidates)))

        def _tier(names):
            out = []
            for name in names:
                try:
                    out.append(self.coord.slot(name))
                except KeyError:
                    continue  # removed concurrently
            return ([s for s in out if not s.degraded]
                    + [s for s in out if s.degraded])

        slots = _tier(candidates[:r]) + _tier(candidates[r:])
        primary = candidates[0]
        attempts_left = self.retries + 1
        tried = 0
        last_exc: Optional[BaseException] = None
        for slot in slots:
            if attempts_left <= 0:
                break
            now = time.perf_counter()
            if deadline is not None and now >= deadline:
                raise DeadlineError(universe, int(month), now - deadline)
            admit = self._admit(slot, now)
            if admit == "no":
                continue
            attempts_left -= 1
            tried += 1
            remaining = (None if deadline is None
                         else max(0.05, deadline - time.perf_counter()))
            per_call = (self.member_timeout_s if remaining is None
                        else min(self.member_timeout_s, remaining))
            try:
                resp = slot.member.score(universe, int(month),
                                         timeout_s=per_call,
                                         request_id=rid)
            except Exception as e:  # noqa: BLE001 — classified below
                if not member_retryable(e):
                    # A client/data error IS an answer: the member is
                    # alive and correct (it would answer identically on
                    # every replica) — it must not feed the member
                    # breaker, and a probe it answers readmits.
                    self._member_ok(slot, probing=(admit == "probe"))
                    raise
                self._member_failed(slot, e, probing=(admit == "probe"))
                last_exc = e
                with self._stats_lock:
                    self._failovers += 1
                telemetry.COUNTERS.bump("fleet_failovers")
                telemetry.instant("fleet_failover", cat="fleet",
                                  member=slot.name, universe=universe,
                                  error=type(e).__name__)
                # The batcher's capped-exponential full-jitter backoff
                # (serve/batcher.py backoff_sleep), reused one level up.
                backoff_sleep(tried)
                continue
            self._member_ok(slot, probing=(admit == "probe"))
            with self.coord._lock:
                slot.universes[universe] = resp.generation
            telemetry.COUNTERS.bump("fleet_requests")
            rerouted = slot.name != primary
            if rerouted:
                telemetry.COUNTERS.bump("fleet_reroutes")
                telemetry.instant("fleet_reroute", cat="fleet",
                                  universe=universe, member=slot.name,
                                  primary=primary)
            with self._stats_lock:
                self._requests += 1
                self._rerouted += int(rerouted)
                self._lat_ms.append(
                    round((time.perf_counter() - t0) * 1e3, 3))
                if len(self._lat_ms) > 65536:
                    del self._lat_ms[:32768]
            return resp
        with self._stats_lock:
            self._unroutable += 1
        telemetry.COUNTERS.bump("fleet_unroutable")
        telemetry.instant("fleet_unroutable", cat="fleet",
                          universe=universe, tried=tried,
                          error=(type(last_exc).__name__
                                 if last_exc else None))
        raise MemberUnavailableError(
            universe, tried=tried,
            retry_after_s=max(0.1, self.cooldown_s))

    # ---- ScoringService-shaped surface ------------------------------

    def universes(self) -> List[str]:
        return self.coord.universes()

    def serveable_months(self, universe: str) -> List[int]:
        for name in self.coord.route(universe):
            try:
                return self.coord.member(name).serveable_months(universe)
            except Exception:  # noqa: BLE001 — next candidate
                continue
        raise KeyError(f"universe {universe!r}: no member answered a "
                       "serveable-months query")

    def health(self) -> Dict[str, Any]:
        return self.snapshot()["health"]

    def stats(self) -> Dict[str, Any]:
        return self.snapshot()["stats"]

    def snapshot(self) -> Dict[str, Any]:
        """The fleet twin of ``ScoringService.snapshot()``: one
        ``{ts, stats, health}`` view aggregating every member's
        snapshot-able state plus the router's own counters. Fleet
        readiness = every universe has at least one IN member holding
        it (one member down is a reroute, not an outage — that is the
        whole point)."""
        from lfm_quant_tpu.serve.stats import latency_summary

        ts = time.time()
        csnap = self.coord.snapshot()
        with self._stats_lock:
            lat = list(self._lat_ms)
            stats: Dict[str, Any] = {
                "completed": self._requests,
                "rerouted": self._rerouted,
                "failovers": self._failovers,
                "unroutable": self._unroutable,
            }
        stats.update(latency_summary(lat))
        stats["ts"] = ts
        stats["members"] = csnap["members"]
        fence = self.coord.fence()
        unis = self.coord.universes()
        stats["universes"] = {u: fence.get(u) for u in unis}
        uncovered = []
        for u in unis:
            covered = any(
                rec["state"] == "in" and u in rec["universes"]
                for rec in csnap["members"].values())
            if not covered:
                uncovered.append(u)
        health: Dict[str, Any] = {
            "ok": not uncovered and bool(csnap["members"]),
            "ts": ts,
            "members": {n: {"state": rec["state"],
                            "degraded": rec["degraded"],
                            "fail_streak": rec["fail_streak"]}
                        for n, rec in csnap["members"].items()},
            "members_in": sum(1 for rec in csnap["members"].values()
                              if rec["state"] == "in"),
            "members_total": len(csnap["members"]),
        }
        if uncovered:
            health["reason"] = (
                f"no routable member for universe(s) {uncovered} — "
                "every replica is out")
            health["retry_after_s"] = round(self.cooldown_s, 3)
        elif not csnap["members"]:
            health["reason"] = "fleet has no members"
        return {"ts": ts, "stats": stats, "health": health}

    def fleet_info(self) -> Dict[str, Any]:
        """The router's ``/fleet`` answer: topology, fence, replicas —
        the operator's view of the coordinator-scoped state."""
        snap = self.coord.snapshot()
        return {"router": True, "members": snap["members"],
                "replicas_default": snap["replicas_default"],
                "replica_overrides": snap["replica_overrides"],
                "fence": self.coord.fence(),
                "universes": self.universes()}

    def metrics_text(self, ts: Optional[float] = None) -> str:
        """The fleet ``/metrics`` aggregation: the router process's own
        registry + counters (the ``lfm_fleet_*`` series), then every
        REMOTE member's scrape with a ``member="name"`` label injected
        into each series (comment lines dropped — the aggregate is the
        parse-twin dialect, one document, no duplicate TYPE lines).
        In-process members share this process's registry and are
        already covered by the first block (their identity rides the
        ``lfm_build_info`` host/pid labels)."""
        from lfm_quant_tpu.utils import metrics

        parts = [metrics.render_prometheus(
            metrics.METRICS, counters=telemetry.COUNTERS.snapshot(),
            ts=ts)]
        for name in self.coord.members():
            slot = self.coord.slot(name)
            if not getattr(slot.member, "remote", False):
                continue
            try:
                text = slot.member.metrics_text()
            except Exception as e:  # noqa: BLE001 — a dead member has no scrape
                parts.append(f"# member {name} scrape unavailable: "
                             f"{type(e).__name__}\n")
                continue
            parts.append(relabel_scrape(text, name))
        return "".join(parts)

    def close(self) -> None:
        self.coord.close()


def relabel_scrape(text: str, member: str) -> str:
    """Inject ``member="name"`` into every series of a member's scrape
    (federation-style source labeling). Comment lines are dropped so
    concatenated member blocks never repeat ``# TYPE`` for one metric
    name; the result is exactly what the ``parse_prometheus`` twins
    read."""
    out: List[str] = []
    tag = f'member="{member}"'
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        brace = line.find("{")
        space = line.find(" ")
        if brace != -1 and (space == -1 or brace < space):
            out.append(f"{line[:brace + 1]}{tag},{line[brace + 1:]}"
                       if line[brace + 1] != "}" else
                       f"{line[:brace + 1]}{tag}{line[brace + 1:]}")
        elif space != -1:
            out.append(f"{line[:space]}{{{tag}}}{line[space:]}")
        else:
            out.append(line)
    return "\n".join(out) + ("\n" if out else "")


# ---- subprocess member entry / spawner -----------------------------------


def _atomic_write_json(path: str, payload: Dict[str, Any]) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(payload, fh)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def member_main(argv: Optional[List[str]] = None) -> int:
    """The subprocess member entry (``python -m
    lfm_quant_tpu.serve.fleet``): bootstrap a ScoringService from the
    durable store ALONE (restore → §20 verification ladder → warm
    ladder from serialized executables), publish a ready file with the
    join report (port, pid, restore verdicts, restore-compile count),
    and serve the standard HTTP front door until killed. A member that
    restores NOTHING exits 2 — it has nothing to be promoted for."""
    import argparse
    import socket

    ap = argparse.ArgumentParser(description=member_main.__doc__)
    ap.add_argument("--store", required=True,
                    help="durable zoo store directory (the deploy "
                         "artifact this member bootstraps from)")
    ap.add_argument("--port", type=int, default=0,
                    help="HTTP port (0 = ephemeral; the ready file "
                         "carries the bound port)")
    ap.add_argument("--ready-file", default=None,
                    help="write the join report JSON here once serving")
    ap.add_argument("--max-rows", type=int, default=None)
    ap.add_argument("--max-wait-ms", type=float, default=None)
    args = ap.parse_args(argv)

    # The front door lives in serve.py at the repo root (ONE handler for
    # every deploy shape — single process, member, router).
    try:
        import serve as serve_root
    except ImportError as e:
        print(f"[fleet-member] cannot import the serve.py front door "
              f"({e}) — run with the repo root on PYTHONPATH/cwd",
              flush=True)
        return 3

    from lfm_quant_tpu.serve.service import ScoringService

    # Adopt the PUBLISHED serving geometry: the exec artifacts cover
    # exactly the publisher's (rows × width) ladder, so a member whose
    # max_rows differed would warm buckets with no serialized
    # executable and pay compiles — "zero restore compiles" must hold
    # from the store alone, no operator coordination.
    max_rows = args.max_rows
    if max_rows is None:
        max_rows = store_max_rows(args.store)

    # READ-ONLY store attach: N members bootstrap from one deploy
    # artifact concurrently — nobody sweeps/journals/quarantines a
    # store they do not own (serve/persist.py readonly contract).
    svc = ScoringService(persist_dir=args.store,
                         persist_readonly=True,
                         max_rows=max_rows,
                         max_wait_ms=args.max_wait_ms)
    restored = svc.restore()
    if not restored:
        print("[fleet-member] restored NOTHING from the store — "
              "refusing to serve (nothing verified)", flush=True)
        svc.close()
        return 2
    httpd = serve_root.make_http_server(svc, args.port)
    port = httpd.server_address[1]
    report = {
        "member": f"{socket.gethostname()}:{port}",
        "port": port,
        "pid": os.getpid(),
        "build": telemetry.build_info(),
        "universes": dict(svc.zoo.snapshot()["universes"]),
        "restore": restored,
        "restore_compiles": svc.last_restore_compiles,
        "restore_panel_h2d": svc.last_restore_panel_h2d,
    }
    if args.ready_file:
        _atomic_write_json(args.ready_file, report)
    print(f"[fleet-member] ready on 127.0.0.1:{port} "
          f"({len(restored)} universe(s), "
          f"{report['restore_compiles']} restore compiles)", flush=True)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.server_close()
        svc.close()
    return 0


def store_max_rows(store_dir: str) -> Optional[int]:
    """The serving row cap the store's committed generations were
    published (and their executables exported) under — the geometry a
    bootstrapping member must adopt for a compile-free warm ladder.
    None when the store has no committed manifest."""
    from lfm_quant_tpu.serve.persist import ZooStore

    manifest = ZooStore(store_dir, readonly=True).load_manifest(
        quarantine=False) or {}
    vals = [int(g.get("max_rows", 0))
            for u in (manifest.get("universes") or {}).values()
            for g in u.get("generations", [])]
    vals = [v for v in vals if v > 0]
    return max(vals) if vals else None


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def spawn_member(store_dir: str, *, ready_file: str,
                 port: int = 0, env: Optional[Dict[str, str]] = None,
                 max_rows: Optional[int] = None,
                 max_wait_ms: Optional[float] = None):
    """Launch one subprocess member (``member_main``) bootstrapping
    from ``store_dir``. Returns the ``Popen`` immediately; pair with
    :func:`wait_member_ready` (spawning concurrently and waiting once
    amortizes the interpreter+restore cost across the fleet). The
    member's stdout+stderr stream to ``<ready_file>.log`` — a FILE,
    never a pipe nobody drains: a long-serving member that warns past
    the OS pipe buffer would block mid-write and wedge."""
    import subprocess
    import sys

    root = repo_root()
    child_env = dict(os.environ)
    if env:
        child_env.update(env)
    child_env["PYTHONPATH"] = (
        root + os.pathsep + child_env["PYTHONPATH"]
        if child_env.get("PYTHONPATH") else root)
    cmd = [sys.executable, "-m", "lfm_quant_tpu.serve.fleet",
           "--store", store_dir, "--port", str(port),
           "--ready-file", ready_file]
    if max_rows is not None:
        cmd += ["--max-rows", str(max_rows)]
    if max_wait_ms is not None:
        cmd += ["--max-wait-ms", str(max_wait_ms)]
    log_path = ready_file + ".log"
    log_fh = open(log_path, "ab", buffering=0)
    try:
        proc = subprocess.Popen(cmd, cwd=root, env=child_env,
                                stdout=log_fh, stderr=log_fh)
    finally:
        log_fh.close()  # the child holds its own descriptor
    proc.lfm_log_path = log_path
    return proc


def _log_tail(proc, ready_file: str, n: int = 800) -> str:
    path = getattr(proc, "lfm_log_path", ready_file + ".log")
    try:
        with open(path, "rb") as fh:
            fh.seek(0, os.SEEK_END)
            size = fh.tell()
            fh.seek(max(0, size - 4096))
            return fh.read().decode(errors="replace")[-n:]
    except OSError:
        return "(no member log)"


def wait_member_ready(proc, ready_file: str, timeout_s: float = 240.0
                      ) -> Dict[str, Any]:
    """Block until the member's ready file appears (join report dict)
    or the process dies / the timeout expires (RuntimeError with the
    member-log tail — a member that cannot restore must fail the spawn
    loudly, not hang the fleet)."""
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < timeout_s:
        if os.path.exists(ready_file):
            try:
                with open(ready_file) as fh:
                    return json.load(fh)
            except (OSError, json.JSONDecodeError):
                pass  # mid-rename; retry
        if proc.poll() is not None:
            raise RuntimeError(
                f"fleet member died during bootstrap (rc="
                f"{proc.returncode}): {_log_tail(proc, ready_file)}")
        time.sleep(0.05)
    proc.kill()
    raise RuntimeError(
        f"fleet member not ready within {timeout_s:.0f}s "
        f"(ready file {ready_file} never appeared): "
        f"{_log_tail(proc, ready_file)}")


if __name__ == "__main__":
    import sys

    sys.exit(member_main())
