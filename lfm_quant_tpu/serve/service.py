"""The always-on scoring service: zoo + batcher + refresh, one object.

``ScoringService`` is the persistent serving front-end over the batch
stack: register a fitted trainer per universe, ``warmup()`` pre-traces
every request-shape bucket the universe can produce, then ``score()``/
``submit()`` serve arbitrary month queries with zero jit traces and
zero panel H2D in steady state (the ``reuse``-counter contract extended
from walk-forward folds to serving traffic). Monthly data arrival is an
**incremental refresh**: rebuild the trainer on the advanced rolling
split (a program-cache HIT — same-shape folds share executables),
warm-start-fit from the served generation's params (the PR 1 warm-start
+ PR 3 pipelined fit; a one-fold "stack" IS the sequential fit — the
PR 5 stacked driver needs ≥ 2 folds and remains the batch-sweep tool),
and atomically publish the new generation — requests in flight finish
on the old generation, new ones route to the new, nothing is dropped
or torn, and nothing recompiles.

Donation safety (load-bearing): the refresh fit's multi-step programs
DONATE their TrainState, so the warm start must feed a COPY of the
served params — handing the live generation's buffers to a donating
dispatch would delete them under in-flight scoring traffic.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional

import numpy as np

from lfm_quant_tpu.serve import buckets
from lfm_quant_tpu.serve.batcher import MicroBatcher, ScoreResponse
from lfm_quant_tpu.serve.monitor import ServiceMonitor
from lfm_quant_tpu.serve.zoo import ModelZoo, ZooEntry
from lfm_quant_tpu.utils import metrics, telemetry


class ScoringService:
    """One process-wide serving object (the serve.py entry point owns
    one; tests construct their own)."""

    def __init__(self, zoo_capacity: Optional[int] = None,
                 max_rows: Optional[int] = None,
                 max_wait_ms: Optional[float] = None,
                 queue_max: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 retries: Optional[int] = None,
                 breaker_threshold: Optional[int] = None,
                 breaker_cooldown_ms: Optional[float] = None,
                 persist_dir: Optional[str] = None,
                 keep_generations: Optional[int] = None,
                 persist_readonly: bool = False,
                 incident_dir: Optional[str] = None,
                 incident_cooldown_s: Optional[float] = None):
        self.zoo = ModelZoo(zoo_capacity or buckets.zoo_capacity_default())
        self.max_rows = max_rows or buckets.max_rows_default()
        self.batcher = MicroBatcher(
            self.zoo, self.max_rows,
            buckets.max_wait_ms_default() if max_wait_ms is None
            else max_wait_ms,
            queue_max=queue_max, deadline_ms=deadline_ms, retries=retries,
            breaker_threshold=breaker_threshold,
            breaker_cooldown_ms=breaker_cooldown_ms)
        self.monitor = ServiceMonitor(self)
        # Automatic incident capture (serve/incident.py, DESIGN.md
        # §21): the existing degradation signals — breaker open, SLO
        # burn, drift veto, snapshot quarantine, shed spike — each
        # write one rate-limited evidence bundle. The batcher and the
        # durable store get back-references so their trigger sites are
        # one attribute read.
        from lfm_quant_tpu.serve.incident import IncidentManager

        self.incidents = IncidentManager(
            self, incident_dir=incident_dir,
            cooldown_s=incident_cooldown_s)
        self.batcher.incidents = self.incidents
        self._refresh_lock = threading.Lock()
        # Durable serving state (serve/persist.py, DESIGN.md §20):
        # explicit ctor dir wins, else the LFM_ZOO_PERSIST knob; unset
        # means NO store object exists and every publish/serve path is
        # byte-for-byte the pre-persistence one (the exact-no-op
        # contract, pinned in the durable lane).
        from lfm_quant_tpu.serve import persist

        pd = persist_dir if persist_dir is not None \
            else persist.persist_dir_default()
        # persist_readonly: the fleet-member bootstrap (DESIGN.md §22)
        # — many members attach ONE deploy-artifact store concurrently,
        # so the attach must not sweep/journal/quarantine (reads only).
        self.store = (persist.ZooStore(pd, keep=keep_generations,
                                       readonly=persist_readonly)
                      if pd else None)
        if self.store is not None:
            self.store.incidents = self.incidents
        # Store-bootstrap accounting (serve/fleet.py, DESIGN.md §22):
        # the last restore()/sync_from_store() outcome plus its counted
        # jit-trace/panel-H2D cost — the join report a fleet
        # coordinator's promotion gate verifies ("joined at zero
        # restore compiles" is a measured number, not a claim).
        self.last_restore: Optional[List[Dict[str, Any]]] = None
        self.last_restore_compiles: Optional[int] = None
        self.last_restore_panel_h2d: Optional[int] = None

    # ---- registration / warmup --------------------------------------

    def register(self, universe: str, trainer: Any, *,
                 warm: bool = True) -> ZooEntry:
        """Make ``trainer`` (fitted; its splits' panel is the universe)
        servable as generation 0 — or the next generation if the
        universe is already registered. ``warm=True`` pre-traces every
        (rows, width) bucket so the first real request already runs
        compile-free."""
        donor = None
        try:
            donor = self.zoo.current(universe)
            gen = donor.generation + 1
        except KeyError:
            gen = 0
        # The knob-gated drift veto (LFM_DRIFT_GATE, DESIGN.md §19):
        # it only reads the CURRENT generation's sketches, so check
        # before paying the warmup compile ladder and the reference
        # batch-scoring for an entry a veto would discard — and before
        # the swap, so a vetoed publish leaves the served generation
        # untouched and still serving.
        self.monitor.check_publish_gate(universe)
        entry = ZooEntry(universe, gen, trainer)
        if donor is not None:
            entry.adopt_programs(donor)
        if warm:
            self.warmup_entry(entry)
            self._stamp_reference(entry)
        # Durable record BEFORE the in-memory swap (DESIGN.md §20): a
        # crash after the manifest commit restores THIS generation, a
        # crash before it restores the predecessor — the zoo is pure
        # derived state either way, never the only copy.
        if self.store is not None:
            self.store.record_publish(entry, max_rows=self.max_rows)
        self.zoo.publish(entry)
        return entry

    def warmup_entry(self, entry: ZooEntry) -> int:
        """Dispatch one zero-weight batch per (rows, width) bucket the
        entry can produce, compiling each bucket program exactly once
        (or zero times when a prior generation/universe with the same
        geometry already did). Returns the bucket count."""
        widths = entry.widths()
        months = entry._sampler.months_with_anchors()
        if not widths or months.size == 0:
            raise ValueError(
                f"universe {entry.universe!r}: no serveable months (no "
                "month has an eligible cross-section under this panel/"
                "window) — nothing to warm, nothing to serve")
        ladder = buckets.rows_ladder(self.max_rows)
        t0 = int(months[0])
        with telemetry.span("serve_warmup", cat="serve",
                            universe=entry.universe,
                            buckets=len(widths) * len(ladder)):
            with entry.lease_panel() as dev:
                for width in widths:
                    for rows in ladder:
                        fi = np.zeros((rows, width), np.int32)
                        ti = np.full((rows,), t0, np.int32)
                        w = np.zeros((rows, width), np.float32)
                        np.asarray(entry.programs_for((rows, width))(
                            entry.params, dev, fi, ti, w))
        return len(widths) * len(ladder)

    #: Cap on the months batch-scored for a publish-time reference
    #: sketch (evenly spread across the serveable range): enough mass
    #: for a stable 16-bin distribution at bounded publish cost.
    REFERENCE_MONTH_CAP = 32

    def _stamp_reference(self, entry: ZooEntry) -> None:
        """Score-drift reference at publish (DESIGN.md §19): batch-score
        an even spread of the entry's serveable months through its
        WARMED bucket programs — every (rows, width) dispatched here is
        a warmup-ladder member, so this adds ZERO jit traces and ZERO
        panel H2D — and stamp the resulting distribution sketch
        (moments + fixed-edge histogram) into the entry. Served scores
        then stream into the live twin (batcher) and the monitor's PSI
        gauge compares the two. Exact no-op when ``LFM_METRICS=0`` or
        drift evaluation is disabled (``LFM_DRIFT_MAX <= 0``)."""
        if not (metrics.enabled() and metrics.drift_max_default() > 0):
            return
        cols = sorted(entry._month_index.values())
        if not cols:
            return
        cap = self.REFERENCE_MONTH_CAP
        if len(cols) > cap:
            step = (len(cols) - 1) / (cap - 1)
            cols = sorted({cols[int(round(i * step))] for i in range(cap)})
        by_width: Dict[int, List[Any]] = {}
        for t in cols:
            pool = entry.pool(t)
            if pool.size == 0:
                continue
            by_width.setdefault(
                buckets.bucket_width(pool.size), []).append((t, pool))
        chunk_scores: List[np.ndarray] = []
        with telemetry.span("drift_reference", cat="serve",
                            universe=entry.universe,
                            generation=entry.generation,
                            months=sum(len(v) for v in by_width.values())):
            with entry.lease_panel() as dev:
                for width, items in sorted(by_width.items()):
                    for k in range(0, len(items), self.max_rows):
                        chunk = items[k:k + self.max_rows]
                        rows = buckets.bucket_rows(len(chunk),
                                                   self.max_rows)
                        fi = np.zeros((rows, width), np.int32)
                        ti = np.zeros((rows,), np.int32)
                        w = np.zeros((rows, width), np.float32)
                        for i, (t, pool) in enumerate(chunk):
                            fi[i, :pool.size] = pool
                            fi[i, pool.size:] = pool[-1]
                            ti[i] = t
                            w[i, :pool.size] = 1.0
                        for i in range(len(chunk), rows):
                            fi[i], ti[i] = fi[0], ti[0]
                        out = np.asarray(
                            entry.programs_for((rows, width))(
                                entry.params, dev, fi, ti, w))
                        for i, (_, pool) in enumerate(chunk):
                            chunk_scores.append(out[i, :pool.size])
        if not chunk_scores:
            return
        try:
            entry.stamp_reference(metrics.ScoreSketch.reference(
                np.concatenate(chunk_scores)))
        except ValueError:
            import warnings

            warnings.warn(
                f"universe {entry.universe!r} gen {entry.generation}: "
                "no finite batch scores — drift reference not stamped "
                "(the drift gauge stays inactive for this generation)",
                RuntimeWarning, stacklevel=2)

    # ---- query path --------------------------------------------------

    def submit(self, universe: str, month: int,
               deadline_ms: Optional[float] = None,
               request_id: Optional[str] = None) -> Future:
        """Async query: Future of a :class:`ScoreResponse`.
        ``deadline_ms`` bounds how long the request may wait — past it
        the batcher drops it BEFORE dispatch (DeadlineError).
        ``request_id`` propagates an inbound trace id (DESIGN.md §21);
        None mints one — the response echoes it either way."""
        return self.batcher.submit(universe, month,
                                   deadline_ms=deadline_ms,
                                   request_id=request_id)

    def score(self, universe: str, month: int,
              timeout: Optional[float] = 60.0,
              request_id: Optional[str] = None) -> ScoreResponse:
        """Sync query: the month's scored cross-section. The client
        ``timeout`` PROPAGATES into the batcher as the request deadline,
        so a request this caller has already given up on is dropped
        instead of costing a device dispatch (DESIGN.md §18).
        ``request_id`` propagates an inbound trace id (DESIGN.md §21)."""
        return self.batcher.submit(
            universe, month,
            deadline_ms=None if timeout is None else timeout * 1e3,
            request_id=request_id,
        ).result(timeout=timeout)

    def serveable_months(self, universe: str) -> List[int]:
        return self.zoo.current(universe).serveable_months()

    # ---- incremental refresh -----------------------------------------

    def refresh(self, universe: str, splits: Any,
                epochs: Optional[int] = None) -> ZooEntry:
        """Monthly data arrival: warm single-fold retrain + atomic swap.

        Builds a FRESH trainer on ``splits`` (the advanced rolling
        boundaries — with an unchanged shape this is a program-cache
        hit: the served generation's executables, zero traces), fits it
        warm-started from a COPY of the served params (copy because the
        fit donates its state — see module docstring), warms the new
        entry's buckets (no-ops on the shared warm programs) and
        publishes it. Serving continues uninterrupted throughout: the
        old generation handles traffic until the publish, then drains.
        Returns the new entry.
        """
        import dataclasses

        import jax
        import jax.numpy as jnp

        with self._refresh_lock:
            cur = self.zoo.current(universe)
            # Drift veto BEFORE the retrain (it only reads the served
            # generation's sketches): a vetoed refresh must not pay a
            # whole warm fit plus the warmup ladder for an entry it
            # then discards.
            self.monitor.check_publish_gate(universe)
            cfg = cur.cfg
            if epochs is not None:
                cfg = dataclasses.replace(
                    cfg, optim=dataclasses.replace(cfg.optim, epochs=epochs))
            with telemetry.span("serve_refresh", cat="serve",
                                universe=universe,
                                generation=cur.generation + 1) as sp:
                from lfm_quant_tpu.train import reuse

                # Re-seed the served generation's trainer bundle before
                # constructing the new trainer: if a crowded LRU evicted
                # the key, re-admission through the existing bundle
                # (builder returns it — no rebuild) keeps the refresh
                # fit on the warm executables instead of re-tracing.
                reuse.get_programs(cur.trainer.program_key,
                                   lambda: cur.trainer.programs)
                trainer = type(cur.trainer)(cfg, splits, run_dir=None)
                init = jax.tree.map(jnp.copy, cur.params)
                fit = trainer.fit(init_params=init)
                sp.set(epochs_run=fit["epochs_run"],
                       best_val_ic=fit["best_val_ic"])
                entry = ZooEntry(universe, cur.generation + 1, trainer)
                entry.adopt_programs(cur)
                self.warmup_entry(entry)
                self._stamp_reference(entry)
                if self.store is not None:
                    self.store.record_publish(entry,
                                              max_rows=self.max_rows)
                self.zoo.publish(entry)
            return entry

    # ---- durable restore / in-process recovery -----------------------

    def restore(self, warm: bool = True) -> List[Dict[str, Any]]:
        """Zero-cold-start restart (serve/persist.py, DESIGN.md §20):
        re-register every committed universe from the durable store —
        params verified by checksum, one stamped month verified
        BIT-EQUAL to the publish-time parity probe, drift references
        re-stamped from the serialized sketches, and the warm ladder
        rebuilt through the serialized lowered executables (zero
        compiles when they load; loud counted recompile fallback).
        Returns one info dict per restored universe; a snapshot that
        fails verification is quarantined and the universe degrades to
        fresh retrain rather than serving wrong numbers."""
        if self.store is None:
            raise RuntimeError(
                "restore() needs a durable store — pass persist_dir= or "
                "set LFM_ZOO_PERSIST to the store directory")
        from lfm_quant_tpu.utils.profiling import REUSE_COUNTERS

        snap = REUSE_COUNTERS.snapshot()
        out = self.store.restore_into(self, warm=warm)
        d = REUSE_COUNTERS.delta(snap)
        self.last_restore = out
        self.last_restore_compiles = int(d.get("jit_traces", 0))
        self.last_restore_panel_h2d = int(d.get("panel_transfers", 0))
        return out

    def sync_from_store(self) -> List[Dict[str, Any]]:
        """Fleet publish propagation (serve/fleet.py, DESIGN.md §22):
        pull every generation the durable store has committed BEYOND
        what this service currently serves — the journaled manifest
        generation is the fence — through the same verification ladder
        a restore uses (checksum + parity probe + warm ladder from
        serialized executables). Universes already at the fence are
        untouched; returns the newly adopted generations."""
        if self.store is None:
            raise RuntimeError(
                "sync_from_store() needs a durable store — pass "
                "persist_dir= or set LFM_ZOO_PERSIST")
        from lfm_quant_tpu.utils.profiling import REUSE_COUNTERS

        snap = REUSE_COUNTERS.snapshot()
        out = self.store.restore_into(self, warm=True, only_newer=True)
        d = REUSE_COUNTERS.delta(snap)
        # Fold the sync into the join-report accounting (same fields
        # restore() stamps): the generations this member serves and
        # what they measurably cost must reflect the LATEST pull, or a
        # behind-fence member that caught up via sync would keep
        # advertising its stale pre-sync verdicts.
        self.last_restore = (self.last_restore or []) + out
        self.last_restore_compiles = (
            (self.last_restore_compiles or 0) + int(d.get("jit_traces",
                                                          0)))
        self.last_restore_panel_h2d = (
            (self.last_restore_panel_h2d or 0)
            + int(d.get("panel_transfers", 0)))
        return out

    def restart_batcher(self) -> Dict[str, Any]:
        """In-process recovery for the ``BatcherDeadError`` path
        (DESIGN.md §20): replace the dead batcher thread with a fresh
        one, SAME knobs, zoo and generations untouched, rolling stats
        carried over. Bounded: the old batcher's close() joins its
        thread for at most 10 s. Pending submits were already failed
        loudly — exactly once — by the death guard (``_die``) or are
        failed by close() here when the operator restarts a LIVE
        batcher; nothing is failed twice (done futures are skipped) and
        nothing hangs. The only remedy before this was a full process
        restart (serve/batcher.py)."""
        old = self.batcher
        was_dead = old._dead is not None
        old.close()
        nb = MicroBatcher(
            self.zoo, self.max_rows, old.max_wait_s * 1e3,
            queue_max=old.queue_max,
            deadline_ms=old.default_deadline_s * 1e3,
            retries=old.retries,
            breaker_threshold=old._breaker_threshold,
            breaker_cooldown_ms=old._breaker_cooldown_s * 1e3)
        nb.carry_stats(old)
        nb.incidents = self.incidents
        self.batcher = nb
        telemetry.COUNTERS.set("serve_batcher_dead", 0)
        telemetry.COUNTERS.bump("serve_batcher_restarts")
        telemetry.instant("batcher_restarted", cat="serve",
                          was_dead=was_dead)
        return {"ok": True, "was_dead": was_dead,
                "restarts": telemetry.COUNTERS.get(
                    "serve_batcher_restarts")}

    # ---- observability / lifecycle -----------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """ONE consistent observability snapshot per caller: ``{ts,
        stats, health}``, each sub-view built from a single locked read
        of the structure that owns it (``batcher.stats()`` under one
        stats lock, ``zoo.snapshot()`` under one zoo lock). The
        pre-metrics ``/stats`` and ``/healthz`` handlers re-derived
        state per field (``zoo.universes()`` then ``generation(u)`` per
        universe — each its own lock acquisition), so a handler racing
        a refresh/breaker transition could report a TORN view; both
        endpoints now share one call to this, and both carry the same
        scrape timestamp.

        ``p50_ms``/``p99_ms`` in ``stats`` come from the same
        per-request ``latency_ms`` values the ``serve_request`` spans
        carry, so ``scripts/trace_report.py`` reproduces them exactly
        from a run dir (the bench cross-check contract)."""
        ts = time.time()
        stats = self.batcher.stats()
        zsnap = self.zoo.snapshot()
        stats["ts"] = ts
        # Member identity (serve/fleet.py, DESIGN.md §22): WHICH
        # host/pid produced this snapshot — the fleet aggregation's
        # attribution key, from the cached telemetry.build_info()
        # probe (the same identity the lfm_build_info gauge labels and
        # every incident bundle carry).
        info = telemetry.build_info()
        stats["member"] = {"host": info.get("host"),
                           "pid": info.get("pid")}
        stats["universes"] = zsnap["universes"]
        stats["zoo_size"] = zsnap["size"]
        stats["zoo_capacity"] = zsnap["capacity"]
        stats["incidents"] = {
            "captured": self.incidents.captured,
            "suppressed": self.incidents.suppressed,
        }
        health = self.batcher.health()
        health["ts"] = ts
        health["zoo_size"] = zsnap["size"]
        if metrics.enabled():
            # SLO / drift DETAIL (DESIGN.md §19): a burning SLO or a
            # drifted universe is an operator alert surfaced here;
            # readiness (the 503 path) stays owned by the batcher/
            # breaker machinery above.
            from lfm_quant_tpu.serve.monitor import slo_status

            slo = slo_status()
            drift = self.monitor.drift_status()
            health["slo"] = {"burning": slo["burning"],
                             "max_burn": slo["max_burn"],
                             "objectives": slo["objectives"]}
            health["drift"] = {"breached": drift["breached"],
                               "threshold": drift["threshold"],
                               "universes": drift["universes"]}
        return {"ts": ts, "stats": stats, "health": health}

    def stats(self) -> Dict[str, Any]:
        """The serving rollup (one consistent :meth:`snapshot` view)."""
        return self.snapshot()["stats"]

    def health(self) -> Dict[str, Any]:
        """REAL readiness (the /healthz contract, DESIGN.md §18): not
        ready — with the reason — when the batcher thread is dead or
        the circuit breaker is open; ``retry_after_s`` carries the
        remaining breaker cooldown. Carries the SLO-burn and
        score-drift DETAIL (DESIGN.md §19) without flipping ``ok`` —
        those are operator alerts, not routing decisions."""
        return self.snapshot()["health"]

    def metrics_snapshot(self) -> Dict[str, Any]:
        """The live metrics plane as JSON: gauges collected, every
        instrument summarized, SLO burn and drift status attached
        (``serve/monitor.py``). The Prometheus text twin is
        :meth:`metrics_text`."""
        return self.monitor.snapshot()

    def metrics_text(self, ts: Optional[float] = None) -> str:
        """The ``GET /metrics`` exposition document (Prometheus text
        format 0.0.4): the instrument registry plus the absorbed
        ``telemetry.COUNTERS``. Pure host-side string building."""
        return self.monitor.metrics_text(ts=ts)

    def close(self) -> None:
        self.batcher.close()
        # A capture racing shutdown finishes its bundle (bounded): a
        # breaker-open incident seconds before close is exactly the
        # evidence worth keeping.
        self.incidents.wait(timeout=5.0)
