"""The always-on scoring service: zoo + batcher + refresh, one object.

``ScoringService`` is the persistent serving front-end over the batch
stack: register a fitted trainer per universe, ``warmup()`` pre-traces
every request-shape bucket the universe can produce, then ``score()``/
``submit()`` serve arbitrary month queries with zero jit traces and
zero panel H2D in steady state (the ``reuse``-counter contract extended
from walk-forward folds to serving traffic). Monthly data arrival is an
**incremental refresh**: rebuild the trainer on the advanced rolling
split (a program-cache HIT — same-shape folds share executables),
warm-start-fit from the served generation's params (the PR 1 warm-start
+ PR 3 pipelined fit; a one-fold "stack" IS the sequential fit — the
PR 5 stacked driver needs ≥ 2 folds and remains the batch-sweep tool),
and atomically publish the new generation — requests in flight finish
on the old generation, new ones route to the new, nothing is dropped
or torn, and nothing recompiles.

Donation safety (load-bearing): the refresh fit's multi-step programs
DONATE their TrainState, so the warm start must feed a COPY of the
served params — handing the live generation's buffers to a donating
dispatch would delete them under in-flight scoring traffic.
"""

from __future__ import annotations

import threading
from concurrent.futures import Future
from typing import Any, Dict, List, Optional

import numpy as np

from lfm_quant_tpu.serve import buckets
from lfm_quant_tpu.serve.batcher import MicroBatcher, ScoreResponse
from lfm_quant_tpu.serve.zoo import ModelZoo, ZooEntry
from lfm_quant_tpu.utils import telemetry


class ScoringService:
    """One process-wide serving object (the serve.py entry point owns
    one; tests construct their own)."""

    def __init__(self, zoo_capacity: Optional[int] = None,
                 max_rows: Optional[int] = None,
                 max_wait_ms: Optional[float] = None,
                 queue_max: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 retries: Optional[int] = None,
                 breaker_threshold: Optional[int] = None,
                 breaker_cooldown_ms: Optional[float] = None):
        self.zoo = ModelZoo(zoo_capacity or buckets.zoo_capacity_default())
        self.max_rows = max_rows or buckets.max_rows_default()
        self.batcher = MicroBatcher(
            self.zoo, self.max_rows,
            buckets.max_wait_ms_default() if max_wait_ms is None
            else max_wait_ms,
            queue_max=queue_max, deadline_ms=deadline_ms, retries=retries,
            breaker_threshold=breaker_threshold,
            breaker_cooldown_ms=breaker_cooldown_ms)
        self._refresh_lock = threading.Lock()

    # ---- registration / warmup --------------------------------------

    def register(self, universe: str, trainer: Any, *,
                 warm: bool = True) -> ZooEntry:
        """Make ``trainer`` (fitted; its splits' panel is the universe)
        servable as generation 0 — or the next generation if the
        universe is already registered. ``warm=True`` pre-traces every
        (rows, width) bucket so the first real request already runs
        compile-free."""
        donor = None
        try:
            donor = self.zoo.current(universe)
            gen = donor.generation + 1
        except KeyError:
            gen = 0
        entry = ZooEntry(universe, gen, trainer)
        if donor is not None:
            entry.adopt_programs(donor)
        if warm:
            self.warmup_entry(entry)
        self.zoo.publish(entry)
        return entry

    def warmup_entry(self, entry: ZooEntry) -> int:
        """Dispatch one zero-weight batch per (rows, width) bucket the
        entry can produce, compiling each bucket program exactly once
        (or zero times when a prior generation/universe with the same
        geometry already did). Returns the bucket count."""
        widths = entry.widths()
        months = entry._sampler.months_with_anchors()
        if not widths or months.size == 0:
            raise ValueError(
                f"universe {entry.universe!r}: no serveable months (no "
                "month has an eligible cross-section under this panel/"
                "window) — nothing to warm, nothing to serve")
        ladder = buckets.rows_ladder(self.max_rows)
        t0 = int(months[0])
        with telemetry.span("serve_warmup", cat="serve",
                            universe=entry.universe,
                            buckets=len(widths) * len(ladder)):
            with entry.lease_panel() as dev:
                for width in widths:
                    for rows in ladder:
                        fi = np.zeros((rows, width), np.int32)
                        ti = np.full((rows,), t0, np.int32)
                        w = np.zeros((rows, width), np.float32)
                        np.asarray(entry.programs_for((rows, width))(
                            entry.params, dev, fi, ti, w))
        return len(widths) * len(ladder)

    # ---- query path --------------------------------------------------

    def submit(self, universe: str, month: int,
               deadline_ms: Optional[float] = None) -> Future:
        """Async query: Future of a :class:`ScoreResponse`.
        ``deadline_ms`` bounds how long the request may wait — past it
        the batcher drops it BEFORE dispatch (DeadlineError)."""
        return self.batcher.submit(universe, month, deadline_ms=deadline_ms)

    def score(self, universe: str, month: int,
              timeout: Optional[float] = 60.0) -> ScoreResponse:
        """Sync query: the month's scored cross-section. The client
        ``timeout`` PROPAGATES into the batcher as the request deadline,
        so a request this caller has already given up on is dropped
        instead of costing a device dispatch (DESIGN.md §18)."""
        return self.batcher.submit(
            universe, month,
            deadline_ms=None if timeout is None else timeout * 1e3,
        ).result(timeout=timeout)

    def serveable_months(self, universe: str) -> List[int]:
        return self.zoo.current(universe).serveable_months()

    # ---- incremental refresh -----------------------------------------

    def refresh(self, universe: str, splits: Any,
                epochs: Optional[int] = None) -> ZooEntry:
        """Monthly data arrival: warm single-fold retrain + atomic swap.

        Builds a FRESH trainer on ``splits`` (the advanced rolling
        boundaries — with an unchanged shape this is a program-cache
        hit: the served generation's executables, zero traces), fits it
        warm-started from a COPY of the served params (copy because the
        fit donates its state — see module docstring), warms the new
        entry's buckets (no-ops on the shared warm programs) and
        publishes it. Serving continues uninterrupted throughout: the
        old generation handles traffic until the publish, then drains.
        Returns the new entry.
        """
        import dataclasses

        import jax
        import jax.numpy as jnp

        with self._refresh_lock:
            cur = self.zoo.current(universe)
            cfg = cur.cfg
            if epochs is not None:
                cfg = dataclasses.replace(
                    cfg, optim=dataclasses.replace(cfg.optim, epochs=epochs))
            with telemetry.span("serve_refresh", cat="serve",
                                universe=universe,
                                generation=cur.generation + 1) as sp:
                from lfm_quant_tpu.train import reuse

                # Re-seed the served generation's trainer bundle before
                # constructing the new trainer: if a crowded LRU evicted
                # the key, re-admission through the existing bundle
                # (builder returns it — no rebuild) keeps the refresh
                # fit on the warm executables instead of re-tracing.
                reuse.get_programs(cur.trainer.program_key,
                                   lambda: cur.trainer.programs)
                trainer = type(cur.trainer)(cfg, splits, run_dir=None)
                init = jax.tree.map(jnp.copy, cur.params)
                fit = trainer.fit(init_params=init)
                sp.set(epochs_run=fit["epochs_run"],
                       best_val_ic=fit["best_val_ic"])
                entry = ZooEntry(universe, cur.generation + 1, trainer)
                entry.adopt_programs(cur)
                self.warmup_entry(entry)
                self.zoo.publish(entry)
            return entry

    # ---- observability / lifecycle -----------------------------------

    def stats(self) -> Dict[str, Any]:
        """The serving rollup: batcher latency/occupancy plus zoo state.
        ``p50_ms``/``p99_ms`` come from the same per-request
        ``latency_ms`` values the ``serve_request`` spans carry, so
        ``scripts/trace_report.py`` reproduces them exactly from a run
        dir (the bench cross-check contract)."""
        out = self.batcher.stats()
        out["universes"] = {
            u: self.zoo.generation(u) for u in self.zoo.universes()}
        out["zoo_size"] = len(self.zoo)
        out["zoo_capacity"] = self.zoo.capacity
        return out

    def health(self) -> Dict[str, Any]:
        """REAL readiness (the /healthz contract, DESIGN.md §18): not
        ready — with the reason — when the batcher thread is dead or
        the circuit breaker is open; ``retry_after_s`` carries the
        remaining breaker cooldown. The pre-chaos endpoint returned a
        constant ``{"ok": true}`` even with the batcher thread dead."""
        h = self.batcher.health()
        h["zoo_size"] = len(self.zoo)
        return h

    def close(self) -> None:
        self.batcher.close()
