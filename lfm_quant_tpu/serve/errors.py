"""Serving failure taxonomy: one vocabulary for shed / deadline /
breaker / dead-batcher outcomes, shared by the micro-batcher (which
raises them), the HTTP front door (which maps them to status codes —
the failure-semantics table in README.md) and the chaos lane (which
asserts on them).

The transient-vs-permanent split is the retry layer's routing decision:
:func:`is_transient` answers "is a retry of the same dispatch worth
anything?" — injected :class:`~lfm_quant_tpu.utils.faults.TransientFault`
and the runtime's retryable status strings say yes; everything else
(routing KeyErrors, shape bugs, injected permanent faults) fails fast
and feeds the circuit breaker instead.
"""

from __future__ import annotations

from typing import Optional


class ServeError(RuntimeError):
    """Base of the serving-degradation failures. ``http_status`` is the
    front door's mapping; ``retry_after_s`` (when set) becomes the
    HTTP ``Retry-After`` hint."""

    http_status = 500
    retry_after_s: Optional[float] = None


class ShedError(ServeError):
    """Bounded admission refused the request: the queue is at
    ``LFM_SERVE_QUEUE_MAX``. Shedding is O(1) and intentional — the
    alternative is unbounded queue growth where EVERY request times out
    instead of most succeeding. HTTP 429."""

    http_status = 429
    retry_after_s = 0.1

    def __init__(self, queue_max: int):
        super().__init__(
            f"request shed: serving queue full ({queue_max} queued, "
            "LFM_SERVE_QUEUE_MAX) — retry after backoff")
        self.queue_max = queue_max


class DeadlineError(ServeError):
    """The request's deadline expired before dispatch — the batcher
    dropped it instead of spending a device dispatch on an answer
    nobody is waiting for. HTTP 504."""

    http_status = 504

    def __init__(self, universe: str, month: int, overdue_s: float):
        super().__init__(
            f"deadline expired {overdue_s * 1e3:.1f} ms before dispatch "
            f"for {universe!r}/{month} — dropped undispatched")
        self.universe = universe
        self.month = month


class CircuitOpenError(ServeError):
    """The circuit breaker is OPEN after consecutive dispatch failures:
    fast-fail instead of queueing onto a backend that is currently
    failing everything. HTTP 503 with a Retry-After of the remaining
    cooldown (after which a half-open probe decides)."""

    http_status = 503

    def __init__(self, retry_after_s: float):
        super().__init__(
            "circuit open after consecutive dispatch failures — "
            f"fast-failing; retry in {retry_after_s:.3f}s "
            "(half-open probe follows)")
        self.retry_after_s = max(0.0, float(retry_after_s))


class BatcherDeadError(ServeError):
    """The batcher thread died outside the per-batch failure path; the
    service is unready until restarted. Pending and subsequent requests
    fail fast with the original cause instead of hanging until client
    timeout. HTTP 503."""

    http_status = 503

    def __init__(self, cause: BaseException):
        super().__init__(
            "scoring service unready: batcher thread died "
            f"({type(cause).__name__}: {cause})")
        self.cause = cause


class MemberUnavailableError(ServeError):
    """The fleet router exhausted every candidate member for the
    universe (``serve/fleet.py``, DESIGN.md §22): each replica was out
    (dead, open-circuit, unready) or failed its attempt within the
    bounded member-retry budget. The fleet-level twin of
    :class:`CircuitOpenError` — fast-fail with a Retry-After covering
    the member cooldown, after which half-open probes readmit. HTTP
    503, so a fleet client sees the same taxonomy a single-process
    client does."""

    http_status = 503

    def __init__(self, universe: str, tried: int,
                 retry_after_s: float = 0.25):
        super().__init__(
            f"no fleet member available for universe {universe!r} "
            f"(tried {tried} member(s); the rest were out) — "
            f"fast-failing; retry in {retry_after_s:.3f}s "
            "(half-open member probes follow)")
        self.universe = universe
        self.tried = int(tried)
        self.retry_after_s = max(0.0, float(retry_after_s))


class SnapshotIntegrityError(ServeError):
    """A durable zoo generation failed restore-time verification
    (``serve/persist.py``, DESIGN.md §20): params checksum mismatch,
    parity-probe bit-inequality, panel hash mismatch, or an unreadable
    artifact. The restore loop catches it, QUARANTINES the snapshot
    (renamed aside, loud warning) and falls back to the next-older
    committed generation — or to a fresh retrain — because serving
    wrong numbers is the one failure mode a restore may never pick.
    ``artifact_quarantined`` True means the failing rung already
    quarantined the faulty artifact itself (e.g. a shared panel file)
    — the catch must then NOT also quarantine the healthy generation
    directory. ``skip_quarantine`` True means the failure was
    ENVIRONMENTAL (a transient device fault mid-restore, an active
    chaos schedule) — the attempt fails but the snapshot, which may be
    perfectly healthy, is not condemned. HTTP 500: if it ever reaches
    a client, something upstream skipped the quarantine ladder."""

    http_status = 500
    artifact_quarantined = False
    skip_quarantine = False


class DriftVetoError(ServeError):
    """The knob-gated publish veto (``LFM_DRIFT_GATE=1``, DESIGN.md
    §19): the universe's served-score distribution has drifted past
    ``LFM_DRIFT_MAX`` from its publish-time reference sketch, so the
    next atomic publish is BLOCKED until the operator re-validates (or
    overrides with the gate off) — the first concrete piece of the
    ROADMAP 5b risk gate. HTTP 409: the request conflicts with the
    service's current (drifted) state, it is not a service outage."""

    http_status = 409

    def __init__(self, universe: str, psi: float, threshold: float):
        super().__init__(
            f"publish vetoed for universe {universe!r}: served-score "
            f"drift PSI {psi:.4f} exceeds LFM_DRIFT_MAX {threshold:g} "
            "against the serving generation's reference sketch — "
            "re-validate the universe (or disable LFM_DRIFT_GATE) "
            "before publishing the next generation")
        self.universe = universe
        self.psi = float(psi)
        self.threshold = float(threshold)


#: Runtime status substrings worth a bounded retry (XLA/PJRT transient
#: status codes surface as RuntimeError text on this jax version).
_TRANSIENT_TOKENS = ("RESOURCE_EXHAUSTED", "DEADLINE_EXCEEDED",
                     "UNAVAILABLE", "ABORTED")


def is_transient(exc: BaseException) -> bool:
    """The retry layer's classification: True when re-dispatching the
    same batch has a chance (injected transient faults, retryable
    runtime statuses); False for everything else — permanent faults,
    routing errors, genuine bugs — which fail fast and count toward
    the circuit breaker."""
    if getattr(exc, "transient", False):
        return True
    if isinstance(exc, (KeyError, ValueError, TypeError)):
        return False
    msg = str(exc)
    return any(tok in msg for tok in _TRANSIENT_TOKENS)


def http_status(exc: BaseException) -> int:
    """Exception → HTTP status for the serve.py front door: shed → 429,
    open circuit / dead batcher → 503, expired deadline → 504, unknown
    universe/month → 404, anything else → 500."""
    if isinstance(exc, ServeError):
        return exc.http_status
    if isinstance(exc, KeyError):
        return 404
    return 500
