"""Live service health: SLO burn rates, score drift, gauge collection.

``utils/metrics.py`` owns the instruments (histograms, rings, gauges);
this module owns their INTERPRETATION for the always-on service:

* **SLO burn rates** (:func:`slo_status`) — the declared objectives
  (``LFM_SLO_P99_MS`` latency, ``LFM_SLO_AVAIL`` availability) are
  evaluated as MULTI-WINDOW burn rates over the windowed rings the
  batcher marks per request (60 s and 300 s — the fast window catches
  an acute outage, the slow one rejects a blip). Burn rate 1.0 means
  the error budget is being consumed exactly at the rate that exhausts
  it at the objective boundary; an objective is ``burning`` only when
  EVERY window's burn exceeds 1.0 (the classic multi-window AND — a
  single bad 10 s ring can spike the fast window, but only a sustained
  breach lights both). Surfaced as ``slo_burn`` gauges and in the
  ``/healthz`` detail — detail, not readiness: a burning SLO is an
  alert for the operator, while readiness (503) stays owned by the
  breaker/batcher machinery (DESIGN.md §18).
* **Score drift** (:func:`drift_status`) — each zoo generation carries
  a publish-time REFERENCE :class:`~lfm_quant_tpu.utils.metrics.ScoreSketch`
  of its batch-scored months and a LIVE twin the batcher streams served
  scores into; their PSI divergence is the ``score_drift_psi`` gauge.
  Crossing ``LFM_DRIFT_MAX`` flips the ``/healthz`` drift detail and —
  knob-gated via ``LFM_DRIFT_GATE``, default OFF —
  :func:`check_publish_gate` VETOES the universe's next atomic publish
  (serve/errors.py ``DriftVetoError``): the first concrete piece of the
  ROADMAP 5b risk gate, where a generation whose serving distribution
  has left its reference must be re-validated before another swap
  compounds the drift.
* **Gauge collection** (:meth:`ServiceMonitor.collect`) — point-in-time
  service state set at scrape/snapshot time, never per event: queue
  depth, zoo entries, resident panel/param bytes (computed from array
  METADATA — shape × dtype — so no device fetch ever originates here),
  ``circuit_state``, the ``slo_burn`` and ``score_drift_psi`` gauges.

Everything here is host-side arithmetic over locked snapshots; under
``LFM_METRICS=0`` collection degrades to an exact no-op and the status
functions report inactive objectives.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

from lfm_quant_tpu.utils import metrics, telemetry
from lfm_quant_tpu.utils.metrics import METRICS

#: Burn windows (seconds): fast catches an acute outage, slow rejects a
#: blip; both must burn > 1.0 for an objective to count as burning.
SLO_WINDOWS = (60.0, 300.0)

#: The p99 objective's error budget: 1% of requests may exceed the
#: latency bound (that is what "p99 <= X" means as a budget).
LATENCY_BUDGET_FRACTION = 0.01

#: A live sketch must hold at least this many served scores before its
#: PSI is reported — a handful of requests is sampling noise, not drift.
DRIFT_MIN_SCORES = 32


def slo_status(now: Optional[float] = None) -> Dict[str, Any]:
    """Evaluate the declared SLOs as multi-window burn rates over the
    ``serve_ok`` / ``serve_err`` / ``serve_slo_lat_bad`` rings the
    batcher marks. Returns ``{active, objectives: {name: {burn: {w: x},
    burning}}, max_burn, burning}``; inactive objectives (disabled by
    knob value) are omitted."""
    p99_ms = metrics.slo_p99_ms_default()
    avail = metrics.slo_avail_default()
    out: Dict[str, Any] = {"objectives": {}, "max_burn": 0.0,
                           "burning": False}
    if not metrics.enabled():
        out["active"] = False
        return out
    totals = {}
    for w in SLO_WINDOWS:
        ok = METRICS.window_total("serve_ok", w, now=now)
        err = METRICS.window_total("serve_err", w, now=now)
        bad = METRICS.window_total("serve_slo_lat_bad", w, now=now)
        totals[w] = (ok, err, bad)
    if 0.0 < avail < 1.0:
        budget = 1.0 - avail
        burns = {}
        for w, (ok, err, _) in totals.items():
            total = ok + err
            frac = err / total if total > 0 else 0.0
            burns[w] = frac / budget
        out["objectives"]["availability"] = {
            "objective": avail,
            "burn": {f"{int(w)}s": round(b, 3) for w, b in burns.items()},
            "burning": all(b > 1.0 for b in burns.values()),
        }
    if p99_ms > 0.0:
        burns = {}
        for w, (ok, _, bad) in totals.items():
            frac = bad / ok if ok > 0 else 0.0
            burns[w] = frac / LATENCY_BUDGET_FRACTION
        out["objectives"]["latency_p99"] = {
            "objective_ms": p99_ms,
            "burn": {f"{int(w)}s": round(b, 3) for w, b in burns.items()},
            "burning": all(b > 1.0 for b in burns.values()),
        }
    out["active"] = bool(out["objectives"])
    all_burns = [b for o in out["objectives"].values()
                 for b in o["burn"].values()]
    out["max_burn"] = round(max(all_burns, default=0.0), 3)
    out["burning"] = any(o["burning"] for o in out["objectives"].values())
    return out


class ServiceMonitor:
    """The evaluation layer bound to one :class:`ScoringService`: turns
    the recorded instruments plus the service's live structures into
    gauges, SLO/drift status, the publish gate and the ``/metrics``
    document. Owns no locks of its own — every read is a locked
    snapshot from the structure that owns the state."""

    def __init__(self, service: Any):
        self._service = service

    # ---- score drift -------------------------------------------------

    def drift_status(self) -> Dict[str, Any]:
        """Per-universe PSI of served scores against the generation's
        publish-time reference. ``breached`` lists universes past
        ``LFM_DRIFT_MAX``; universes whose live sketch holds fewer than
        ``DRIFT_MIN_SCORES`` scores report ``psi: None`` (not enough
        served mass to call drift either way)."""
        drift_max = metrics.drift_max_default()
        out: Dict[str, Any] = {"threshold": drift_max, "universes": {},
                               "breached": []}
        if not metrics.enabled() or drift_max <= 0:
            out["active"] = False
            return out
        zoo = self._service.zoo
        for universe in zoo.universes():
            try:
                entry = zoo.current(universe)
            except KeyError:
                continue  # dropped between listing and read
            psi = entry.drift_psi(min_scores=DRIFT_MIN_SCORES)
            if entry.ref_sketch is None:
                continue  # no reference stamped (metrics were off)
            rec = {"generation": entry.generation,
                   "psi": None if psi is None else round(psi, 4),
                   "served_scores": (entry.live_sketch.size()
                                     if entry.live_sketch is not None
                                     else 0)}
            out["universes"][universe] = rec
            if psi is not None and psi > drift_max:
                out["breached"].append(universe)
        out["active"] = bool(out["universes"])
        return out

    def check_publish_gate(self, universe: str) -> None:
        """The knob-gated publish veto (``LFM_DRIFT_GATE=1``): raise
        :class:`~lfm_quant_tpu.serve.errors.DriftVetoError` when the
        universe's CURRENT generation is past ``LFM_DRIFT_MAX`` — a
        serving distribution that has left its reference must be
        re-validated before another atomic swap compounds it. With the
        gate off (the default) drift stays observable (gauge +
        ``/healthz`` detail) but never blocks an operator."""
        if not (metrics.enabled() and metrics.drift_gate_enabled()):
            return
        drift_max = metrics.drift_max_default()
        if drift_max <= 0:
            return
        try:
            entry = self._service.zoo.current(universe)
        except KeyError:
            return  # first publish of a new universe: nothing to drift
        psi = entry.drift_psi(min_scores=DRIFT_MIN_SCORES)
        if psi is not None and psi > drift_max:
            from lfm_quant_tpu.serve.errors import DriftVetoError

            telemetry.COUNTERS.bump("serve_drift_vetoes")
            telemetry.instant("drift_veto", cat="serve",
                              universe=universe, psi=round(psi, 4),
                              threshold=drift_max)
            # A veto IS the incident (DESIGN.md §21): the serving
            # distribution left its reference badly enough to block a
            # publish — capture the evidence while the live sketch
            # still holds the drifted stream.
            inc = getattr(self._service, "incidents", None)
            if inc is not None:
                inc.trigger("drift_veto", universe=universe,
                            psi=round(psi, 4), threshold=drift_max)
            raise DriftVetoError(universe, psi, drift_max)

    # ---- gauge collection --------------------------------------------

    def collect(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Set every point-in-time gauge (called at scrape/snapshot
        time, never per event) and return ``{slo, drift}`` for the
        ``/healthz`` detail. Exact no-op (beyond computing the returned
        status) under ``LFM_METRICS=0``."""
        slo = slo_status(now=now)
        drift = self.drift_status()
        if not metrics.enabled():
            return {"slo": slo, "drift": drift}
        svc = self._service
        batcher = svc.batcher
        # Per-entity families are REBUILT from live state each
        # collection: clear them first so a retired generation's PSI or
        # an evicted universe's bytes can't linger in the exposition
        # (an alert on a series that no longer serves).
        for name in ("zoo_param_bytes", "slo_burn_window",
                     "score_drift_psi"):
            METRICS.clear_gauges(name)
        METRICS.gauge("serve_queue_depth", float(batcher.queue_depth()))
        METRICS.gauge("circuit_state", float(batcher.circuit_state_code()))
        zsnap = svc.zoo.snapshot()
        METRICS.gauge("zoo_entries", float(zsnap["size"]))
        METRICS.gauge("zoo_capacity", float(zsnap["capacity"]))
        # Resident bytes from array METADATA (shape × dtype) — the
        # metrics path must never fetch from the device. Distinct
        # panel objects counted once (a refresh generation shares its
        # predecessor's panel).
        param_bytes = 0
        panel_bytes = 0
        seen_panels: set = set()
        zoo = svc.zoo
        for universe in zsnap["universes"]:
            try:
                entry = zoo.current(universe)
            except KeyError:
                continue
            pb = entry.param_bytes()
            param_bytes += pb
            METRICS.gauge("zoo_param_bytes", float(pb), universe=universe)
            if id(entry.panel) not in seen_panels:
                seen_panels.add(id(entry.panel))
                panel_bytes += entry.panel_bytes()
        METRICS.gauge("zoo_param_bytes_total", float(param_bytes))
        METRICS.gauge("zoo_panel_bytes_total", float(panel_bytes))
        METRICS.gauge("slo_burn", float(slo["max_burn"]))
        for name, obj in slo["objectives"].items():
            for w, b in obj["burn"].items():
                METRICS.gauge("slo_burn_window", float(b),
                              objective=name, window=w)
        for universe, rec in drift["universes"].items():
            if rec["psi"] is not None:
                METRICS.gauge("score_drift_psi", float(rec["psi"]),
                              universe=universe,
                              generation=rec["generation"])
        # Fleet identity (serve/fleet.py, DESIGN.md §22): WHICH build,
        # backend and MEMBER (host + pid) produced this scrape — the
        # classic value-1 info gauge, from the cached
        # telemetry.build_info() probe. The fleet aggregator relabels
        # each member's scrape with member="name", and host/pid here
        # let every stat and incident bundle be attributed to the
        # member process that produced it.
        info = telemetry.build_info()
        METRICS.clear_gauges("build_info")
        METRICS.gauge(
            "build_info", 1.0,
            git_sha=(info.get("git_sha") or "unknown")[:12],
            jax=info.get("jax") or "unknown",
            jaxlib=info.get("jaxlib") or "unknown",
            backend=info.get("backend") or "unknown",
            dtype=info.get("dtype") or "unknown",
            device_count=info.get("device_count") or 0,
            host=info.get("host") or "unknown",
            pid=info.get("pid") or 0)
        # Incident triggers evaluated at scrape/snapshot time (the
        # signals are windowed aggregates — there is no per-event
        # moment to hook): a burning SLO or a shed-rate spike starts a
        # rate-limited capture (serve/incident.py; its own scrape is
        # re-entrancy-guarded there).
        inc = getattr(svc, "incidents", None)
        if inc is not None:
            if slo.get("burning"):
                inc.trigger("slo_burn", max_burn=slo.get("max_burn"),
                            objectives=sorted(slo.get("objectives", {})))
            from lfm_quant_tpu.serve.incident import (
                SHED_SPIKE_FRACTION, SHED_SPIKE_MIN_EVENTS,
                SHED_SPIKE_WINDOW_S)

            shed = METRICS.window_total("serve_shed",
                                        SHED_SPIKE_WINDOW_S, now=now)
            ok = METRICS.window_total("serve_ok", SHED_SPIKE_WINDOW_S,
                                      now=now)
            err = METRICS.window_total("serve_err", SHED_SPIKE_WINDOW_S,
                                       now=now)
            total = ok + err
            if (total >= SHED_SPIKE_MIN_EVENTS
                    and shed / total > SHED_SPIKE_FRACTION):
                inc.trigger("shed_spike", shed_60s=int(shed),
                            traffic_60s=int(total),
                            fraction=round(shed / total, 4))
        return {"slo": slo, "drift": drift}

    # ---- exposition --------------------------------------------------

    def metrics_text(self, ts: Optional[float] = None) -> str:
        """The ``GET /metrics`` document: collect gauges, then render
        the registry plus the absorbed telemetry counters as Prometheus
        text format 0.0.4."""
        self.collect()
        return metrics.render_prometheus(
            METRICS, counters=telemetry.COUNTERS.snapshot(), ts=ts)

    def snapshot(self, ts: Optional[float] = None) -> Dict[str, Any]:
        """The JSON twin of the scrape (``ScoringService.
        metrics_snapshot()``): gauges collected, every instrument
        summarized, SLO/drift status attached."""
        status = self.collect()
        return {
            "ts": time.time() if ts is None else ts,
            "metrics_enabled": metrics.enabled(),
            "slo": status["slo"],
            "drift": status["drift"],
            "instruments": METRICS.snapshot(),
            # Trace-id exemplars per latency bucket (DESIGN.md §21):
            # the JSON surface only — the text exposition stays plain
            # 0.0.4 (OpenMetrics exemplar syntax would break every
            # parse twin and any strict scraper).
            "exemplars": METRICS.exemplar_snapshot("serve_latency_ms"),
            "counters": {
                k: v for k, v in
                sorted(telemetry.COUNTERS.snapshot().items())
                if isinstance(v, (int, float))},
        }
