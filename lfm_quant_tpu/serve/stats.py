"""Latency/occupancy statistics for the scoring service — pure python.

One percentile implementation, used by ``ScoringService.stats()`` and
``bench.py serve``, and duplicated VERBATIM in ``scripts/trace_report.py``
(which must stay importable with no package/jax dependency — it runs as
a bare script from any host). The serve test lane cross-checks the two
against each other on the same run dir, so they cannot drift silently.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence


def percentile(values: Sequence[float], q: float) -> Optional[float]:
    """Linear-interpolation percentile (numpy's default rule) over a
    small sample; None on empty input. ``q`` in [0, 100]."""
    if not values:
        return None
    v = sorted(values)
    k = (len(v) - 1) * q / 100.0
    f, c = math.floor(k), math.ceil(k)
    if f == c:
        return float(v[int(k)])
    return float(v[f] * (c - k) + v[c] * (k - f))


def latency_summary(lat_ms: Sequence[float]) -> dict:
    """The serve latency rollup both bench and stats() report."""
    return {
        "requests": len(lat_ms),
        "p50_ms": percentile(lat_ms, 50.0),
        "p99_ms": percentile(lat_ms, 99.0),
        "max_ms": max(lat_ms) if lat_ms else None,
    }


def load_trace_report(repo_root: str):
    """Import ``scripts/trace_report.py`` as a module (it is a bare
    script, not a package member, by design — no jax/package imports).
    One loader shared by ``bench.py serve`` and the serve test lane so
    a relocation of the script cannot silently split the cross-check."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "lfm_trace_report", os.path.join(repo_root, "scripts",
                                         "trace_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod
