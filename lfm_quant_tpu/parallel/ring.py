"""Ring attention — sequence/context parallelism over a mesh axis.

Scope note (honest parity accounting): the reference has NO sequence
parallelism and needs none — its lookback is 60 months and it scales over
firms and seeds (SURVEY.md §3 parallelism table, §6 "Long-context" row).
This module is the framework's long-context capability beyond the
reference: when a panel is sampled at higher frequency (daily bars,
tick-aggregated fundamentals+price windows of thousands of steps), full
attention's O(W²) memory stops fitting one chip, and the window axis
itself must shard.

Design (the standard TPU recipe — blockwise/ring attention over ICI):

* The window (token) axis is sharded over a mesh axis (``seq``); each
  device holds local Q/K/V blocks ``[B, H, W_local, Dh]``.
* K/V blocks (with their key-validity mask) rotate around the ring via
  ``jax.lax.ppermute`` — after P-1 hops every query block has attended to
  every key block. ICI neighbours only; no all-gather materializes the
  full sequence anywhere.
* Numerical form is the flash-attention online softmax: running max,
  running denominator, running numerator, rescaled per hop — bitwise
  stable regardless of hop order, so results match full attention to
  float tolerance.
* Everything is differentiable JAX (ppermute has a transpose rule); the
  backward pass rides the same ring reversed, courtesy of AD — no custom
  VJP needed at these sizes. A Pallas RDMA double-buffered ring (guide
  §Ring Collectives) is the next step if hop latency ever dominates.

Usage: inside ``shard_map`` over a mesh with a ``seq`` axis — see
``sequence_parallel_apply`` for the packaged entry point, and
``TransformerModel(seq_axis=...)`` (models/transformer.py) for the
model-side integration.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from lfm_quant_tpu.parallel.mesh import SEQ_AXIS  # single source of truth

_NEG = -1e30  # additive mask for invalid keys (f32-safe, exp() == 0.0)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_mask: jax.Array,
    axis_name: str = SEQ_AXIS,
    scale: Optional[float] = None,
) -> jax.Array:
    """Masked bidirectional attention with K/V ring-rotated over a mesh axis.

    Must run inside ``shard_map``/``pmap`` binding ``axis_name``; the token
    axis of all inputs is the LOCAL shard.

    Args:
      q, k, v: ``[B, H, Wl, Dh]`` local blocks.
      kv_mask: ``[B, Wl]`` bool — key validity of the LOCAL K/V block
        (padding months are False). Queries need no mask: consumers pool
        only valid positions.
      axis_name: mesh axis to rotate around.
      scale: attention scale (default ``Dh**-0.5``).

    Returns:
      ``[B, H, Wl, Dh]`` attention output for the local query block, in
      ``q.dtype``. Queries whose global key set is empty return 0.
    """
    n_dev = jax.lax.psum(1, axis_name)
    if scale is None:
        scale = q.shape[-1] ** -0.5
    qf = q.astype(jnp.float32) * scale

    def block(qf, kb, vb, mb):
        """One (local Q) × (rotated K/V) block: partial softmax stats."""
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kb.astype(jnp.float32))
        s = s + jnp.where(mb, 0.0, _NEG)[:, None, None, :]
        m = jnp.max(s, axis=-1)                      # [B, H, Wq]
        p = jnp.exp(s - m[..., None])
        l = jnp.sum(p, axis=-1)                      # [B, H, Wq]
        o = jnp.einsum("bhqk,bhkd->bhqd", p, vb.astype(jnp.float32))
        return m, l, o

    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

    m_acc = jnp.full(qf.shape[:-1], _NEG, jnp.float32)
    l_acc = jnp.zeros(qf.shape[:-1], jnp.float32)
    o_acc = jnp.zeros(qf.shape, jnp.float32)
    kb, vb, mb = k, v, kv_mask
    for hop in range(n_dev):
        m_b, l_b, o_b = block(qf, kb, vb, mb)
        m_new = jnp.maximum(m_acc, m_b)
        c_acc = jnp.exp(m_acc - m_new)
        c_b = jnp.exp(m_b - m_new)
        l_acc = l_acc * c_acc + l_b * c_b
        o_acc = o_acc * c_acc[..., None] + o_b * c_b[..., None]
        m_acc = m_new
        if hop + 1 < n_dev:  # last hop: no rotation needed
            kb, vb, mb = (jax.lax.ppermute(x, axis_name, perm)
                          for x in (kb, vb, mb))
    # Queries with zero valid keys anywhere have l == exp(_NEG-_NEG)*Wg;
    # their m_acc is still _NEG — zero them rather than emit garbage.
    empty = m_acc <= _NEG * 0.5
    out = o_acc / jnp.where(empty, 1.0, l_acc)[..., None]
    out = jnp.where(empty[..., None], 0.0, out)
    return out.astype(q.dtype)


def sequence_parallel_apply(model, params, x, m, mesh: Mesh,
                            axis_name: str = SEQ_AXIS):
    """Apply a ``seq_axis``-aware model with the WINDOW axis sharded.

    Wraps ``model.apply`` in ``shard_map`` over ``mesh``: ``x [B, W, F]``
    and ``m [B, W]`` shard their window axis over ``axis_name``; params
    replicate; the output (one forecast per window — every shard holds the
    identical psum-pooled value) replicates. The model must handle its
    sharded internals itself (ring attention, position-embedding offset,
    psum pooling) — exactly what ``TransformerModel(seq_axis=...)`` does.

    The window length must divide by the mesh axis size.
    """
    from lfm_quant_tpu.parallel.mesh import shard_map_compat as shard_map

    W = x.shape[-2]
    n = mesh.shape[axis_name]
    if W % n:
        raise ValueError(f"window {W} not divisible by seq axis size {n}")

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(), P(None, axis_name, None), P(None, axis_name)),
        out_specs=P(),
    )
    def fwd(params, x, m):
        out = model.apply({"params": params}, x, m)
        if isinstance(out, tuple):
            return tuple(o for o in out)
        return out

    return fwd(params, x, m)


def seq_mesh(n: Optional[int] = None) -> Mesh:
    """A 1-axis ('seq',) mesh over n (default: all) devices."""
    import numpy as np

    devices = jax.devices()
    n = n or len(devices)
    return Mesh(np.asarray(devices[:n]), (SEQ_AXIS,))


def window_sharding(mesh: Mesh, axis_name: str = SEQ_AXIS) -> NamedSharding:
    """NamedSharding for [B, W, F] windows with W over the seq axis."""
    return NamedSharding(mesh, P(None, axis_name, None))
