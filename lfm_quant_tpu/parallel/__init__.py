"""Parallelism layer: device mesh + shardings (seed × data axes) and
sequence/context parallelism (ring attention over a 'seq' axis)."""

from lfm_quant_tpu.parallel.mesh import (
    DATA_AXIS,
    FOLD_AXIS,
    SEED_AXIS,
    SEQ_AXIS,
    STACK_AXIS,
    batch_sharding,
    make_fold_mesh,
    make_mesh,
    make_stack_mesh,
    mesh_fingerprint,
    replicated,
    seed_sharding,
    shard_batch,
    state_sharding,
)
from lfm_quant_tpu.parallel.ring import (
    ring_attention,
    seq_mesh,
    sequence_parallel_apply,
    window_sharding,
)

__all__ = [
    "SEED_AXIS",
    "DATA_AXIS",
    "SEQ_AXIS",
    "FOLD_AXIS",
    "STACK_AXIS",
    "make_mesh",
    "make_fold_mesh",
    "make_stack_mesh",
    "mesh_fingerprint",
    "replicated",
    "batch_sharding",
    "seed_sharding",
    "state_sharding",
    "shard_batch",
    "ring_attention",
    "seq_mesh",
    "sequence_parallel_apply",
    "window_sharding",
]
