"""Parallelism layer: device mesh + shardings (seed × data axes)."""

from lfm_quant_tpu.parallel.mesh import (
    batch_sharding,
    make_mesh,
    replicated,
    seed_sharding,
    shard_batch,
    state_sharding,
)

__all__ = [
    "make_mesh",
    "replicated",
    "batch_sharding",
    "seed_sharding",
    "state_sharding",
    "shard_batch",
]
