"""Device mesh + shardings — the distributed communication backend
(SURVEY.md §6 "Distributed communication backend").

Parity target: the reference's ``tf.distribute`` data parallelism with
NCCL all-reduce underneath (SURVEY.md §3; BASELINE.json:5). The TPU-native
equivalent is *declarative*: build a ``jax.sharding.Mesh`` over the slice,
annotate array shardings, and let XLA insert the collectives (psum over
ICI for gradient reduction, DCN-transparent across hosts). There is no
NCCL/MPI layer to port — XLA *is* the backend (prescribed verbatim at
BASELINE.json:5: "vmap'd replicas … gradients reduced via lax.psum over
ICI instead of per-GPU tf.distribute").

Axes:
  * ``seed`` — ensemble replicas (the reference's signature scaling axis:
    64 seeds on a v5e-64, one per chip).
  * ``data`` — batch data parallelism. Batches use the [D dates, Bf firms]
    layout and shard the DATE axis only, so each month's cross-section is
    shard-local and the rank-IC loss needs no collective (SURVEY.md §8
    step 8's correctness requirement).

Multi-host: the same code runs under ``jax.distributed.initialize()`` —
``jax.devices()`` then spans all hosts and XLA routes collectives over
ICI within a slice and DCN across slices. Nothing here is host-count
aware by construction.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SEED_AXIS = "seed"
DATA_AXIS = "data"
SEQ_AXIS = "seq"  # matches parallel/ring.py's axis name
# Fold-stacked walk-forward (train/foldstack.py): independent same-shape
# folds stacked on a leading axis of one program — the OUTERMOST mesh
# axis because, like 'seed', folds exchange no traffic (no per-step
# collective ever crosses it).
FOLD_AXIS = "fold"
# Generic stacked-run axis (train/stacked.py): the same leading
# independent-work axis when the runs are hyperparameter configs or
# ensemble replicate groups rather than walk-forward folds. A distinct
# name keeps fold meshes and config-sweep meshes from fingerprinting
# equal in the program caches (mesh_fingerprint includes axis names).
STACK_AXIS = "stack"


def make_mesh(n_seed: int = 1, n_data: Optional[int] = None,
              devices: Optional[Sequence[jax.Device]] = None,
              n_seq: int = 1) -> Mesh:
    """Build a (seed × data[× seq]) mesh over the available devices.

    ``n_data`` defaults to ``len(devices) // n_seed``. A 1×1 mesh on a
    single device is valid and keeps the code path uniform. ``n_seq > 1``
    appends a 'seq' axis (sequence/context parallelism — the window axis
    of the train forward; see parallel/ring.py) as the INNERMOST mesh
    dimension, so its per-layer collectives (ring ppermute / scan psum)
    ride physically-adjacent ICI links.

    Topology awareness: when the mesh spans ALL devices, the grid comes
    from ``mesh_utils`` so the 'data' axis (the only axis with a per-step
    collective — the gradient psum) lands on physically-adjacent devices
    and rides ICI. On multi-host runs the communication-FREE 'seed' axis
    is placed across hosts first (``create_hybrid_device_mesh`` with
    seeds on the DCN dimension): independent ensemble members are the
    only traffic crossing DCN — none. Explicit ``devices`` or partial
    meshes fall back to the given order.
    """
    explicit = devices is not None
    devices = list(devices if explicit else jax.devices())
    if n_data is None:
        if len(devices) % (n_seed * n_seq):
            raise ValueError(
                f"{len(devices)} devices not divisible by "
                f"n_seed×n_seq={n_seed * n_seq}")
        n_data = len(devices) // (n_seed * n_seq)
    shape = (n_seed, n_data, n_seq)
    need = n_seed * n_data * n_seq
    if need > len(devices):
        raise ValueError(
            f"mesh {n_seed}x{n_data}x{n_seq} needs {need} devices, "
            f"have {len(devices)}")
    grid = None
    if not explicit and need == len(devices):
        try:
            from jax.experimental import mesh_utils

            n_proc = jax.process_count()
            if n_proc > 1 and n_seed % n_proc == 0:
                grid = mesh_utils.create_hybrid_device_mesh(
                    (n_seed // n_proc, n_data, n_seq),
                    dcn_mesh_shape=(n_proc, 1, 1),
                ).reshape(shape)
            else:
                grid = mesh_utils.create_device_mesh(shape)
        except Exception as e:  # pragma: no cover - topology-dependent
            import warnings

            warnings.warn(
                f"mesh_utils device-mesh construction failed ({e!r}); "
                "falling back to positional device order — on multi-host "
                "runs the 'data' axis psum may cross DCN",
                RuntimeWarning, stacklevel=2)
            grid = None
    if grid is None:
        grid = np.asarray(devices[:need]).reshape(shape)
    if n_seq > 1:
        return Mesh(grid, (SEED_AXIS, DATA_AXIS, SEQ_AXIS))
    return Mesh(grid.reshape(n_seed, n_data), (SEED_AXIS, DATA_AXIS))


def make_stack_mesh(run_count: int, inner_mesh: Optional[Mesh] = None,
                    max_shards: Optional[int] = None,
                    axis_name: str = STACK_AXIS) -> Optional[Mesh]:
    """Mesh for a stacked-run sweep (train/stacked.py): a leading
    independent-run axis — walk-forward folds, hyperparameter configs —
    composed OUTSIDE the trainer's existing seed/data axes.

    The stack axis takes the largest divisor of ``run_count`` that fits
    the devices left after the inner mesh's axes (runs are independent,
    so any divisor is legal — a non-divisor would leave ragged shards).
    ``inner_mesh`` is the trainer's own mesh: its seed/data axis SIZES
    are preserved so the inner step/eval programs' collectives (psum over
    'data'/'seed') bind unchanged inside the stack shard_map. Returns
    ``None`` when no sharding applies (single device, no inner axes, and
    no divisor > 1) — the caller then runs the pure-vmap stack.
    ``max_shards`` caps the stack axis (the ``LFM_FOLDSTACK_SHARDS`` /
    ``LFM_STACK_SHARDS`` knobs; 0 forces the axis to 1). ``axis_name``
    is 'fold' for the walk-forward adapter and 'stack' for the generic
    engine — distinct names keep their mesh fingerprints (and therefore
    program-cache keys) from colliding.

    A seq axis is NOT composed: sequence parallelism's ring collectives
    assume the window shards are the innermost ICI neighbors, which a
    stack axis would interleave — callers degrade to sequential
    execution instead (train/stacked.py).
    """
    inner_shape = dict(inner_mesh.shape) if inner_mesh is not None else {}
    if inner_shape.get(SEQ_AXIS, 1) > 1:
        raise ValueError("stack mesh cannot compose with a live seq axis")
    inner_shape.pop(SEQ_AXIS, None)
    inner_n = 1
    for v in inner_shape.values():
        inner_n *= v
    budget = max(1, len(jax.devices()) // inner_n)
    if max_shards is not None:
        budget = min(budget, max(1, max_shards)) if max_shards > 0 else 1
    n_fold = 1
    for cand in range(min(run_count, budget), 1, -1):
        if run_count % cand == 0:
            n_fold = cand
            break
    if n_fold == 1 and not inner_shape:
        return None  # nothing to shard — pure vmap over the stack axis
    axes, sizes = [axis_name], [n_fold]
    for name in (SEED_AXIS, DATA_AXIS):
        if name in inner_shape:
            axes.append(name)
            sizes.append(inner_shape[name])
    need = int(np.prod(sizes))
    # Preserve the inner mesh's topology-aware placement (make_mesh puts
    # the 'data' psum axis on ICI-adjacent devices and keeps 'seed'
    # across DCN): the inner devices lead the grid IN THEIR MESH ORDER,
    # so with fold=1 the fold mesh is exactly the inner placement plus a
    # leading axis; extra fold blocks fill from the remaining devices in
    # positional order (best effort — folds themselves are traffic-free).
    if inner_mesh is not None:
        inner_devs = list(inner_mesh.devices.flat)
        inner_ids = {d.id for d in inner_devs}
        devs = inner_devs + [d for d in jax.devices()
                             if d.id not in inner_ids]
    else:
        devs = jax.devices()
    grid = np.asarray(devs[:need]).reshape(sizes)
    return Mesh(grid, tuple(axes))


def make_fold_mesh(fold_count: int, inner_mesh: Optional[Mesh] = None,
                   max_fold: Optional[int] = None) -> Optional[Mesh]:
    """Fold-stacked walk-forward mesh — :func:`make_stack_mesh` with the
    'fold' axis name (kept so fold meshes fingerprint exactly as they
    did before the stack generalization)."""
    return make_stack_mesh(fold_count, inner_mesh, max_fold,
                           axis_name=FOLD_AXIS)


def shard_map_compat(fn, *, mesh: Mesh, in_specs, out_specs,
                     check_vma: bool = False):
    """``jax.shard_map`` across the jax versions this repo must run on.

    Newer jax exposes it as ``jax.shard_map(..., check_vma=)``; jax
    0.4.x (the CI image) only has ``jax.experimental.shard_map`` with
    the older ``check_rep=`` spelling of the same knob. Every shard_map
    call site in the trainers/ring layer routes through here so the
    whole mesh test surface runs on both."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)


def mesh_fingerprint(mesh: Optional[Mesh]):
    """Hashable identity of a mesh for the cross-fold reuse caches
    (train/reuse.py, data/windows.py cached_device_panel): axis names,
    shape, and the concrete device ids. Two meshes built independently
    over the same devices fingerprint equal — exactly the walk-forward
    case where every fold's trainer builds its own (equal) mesh and must
    bind the previous fold's executables and resident panel. ``None``
    (no mesh — single device) fingerprints as the default device's id so
    a device hot-swap cannot alias a stale panel."""
    if mesh is None:
        d = jax.devices()[0]
        return (d.platform, d.id)
    return (
        tuple(mesh.axis_names),
        tuple(mesh.devices.shape),
        tuple(d.id for d in mesh.devices.flat),
    )


def resolve_seq_shards(requested: int, devices_left: int) -> int:
    """Degrade a requested seq-axis size to the devices actually left
    over (after the seed/data axes took theirs), warning when it shrinks
    — the shared contract that keeps pod-trained configs loadable for
    eval/backtest on smaller hosts. Returns the effective size (>= 1;
    1 means 'no seq axis: train/eval with the plain full-window model')."""
    n_seq = max(1, min(requested, devices_left))
    if n_seq < requested:
        import warnings

        warnings.warn(
            f"n_seq_shards={requested} exceeds the {devices_left} "
            f"device(s) left by the other mesh axes; degrading to "
            f"{n_seq}", stacklevel=3)
    return n_seq


def replicated(mesh: Mesh) -> NamedSharding:
    """Fully-replicated sharding (the device-resident panel, scalars)."""
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, with_seed_axis: bool = False) -> NamedSharding:
    """Sharding for index batches.

    [D, Bf] → dates over 'data', firms unsharded (cross-sections stay
    whole). With a leading seed axis: [S, D, Bf] → ('seed', 'data', None).
    """
    spec = P(SEED_AXIS, DATA_AXIS) if with_seed_axis else P(DATA_AXIS)
    return NamedSharding(mesh, spec)


def seed_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for seed-stacked pytree leaves: leading axis over 'seed'."""
    return NamedSharding(mesh, P(SEED_AXIS))


def state_sharding(mesh: Mesh, state: Any, stacked: bool) -> Any:
    """A sharding pytree matching ``state``.

    ``stacked=True``: every array leaf gets its LEADING axis sharded over
    'seed' (ensemble-stacked states); scalars (rank 0) replicate.
    ``stacked=False``: fully replicated (plain DP).
    """
    def leaf_sharding(x):
        if stacked and getattr(x, "ndim", 0) >= 1:
            return seed_sharding(mesh)
        return replicated(mesh)

    return jax.tree.map(leaf_sharding, state)


def shard_batch(mesh: Mesh, arrays: Sequence[jax.Array],
                with_seed_axis: bool = False, steps_axis: bool = False):
    """device_put a (firm_idx, time_idx, weight) batch with date-axis
    sharding. time_idx has no firm axis, so its spec drops the last dim.
    ``steps_axis`` prefixes an unsharded leading K axis (the in-jit
    multi-step stack scanned by lax.scan)."""
    lead = (None,) if steps_axis else ()
    if with_seed_axis:
        spec = P(*lead, SEED_AXIS, DATA_AXIS)
    else:
        spec = P(*lead, DATA_AXIS)
    firm_idx, time_idx, weight = arrays
    return (
        jax.device_put(firm_idx, NamedSharding(mesh, spec)),
        jax.device_put(time_idx, NamedSharding(mesh, spec)),
        jax.device_put(weight, NamedSharding(mesh, spec)),
    )
