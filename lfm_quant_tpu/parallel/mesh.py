"""Device mesh + shardings — the distributed communication backend
(SURVEY.md §6 "Distributed communication backend").

Parity target: the reference's ``tf.distribute`` data parallelism with
NCCL all-reduce underneath (SURVEY.md §3; BASELINE.json:5). The TPU-native
equivalent is *declarative*: build a ``jax.sharding.Mesh`` over the slice,
annotate array shardings, and let XLA insert the collectives (psum over
ICI for gradient reduction, DCN-transparent across hosts). There is no
NCCL/MPI layer to port — XLA *is* the backend (prescribed verbatim at
BASELINE.json:5: "vmap'd replicas … gradients reduced via lax.psum over
ICI instead of per-GPU tf.distribute").

Axes:
  * ``seed`` — ensemble replicas (the reference's signature scaling axis:
    64 seeds on a v5e-64, one per chip).
  * ``data`` — batch data parallelism. Batches use the [D dates, Bf firms]
    layout and shard the DATE axis only, so each month's cross-section is
    shard-local and the rank-IC loss needs no collective (SURVEY.md §8
    step 8's correctness requirement).

Multi-host: the same code runs under ``jax.distributed.initialize()`` —
``jax.devices()`` then spans all hosts and XLA routes collectives over
ICI within a slice and DCN across slices. Nothing here is host-count
aware by construction.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SEED_AXIS = "seed"
DATA_AXIS = "data"


def make_mesh(n_seed: int = 1, n_data: Optional[int] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a (seed × data) mesh over the available devices.

    ``n_data`` defaults to ``len(devices) // n_seed``. A 1×1 mesh on a
    single device is valid and keeps the code path uniform.
    """
    devices = list(devices if devices is not None else jax.devices())
    if n_data is None:
        if len(devices) % n_seed:
            raise ValueError(
                f"{len(devices)} devices not divisible by n_seed={n_seed}")
        n_data = len(devices) // n_seed
    need = n_seed * n_data
    if need > len(devices):
        raise ValueError(
            f"mesh {n_seed}x{n_data} needs {need} devices, "
            f"have {len(devices)}")
    grid = np.asarray(devices[:need]).reshape(n_seed, n_data)
    return Mesh(grid, (SEED_AXIS, DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    """Fully-replicated sharding (the device-resident panel, scalars)."""
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, with_seed_axis: bool = False) -> NamedSharding:
    """Sharding for index batches.

    [D, Bf] → dates over 'data', firms unsharded (cross-sections stay
    whole). With a leading seed axis: [S, D, Bf] → ('seed', 'data', None).
    """
    spec = P(SEED_AXIS, DATA_AXIS) if with_seed_axis else P(DATA_AXIS)
    return NamedSharding(mesh, spec)


def seed_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for seed-stacked pytree leaves: leading axis over 'seed'."""
    return NamedSharding(mesh, P(SEED_AXIS))


def state_sharding(mesh: Mesh, state: Any, stacked: bool) -> Any:
    """A sharding pytree matching ``state``.

    ``stacked=True``: every array leaf gets its LEADING axis sharded over
    'seed' (ensemble-stacked states); scalars (rank 0) replicate.
    ``stacked=False``: fully replicated (plain DP).
    """
    def leaf_sharding(x):
        if stacked and getattr(x, "ndim", 0) >= 1:
            return seed_sharding(mesh)
        return replicated(mesh)

    return jax.tree.map(leaf_sharding, state)


def shard_batch(mesh: Mesh, arrays: Sequence[jax.Array],
                with_seed_axis: bool = False, steps_axis: bool = False):
    """device_put a (firm_idx, time_idx, weight) batch with date-axis
    sharding. time_idx has no firm axis, so its spec drops the last dim.
    ``steps_axis`` prefixes an unsharded leading K axis (the in-jit
    multi-step stack scanned by lax.scan)."""
    lead = (None,) if steps_axis else ()
    if with_seed_axis:
        spec = P(*lead, SEED_AXIS, DATA_AXIS)
    else:
        spec = P(*lead, DATA_AXIS)
    firm_idx, time_idx, weight = arrays
    return (
        jax.device_put(firm_idx, NamedSharding(mesh, spec)),
        jax.device_put(time_idx, NamedSharding(mesh, spec)),
        jax.device_put(weight, NamedSharding(mesh, spec)),
    )
