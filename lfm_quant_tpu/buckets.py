"""Shared shape-bucket ladders: quantize ragged geometry to compiled shapes.

The sequence-bucketing idea of Khomenko et al. (1708.05604) shows up
twice in this system, on the same program-cache machinery
(train/reuse.py):

* **Serving** (lfm_quant_tpu/serve/buckets.py, PR 6): arbitrary request
  shapes — coalesced-row count × cross-section width — round UP to a
  power-of-two bucket folded into ``reuse.serve_program_key``, so steady
  state pays zero jit traces.
* **Training / batch scoring** (data/windows.py ``bucketed_epoch`` /
  ``bucketed_cross_sections``, ``LFM_BUCKETS``): instead of padding
  every batch to ONE static max shape (the largest cross-section × the
  full lookback window), dates and eval months are grouped into a
  finite (lookback-rows × cross-section-width) ladder, each rung keyed
  into ``reuse.train_bucket_program_key`` — thin dates stop carrying
  hundreds of weight-0 pad columns and short-history cohorts stop
  paying the full 60-step scan.

This module is the single source of the ladder arithmetic; the serve
package re-exports its half so the two paths can never drift. Padding
waste stays bounded by construction (< 2× slots worst case on a pow2
ladder), and weight-0 slots / mask-False steps cost only FLOPs, not
correctness: the weighted losses/metrics treat w=0 entries as absent
exactly (zero contributions are exact fp no-ops) and the recurrent
models HOLD state through masked steps — which is what makes a bucketed
batch's outputs BIT-identical to the same batch padded to max shape
(DESIGN.md §16; the ``bucketed`` test lane pins it).
"""

from __future__ import annotations

import os
from typing import List, Sequence, Tuple

#: Smallest cross-section bucket (sublane-tiling floor, matching the
#: sampler's minimum pad multiple in data/windows.py).
MIN_WIDTH = 8

#: Smallest lookback-rows bucket: below this the per-dispatch fixed
#: costs dwarf the scan savings, and the eligibility floor
#: (``min_valid_months``, default window//2) rarely admits shorter
#: histories anyway.
MIN_LOOKBACK = 8

#: A training-geometry bucket: (lookback rows W_b, cross-section width).
TrainBucket = Tuple[int, int]


def next_pow2(n: int, floor: int = 1) -> int:
    """Smallest power of two >= max(n, floor)."""
    n = max(int(n), floor)
    p = 1 << (n - 1).bit_length()
    return p


def bucket_width(n_firms: int) -> int:
    """Cross-section bucket for a month's eligible pool: next power of
    two, floored at :data:`MIN_WIDTH`."""
    if n_firms < 1:
        raise ValueError(f"bucket_width needs >= 1 firm, got {n_firms}")
    return next_pow2(n_firms, MIN_WIDTH)


def rows_ladder(max_rows: int) -> List[int]:
    """Every row bucket a pow2 ladder capped at ``max_rows`` can
    produce: 1, 2, 4, … max bucket."""
    top = next_pow2(max_rows)
    out, r = [], 1
    while r <= top:
        out.append(r)
        r <<= 1
    return out


def width_ladder(pool_sizes: Sequence[int]) -> List[int]:
    """The distinct cross-section buckets a universe's serveable months
    occupy — what warmup must pre-trace (sorted ascending)."""
    return sorted({bucket_width(int(n)) for n in pool_sizes if n > 0})


def capped_width(n: int, cap: int) -> int:
    """Cross-section bucket CAPPED at ``cap`` — the cap itself is a
    ladder member, so the widest months produce exactly the legacy
    max-shape batch (bit-for-bit the un-bucketed geometry) while thin
    months ride the pow2 rungs below it."""
    if cap < 1:
        raise ValueError(f"capped_width needs cap >= 1, got {cap}")
    return min(bucket_width(max(1, n)), cap)


def width_rungs(cap: int) -> List[int]:
    """Every width :func:`capped_width` can produce under ``cap``:
    the pow2 rungs in [MIN_WIDTH, cap) plus ``cap`` itself (ascending).
    The ladder is finite and known up front — the totality argument
    behind compile-once bucketed training."""
    out = [w for w in
           (MIN_WIDTH << i for i in range(max(1, cap).bit_length()))
           if w < cap]
    return out + [cap]


def lookback_rungs(window: int) -> List[int]:
    """The lookback-rows ladder for a ``window``-month model: pow2 rungs
    in [MIN_LOOKBACK, window) plus the full ``window`` itself (the cap
    member — anchors with deep history pay exactly the legacy scan)."""
    if window < 1:
        raise ValueError(f"lookback_rungs needs window >= 1, got {window}")
    out = [r for r in
           (MIN_LOOKBACK << i for i in range(window.bit_length()))
           if r < window]
    return out + [window]


def bucket_lookback(depth: int, window: int) -> int:
    """Smallest lookback rung >= ``depth`` (the trailing-window span an
    anchor's valid history actually occupies), capped at ``window``."""
    for r in lookback_rungs(window):
        if r >= depth:
            return r
    return window


def buckets_enabled() -> bool:
    """``LFM_BUCKETS=1`` opts training + batch scoring into the
    (lookback × width) geometry-bucket ladder (data/windows.py,
    DESIGN.md §16). Default OFF: bucketing regroups batches by
    geometry, which changes batch COMPOSITION (never per-batch
    numerics — those stay bit-identical to max-shape padding), so it is
    an explicit opt-in like ``LFM_FOLDSTACK``, not a transparent
    fast-path default. NOT a program-cache key: the bucket rides in its
    own tagged key family (``reuse.train_bucket_program_key``)."""
    return os.environ.get("LFM_BUCKETS", "0") not in ("0", "")
