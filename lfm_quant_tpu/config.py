"""Experiment configuration system.

Parity target: the reference's config/flag system selecting model type,
features, window, universe, seeds (SURVEY.md §3 [INFERRED]; the five ladder
configs at BASELINE.json:6-12 are checked in as named presets below).

Plain dataclasses, JSON-loadable, no external config framework.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, Optional, Tuple

#: The two supported compute-precision lanes (DESIGN.md §17).
PRECISIONS = ("f32", "bf16")


def resolve_precision(cfg: Optional["RunConfig"] = None) -> str:
    """Resolve the whole-stack compute-precision lane: an explicit
    ``RunConfig.precision`` wins, else the ``LFM_PRECISION`` env knob,
    else ``"f32"``. With no ``cfg`` this is the pure env resolution —
    the zero-arg form the telemetry manifest probes.

    ``"bf16"`` selects the mixed-precision lane end to end: bf16 model
    compute (f32 master params — Flax param dtype is untouched), bf16
    device-panel residency, f32 reductions/decisions (DESIGN.md §17).
    ``"f32"`` (the default) leaves every per-model ``ModelConfig.bf16``
    choice exactly as configured — the pre-lane behavior.
    """
    p = ((cfg.precision if cfg is not None else "")
         or os.environ.get("LFM_PRECISION", "")) or "f32"
    if p not in PRECISIONS:
        raise ValueError(
            f"precision must be one of {PRECISIONS}, got {p!r} "
            "(RunConfig.precision / LFM_PRECISION)")
    return p


def compute_dtype(cfg: "RunConfig"):
    """The effective COMPUTE dtype for a config — ``jnp.bfloat16`` when
    either the per-model ``ModelConfig.bf16`` flag or the whole-stack
    precision lane selects bf16, else None (f32 compute). The single
    source every dtype consumer reads: model construction
    (:func:`model_kwargs`), device-panel residency
    (``data/windows.py cached_device_panel``), gather resolution, the
    serving zoo's panel leases and the stacked engines' stack-mesh
    panel — so no path can disagree about the lane."""
    import jax.numpy as jnp

    if cfg.model.bf16 or resolve_precision(cfg) == "bf16":
        return jnp.bfloat16
    return None


@dataclasses.dataclass
class DataConfig:
    """Panel + windowing parameters (L1/L2)."""

    n_firms: int = 1000
    n_months: int = 240
    n_features: int = 5
    start_yyyymm: int = 197001
    window: int = 60
    horizon: int = 12
    dates_per_batch: int = 8
    # Firms sampled per month row; 0 = FULL UNIVERSE (every batch row
    # carries a month's entire eligible cross-section, padded to a static
    # rounded max — what the c3 rank-IC objective requires, BASELINE.json:9;
    # a positive value is the explicit subsampling approximation).
    firms_per_date: int = 128
    min_valid_months: Optional[int] = None
    # Date splits (YYYYMM): computed from panel range when None.
    train_end: Optional[int] = None
    val_end: Optional[int] = None
    # Rolling train window start (YYYYMM): None = expanding window (train
    # on all history up to train_end). Walk-forward pins this per fold
    # when ``train_months`` is set, so fold run dirs reload with the
    # exact rolling boundaries they trained under.
    train_start: Optional[int] = None
    panel_path: Optional[str] = None  # load a real panel instead of synthetic
    # Which (standardized) feature column the model forecasts ``horizon``
    # months ahead — real panels only (data/compustat.py); None = the
    # file's first feature column.
    target_col: Optional[str] = None
    panel_seed: int = 0
    # Synthetic-panel heteroscedasticity (data/panel.py synthetic_panel):
    # 0.0 = the legacy homoscedastic generator; > 0 ties the target-noise
    # scale to an observable feature — the uncertainty stack's testbed.
    het_noise: float = 0.0
    # Epoch index sampling: "python" (numpy RNG), "native" (C++ sampler,
    # lfm_quant_tpu/native/), "auto" (native when built). The two engines
    # produce different-but-equally-valid deterministic orders.
    sampler_engine: str = "python"
    # Window gather: "auto" picks the Pallas DMA gather
    # (ops/pallas_gather.py) on TPU when the step is un-partitioned, else
    # the XLA row gather (data/windows.py).
    gather_impl: str = "auto"  # auto | xla | pallas
    # Derived feature columns appended at load (data/features.py):
    # e.g. ("mom_12_1", "vol_12", "rev_1", "chg_<col>_<k>").
    derived_features: Tuple[str, ...] = ()


@dataclasses.dataclass
class ModelConfig:
    """Model selection + hyperparameters (L3)."""

    kind: str = "mlp"  # mlp | lstm | gru | transformer | lru
    kwargs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    bf16: bool = False
    heteroscedastic: bool = False
    # RNN recurrence implementation: "auto" picks the fused-projection
    # Pallas kernel (ops/pallas_rnn.py rnn_scan_fused) on TPU — measured
    # on chip at c2 geometry: 40.4M fm/s vs 34.8M ("pallas") vs 19.3M
    # ("xla"), and +31% ensemble throughput — else the XLA lax.scan.
    # Under a mesh the step runs inside shard_map where each shard is
    # locally un-partitioned, so the kernel stays legal (train/loop.py).
    # auto | xla | pallas | pallas_fused.
    scan_impl: str = "auto"


@dataclasses.dataclass
class OptimConfig:
    """Optimizer / schedule / stopping (L4)."""

    lr: float = 1e-3
    weight_decay: float = 1e-4
    warmup_steps: int = 100
    grad_clip: float = 1.0
    epochs: int = 20
    early_stop_patience: int = 5  # epochs without val improvement
    loss: str = "mse"  # mse | huber | rank_ic | nll
    # adamw | lamb. LAMB (layerwise-adaptive Adam; the large-batch-LSTM
    # recipe, PAPERS.md "Large-Batch Training for LSTM and Beyond") is
    # the CONTINGENCY for pod-scale effective batches (dates_per_batch ×
    # firms × n_data_shards reaching 10^5-10^6 firm rows/step, where
    # plain AdamW is known to degrade). Measured at 8× batch
    # (ledger `large_batch_optimizer` rows, 2026-07-31): linearly-scaled
    # AdamW HOLDS accuracy (0.529 vs 0.528 reference val IC) and LAMB
    # trails slightly (0.507) — keep adamw until the batch is large
    # enough that it visibly breaks; don't switch preemptively.
    optimizer: str = "adamw"


@dataclasses.dataclass
class RunConfig:
    """Top-level experiment config (L5/L6)."""

    name: str = "default"
    data: DataConfig = dataclasses.field(default_factory=DataConfig)
    model: ModelConfig = dataclasses.field(default_factory=ModelConfig)
    optim: OptimConfig = dataclasses.field(default_factory=OptimConfig)
    seed: int = 0
    n_seeds: int = 1  # >1 → ensemble (vmapped replicas)
    n_data_shards: int = 1  # data-parallel axis size
    # Sequence/context parallelism: >1 shards the WINDOW axis of the
    # train-step forward over a ('seq',) device mesh — ring attention for
    # the transformer, distributed associative scan for the LRU
    # (parallel/ring.py, models/lru.py). The long-context training mode
    # for windows that outgrow one chip. Transformer/lru only (a serial
    # LSTM/GRU recurrence cannot window-shard); currently exclusive with
    # n_data_shards/n_seeds meshes; window must divide by it.
    n_seq_shards: int = 1
    # Compute-precision lane (DESIGN.md §17): "" = inherit the
    # LFM_PRECISION env knob (default f32); "bf16" selects mixed
    # precision end to end — bf16 model compute + bf16 panel residency
    # with f32 master params, f32 Adam moments and f32 reductions.
    # Resolved via config.resolve_precision(cfg); a member of every
    # program-cache key family (train/reuse.py trainer_program_key).
    precision: str = ""
    # Seed microbatching: >0 scans the (per-device) seed stack in blocks
    # of this size inside the train step, bounding activation memory to
    # seed_block × per-seed instead of all resident seeds at once — the
    # HBM-fit fallback for wide ensembles (e.g. 64 seeds on one chip).
    # 0 = all local seeds in one vmapped step. Must divide the per-shard
    # seed count. Trades step-level parallelism for memory; throughput is
    # unchanged when the per-block batch already fills the chip.
    seed_block: int = 0
    # JAX persistent compilation cache directory (train/reuse.py
    # enable_persistent_cache): compiled XLA programs are written here so
    # even a COLD process skips re-optimization — the cross-process twin
    # of the in-process compiled-program cache that makes walk-forward
    # folds compile once. None = env fallback LFM_COMPILATION_CACHE,
    # else off. (JAX's own JAX_COMPILATION_CACHE_DIR also still works.)
    compilation_cache_dir: Optional[str] = None
    out_dir: str = "runs"

    @property
    def is_heteroscedastic(self) -> bool:
        """Whether the built model carries a (mean, log_var) head — the
        single source of truth shared by model building (model_kwargs)
        and the variance-stitching prediction paths."""
        return self.model.heteroscedastic or self.optim.loss == "nll"

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2)

    @staticmethod
    def from_json(text: str) -> "RunConfig":
        raw = json.loads(text)
        return RunConfig(
            name=raw.get("name", "default"),
            data=DataConfig(**raw.get("data", {})),
            model=ModelConfig(**raw.get("model", {})),
            optim=OptimConfig(**raw.get("optim", {})),
            seed=raw.get("seed", 0),
            n_seeds=raw.get("n_seeds", 1),
            n_data_shards=raw.get("n_data_shards", 1),
            n_seq_shards=raw.get("n_seq_shards", 1),
            precision=raw.get("precision", ""),
            seed_block=raw.get("seed_block", 0),
            compilation_cache_dir=raw.get("compilation_cache_dir"),
            out_dir=raw.get("out_dir", "runs"),
        )


def _ladder() -> Dict[str, RunConfig]:
    """The five capability-ladder presets (BASELINE.json:6-12)."""
    c1 = RunConfig(
        name="c1_mlp_toy",
        data=DataConfig(n_firms=1000, n_months=240, n_features=5, window=12,
                        dates_per_batch=8, firms_per_date=128),
        model=ModelConfig(kind="mlp", kwargs={"hidden": (64, 32)}),
        optim=OptimConfig(lr=1e-3, epochs=20, loss="mse"),
    )
    c2 = RunConfig(
        name="c2_lstm_single",
        data=DataConfig(n_firms=4000, n_months=480, n_features=20, window=60,
                        dates_per_batch=8, firms_per_date=256),
        model=ModelConfig(kind="lstm", kwargs={"hidden": 128}, bf16=True),
        optim=OptimConfig(lr=1e-3, epochs=30, loss="mse"),
    )
    c3 = RunConfig(
        name="c3_gru_rank_ic",
        # firms_per_date=0: the rank-IC loss ranks each month's FULL
        # eligible cross-section (~8000 firms), as the spec requires —
        # not a subsample. Set a positive value to opt into subsampling.
        data=DataConfig(n_firms=8000, n_months=480, n_features=20, window=60,
                        dates_per_batch=8, firms_per_date=0),
        model=ModelConfig(kind="gru", kwargs={"hidden": 128}, bf16=True),
        optim=OptimConfig(lr=5e-4, epochs=30, loss="rank_ic"),
        n_data_shards=8,
    )
    c4 = RunConfig(
        name="c4_transformer_bf16",
        data=DataConfig(n_firms=8000, n_months=480, n_features=20, window=60,
                        dates_per_batch=16, firms_per_date=512),
        model=ModelConfig(kind="transformer",
                          kwargs={"dim": 64, "depth": 2, "heads": 4}, bf16=True),
        optim=OptimConfig(lr=5e-4, epochs=30, loss="mse"),
        n_data_shards=16,
    )
    c5 = RunConfig(
        name="c5_lstm_ensemble64",
        data=DataConfig(n_firms=8000, n_months=660, n_features=20, window=60,
                        start_yyyymm=197001, dates_per_batch=8,
                        firms_per_date=256),
        model=ModelConfig(kind="lstm", kwargs={"hidden": 128}, bf16=True),
        optim=OptimConfig(lr=1e-3, epochs=30, loss="mse"),
        n_seeds=64,
        n_data_shards=1,
    )
    # Beyond-ladder preset: the time-parallel LRU (models/lru.py) at the
    # c2 geometry — the apples-to-apples throughput/accuracy comparison
    # against the LSTM's serial recurrence.
    lru = RunConfig(
        name="lru_c2_geometry",
        data=dataclasses.replace(c2.data),
        model=ModelConfig(kind="lru",
                          kwargs={"hidden": 128, "state_dim": 128},
                          bf16=True),
        optim=OptimConfig(lr=1e-3, epochs=30, loss="mse"),
    )
    # Beyond-ladder: the LRU at the c5 ENSEMBLE geometry. The flagship
    # recurrence DECISION went to the LSTM on measured accuracy
    # (DESIGN.md §8: capacity gap, not budget — ledger
    # recurrence_accuracy rows); this row completes the throughput
    # record and serves workloads where the linear recurrence's
    # accuracy holds (bench via LFM_BENCH_SEEDS like c5).
    # Derived from `lru` so hyperparameter tuning there carries over.
    lru64 = dataclasses.replace(
        lru,
        name="lru64_c5_ensemble",
        data=dataclasses.replace(c5.data),
        model=dataclasses.replace(lru.model,
                                  kwargs=dict(lru.model.kwargs)),
        n_seeds=64,
        n_data_shards=1,
    )
    # Beyond-ladder: the long-context mode at preset level — a 240-month
    # (20-year) window transformer with the window axis sharded 8 ways
    # (ring attention; n_seq_shards degrades to the visible devices).
    lc = RunConfig(
        name="lc_transformer_seq8",
        data=DataConfig(n_firms=4000, n_months=600, n_features=20,
                        window=240, dates_per_batch=8, firms_per_date=128),
        model=ModelConfig(kind="transformer",
                          kwargs={"dim": 64, "depth": 2, "heads": 4},
                          bf16=True),
        optim=OptimConfig(lr=5e-4, epochs=30, loss="mse"),
        n_seq_shards=8,
    )
    return {c.name: c for c in (c1, c2, c3, c4, c5, lru, lru64, lc)}


PRESETS: Dict[str, RunConfig] = _ladder()
# Short aliases derived from the names themselves ("c2_lstm_single" →
# "c2", "lru_c2_geometry" → "lru") — immune to ladder reordering. Alias
# collisions (two presets sharing a first token, or an alias shadowing a
# full name) must fail loudly at import time, not silently last-wins.
for _name, _cfg in list(PRESETS.items()):
    _alias = _name.split("_")[0]
    if _alias in PRESETS and PRESETS[_alias] is not _cfg:
        raise ValueError(
            f"preset alias {_alias!r} (from {_name!r}) collides with an "
            f"existing preset/alias; rename the preset")
    PRESETS[_alias] = _cfg
del _name, _cfg, _alias


def get_preset(name: str) -> RunConfig:
    try:
        return PRESETS[name]
    except KeyError:
        raise ValueError(
            f"unknown preset {name!r}; available: "
            f"{sorted(k for k in PRESETS if not k.startswith('c') or len(k) > 2)}"
        ) from None


def model_kwargs(cfg: RunConfig, mesh=None,
                 force_xla_scan: bool = False,
                 seq_axis: bool = False) -> Tuple[str, Dict[str, Any]]:
    """Resolve ModelConfig into build_model(kind, **kwargs) arguments.

    "auto" scan_impl picks the fused Pallas recurrence on a real TPU. A
    mesh does not disqualify it: train steps run inside ``shard_map``
    whenever a mesh exists (train/loop.py), where each shard is locally
    un-partitioned and a pallas_call is legal. ``force_xla_scan=True``
    overrides to the GSPMD-partitionable ``lax.scan`` — trainers use it to
    build the eval-forward model, which stays outside shard_map.
    ``seq_axis=True`` builds the window-sharded (sequence-parallel)
    variant — transformer/lru only; the trainer passes it for its train
    model when ``cfg.n_seq_shards > 1`` (checkpoints interchange with the
    plain variant — no per-position params).
    """
    import jax
    import jax.numpy as jnp

    del mesh  # kept in the signature: callers resolve per execution context
    kw = dict(cfg.model.kwargs)
    # Compute dtype: per-model bf16 flag OR the whole-stack precision
    # lane (LFM_PRECISION=bf16, DESIGN.md §17). Param dtype stays f32
    # either way — every model keeps f32 master params and an f32 head
    # boundary; only trunk compute casts down.
    if compute_dtype(cfg) is not None:
        kw["dtype"] = jnp.bfloat16
    if cfg.is_heteroscedastic:
        kw["heteroscedastic"] = True
    if cfg.model.kind in ("lstm", "gru"):
        # Factorized recurrences (PAPERS.md F-/G-LSTM: factor_rank /
        # n_groups kwargs) run on the XLA scan only — the Pallas kernels'
        # VMEM/MXU layout assumes dense gate weights.
        factored = bool(kw.get("factor_rank")) or kw.get("n_groups", 1) > 1
        if "scan_impl" not in kw:
            impl = cfg.model.scan_impl
            if impl == "auto":
                impl = ("pallas_fused" if jax.default_backend() == "tpu"
                        and not factored else "xla")
            kw["scan_impl"] = impl
        if force_xla_scan:
            kw["scan_impl"] = "xla"
    if seq_axis:
        if cfg.model.kind not in ("transformer", "lru"):
            raise ValueError(
                f"n_seq_shards > 1 needs a window-shardable model "
                f"(transformer | lru), got {cfg.model.kind!r} — a serial "
                "recurrence cannot shard its time axis")
        kw["seq_axis"] = "seq"
    return cfg.model.kind, kw
