"""Derived feature engineering (L1) — the price/momentum companions to
the fundamental columns.

The reference lineage feeds trailing-window models a mix of fundamental
ratios AND price-derived features (momentum et al., SURVEY.md §1
[BACKGROUND]); vendor files carry the fundamentals, while the
price-derived columns are computed from the returns history. This module
derives them from the panel's own monthly returns / feature columns and
appends them as additional standardized feature columns.

Specs (strings, composable in any order):

* ``mom_<L>_<S>`` — momentum: cumulative log return over the window
  ``(t-L, t-S]`` months (e.g. ``mom_12_1`` = classic 12-1 momentum,
  skipping the most recent month's reversal).
* ``vol_<K>`` — realized volatility: std of the last K monthly returns.
* ``rev_<K>`` — short-term reversal: NEGATIVE cumulative log return over
  the last K months (``rev_1`` = classic 1-month reversal).
* ``chg_<name>_<K>`` — K-month change in an existing feature column
  ``<name>`` (a delta of the already-standardized column — fundamental
  momentum).

Every derived column uses ONLY information available at the anchor month
(trailing returns; no forward peeking), requires its full history window
to be observed, and is winsorized + z-scored per month over the
available cross-section exactly like the loader's fundamental columns
(data/compustat.py). Cells where a derived value is unavailable but the
month is otherwise valid are zero-filled — the z-scored mean, the same
imputation the base features use.

All computation is host-side numpy at load time (L1 preprocessing); the
derived panel then lives in HBM like any other.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Optional, Sequence, Tuple

import numpy as np

from lfm_quant_tpu.data.panel import Panel

_SPEC_RE = re.compile(
    r"^(?:mom_(?P<mL>\d+)_(?P<mS>\d+)|vol_(?P<vK>\d+)|rev_(?P<rK>\d+)"
    r"|chg_(?P<cname>.+)_(?P<cK>\d+))$")

# One small-month policy for the whole panel: months with fewer valid
# firms than this get no standardized values (the loader invalidates
# them outright; derived columns zero-fill). Shared with
# data/compustat.py so base and derived columns never drift apart.
MIN_CROSS_SECTION = 5


def winsorize_zscore(x: np.ndarray,
                     winsor: Optional[Tuple[float, float]]) -> np.ndarray:
    """One month's valid cross-section ``[K, F]`` (or ``[K]``) →
    winsorized + z-scored per column — THE standardization recipe, used
    by the loader's fundamental columns (data/compustat.py) and the
    derived columns here. Order-statistic quantiles (no interpolation):
    an interpolated 99th pct is itself dragged by a single extreme
    outlier."""
    if winsor is not None:
        lo = np.nanquantile(x, winsor[0], axis=0, method="higher")
        hi = np.nanquantile(x, winsor[1], axis=0, method="lower")
        x = np.clip(x, lo, hi)
    mu = x.mean(axis=0)
    sd = x.std(axis=0)
    sd = np.where(sd < 1e-8, 1.0, sd)
    return (x - mu) / sd


def _trailing_log_returns(panel: Panel) -> Tuple[np.ndarray, np.ndarray]:
    """(lr, obs): lr[i, u] = log1p of the return earned over (u-1, u]
    — ``panel.returns[:, u-1]`` (forward-indexed) — and obs marks it
    observed. Column 0 has no trailing month."""
    n, t = panel.returns.shape
    rv = panel.ret_valid if panel.ret_valid is not None else panel.valid
    lr = np.zeros((n, t), np.float64)
    obs = np.zeros((n, t), bool)
    lr[:, 1:] = np.log1p(np.clip(panel.returns[:, :-1], -0.9999, None))
    obs[:, 1:] = rv[:, :-1]
    lr[~obs] = 0.0
    return lr, obs


def _window_sum(x: np.ndarray, obs: np.ndarray, lo: int, hi: int
                ) -> np.ndarray:
    """At each anchor t: sum of ``x[:, u]`` over the trailing months
    ``u`` in ``(t-lo, t-hi]``; NaN where any constituent month is
    unobserved (or the window extends before the panel)."""
    n, t = x.shape
    out = np.full((n, t), np.nan)
    if lo >= t:
        return out
    cs = np.concatenate([np.zeros((n, 1)), np.cumsum(x, axis=1)], axis=1)
    cn = np.concatenate([np.zeros((n, 1), int),
                         np.cumsum(obs, axis=1)], axis=1)
    width = lo - hi
    # anchor t in [lo, T): window months [t-lo+1, t-hi] = cs[b] - cs[a]
    # with a = t-lo+1, b = t-hi+1.
    b = np.arange(lo - hi + 1, t - hi + 1)
    a = b - width
    vals = cs[:, b] - cs[:, a]
    full = (cn[:, b] - cn[:, a]) == width
    out[:, lo:] = np.where(full, vals, np.nan)
    return out


def _raw_column(panel: Panel, spec: str, lr_obs=None) -> np.ndarray:
    """[N, T] raw derived values (NaN = unavailable at that anchor).

    ``lr_obs``: precomputed :func:`_trailing_log_returns` pair, so a
    multi-spec load does the panel-wide log-return pass once."""
    m = _SPEC_RE.match(spec)
    if not m:
        raise ValueError(
            f"unknown feature spec {spec!r}; expected mom_<L>_<S>, "
            "vol_<K>, rev_<K> or chg_<name>_<K>")
    g = m.groupdict()
    if g["cname"] is None and lr_obs is None:
        lr_obs = _trailing_log_returns(panel)
    if g["mL"] is not None:
        L, S = int(g["mL"]), int(g["mS"])
        if not 0 <= S < L:
            raise ValueError(f"{spec!r}: need lookback > skip >= 0")
        lr, obs = lr_obs
        return _window_sum(lr, obs, L, S)
    if g["vK"] is not None:
        K = int(g["vK"])
        if K < 2:
            raise ValueError(f"{spec!r}: vol needs K >= 2")
        lr, obs = lr_obs
        s1 = _window_sum(lr, obs, K, 0)
        s2 = _window_sum(lr * lr, obs, K, 0)
        var = np.maximum(s2 / K - (s1 / K) ** 2, 0.0)
        return np.sqrt(var)
    if g["rK"] is not None:
        K = int(g["rK"])
        if K < 1:
            raise ValueError(f"{spec!r}: rev needs K >= 1")
        lr, obs = lr_obs
        return -_window_sum(lr, obs, K, 0)
    name, K = g["cname"], int(g["cK"])
    if name not in panel.feature_names:
        raise ValueError(
            f"{spec!r}: no feature column {name!r} "
            f"(have {list(panel.feature_names)})")
    if K < 1:
        raise ValueError(f"{spec!r}: chg needs K >= 1")
    j = list(panel.feature_names).index(name)
    col = panel.features[:, :, j].astype(np.float64)
    avail = panel.valid
    out = np.full(col.shape, np.nan)
    out[:, K:] = col[:, K:] - col[:, :-K]
    out[:, K:] = np.where(avail[:, K:] & avail[:, :-K], out[:, K:], np.nan)
    return out


def standardize_column(raw: np.ndarray, month_valid: np.ndarray,
                       winsor: Tuple[float, float] = (0.01, 0.99),
                       min_cross_section: int = MIN_CROSS_SECTION
                       ) -> np.ndarray:
    """Per-month :func:`winsorize_zscore` of one [N, T] column over its
    available cross-section; unavailable cells → 0 (the z-mean)."""
    avail = np.isfinite(raw) & month_valid
    out = np.zeros(raw.shape, np.float32)
    for j in range(raw.shape[1]):
        sel = avail[:, j]
        if sel.sum() < min_cross_section:
            continue
        out[sel, j] = winsorize_zscore(raw[sel, j], winsor)
    return out


def add_derived_features(panel: Panel, specs: Sequence[str],
                         winsor: Tuple[float, float] = (0.01, 0.99),
                         min_cross_section: int = MIN_CROSS_SECTION
                         ) -> Panel:
    """Append derived feature columns to a panel (new Panel; input is
    untouched). ``specs`` — see the module docstring. Months/firms keep
    their validity: a valid month with an unavailable derived value gets
    the zero-imputed (z-mean) cell, like the base features."""
    if not specs:
        return panel
    lr_obs = _trailing_log_returns(panel)
    cols = [standardize_column(_raw_column(panel, s, lr_obs), panel.valid,
                               winsor, min_cross_section)
            for s in specs]
    features = np.concatenate(
        [panel.features] + [c[..., None] for c in cols], axis=2)
    return dataclasses.replace(
        panel,
        features=features.astype(np.float32),
        feature_names=list(panel.feature_names) + list(specs),
    )
