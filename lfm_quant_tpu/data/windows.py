"""Windowing pipeline (L2): the TPU-native `BatchGenerator`/`Dataset`.

Functional parity target: the reference's ``BatchGenerator`` / ``Dataset``
pipeline (SURVEY.md §3; BASELINE.json:5) — panel → per-firm lookback windows
(60 months in the ladder configs), padding/masking of short histories,
seed-keyed shuffling.

TPU-first redesign (SURVEY.md §8, "hard parts"): instead of the reference's
host-side streaming of materialized ``(B, T, F)`` batches into the device,
the *panel lives in HBM* and every batch is a cheap on-device gather:

  host side   — tiny int32 index batches (which firm, which anchor month),
                computed by a numpy sampler keyed by seed;
  device side — ``gather_windows`` turns index batches into ``(…, W, F)``
                windows with validity masks, inside the jitted train step.

This makes input bandwidth per step O(batch indices) instead of
O(batch × window × features), which is the single decision SURVEY.md §8
flags as likely dominating the throughput target.

Batch layout: ``[D, Bf]`` — D distinct *months* per batch, Bf firms sampled
per month.  The cross-sectional rank-IC loss (BASELINE.json:9) normalizes
and ranks *within a month*; keeping each month's firms contiguous in one row
(and, under data parallelism, on one shard — see parallel/mesh.py) means the
loss never needs a cross-shard collective.  This is the sharding subtlety
SURVEY.md §8 step 8 says to decide up front.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
import weakref
from collections import OrderedDict
from typing import Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from lfm_quant_tpu.buckets import TrainBucket, capped_width, lookback_rungs
from lfm_quant_tpu.data.panel import Panel


# Firm-axis chunk for the XLA row gather at full-universe widths: the fast
# path materializes [D, Bf, T, F] firm rows, which at Bf ≈ 8000 full
# cross-sections is T/W × the window bytes (~GBs); chunking bounds the
# transient to [D, FIRM_CHUNK, T, F] per lax.map step. Full-universe Bf is
# rounded to a multiple of this so the chunks always divide evenly.
FIRM_CHUNK = 512


@dataclasses.dataclass
class WindowIndex:
    """One batch of window anchors, in the [D, Bf] per-date layout.

    Attributes:
      firm_idx: ``[D, Bf]`` int32 — panel row of each sampled firm.
      time_idx: ``[D]`` int32 — anchor month (column) shared by each row.
      weight:   ``[D, Bf]`` float32 — 1.0 for real samples, 0.0 for padding
        (dates whose eligible cross-section is smaller than Bf are padded by
        repetition with zero weight so shapes stay static).
    """

    firm_idx: np.ndarray
    time_idx: np.ndarray
    weight: np.ndarray


def rolling_valid_count(valid: np.ndarray, window: int) -> np.ndarray:
    """``[N, T]`` count of valid months in the trailing window ``[t-W+1, t]``."""
    csum = np.cumsum(valid.astype(np.int64), axis=1)
    total = np.empty_like(csum)
    total[:, :window] = csum[:, :window]
    total[:, window:] = csum[:, window:] - csum[:, :-window]
    return total


def anchor_index(
    panel: Panel, window: int, min_valid_months: Optional[int] = None,
    require_target: bool = True,
) -> np.ndarray:
    """Eligibility matrix of window anchors.

    Returns ``[N, T]`` bool: True where (firm, t) is a trainable anchor —
    the target is observable at t and the lookback window ``[t-W+1, t]``
    contains at least ``min_valid_months`` valid months (default: W//2, so
    young firms with ≥30 months of history still train, padded+masked, which
    matches the reference's padding of short histories per SURVEY.md §3).

    ``require_target=False`` drops the target-observability conjunct —
    LIVE-forecast eligibility: the model only needs the lookback window,
    and the anchors a production user wants ranked are exactly the last
    ``horizon`` months where ``target_valid`` is False by construction.
    Training/backtest paths must keep the default (scoring an anchor
    needs the realized outcome).
    """
    if min_valid_months is None:
        min_valid_months = max(1, window // 2)
    total = rolling_valid_count(panel.valid, window)
    elig = (total >= min_valid_months) & panel.valid
    return elig if not require_target else elig & panel.target_valid


@dataclasses.dataclass
class BucketGeometry:
    """A sampler's (lookback-rows × cross-section-width) bucket ladder
    (DESIGN.md §16, ``LFM_BUCKETS``): the epoch-invariant assignment of
    training dates and eval months to shape buckets, so every batch a
    bucketed epoch can ever emit has a shape from a finite, known
    ladder — each rung compiles exactly once (the compile-once totality
    argument, same as the serving ladder's warmup).

    Buckets are keyed ``(lookback_rows, width)``; the cap bucket
    ``(window, width_cap)`` reproduces the legacy max-shape geometry
    bit-for-bit. ``train_buckets`` maps bucket → training-date array
    (small buckets folded into a containing bucket so every bucket
    fills whole [D]-date batches); ``eval_buckets`` maps bucket →
    POSITIONS into the stacked eval-month order (what
    ``stacked_cross_sections`` would emit), so per-month outputs
    reassemble exactly."""

    window: int
    width_cap: int        # the static training Bf widths are capped at
    eval_width_cap: int   # the panel-wide eval pad width (_eval_bf)
    train_buckets: "OrderedDict[TrainBucket, np.ndarray]"
    eval_buckets: "OrderedDict[TrainBucket, np.ndarray]"

    def summary(self, dates_per_batch: int) -> Dict[str, object]:
        """JSON-able geometry digest (telemetry instant / bench row):
        per-epoch dispatched firm-month cells on the bucket ladder vs
        the same batches padded to max shape, and the eval-sweep twin.
        'Cells' are firm-month positions inside a dispatch — the FLOP
        unit every padding cost here scales with."""
        tr_disp = tr_max = 0
        for (lb, w), dates in self.train_buckets.items():
            nb = dates.size // dates_per_batch
            tr_disp += nb * dates_per_batch * w * lb
            tr_max += nb * dates_per_batch * self.width_cap * self.window
        ev_disp = ev_max = 0
        for (lb, w), pos in self.eval_buckets.items():
            ev_disp += pos.size * w * lb
            ev_max += pos.size * self.eval_width_cap * self.window
        return {
            "ladder": sorted([list(k) for k in
                              set(self.train_buckets) | set(self.eval_buckets)]),
            "n_train_buckets": len(self.train_buckets),
            "n_eval_buckets": len(self.eval_buckets),
            "train_cells_bucketed": int(tr_disp),
            "train_cells_max_shape": int(tr_max),
            "eval_cells_bucketed": int(ev_disp),
            "eval_cells_max_shape": int(ev_max),
        }


class DateBatchSampler:
    """Seed-keyed sampler emitting ``WindowIndex`` batches in [D, Bf] layout.

    Every epoch: shuffle eligible dates, and for each date sample ``firms_per
    _date`` eligible firms without replacement (re-shuffled per epoch). All
    randomness flows from ``seed`` so ensemble members get independent data
    orders via distinct seeds (SURVEY.md §8 "per-seed PRNG folds").
    """

    def __init__(
        self,
        panel: Panel,
        window: int,
        dates_per_batch: int,
        firms_per_date: int,
        seed: int = 0,
        min_valid_months: Optional[int] = None,
        min_cross_section: int = 8,
        date_range: Optional[tuple] = None,
        engine: str = "python",
        require_target: bool = True,
    ):
        """``date_range=(lo, hi)`` restricts ANCHOR months to panel column
        indices [lo, hi) — the split mechanism (PanelSplits): windows still
        reach back before ``lo`` for history; only anchors are bounded.

        ``firms_per_date=0`` selects FULL-UNIVERSE mode (BASELINE.json:9 —
        the c3 rank-IC loss ranks the full monthly cross-section): every
        batch row carries a date's ENTIRE eligible pool, padded to a static
        Bf = the largest pool, rounded up (multiple of FIRM_CHUNK=512 for
        pools ≥ 2×FIRM_CHUNK=1024 so the firm-chunked gather divides
        evenly, else 8 for sublane tiling).
        Padding is repetition at weight 0, exactly like thin dates in
        subsampled mode.

        ``engine``: "python" (numpy RNG, the determinism contract tests pin
        down), "native" (the C++ sampler in lfm_quant_tpu/native/ — its own
        deterministic order keyed by (seed, epoch), ~29× faster epoch
        generation (median of the latest capture ± 34% within-capture
        spread; cross-session range 13–52× — ledger
        `native_host_runtime` epoch_sampling rows, per BASELINE.md's
        error-bar protocol), the host-side win for many-seed ensembles),
        or "auto" (native when built, else python)."""
        self.window = window
        self.dates_per_batch = dates_per_batch
        if firms_per_date < 0:
            raise ValueError(
                f"firms_per_date must be >= 0 (0 = full universe), got "
                f"{firms_per_date}")
        self.firms_per_date = firms_per_date
        self.seed = seed
        if engine not in ("python", "native", "auto"):
            raise ValueError(
                f"engine must be python|native|auto, got {engine!r}")
        self.engine = engine
        # Kept for the lazy geometry-bucket analysis (bucket_geometry):
        # the lookback-rung safety test reads per-firm validity counts at
        # each rung. A reference, not a copy.
        self._valid = panel.valid
        self._bucket_geo: Optional["BucketGeometry"] = None
        eligible = anchor_index(panel, window, min_valid_months,
                                require_target=require_target)
        # Panel-wide max cross-section, computed BEFORE the date_range
        # bound: the static eval padding width (full_cross_sections).
        # Range-local padding would make eval batch shapes a function of
        # the split boundaries — every walk-forward fold would re-trace
        # the eval/predict forward for a new [M, bf] even when the
        # program cache handed it fold 1's executables. A panel-level
        # constant keeps the shape fold-invariant at the cost of a few
        # weight-0 pad columns on thin ranges.
        self._eval_bf = int(eligible.sum(axis=0).max())
        if date_range is not None:
            lo, hi = date_range
            if not (0 <= lo < hi <= panel.n_months):
                raise ValueError(
                    f"date_range {date_range} outside panel months "
                    f"[0, {panel.n_months})")
            bounded = np.zeros_like(eligible)
            bounded[:, lo:hi] = eligible[:, lo:hi]
            eligible = bounded
        counts = eligible.sum(axis=0)
        self._dates = np.nonzero(counts >= min_cross_section)[0].astype(np.int32)
        if self._dates.size == 0:
            raise ValueError(
                "no date has an eligible cross-section >= "
                f"{min_cross_section}; panel too small for window={window}"
            )
        if self._dates.size < dates_per_batch:
            raise ValueError(
                f"dates_per_batch={dates_per_batch} exceeds the "
                f"{self._dates.size} eligible dates in the panel"
            )
        # Eval sweeps cover every date with any eligible anchor — the
        # min_cross_section filter is a *training* concern only.
        self._all_dates = np.nonzero(counts > 0)[0].astype(np.int32)
        self._firms_by_date = {
            int(t): np.nonzero(eligible[:, t])[0].astype(np.int32)
            for t in self._all_dates
        }
        if self.firms_per_date == 0:
            # Full-universe mode: static Bf from the largest TRAINING pool.
            mx = max(self._firms_by_date[int(t)].size for t in self._dates)
            mult = FIRM_CHUNK if mx >= 2 * FIRM_CHUNK else 8
            self.firms_per_date = -(-mx // mult) * mult
        # CSR pools over the TRAINING dates, for the native sampler.
        pools = [self._firms_by_date[int(t)] for t in self._dates]
        self._pool_offs = np.zeros(len(pools) + 1, np.int64)
        np.cumsum([p.size for p in pools], out=self._pool_offs[1:])
        self._pool_flat = (np.concatenate(pools) if pools
                           else np.zeros(0, np.int32))
        self._epoch = 0

    def _use_native(self) -> bool:
        if self.engine == "python":
            return False
        from lfm_quant_tpu import native

        ok = native.available()
        if not ok and self.engine == "native":
            raise RuntimeError(
                "engine='native' but the native library is unavailable")
        return ok

    def _native_epoch(self, epoch: int) -> WindowIndex:
        """One epoch as stacked [K, D, Bf] arrays from the C++ sampler."""
        import ctypes

        from lfm_quant_tpu import native

        lib = native.get_lib()
        D, bf = self.dates_per_batch, self.firms_per_date
        K = self.batches_per_epoch()
        fi = np.empty((K, D, bf), np.int32)
        ti = np.empty((K, D), np.int32)
        w = np.empty((K, D, bf), np.float32)

        def p(a, ty):
            return a.ctypes.data_as(ctypes.POINTER(ty))

        got = lib.sample_epoch(
            p(self._dates, ctypes.c_int32), self._dates.size,
            p(self._pool_flat, ctypes.c_int32),
            p(self._pool_offs, ctypes.c_int64),
            self.seed, epoch, D, bf,
            p(fi, ctypes.c_int32), p(ti, ctypes.c_int32),
            p(w, ctypes.c_float))
        assert got == K, (got, K)
        return WindowIndex(firm_idx=fi, time_idx=ti, weight=w)

    @property
    def n_eligible_dates(self) -> int:
        return int(self._dates.size)

    def batches_per_epoch(self) -> int:
        return self._dates.size // self.dates_per_batch

    def epoch(self, epoch: Optional[int] = None) -> Iterator[WindowIndex]:
        """Iterate one epoch of batches. Deterministic in (seed, epoch)."""
        if epoch is None:
            epoch = self._epoch
            self._epoch += 1
        if self._use_native():
            b = self._native_epoch(epoch)
            for k in range(b.firm_idx.shape[0]):
                yield WindowIndex(firm_idx=b.firm_idx[k],
                                  time_idx=b.time_idx[k],
                                  weight=b.weight[k])
            return
        rng = np.random.default_rng(
            np.random.SeedSequence([self.seed, epoch, 0xF1B])
        )
        order = rng.permutation(self._dates)
        nb = self.batches_per_epoch()
        bf = self.firms_per_date
        for b in range(nb):
            dsel = order[b * self.dates_per_batch : (b + 1) * self.dates_per_batch]
            if dsel.size < self.dates_per_batch:
                break
            firm_idx = np.empty((dsel.size, bf), dtype=np.int32)
            weight = np.ones((dsel.size, bf), dtype=np.float32)
            for j, t in enumerate(dsel):
                pool = self._firms_by_date[int(t)]
                if pool.size >= bf:
                    firm_idx[j] = rng.choice(pool, size=bf, replace=False)
                else:
                    firm_idx[j, : pool.size] = rng.permutation(pool)
                    firm_idx[j, pool.size :] = pool[
                        rng.integers(0, pool.size, size=bf - pool.size)
                    ]
                    weight[j, pool.size :] = 0.0
            yield WindowIndex(
                firm_idx=firm_idx,
                time_idx=dsel.astype(np.int32),
                weight=weight,
            )

    def stacked_cross_sections(self) -> WindowIndex:
        """All eligible cross-sections as ONE [M, bf] index batch (M eval
        months × padded max cross-section) — a single device dispatch for
        the whole eval/inference sweep, instead of one per month (dispatch
        latency dominates small ops on remote/tunneled devices)."""
        batches = list(self.full_cross_sections())
        return WindowIndex(
            firm_idx=np.concatenate([b.firm_idx for b in batches], axis=0),
            time_idx=np.concatenate([b.time_idx for b in batches], axis=0),
            weight=np.concatenate([b.weight for b in batches], axis=0),
        )

    def stacked_epoch(self, epoch: Optional[int] = None) -> WindowIndex:
        """One whole epoch as a [K, D, Bf] index stack for the in-jit
        multi-step scan (lax.scan over training steps: one dispatch per
        epoch)."""
        if epoch is None:
            epoch = self._epoch
            self._epoch += 1
        if self._use_native():
            return self._native_epoch(epoch)  # already stacked, zero-copy
        batches = list(self.epoch(epoch))
        return WindowIndex(
            firm_idx=np.stack([b.firm_idx for b in batches]),
            time_idx=np.stack([b.time_idx for b in batches]),
            weight=np.stack([b.weight for b in batches]),
        )

    def stacked_eval_months(self) -> int:
        """Number of eval months :meth:`stacked_cross_sections` covers —
        the fold-stacked walk-forward's shape-alignment probe (folds must
        agree on it before their eval sweeps can stack)."""
        return int(self._all_dates.size)

    def months_with_anchors(self) -> np.ndarray:
        """Month indices (panel columns) with ≥1 eligible anchor — the
        scoring service's serveable-month probe (int32, sorted)."""
        return self._all_dates.copy()

    def cross_section(self, t: int) -> np.ndarray:
        """Month ``t``'s eligible firm pool (int32 panel rows; empty
        when the month has no eligible anchor). The per-request universe
        the serving micro-batcher pads into a bucket row."""
        pool = self._firms_by_date.get(int(t))
        return (pool.copy() if pool is not None
                else np.zeros(0, dtype=np.int32))

    def full_cross_sections(self) -> Iterator[WindowIndex]:
        """Deterministic sweep over every eligible (date, firm) pair, for
        eval/inference: each batch is one date's full cross-section padded
        to the PANEL-wide max cross-section (``_eval_bf`` — computed before
        any date_range bound, so the batch shape is split-invariant and
        walk-forward folds reuse one compiled eval program). Covers ALL
        dates with eligible anchors, including those below the training
        ``min_cross_section`` filter."""
        bf = self._eval_bf
        for t in self._all_dates:
            pool = self._firms_by_date[int(t)]
            firm_idx = np.empty((1, bf), dtype=np.int32)
            weight = np.zeros((1, bf), dtype=np.float32)
            firm_idx[0, : pool.size] = pool
            firm_idx[0, pool.size :] = pool[-1] if pool.size else 0
            weight[0, : pool.size] = 1.0
            yield WindowIndex(
                firm_idx=firm_idx,
                time_idx=np.asarray([t], dtype=np.int32),
                weight=weight,
            )

    # ---- geometry buckets (LFM_BUCKETS; DESIGN.md §16) ----------------

    def _safe_lookback_rung(self, months: np.ndarray) -> Dict[int, int]:
        """Per-month smallest SAFE lookback rung: rung r is safe for
        month t iff NO firm in t's eligible pool has a valid month in
        the window gap [t-W+1, t-r] — then the r-step gather sees
        exactly the valid history the full W-step gather sees, and the
        models hold state through masked steps, so outputs are
        bit-identical (the parity contract; keying on valid-month COUNT
        alone would truncate gapped histories and break it)."""
        rungs = lookback_rungs(self.window)
        out = {int(t): self.window for t in months}
        if len(rungs) == 1:
            return out
        full = rolling_valid_count(self._valid, self.window)
        for r in rungs[:-1]:
            # Valid months in [t-W+1, t-r]: anything the r-rung window
            # would drop.
            beyond = full - rolling_valid_count(self._valid, r)
            for t in months:
                t = int(t)
                if out[t] < self.window:
                    continue  # already found a smaller safe rung
                pool = self._firms_by_date[t]
                if pool.size and not beyond[pool, t].any():
                    out[t] = r
        return out

    def bucket_geometry(self) -> BucketGeometry:
        """The sampler's epoch-invariant bucket ladder (memoized).

        Training dates bucket on ``(safe lookback rung, capped_width of
        the date's pool under the static Bf)``; buckets too thin to
        fill one [D]-date batch fold into the CHEAPEST containing
        bucket (>= in both dims, minimal lookback × width cells; the
        ``(window, Bf)`` cap bucket always contains) — padding up is
        always legal, so folding never affects correctness, only
        occupancy. Eval months bucket the
        same way under the panel-wide ``_eval_bf`` cap, with no folding
        (each month is one batch row)."""
        if self._bucket_geo is not None:
            return self._bucket_geo
        D = self.dates_per_batch
        cap = self.firms_per_date
        months = np.unique(np.concatenate([self._dates, self._all_dates]))
        rung = self._safe_lookback_rung(months)

        train: Dict[TrainBucket, List[int]] = {}
        for t in self._dates:
            t = int(t)
            key = (rung[t], capped_width(self._firms_by_date[t].size, cap))
            train.setdefault(key, []).append(t)
        cap_key = (self.window, cap)
        while True:
            small = sorted(k for k, v in train.items() if len(v) < D)
            if not small:
                break
            if small == [cap_key]:
                if len(train) == 1:
                    break  # degenerate tiny panel: one thin cap bucket
                # A thin CAP residue has no container to fold into —
                # fold another bucket INTO it instead (the cap contains
                # every bucket), so no date is silently dropped forever.
                k = min(c for c in train if c != cap_key)
                train[cap_key].extend(train.pop(k))
                continue
            k = next(c for c in small if c != cap_key)
            cands = [c for c in train
                     if c != k and c[0] >= k[0] and c[1] >= k[1]]
            # Cheapest container by per-date cell cost (lookback ×
            # width), not tuple order — folding is a padding tax and
            # (16, 8) at 128 cells beats (8, 64) at 512. Lexicographic
            # tie-break keeps the assignment deterministic.
            dest = (min(cands, key=lambda c: (c[0] * c[1], c))
                    if cands else cap_key)
            train.setdefault(dest, []).extend(train.pop(k))

        evals: Dict[TrainBucket, List[int]] = {}
        for pos, t in enumerate(self._all_dates):
            t = int(t)
            key = (rung[t],
                   capped_width(self._firms_by_date[t].size, self._eval_bf))
            evals.setdefault(key, []).append(pos)

        self._bucket_geo = BucketGeometry(
            window=self.window, width_cap=cap, eval_width_cap=self._eval_bf,
            train_buckets=OrderedDict(
                (k, np.asarray(sorted(v), np.int32))
                for k, v in sorted(train.items())),
            eval_buckets=OrderedDict(
                (k, np.asarray(v, np.int64))
                for k, v in sorted(evals.items())),
        )
        return self._bucket_geo

    def bucketed_batches_per_epoch(self) -> int:
        """Steps per bucketed epoch: Σ over buckets of whole [D]-date
        batches. May differ from :meth:`batches_per_epoch` (per-bucket
        flooring drops up to D-1 dates per bucket instead of per
        epoch) — the trainer threads THIS count into the LR-schedule
        horizon and the program key, so the schedule always matches the
        steps actually taken."""
        geo = self.bucket_geometry()
        return sum(d.size // self.dates_per_batch
                   for d in geo.train_buckets.values())

    def bucketed_epoch(self, epoch: Optional[int] = None
                       ) -> List[Tuple[TrainBucket, WindowIndex]]:
        """One training epoch on the bucket ladder: per bucket, a
        stacked ``[K_b, D, width]`` index batch whose dates are the
        bucket's own (re-shuffled per epoch, deterministic in
        (seed, epoch, bucket)). Shapes are EPOCH-INVARIANT — bucket
        membership and K_b never change — so warm epochs re-dispatch
        the same compiled programs (zero jit traces, the reuse-lane
        guard). A bucketed epoch is its own deterministic stream, not a
        regrouping of :meth:`epoch`'s batches: bucketing changes batch
        COMPOSITION by design (Khomenko-style length grouping); the
        parity contract is per-batch vs max-shape padding, not
        per-epoch vs the unbucketed order."""
        geo = self.bucket_geometry()
        if epoch is None:
            epoch = self._epoch
            self._epoch += 1
        D = self.dates_per_batch
        out: List[Tuple[TrainBucket, WindowIndex]] = []
        for (lb, w), dates in geo.train_buckets.items():
            nb = dates.size // D
            if nb == 0:
                continue  # the cap bucket absorbed a thin residue
            rng = np.random.default_rng(np.random.SeedSequence(
                [self.seed, epoch, 0xB5C, lb, w]))
            order = rng.permutation(dates)
            fi = np.empty((nb, D, w), np.int32)
            ti = np.empty((nb, D), np.int32)
            wt = np.ones((nb, D, w), np.float32)
            for b in range(nb):
                dsel = order[b * D:(b + 1) * D]
                ti[b] = dsel
                for j, t in enumerate(dsel):
                    pool = self._firms_by_date[int(t)]
                    if pool.size >= w:
                        fi[b, j] = rng.choice(pool, size=w, replace=False)
                    else:
                        fi[b, j, :pool.size] = rng.permutation(pool)
                        fi[b, j, pool.size:] = pool[rng.integers(
                            0, pool.size, size=w - pool.size)]
                        wt[b, j, pool.size:] = 0.0
            out.append(((lb, w), WindowIndex(fi, ti, wt)))
        return out

    def bucketed_cross_sections(
            self) -> List[Tuple[TrainBucket, WindowIndex, np.ndarray]]:
        """The eval sweep on the bucket ladder: per bucket, an
        ``[M_b, width]`` batch of its months' full cross-sections (same
        pool layout and pad convention as :meth:`full_cross_sections`,
        just narrower) plus the months' POSITIONS in the
        :meth:`stacked_cross_sections` order — callers scatter
        per-month outputs back through them, so downstream aggregation
        sees exactly the month order the max-shape sweep produces."""
        geo = self.bucket_geometry()
        out: List[Tuple[TrainBucket, WindowIndex, np.ndarray]] = []
        for (lb, w), pos in geo.eval_buckets.items():
            months = self._all_dates[pos]
            fi = np.empty((months.size, w), np.int32)
            wt = np.zeros((months.size, w), np.float32)
            for j, t in enumerate(months):
                pool = self._firms_by_date[int(t)]
                fi[j, :pool.size] = pool
                fi[j, pool.size:] = pool[-1] if pool.size else 0
                wt[j, :pool.size] = 1.0
            out.append(((lb, w),
                        WindowIndex(fi, months.astype(np.int32), wt), pos))
        return out


def stack_fold_epochs(samplers, epoch: int) -> WindowIndex:
    """One training epoch from EACH fold's sampler, stacked on a leading
    fold axis: ``firm_idx [F, K, D, Bf]``, ``time_idx [F, K, D]``,
    ``weight [F, K, D, Bf]`` — the fold-vectorized walk-forward's batch
    supply (train/foldstack.py).

    Per-fold PRNG streams are threaded untouched: entry k is EXACTLY the
    index stack fold k's sequential run would sample for this epoch —
    each sampler keeps its own fold seed and anchor range, and
    ``stacked_epoch`` with an explicit epoch is a pure deterministic read
    (prefetch-thread-safe, same contract as the async pipeline relies
    on). Raises when folds disagree on steps-per-epoch: stacking requires
    the same-shape schedule a rolling ``train_months`` window guarantees,
    and a silent truncation would train some folds on partial epochs.
    """
    per = [s.stacked_epoch(epoch) for s in samplers]
    ks = {b.firm_idx.shape[0] for b in per}
    if len(ks) != 1:
        raise ValueError(
            f"fold-stacked epoch needs equal steps-per-epoch across "
            f"folds, got {sorted(ks)} — use a rolling train_months "
            "window (same-shape folds)")
    return WindowIndex(
        firm_idx=np.stack([b.firm_idx for b in per]),
        time_idx=np.stack([b.time_idx for b in per]),
        weight=np.stack([b.weight for b in per]),
    )


def resolve_gather_impl(impl: str, mesh, panel: Panel, window: int,
                        bf16: bool = False) -> str:
    """Resolve a gather_impl config ("auto"|"xla"|"pallas") against the
    execution context: the Pallas DMA gather (ops/pallas_gather.py) needs
    a real TPU and a panel long enough for an aligned DMA span.

    A mesh no longer disqualifies the fast path: train steps run inside
    ``shard_map`` whenever a mesh exists (train/loop.py), where each shard
    is locally un-partitioned and runs its own pallas_call. Only the eval
    forward stays GSPMD-partitioned under a mesh — trainers route it to
    the XLA gather separately (``Trainer._eval_gather_impl``).

    ``bf16``: the packed panel's compute dtype (cfg.model.bf16). "auto"
    resolves the f32 panel to the XLA gather: every successful on-chip
    gather to date was bf16, while the first f32 DMA-gather attempt was
    the first victim of the 2026-07-30 tunnel wedge and remains the
    prime suspect (scripts/diag_c1.py — the geometry LOWERS cleanly, so
    the failure is compile/runtime-side). Until the staged on-chip
    diagnosis clears it, the DEFAULT must not route users onto the
    suspect path; an explicit ``gather_impl="pallas"`` still forces it
    (that is how the diagnosis itself runs). The parameter FAILS CLOSED:
    callers that don't state the dtype get the safe XLA resolution.
    """
    import jax

    from lfm_quant_tpu.ops.pallas_gather import _aligned_span, padded_months

    if impl not in ("auto", "xla", "pallas"):
        raise ValueError(f"gather_impl must be auto|xla|pallas, got {impl!r}")
    if impl != "auto":
        return impl
    del mesh  # kept in the signature: callers resolve per execution context
    ok = (jax.default_backend() == "tpu"
          and bf16
          and panel.n_months >= window
          and _aligned_span(window, padded_months(panel.n_months)) is not None)
    return "pallas" if ok else "xla"


def device_panel(panel: Panel, sharding=None, compute_dtype=None,
                 raw: bool = True, lane_pad: bool = False) -> dict:
    """Pin the panel's jit-visible arrays in device memory (HBM).

    Returns a dict pytree {features, valid, targets, target_valid, xm} of
    ``jnp`` arrays.  With a ``NamedSharding`` the panel is replicated or
    sharded as requested; by default it lands on the local device.  The
    returns/dates stay host-side — only the training path needs HBM.

    ``xm`` is the hot-path packed panel: features with validity appended as
    one extra column (``[N, T, F+1]``), stored in ``compute_dtype`` (pass
    the model's compute dtype — trainers resolve it once via
    ``config.compute_dtype``, which folds the per-model bf16 flag and
    the whole-stack ``LFM_PRECISION`` lane together; bf16 is numerically
    free for bf16-compute models, which cast inputs anyway, and HALVES
    the resident-panel HBM, every gather's bytes and every panel H2D —
    the mixed-precision lane's footprint win, DESIGN.md §17). Packing exists
    because a separate bool ``valid[firm_idx]`` gather profiled ~2× slower
    on TPU than the 80×-larger feature gather; one fused gather serves
    both.

    ``raw=False`` drops the separate ``features``/``valid`` arrays (the
    trainers only read ``xm`` and ``targets`` — keeping both would double
    the panel's HBM footprint).

    ``lane_pad=True`` makes ``xm`` Pallas-DMA-ready: zero-pads the packed
    width to a 128 multiple AND the month dim to a multiple of 8 (both
    required by ops/pallas_gather.py — 8-aligned superwindow DMAs cannot
    reach the tail of an unpadded month axis). The logical width stays
    ``panel.n_features + 1`` (callers pass it as ``fp``); phantom months
    carry validity 0.
    """
    from lfm_quant_tpu.utils import faults
    from lfm_quant_tpu.utils.telemetry import COUNTERS

    # Chaos lane (utils/faults.py): the panel transfer is the residency
    # layer's only H2D — an injectable failure here exercises every
    # caller's cold-path error handling. Exact no-op when LFM_FAULTS is
    # unset.
    faults.check("panel_h2d", n_firms=panel.n_firms, n_months=panel.n_months)
    put = (lambda x: jax.device_put(x, sharding)) if sharding is not None else jnp.asarray
    # Locked bump, not the property view's `+=`: cold transfers of
    # DIFFERENT panels can now run concurrently (the residency cache
    # builds outside its lock), and a read-modify-write would lose
    # increments the reuse lanes assert on exactly.
    COUNTERS.bump("panel_transfers")
    xm = np.concatenate(
        [panel.features, panel.valid[..., None].astype(panel.features.dtype)],
        axis=-1,
    )
    # Host→device bytes are the scarce resource (the axon tunnel moves
    # ~MBs/sec): cast to the compute dtype ON THE HOST (ml_dtypes handles
    # bf16 in numpy) so the wire carries 2-byte elements, and apply the
    # 128-lane/8-month pallas padding ON THE DEVICE so the wire never
    # carries padding (6× fewer bytes at 20 features).
    if compute_dtype is not None:
        import ml_dtypes  # numpy bf16 etc. — ships with jax

        xm = xm.astype(ml_dtypes.bfloat16 if compute_dtype == jnp.bfloat16
                       else compute_dtype)
    xm_dev = put(xm)
    if lane_pad:
        from lfm_quant_tpu.ops.pallas_gather import padded_lanes, padded_months

        pad_f = padded_lanes(xm.shape[-1]) - xm.shape[-1]
        pad_t = padded_months(xm.shape[1]) - xm.shape[1]
        if pad_f or pad_t:
            xm_dev = put(jnp.pad(
                xm_dev, ((0, 0), (0, pad_t), (0, pad_f))))
    dev = {
        "targets": put(panel.targets),
        "target_valid": put(panel.target_valid),
        "xm": xm_dev,
    }
    if raw:
        dev["features"] = put(panel.features)
        dev["valid"] = put(panel.valid)
    COUNTERS.bump("panel_bytes", int(
        xm.nbytes + panel.targets.nbytes + panel.target_valid.nbytes
        + (panel.features.nbytes + panel.valid.nbytes if raw else 0)))
    return dev


# ---- shared device-panel residency (cross-fold reuse layer) ------------
#
# A walk-forward sweep re-transfers the SAME HBM-resident panel once per
# fold because every fold's Trainer calls device_panel afresh. Over the
# axon tunnel (~MBs/sec) that is the second-largest fixed cost after XLA
# recompilation. The cache below makes the transfer once-per-(panel,
# placement, dtype, padding) for the whole process, with explicit
# invalidation. Entries are keyed by PANEL OBJECT IDENTITY (content
# hashing a [N, T, F] array per lookup would defeat the purpose) plus
# the mesh fingerprint — a mutated-in-place panel therefore requires an
# explicit invalidate_panel() call, same contract as any residency
# cache. Garbage-collected panels evict themselves (weakref.finalize),
# so id() reuse can never alias a dead entry.
#
# Concurrency (serving): the scoring service dispatches from a
# micro-batcher thread while a refresh fit (or an operator invalidation)
# runs on another, so the cache is lock-guarded and every entry carries
# a LEASE COUNT. ``lease_device_panel`` pins an entry for the duration
# of a dispatch; ``invalidate_panel`` during an in-flight lease removes
# the entry from the cache immediately (new readers re-transfer fresh
# bytes) but defers the final drop to the last release — a live
# dispatch can never observe its panel arrays torn out from under it,
# and two racing readers can never double-transfer the same panel.

_PANEL_LOCK = threading.RLock()
_PANEL_CACHE: dict = {}  # key -> _PanelEntry


class _PanelEntry:
    """One resident device panel + its residency bookkeeping.

    ``dev`` is None while the H2D transfer is still in flight (the
    ``ready`` event gates waiters); the entry enters the cache as a
    placeholder FIRST so same-key racers wait instead of
    double-transferring, while different keys proceed untouched."""

    __slots__ = ("key", "dev", "leases", "doomed", "ready")

    def __init__(self, key):
        self.key = key
        self.dev: Optional[dict] = None
        self.leases = 0       # in-flight dispatches pinning this entry
        self.doomed = False   # invalidated while leased — drop on release
        self.ready = threading.Event()


def _panel_cache_key(panel, mesh, compute_dtype, raw, lane_pad):
    from lfm_quant_tpu.parallel.mesh import mesh_fingerprint

    return (id(panel), mesh_fingerprint(mesh),
            jnp.dtype(compute_dtype).name if compute_dtype is not None
            else None, bool(raw), bool(lane_pad))


def _gc_pop(key) -> None:
    with _PANEL_LOCK:
        _PANEL_CACHE.pop(key, None)


def _get_or_transfer(panel: Panel, mesh, compute_dtype, raw,
                     lane_pad) -> "_PanelEntry":
    """Entry for the key, transferring on miss. Two threads racing a
    cold key pay exactly ONE H2D: the first inserts a placeholder entry
    and transfers OUTSIDE the cache lock; same-key racers wait on the
    entry's ready event; other keys' readers (the serving hot path
    leasing an already-resident panel) are never blocked behind a
    multi-second cold transfer — a refresh binding a new panel must not
    spike every universe's serving latency."""
    # Imported BEFORE any placeholder is inserted: an import failure
    # (or an interrupt delivered inside it) after the placeholder
    # would strand a never-ready entry that hangs all future readers.
    from lfm_quant_tpu.parallel.mesh import replicated

    key = _panel_cache_key(panel, mesh, compute_dtype, raw, lane_pad)
    while True:
        with _PANEL_LOCK:
            entry = _PANEL_CACHE.get(key)
            if entry is not None and entry.dev is not None:
                from lfm_quant_tpu.utils.telemetry import COUNTERS

                # Locked bump for the same reason as device_panel's
                # transfer counters — no bare `+=` RMWs on counters the
                # lanes assert exact values on.
                COUNTERS.bump("panel_cache_hits")
                return entry
            if entry is None:
                entry = _PANEL_CACHE[key] = _PanelEntry(key)
                # Evict on panel gc: entries must never outlive their
                # panel (id() reuse would silently serve another
                # panel's bytes).
                weakref.finalize(panel, _gc_pop, key)
                building = True
            else:
                building = False  # someone else's transfer in flight
        if not building:
            entry.ready.wait()
            continue  # re-read: ready entry, or invalidated → rebuild
        try:
            sharding = replicated(mesh) if mesh is not None else None
            dev = device_panel(panel, sharding,
                               compute_dtype=compute_dtype, raw=raw,
                               lane_pad=lane_pad)
        except BaseException:
            with _PANEL_LOCK:
                if _PANEL_CACHE.get(key) is entry:
                    del _PANEL_CACHE[key]
            entry.ready.set()  # waiters retry (and become the builder)
            raise
        entry.dev = dev
        entry.ready.set()
        # The entry may have been invalidated mid-transfer (popped +
        # doomed): it still serves THIS caller — fresh bytes from the
        # live panel object — and waiters re-read the cache.
        return entry


def cached_device_panel(panel: Panel, mesh=None, compute_dtype=None,
                        raw: bool = False, lane_pad: bool = False) -> dict:
    """:func:`device_panel` behind the per-process residency cache.

    ``mesh`` replaces device_panel's raw ``sharding`` argument: the
    placement every trainer actually wants is replicated-over-mesh (or
    default-device when None), and taking the mesh keeps the cache key
    well-defined (NamedShardings over equal meshes compare equal, but
    fingerprinting the mesh directly is simpler and covers None). A hit
    returns the SAME device arrays the previous trainer bound — zero H2D
    traffic — and bumps ``REUSE_COUNTERS.panel_cache_hits``; a miss
    transfers via device_panel (which bumps the transfer counters).
    """
    return _get_or_transfer(panel, mesh, compute_dtype, raw, lane_pad).dev


@contextlib.contextmanager
def lease_device_panel(panel: Panel, mesh=None, compute_dtype=None,
                       raw: bool = False, lane_pad: bool = False):
    """:func:`cached_device_panel` with the entry PINNED for the block:
    the serving dispatch path wraps every scoring dispatch in a lease so
    a concurrent :func:`invalidate_panel` (monthly data arrival, zoo
    eviction) can never finalize the entry mid-dispatch. Yields the same
    dev dict ``cached_device_panel`` would return."""
    entry = _get_or_transfer(panel, mesh, compute_dtype, raw, lane_pad)
    with _PANEL_LOCK:
        entry.leases += 1
    try:
        yield entry.dev
    finally:
        with _PANEL_LOCK:
            entry.leases -= 1
            if entry.doomed and entry.leases == 0:
                # Last reader of an invalidated entry: the deferred drop
                # (the entry left the cache at invalidation; its arrays
                # are freed by GC once this reference dies). Counted so
                # the regression tests can assert the deferral happened.
                from lfm_quant_tpu.utils.telemetry import COUNTERS

                COUNTERS.bump("panel_deferred_drops")


def invalidate_panel(panel: Panel) -> int:
    """Drop every cached device copy of ``panel`` (all placements/dtypes)
    — the TRAINING residency cache here AND the backtest engine's
    scoring-panel cache (returns/targets/tradeability;
    backtest/jax_engine.py), so one call covers every device copy a
    mutated-in-place panel could go stale in. Entries with in-flight
    leases are marked doomed and finalized at the LAST release instead
    of immediately (refcount-safe: a live scoring dispatch keeps its
    arrays); either way the entry leaves the cache NOW, so the next
    reader re-transfers fresh bytes. Returns the number of
    training-cache entries dropped (the reuse tests' counter; scoring
    entries are dropped on top)."""
    with _PANEL_LOCK:
        doomed = [k for k in _PANEL_CACHE if k[0] == id(panel)]
        for k in doomed:
            entry = _PANEL_CACHE.pop(k)
            if entry.leases > 0:
                entry.doomed = True
    try:
        from lfm_quant_tpu.backtest.jax_engine import invalidate_score_panel

        invalidate_score_panel(panel)
    except ImportError:  # scoring engine unavailable — nothing resident
        pass
    return len(doomed)


def clear_panel_cache() -> None:
    """Drop all cached device panels (tests / memory pressure)."""
    with _PANEL_LOCK:
        _PANEL_CACHE.clear()


def _slice_windows(rows, vrows, time_idx, window: int):
    """Shared fast-path core: per-date window slice of pre-gathered firm
    rows.

    rows ``[D, Bf, T, F]``, vrows ``[D, Bf, T]`` bool, time_idx ``[D]`` →
    ``(x [D, Bf, W, F], m [D, Bf, W])``. Anchors younger than the window
    clamp the slice start to 0 and roll so the anchor still sits at the
    LAST position (wrapped future months land at the front mask-False).
    """
    T = rows.shape[2]
    start = jnp.clip(time_idx - (window - 1), 0, max(0, T - window))

    def slice_date(r, v, s, t):
        xw = jax.lax.dynamic_slice_in_dim(r, s, window, axis=1)
        vw = jax.lax.dynamic_slice_in_dim(v, s, window, axis=1)
        pos = s + jnp.arange(window, dtype=jnp.int32)
        mw = vw & (pos <= t)[None, :]
        shift = (window - 1) - (t - s)
        return jnp.roll(xw, shift, axis=1), jnp.roll(mw, shift, axis=1)

    x, m = jax.vmap(slice_date)(rows, vrows, start, time_idx)
    x = jnp.where(m[..., None], x, jnp.zeros((), dtype=rows.dtype))
    return x, m


def _is_date_layout(firm_idx, time_idx) -> bool:
    return (
        time_idx.ndim == 1
        and firm_idx.ndim == 2
        and time_idx.shape[0] == firm_idx.shape[0]
    )


def gather_windows(
    features: jax.Array,
    valid: jax.Array,
    firm_idx: jax.Array,
    time_idx: jax.Array,
    window: int,
):
    """On-device window gather: index batch → (windows, mask).

    Args:
      features: ``[N, T, F]`` panel features (device-resident).
      valid:    ``[N, T]`` bool validity.
      firm_idx: ``[..., ]`` int32 firm rows (any batch shape).
      time_idx: int32 anchor months, broadcastable to ``firm_idx``'s shape
        (the [D, Bf] layout passes ``[D]`` against ``[D, Bf]``).
      window:   static lookback length W.

    Returns:
      ``(x, m)`` where ``x`` is ``[..., W, F]`` float windows (invalid steps
      zero-filled) and ``m`` is ``[..., W]`` bool step-validity.

    TPU note: the hot [D, Bf] path deliberately avoids XLA's general
    (firm, month) pair gather — on TPU that lowers to a scalar-indexed
    gather that profiled at ~55% of the whole train step. Instead it does a
    contiguous *firm-row* gather (each row is a [T, F] block) followed by a
    per-date ``dynamic_slice`` on the month axis (every firm in a date row
    shares the anchor); see ``_slice_windows``. The fast path materializes
    ``[D, Bf, T, F]`` — callers with a large leading axis (eval sweeps)
    must chunk it (Trainer._forward_impl does, via ``lax.map``).
    """
    if _is_date_layout(firm_idx, time_idx) and features.shape[1] >= window:
        return _slice_windows(
            features[firm_idx], valid[firm_idx], time_idx, window)

    # General fallback: pairwise gather (any index shape; also the T < W
    # case, where a window-length slice cannot exist).
    if time_idx.ndim == firm_idx.ndim - 1:
        time_idx = time_idx[..., None]
    time_b = jnp.broadcast_to(time_idx, firm_idx.shape)
    offs = jnp.arange(window, dtype=jnp.int32) - (window - 1)
    t = time_b[..., None] + offs  # [..., W]
    in_range = t >= 0
    t_c = jnp.clip(t, 0, features.shape[1] - 1)
    f = firm_idx[..., None]  # [..., 1] broadcast over W
    x = features[f, t_c]  # [..., W, F]
    m = valid[f, t_c] & in_range  # [..., W]
    x = jnp.where(m[..., None], x, jnp.zeros((), dtype=features.dtype))
    return x, m


def gather_windows_packed(
    xm: jax.Array,
    firm_idx: jax.Array,
    time_idx: jax.Array,
    window: int,
    fp: Optional[int] = None,
    firm_chunk: Optional[int] = None,
):
    """Hot-path window gather over the packed panel (``device_panel``'s
    ``xm``: ``[N, T, F+1]`` with validity as the last column).

    Expects the [D, Bf] training/eval layout (``firm_idx [D, Bf]``,
    ``time_idx [D]``). One contiguous firm-row gather + per-date
    ``dynamic_slice`` on the month axis; see ``gather_windows`` for why —
    including the caller-must-chunk caveat for large leading axes.
    Returns ``(x [D, Bf, W, F], m [D, Bf, W] bool)`` with ``x`` in
    ``xm.dtype`` (store bf16 for bf16 models — they cast inputs anyway).

    ``fp``: the LOGICAL packed width (features + validity column). Pass it
    when ``xm`` is lane-padded for the Pallas DMA gather
    (``device_panel(..., lane_pad=True)``) — the validity column then sits
    at ``fp - 1``, not at the (zero-padding) last column.

    ``firm_chunk``: chunk the firm axis with ``lax.map`` so the [D, Bf, T,
    Fp] row transient never materializes whole — required at full-universe
    widths (Bf ≈ the whole cross-section). Applied only when it divides
    ``Bf``; pass ``FIRM_CHUNK`` (the sampler rounds full-universe Bf to a
    multiple of it) or None to disable.
    """
    fp = fp or xm.shape[-1]
    if not (_is_date_layout(firm_idx, time_idx) and xm.shape[1] >= window):
        return gather_windows(
            xm[..., :fp - 1], xm[..., fp - 1] != 0, firm_idx, time_idx,
            window)
    D, bf = firm_idx.shape
    if firm_chunk and bf > firm_chunk:
        # Non-multiple widths (eval sweeps pad Bf to the raw max pool) are
        # padded with firm-0 repeats and sliced back after — the bound on
        # the row transient must hold for every caller, not just widths
        # the sampler pre-rounded.
        pad = -bf % firm_chunk
        fi_p = (jnp.pad(firm_idx, ((0, 0), (0, pad))) if pad else firm_idx)
        fi = fi_p.reshape(D, (bf + pad) // firm_chunk, firm_chunk)
        fi = jnp.swapaxes(fi, 0, 1)  # [C, D, chunk]

        def one(fic):
            rows = xm[fic]  # [D, chunk, T, Fp]
            return _slice_windows(
                rows[..., :fp - 1], rows[..., fp - 1] != 0, time_idx,
                window)

        x, m = jax.lax.map(one, fi)  # [C, D, chunk, W, F], [C, D, chunk, W]
        x = jnp.swapaxes(x, 0, 1).reshape(D, bf + pad, window, x.shape[-1])
        m = jnp.swapaxes(m, 0, 1).reshape(D, bf + pad, window)
        return x[:, :bf], m[:, :bf]
    rows = xm[firm_idx]  # [D, Bf, T, Fp] contiguous row gather
    return _slice_windows(
        rows[..., :fp - 1], rows[..., fp - 1] != 0, time_idx, window)


def gather_targets(targets: jax.Array, firm_idx: jax.Array, time_idx: jax.Array):
    """Gather anchor-month targets for an index batch → ``firm_idx``-shaped."""
    if time_idx.ndim == firm_idx.ndim - 1:
        time_idx = time_idx[..., None]
    t = jnp.broadcast_to(time_idx, firm_idx.shape)
    return targets[firm_idx, t]
