"""Real-data loader: Compustat-style long-format files → Panel (L1).

Parity target: the reference's panel loader / preprocessor reading
Compustat-style fundamentals into firm×month matrices (SURVEY.md §3;
BASELINE.json:5). The reference's format was unobservable (SURVEY.md §0),
so this loader defines and documents its own simple interchange schema:

Long format (CSV or parquet), one row per (firm, month):

    gvkey,yyyymm,<feature columns...>,ret
    1001,199001,0.08,1.2,...,0.013

* ``gvkey``   — integer firm identifier (any stable int id).
* ``yyyymm``  — calendar month.
* features   — raw fundamental/price-derived columns (any numeric names).
* ``ret``     — TRAILING 1-month total return (month t-1 → t close), the
  convention vendor files use; converted to the forward returns the
  backtester needs.

Preprocessing (the standard cross-sectional factor recipe):

1. winsorize each feature per month at configurable quantiles;
2. z-score each feature within the month's cross-section (so every
   feature is a comparable cross-sectional signal, and the planted-signal
   tests on synthetic data transfer to real data unchanged);
3. the forecast target at anchor t is the *standardized* value of
   ``target_col`` observed at t+horizon (lookahead-factor convention:
   predict where the firm's factor will stand a year from now);
4. validity masks from row presence; missing (firm, month) rows or NaN
   features ⇒ invalid cell, zero-filled.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np
import pandas as pd

from lfm_quant_tpu.data.panel import Panel

RESERVED = ("gvkey", "yyyymm", "ret")


def _read_table(path: str) -> pd.DataFrame:
    if path.endswith((".parquet", ".pq")):
        return pd.read_parquet(path)
    return pd.read_csv(path)


def _parse_pandas(path, feature_cols):
    """→ (gvkey[int32 R], yyyymm[int32 R], feats[f32 R×F], ret[f32 R]|None,
    feature_cols). NaN marks missing feature/return fields."""
    df = _read_table(path)
    missing = [c for c in ("gvkey", "yyyymm") if c not in df.columns]
    if missing:
        raise ValueError(f"input file lacks required columns {missing}")
    if feature_cols is None:
        feature_cols = [
            c for c in df.columns
            if c not in RESERVED and pd.api.types.is_numeric_dtype(df[c])
        ]
        ignored = [c for c in df.columns
                   if c not in RESERVED and c not in feature_cols]
        if ignored:
            import sys

            print(f"load_compustat_csv: ignoring non-numeric columns "
                  f"{ignored}", file=sys.stderr)
    else:
        absent = [c for c in feature_cols if c not in df.columns]
        if absent:
            raise ValueError(f"feature columns {absent} not in file")
    gvkey = df["gvkey"].to_numpy(dtype=np.int32)
    yyyymm = df["yyyymm"].to_numpy(dtype=np.int32)
    feats = (df[list(feature_cols)].to_numpy(dtype=np.float32)
             if feature_cols else
             np.zeros((len(df), 0), np.float32))
    ret = (df["ret"].to_numpy(dtype=np.float32)
           if "ret" in df.columns else None)
    return gvkey, yyyymm, feats, ret, list(feature_cols)


def _parse_native(path, feature_cols):
    """Native C++ CSV parse (lfm_quant_tpu.native) — same contract as
    :func:`_parse_pandas`; returns None when the native library is
    unavailable so the caller can fall back."""
    from lfm_quant_tpu import native

    lib = native.get_lib()
    if lib is None:
        return None
    import ctypes
    import csv as _csv
    import io

    # ONE disk read: the bytes are passed straight into the (non-mutating)
    # C parser; header/first-row sniffing reuses the same buffer. csv.reader
    # handles RFC-4180 quoting in the header, matching the C field scanner.
    with open(path, "rb") as fh:
        data = fh.read()
    head_bytes = data[:1 << 20]
    if len(data) > len(head_bytes):
        # The buffer cut the file mid-row: drop the trailing partial line
        # or the sniff would misread a truncated numeric ('1.25e-') as text.
        head_bytes = head_bytes[:head_bytes.rfind(b"\n") + 1]
    head = io.StringIO(head_bytes.decode("utf-8", "replace"))
    reader = _csv.reader(head)
    header = next(reader, [])
    cols = {c: i for i, c in enumerate(header)}
    missing = [c for c in ("gvkey", "yyyymm") if c not in cols]
    if missing:
        raise ValueError(f"input file lacks required columns {missing}")
    if feature_cols is None:
        # Type-sniff candidate feature columns over MANY rows (the whole
        # 1 MB head buffer, up to 4096 rows) — a single-row sniff
        # misclassifies sparse text columns whose first value is blank,
        # and silently NaNs text in mostly-numeric columns. A column is
        # numeric iff no scanned non-empty value fails float(); all-empty
        # columns stay included (pandas parses those as float NaN columns,
        # so inclusion is the parity behavior).
        saw_text = [False] * len(header)
        n_scanned = 0
        for row in reader:
            if not row:
                continue
            for i in range(min(len(row), len(header))):
                v = row[i].strip().strip('"')  # parser strips quotes too
                if not v:
                    continue
                try:
                    float(v)
                except ValueError:
                    saw_text[i] = True
            n_scanned += 1
            if n_scanned >= 4096:
                break

        feature_cols = [c for c in header
                        if c not in RESERVED and not saw_text[cols[c]]]
        ignored = [c for c in header
                   if c not in RESERVED and c not in feature_cols]
        if ignored:
            import sys

            print(f"load_compustat_csv: ignoring non-numeric columns "
                  f"{ignored}", file=sys.stderr)
    else:
        absent = [c for c in feature_cols if c not in cols]
        if absent:
            raise ValueError(f"feature columns {absent} not in file")

    n_rows = max(data.count(b"\n"), 1)  # capacity bound (header + blanks)
    F = len(feature_cols)
    gvkey = np.empty(n_rows, np.int32)
    yyyymm = np.empty(n_rows, np.int32)
    feats = np.empty((n_rows, max(F, 1)), np.float32)
    has_ret = "ret" in cols
    ret = np.empty(n_rows, np.float32) if has_ret else None
    feat_idx = np.asarray([cols[c] for c in feature_cols], np.int32)

    def ptr(a, ty):
        return a.ctypes.data_as(ctypes.POINTER(ty)) if a is not None else None

    got = lib.csv_parse_buf(
        data, len(data), len(header), cols["gvkey"], cols["yyyymm"],
        cols.get("ret", -1), ptr(feat_idx, ctypes.c_int32), F, n_rows,
        ptr(gvkey, ctypes.c_int32), ptr(yyyymm, ctypes.c_int32),
        ptr(feats, ctypes.c_float), ptr(ret, ctypes.c_float))
    if got < 0:
        raise ValueError(f"{path}: malformed data row {-got} "
                         "(bad gvkey/yyyymm field)")
    n = int(got)  # blank lines make got < the newline-count estimate
    return (gvkey[:n], yyyymm[:n], feats[:n, :F], ret[:n] if has_ret else
            None, list(feature_cols))


def _month_grid(months: np.ndarray) -> np.ndarray:
    """Full consecutive YYYYMM range spanning the observed months."""
    lo, hi = int(months.min()), int(months.max())
    y, m = lo // 100, lo % 100
    out = []
    while y * 100 + m <= hi:
        out.append(y * 100 + m)
        m += 1
        if m > 12:
            m, y = 1, y + 1
    return np.asarray(out, dtype=np.int32)


def load_compustat_csv(
    path: str,
    feature_cols: Optional[Sequence[str]] = None,
    target_col: Optional[str] = None,
    horizon: int = 12,
    winsor: Tuple[float, float] = (0.01, 0.99),
    min_cross_section: int = 5,
    engine: str = "auto",
) -> Panel:
    """Load a long-format fundamentals file into a :class:`Panel`.

    Args:
      path: CSV or parquet file in the documented schema.
      feature_cols: columns to use as features (default: every non-reserved
        numeric column, in file order).
      target_col: which (standardized) feature the model forecasts
        ``horizon`` months ahead (default: the first feature).
      horizon: forecast lookahead in months.
      winsor: per-month winsorization quantiles (lo, hi); None disables.
      min_cross_section: months with fewer valid firms than this are left
        unstandardized-invalid (degenerate z-scores are worse than no data).
      engine: "auto" (native C++ parser for .csv when built, else pandas),
        "native", or "pandas". On well-formed numeric files (including
        RFC-4180 quoted fields) the engines produce identical panels; the
        native one (lfm_quant_tpu/native/) parses ~1.8× faster than the
        pandas C parser (measured at c5 scale — 418 MB / 5.3M rows:
        parse-only 2.0–2.1 s vs 3.8–4.9 s, end-to-end load 6.2 s vs
        8.0 s; `scripts/dress_rehearsal.py` reproduces the artifact). One
        divergence remains: with ``feature_cols=None`` the native engine
        type-sniffs from the first ~4096 rows (1 MB), pandas from whole
        columns — pass explicit ``feature_cols`` for files whose first
        text value appears later than that.
    """
    if engine not in ("auto", "native", "pandas"):
        raise ValueError(f"engine must be auto|native|pandas, got {engine!r}")
    parsed = None
    if engine in ("auto", "native") and path.endswith(".csv"):
        parsed = _parse_native(path, feature_cols)
        if parsed is None and engine == "native":
            raise RuntimeError(
                "engine='native' but the native library is unavailable "
                "(no toolchain, or the build failed — see stderr)")
    elif engine == "native":
        raise ValueError("engine='native' supports only .csv inputs")
    if parsed is None:
        parsed = _parse_pandas(path, feature_cols)
    gvkey, yyyymm, row_feats, row_rets, feature_cols = parsed

    if not feature_cols:
        raise ValueError("no feature columns found")
    if target_col is None:
        target_col = feature_cols[0]
    if target_col not in feature_cols:
        raise ValueError(
            f"target_col {target_col!r} must be one of the features "
            f"{list(feature_cols)}")

    key = gvkey.astype(np.int64) * 1_000_000 + yyyymm
    uniq, counts = np.unique(key, return_counts=True)
    if (counts > 1).any():
        bad = uniq[counts > 1][:3]
        raise ValueError(
            "duplicate (gvkey, yyyymm) rows, e.g. "
            f"{[(int(k // 1_000_000), int(k % 1_000_000)) for k in bad]}")

    dates = _month_grid(yyyymm)
    firms = np.unique(gvkey).astype(np.int32)
    n, t, f = len(firms), len(dates), len(feature_cols)
    rows = np.searchsorted(firms, gvkey)
    cols = np.searchsorted(dates, yyyymm)
    # searchsorted maps an off-grid month (e.g. 199913) to its insertion
    # point — validate exact grid membership or rows would silently land
    # in the wrong month's cell.
    bad = dates[np.minimum(cols, t - 1)] != yyyymm
    if bad.any():
        idx = np.nonzero(bad)[0][:3]
        raise ValueError(
            "rows with invalid yyyymm (not a real calendar month): "
            f"{[(int(gvkey[i]), int(yyyymm[i])) for i in idx]}")

    feats = np.full((n, t, f), np.nan, dtype=np.float32)
    rets = np.full((n, t), np.nan, dtype=np.float32)
    feats[rows, cols] = row_feats
    has_ret = row_rets is not None
    if has_ret:
        rets[rows, cols] = row_rets

    valid = ~np.isnan(feats).any(axis=2)

    # Per-month winsorize + z-score over the valid cross-section — the
    # shared recipe (data/features.py winsorize_zscore) so derived
    # columns standardize identically.
    from lfm_quant_tpu.data.features import winsorize_zscore

    for j in range(t):
        rowsel = valid[:, j]
        if rowsel.sum() < min_cross_section:
            valid[:, j] = False
            continue
        feats[rowsel, j, :] = winsorize_zscore(feats[rowsel, j, :], winsor)

    feats = np.where(valid[..., None], feats, 0.0).astype(np.float32)

    # Targets: standardized target feature at t+horizon.
    ti = list(feature_cols).index(target_col)
    targets = np.zeros((n, t), dtype=np.float32)
    target_valid = np.zeros((n, t), dtype=bool)
    if horizon < t:
        future = feats[:, horizon:, ti]
        fvalid = valid[:, horizon:]
        targets[:, :-horizon] = np.where(fvalid, future, 0.0)
        target_valid[:, :-horizon] = valid[:, :-horizon] & fvalid

    # Returns: vendor files carry trailing returns (t-1 → t); the backtest
    # wants the forward return earned from holding over [t, t+1]. A missing
    # t+1 observation (delisting, gap) makes the forward return UNOBSERVED
    # — flagged in ret_valid, never fabricated as 0% (delisting bias).
    fwd = np.zeros((n, t), dtype=np.float32)
    ret_valid = np.zeros((n, t), dtype=bool)
    if not has_ret:
        # No return data at all: every cell unobserved; backtests on this
        # panel are meaningless and will raise on an empty universe.
        pass
    elif t > 1:
        nxt = rets[:, 1:]
        obs = ~np.isnan(nxt)
        fwd[:, :-1] = np.where(obs, nxt, 0.0)
        ret_valid[:, :-1] = obs & valid[:, :-1]
    fwd = np.where(valid, fwd, 0.0).astype(np.float32)

    panel = Panel(
        features=feats,
        targets=targets,
        target_valid=target_valid,
        valid=valid,
        returns=fwd,
        dates=dates,
        firm_ids=firms,
        feature_names=list(feature_cols),
        horizon=horizon,
        ret_valid=ret_valid,
    )
    panel.validate()
    return panel


def to_long_frame(panel: Panel) -> pd.DataFrame:
    """Inverse helper: Panel → long-format DataFrame (fixtures, exports).
    Emits one row per valid (firm, month); ``ret`` is re-expressed in the
    trailing convention (row t carries the return from t-1 to t)."""
    n, t = panel.valid.shape
    fi, ti = np.nonzero(panel.valid)
    data = {
        "gvkey": panel.firm_ids[fi],
        "yyyymm": panel.dates[ti],
    }
    for k, name in enumerate(panel.feature_names):
        data[name] = panel.features[fi, ti, k]
    trailing = np.zeros_like(panel.returns)
    trailing[:, 1:] = panel.returns[:, :-1]
    data["ret"] = trailing[fi, ti]
    return pd.DataFrame(data)
