"""Data layer: panel store (L1) and windowing pipeline (L2)."""

from lfm_quant_tpu.data.panel import Panel, PanelSplits, load_panel, synthetic_panel
from lfm_quant_tpu.data.windows import (
    DateBatchSampler,
    WindowIndex,
    anchor_index,
    device_panel,
    gather_targets,
    gather_windows,
    gather_windows_packed,
)

__all__ = [
    "Panel",
    "PanelSplits",
    "load_panel",
    "synthetic_panel",
    "WindowIndex",
    "anchor_index",
    "DateBatchSampler",
    "device_panel",
    "gather_targets",
    "gather_windows",
    "gather_windows_packed",
]
