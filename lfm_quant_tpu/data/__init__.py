"""Data layer: panel store (L1) and windowing pipeline (L2)."""

from lfm_quant_tpu.data.panel import Panel, PanelSplits, load_panel, synthetic_panel
from lfm_quant_tpu.data.windows import (
    DateBatchSampler,
    WindowIndex,
    anchor_index,
    cached_device_panel,
    clear_panel_cache,
    device_panel,
    gather_targets,
    gather_windows,
    gather_windows_packed,
    invalidate_panel,
)

__all__ = [
    "Panel",
    "PanelSplits",
    "load_panel",
    "synthetic_panel",
    "WindowIndex",
    "anchor_index",
    "DateBatchSampler",
    "cached_device_panel",
    "clear_panel_cache",
    "device_panel",
    "gather_targets",
    "gather_windows",
    "gather_windows_packed",
    "invalidate_panel",
]
