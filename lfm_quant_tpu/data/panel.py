"""Panel store (L1): aligned firm×month matrices + synthetic generator.

Functional parity target: the reference's Compustat-style panel loader /
preprocessor (SURVEY.md §3, BASELINE.json:5 — "BatchGenerator/Dataset
pipeline streams Compustat-style firm×month panels"). The reference code was
not observable (SURVEY.md §0), so the schema here is designed TPU-first:

* The whole panel is a small number of dense rectangular arrays
  (``[N_firms, T_months, F]`` features + ``[N, T]`` masks/targets/returns).
  The full 1970–2024 panel at 20 features is O(10^8) floats — it fits in a
  single v5e chip's HBM, so the framework keeps the panel *device-resident*
  and gathers lookback windows on-device (see data/windows.py) instead of
  host-streaming batches the way a tf.data input pipeline would.
* Ragged firm histories (IPO/delisting) are encoded in a validity mask, not
  by ragged tensors — static shapes keep everything jit/pjit friendly.

The synthetic generator plants a known linear+nonlinear signal mapping
trailing fundamentals to the forecast target, so tests can assert that
training recovers the signal (SURVEY.md §5) and the backtest recovers alpha.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional, Sequence, Tuple

import numpy as np

DEFAULT_FEATURES_5 = (
    "ebit_ev",  # earnings yield style value factor
    "book_to_market",
    "asset_growth",
    "momentum_12m",
    "accruals",
)

_EXTRA_FEATURES = (
    "gross_profitability",
    "roe",
    "roa",
    "leverage",
    "sales_growth",
    "capex_to_assets",
    "rnd_to_sales",
    "cash_to_assets",
    "dividend_yield",
    "short_term_reversal",
    "volatility_12m",
    "turnover",
    "size_log_mktcap",
    "earnings_variability",
    "net_share_issuance",
)

DEFAULT_FEATURES_20 = DEFAULT_FEATURES_5 + _EXTRA_FEATURES


@dataclasses.dataclass
class Panel:
    """A firm×month fundamentals panel in dense, mask-annotated form.

    Attributes:
      features: ``[N, T, F]`` float32 — standardized fundamental features.
        Invalid (firm, month) cells are zero-filled.
      targets:  ``[N, T]`` float32 — the supervised forecast target aligned to
        the *anchor* month: ``targets[i, t]`` is the future-fundamental value
        (e.g. EBIT/EV twelve months ahead) that a model predicting at month
        ``t`` is scored against.  Zero-filled where invalid.
      target_valid: ``[N, T]`` bool — target observable (anchor valid AND the
        lookahead month exists; False in the last ``horizon`` live months of
        a firm's history and after delisting).
      valid:    ``[N, T]`` bool — firm has data at month t (between first and
        last live month, minus missing rows).
      returns:  ``[N, T]`` float32 — forward 1-month total return from month
        t to t+1, used by the backtester. Zero-filled where invalid.
      ret_valid: ``[N, T]`` bool or None — forward return OBSERVED (firm
        still listed at t+1). None means "trust ``valid``". Distinct from
        ``valid`` to prevent delisting/survivorship bias: a firm with
        features at t but no t+1 observation must be excluded from the
        month-t tradeable universe, not credited a fabricated 0% return.
      dates:    ``[T]`` int32 — months as YYYYMM.
      firm_ids: ``[N]`` int32 — stable firm identifiers (gvkey-style).
      feature_names: length-F list of feature names.
      horizon:  months between anchor and target observation (default 12).
    """

    features: np.ndarray
    targets: np.ndarray
    target_valid: np.ndarray
    valid: np.ndarray
    returns: np.ndarray
    dates: np.ndarray
    firm_ids: np.ndarray
    feature_names: Sequence[str]
    horizon: int = 12
    ret_valid: Optional[np.ndarray] = None

    @property
    def n_firms(self) -> int:
        return int(self.features.shape[0])

    @property
    def n_months(self) -> int:
        return int(self.features.shape[1])

    @property
    def n_features(self) -> int:
        return int(self.features.shape[2])

    def validate(self) -> None:
        n, t, f = self.features.shape
        assert self.targets.shape == (n, t), self.targets.shape
        assert self.valid.shape == (n, t)
        assert self.target_valid.shape == (n, t)
        assert self.returns.shape == (n, t)
        assert self.dates.shape == (t,)
        assert self.firm_ids.shape == (n,)
        assert len(self.feature_names) == f
        assert self.features.dtype == np.float32
        assert self.valid.dtype == np.bool_
        assert not np.any(self.target_valid & ~self.valid), (
            "target_valid must imply valid"
        )
        assert np.all(np.isfinite(self.features))
        assert np.all(np.isfinite(self.targets))
        assert np.all(np.isfinite(self.returns))
        if self.ret_valid is not None:
            assert self.ret_valid.shape == (n, t)
            assert self.ret_valid.dtype == np.bool_

    def tradeable(self) -> np.ndarray:
        """``[N, T]`` bool: in-universe AND forward return observed."""
        if self.ret_valid is None:
            return self.valid
        return self.valid & self.ret_valid

    def date_slice(self, start: int, stop: int) -> "Panel":
        """Restrict the panel to months with start <= YYYYMM < stop."""
        sel = (self.dates >= start) & (self.dates < stop)
        (idx,) = np.nonzero(sel)
        if idx.size == 0:
            raise ValueError(f"empty date slice [{start}, {stop})")
        lo, hi = int(idx[0]), int(idx[-1]) + 1
        return dataclasses.replace(
            self,
            features=self.features[:, lo:hi],
            targets=self.targets[:, lo:hi],
            target_valid=self.target_valid[:, lo:hi],
            valid=self.valid[:, lo:hi],
            returns=self.returns[:, lo:hi],
            dates=self.dates[lo:hi],
            ret_valid=(None if self.ret_valid is None
                       else self.ret_valid[:, lo:hi]),
        )

    def save(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        extra = {}
        if self.ret_valid is not None:
            extra["ret_valid"] = self.ret_valid
        np.savez_compressed(
            os.path.join(path, "panel.npz"),
            features=self.features,
            targets=self.targets,
            target_valid=self.target_valid,
            valid=self.valid,
            returns=self.returns,
            dates=self.dates,
            firm_ids=self.firm_ids,
            **extra,
        )
        with open(os.path.join(path, "panel_meta.json"), "w") as fh:
            json.dump(
                {"feature_names": list(self.feature_names), "horizon": self.horizon},
                fh,
            )


def load_panel(path: str) -> Panel:
    """Load a panel saved by :meth:`Panel.save`."""
    with np.load(os.path.join(path, "panel.npz")) as z:
        arrays = {k: z[k] for k in z.files}
    with open(os.path.join(path, "panel_meta.json")) as fh:
        meta = json.load(fh)
    p = Panel(
        features=arrays["features"],
        targets=arrays["targets"],
        target_valid=arrays["target_valid"],
        valid=arrays["valid"],
        returns=arrays["returns"],
        dates=arrays["dates"],
        firm_ids=arrays["firm_ids"],
        feature_names=meta["feature_names"],
        horizon=meta["horizon"],
        ret_valid=arrays.get("ret_valid"),
    )
    p.validate()
    return p


def _month_range(start_yyyymm: int, n_months: int) -> np.ndarray:
    y, m = divmod(start_yyyymm, 100)
    months = []
    for _ in range(n_months):
        months.append(y * 100 + m)
        m += 1
        if m > 12:
            m = 1
            y += 1
    return np.asarray(months, dtype=np.int32)


def synthetic_panel(
    n_firms: int = 1000,
    n_months: int = 240,
    n_features: int = 5,
    start_yyyymm: int = 197001,
    horizon: int = 12,
    signal_strength: float = 0.6,
    noise: float = 0.5,
    het_noise: float = 0.0,
    trend_weight: float = 0.5,
    phi_range: Tuple[float, float] = (0.94, 0.995),
    min_history: int = 72,
    seed: int = 0,
) -> Panel:
    """Generate a Compustat-like panel with a planted, recoverable signal.

    The generative story (chosen so every moving part of the framework is
    exercised, per SURVEY.md §5):

    * Features follow per-firm AR(1) dynamics with firm fixed effects, so
      lookback windows carry real information beyond the last month.
    * The forecast target at anchor ``t`` is a fixed linear combination of the
      current features plus a nonlinear interaction plus a *trend* term (the
      mean feature drift over the trailing year). CAVEAT, measured
      (2026-07-31, ledger ``derived_features`` rows): at DEFAULT
      parameters the anchor month proxies essentially all recoverable
      signal — the 0.94–0.995 AR(1) persistence makes ``x_t`` carry the
      trend's usable content, and anchor-only, windowed-MLP, windowed-
      LSTM, and derived-``chg_12`` models all tie within ±0.01 val IC.
      The generator separates window models from anchor models only when
      the trend weight is raised or persistence lowered — the
      ``trend_weight`` and ``phi_range`` parameters exist for exactly
      that (measured: ``trend_weight=2.0, phi_range=(0.5, 0.7)`` gives a
      windowed MLP +0.024 val IC over the anchor-only MLP at a 10-epoch
      budget; the separation is real but stays modest at small budgets).
      Tests that need it must set these knobs explicitly rather than
      rely on the defaults.
    * Forward returns = next-month target innovation × ``signal_strength`` +
      idiosyncratic noise, so a correct forecast ranks next-month winners and
      the backtest shows positive IC/alpha on the planted signal.
    * Ragged histories: each firm gets a random [first, last] live span of at
      least ``min_history`` months, with a small rate of missing months
      inside the span.
    * ``het_noise > 0`` makes the target noise HETEROSCEDASTIC and
      *learnable*: cell (i, t)'s noise scale is
      ``noise · exp(het_noise · feats[i, t, -1])`` — driven by the LAST
      feature, which sits in the model's own input window (anchor-last),
      so an NLL head can and must recover the profile, and
      ``mean_minus_total_std`` aggregation has real predicted-variance
      differences to act on. (A latent per-firm scale independent of the
      features would be unlearnable by construction — the first draft of
      this testbed made exactly that mistake.) The default 0.0 keeps
      every existing test's homoscedastic generator byte-identical.
    """
    if n_features < 2:
        raise ValueError("need >= 2 features for the planted interaction term")
    if n_months <= min_history:
        raise ValueError(
            f"n_months={n_months} must exceed min_history={min_history} "
            "(every firm needs a live span shorter than the panel)"
        )
    rng = np.random.default_rng(seed)
    names = list((DEFAULT_FEATURES_20 * ((n_features // 20) + 1))[:n_features])
    for i in range(20, n_features):
        names[i] = f"{names[i]}_{i // 20}"

    # AR(1) feature dynamics with firm fixed effects.
    # Fundamentals are sticky: high AR(1) persistence + sizeable firm fixed
    # effects make the 12-month-ahead target genuinely forecastable, which the
    # signal-recovery tests rely on.
    phi = rng.uniform(phi_range[0], phi_range[1],
                      size=(1, 1, n_features)).astype(np.float32)
    firm_mean = (0.6 * rng.standard_normal((n_firms, 1, n_features))).astype(np.float32)
    innov_scale = np.sqrt(1.0 - phi**2).astype(np.float32)  # unit stationary var
    feats = np.empty((n_firms, n_months, n_features), dtype=np.float32)
    x = rng.standard_normal((n_firms, n_features)).astype(np.float32)
    for t in range(n_months):
        eps = rng.standard_normal((n_firms, n_features)).astype(np.float32)
        x = phi[:, 0] * x + innov_scale[:, 0] * eps
        feats[:, t] = x + firm_mean[:, 0]

    # Planted signal: linear + one interaction + trailing-12m trend of feat 0.
    w = np.zeros((n_features,), dtype=np.float32)
    w[: min(5, n_features)] = np.asarray([0.8, -0.5, 0.4, 0.6, -0.3])[: min(5, n_features)]
    lin = feats @ w
    inter = 0.4 * feats[..., 0] * feats[..., 1]
    trend = np.zeros((n_firms, n_months), dtype=np.float32)
    trend[:, 12:] = feats[:, 12:, 0] - feats[:, :-12, 0]
    signal = lin + inter + trend_weight * trend

    if het_noise > 0.0:
        # Noise scale driven by the OBSERVABLE last feature AT THE ANCHOR
        # month (clipped so a tail draw can't explode the target range).
        # Targets built at raw month τ are later shifted to anchor
        # t = τ − horizon, so the driver must be indexed τ − horizon for
        # the anchor's own window — in the model's input — to carry the
        # noise information. No extra rng draw on either branch:
        # het_noise=0.0 keeps the legacy RNG stream — and every seeded
        # fixture — byte-identical.
        driver = np.zeros((n_firms, n_months), np.float32)
        if horizon < n_months:
            driver[:, horizon:] = feats[:, :-horizon, -1]
        cell_scale = np.exp(
            het_noise * np.clip(driver, -2.5, 2.5)).astype(np.float32)
    else:
        # Plain python 1.0: a float32 scalar would demote a python-float
        # `noise` under NEP 50 and break legacy byte-identity in the
        # last ulp for noise values not representable in float32.
        cell_scale = 1.0
    targets = (signal + noise * cell_scale
               * rng.standard_normal((n_firms, n_months))).astype(np.float32)

    # Forward 1-month returns: loaded on the *future* signal so that ranking
    # firms by a good forecast of `targets` earns positive forward returns.
    ret_noise = 0.06 * rng.standard_normal((n_firms, n_months)).astype(np.float32)
    fwd_sig = np.zeros((n_firms, n_months), dtype=np.float32)
    fwd_sig[:, :-1] = signal[:, 1:]
    returns = (0.01 * signal_strength * fwd_sig + ret_noise).astype(np.float32)

    # Ragged live spans.
    valid = np.zeros((n_firms, n_months), dtype=np.bool_)
    max_start = max(n_months - min_history, 1)
    starts = rng.integers(0, max_start, size=n_firms)
    for i in range(n_firms):
        lo = int(starts[i])
        span = int(rng.integers(min_history, n_months - lo + 1))
        valid[i, lo : lo + span] = True
    # Sparse missing months inside spans (data vendor gaps).
    gaps = rng.random((n_firms, n_months)) < 0.01
    valid &= ~gaps

    # Target observability: anchor valid AND t+horizon within the firm's span.
    target_valid = np.zeros_like(valid)
    if horizon < n_months:
        target_valid[:, :-horizon] = valid[:, :-horizon] & valid[:, horizon:]
    # Targets are the realized future signal: shift so targets[i,t] is the
    # fundamental observed at t+horizon.
    shifted = np.zeros_like(targets)
    if horizon < n_months:
        shifted[:, :-horizon] = targets[:, horizon:]
    targets = shifted

    feats = np.where(valid[..., None], feats, 0.0).astype(np.float32)
    targets = np.where(target_valid, targets, 0.0).astype(np.float32)
    returns = np.where(valid, returns, 0.0).astype(np.float32)

    # Forward return observable only while the firm is still listed at t+1.
    ret_valid = np.zeros_like(valid)
    ret_valid[:, :-1] = valid[:, :-1] & valid[:, 1:]

    panel = Panel(
        features=feats,
        targets=targets,
        target_valid=target_valid,
        valid=valid,
        returns=returns,
        dates=_month_range(start_yyyymm, n_months),
        firm_ids=np.arange(1, n_firms + 1, dtype=np.int32),
        feature_names=names,
        horizon=horizon,
        ret_valid=ret_valid,
    )
    panel.validate()
    return panel


@dataclasses.dataclass
class PanelSplits:
    """Date-based train/val/test split over ONE shared panel.

    The panel is NOT sliced: every split is an *anchor-month index range*
    over the same arrays. This is deliberate and load-bearing:

    * Lookback windows anchored early in the val/test range legitimately
      reach back into earlier months for history — slicing the panel would
      amputate that context (a 60-month window has no eligible anchors in
      a 24-month slice at all).
    * One panel ⇒ one HBM-resident copy serving train, eval and inference.
    * No leakage: what separates the splits is the *anchor* (and therefore
      target/trade) months, not feature visibility — same firms, separated
      in time, the standard protocol for this workload.  Training anchors
      are additionally embargoed ``horizon`` months before ``train_end`` so
      no training target is realized inside the validation period.
    """

    panel: Panel
    train_end_idx: int  # first month index NOT in train
    val_end_idx: int    # first month index NOT in val
    # First month index IN train: 0 = expanding window (train on all
    # history, the reference protocol); nonzero = rolling window (fixed-
    # length train periods — the walk-forward mode whose folds keep
    # identical batch shapes, which is what lets the cross-fold reuse
    # layer bind one set of compiled programs for the whole sweep).
    train_start_idx: int = 0

    @staticmethod
    def by_date(panel: Panel, train_end: int, val_end: int,
                train_start: Optional[int] = None) -> "PanelSplits":
        """Boundaries as YYYYMM: train = [train_start, train_end), val =
        [train_end, val_end), test = [val_end, end). ``train_start``
        None = panel start (expanding window). Each period must be
        longer than ``panel.horizon`` so the target-embargoed anchor ranges
        (see ``train_range``/``val_range``) stay non-empty."""
        dates = panel.dates
        t_idx = int(np.searchsorted(dates, train_end))
        v_idx = int(np.searchsorted(dates, val_end))
        s_idx = (int(np.searchsorted(dates, train_start))
                 if train_start is not None else 0)
        if not (0 < t_idx < v_idx < panel.n_months):
            raise ValueError(
                f"split boundaries ({train_end}, {val_end}) must fall "
                f"strictly inside the panel's date range "
                f"[{dates[0]}, {dates[-1]}] in order")
        h = panel.horizon
        if t_idx - s_idx <= h or v_idx - t_idx <= h:
            raise ValueError(
                f"train period ({t_idx - s_idx} months) and val period "
                f"({v_idx - t_idx} months) must each exceed the target "
                f"horizon ({h} months) for embargoed anchors to exist")
        return PanelSplits(panel=panel, train_end_idx=t_idx,
                           val_end_idx=v_idx, train_start_idx=s_idx)

    @property
    def train_range(self) -> tuple:
        """Anchor range for training, embargoed so targets (realized
        ``horizon`` months after the anchor) stay inside the train period."""
        return (self.train_start_idx, self.train_end_idx - self.panel.horizon)

    @property
    def val_range(self) -> tuple:
        """Anchor range for validation, embargoed at the far end so no val
        target is realized inside the test period (early stopping selects
        on val IC — without this embargo, checkpoint selection would be
        conditioned on test-period outcomes)."""
        return (self.train_end_idx, self.val_end_idx - self.panel.horizon)

    @property
    def test_range(self) -> tuple:
        return (self.val_end_idx, self.panel.n_months)

    def range_of(self, split: str) -> tuple:
        try:
            return {"train": self.train_range, "val": self.val_range,
                    "test": self.test_range}[split]
        except KeyError:
            raise ValueError(f"unknown split {split!r}") from None
