"""Pallas TPU kernels for the fused LSTM/GRU recurrence — the hot serial op.

Parity/perf target: the reference's cuDNN-backed RNN execution (SURVEY.md §3
``rnn_model`` row: "cuDNN RNN kernels via TF [INFERRED]"). The XLA path
(models/rnn.py) drives the recurrence with ``lax.scan``; these kernels fuse
the whole scan into ONE Pallas call so that:

* the carried state (h, and c for LSTM) lives in **VMEM scratch** across all
  T steps — it never round-trips through HBM between steps;
* the per-step gate inputs ``xw[t]`` (the hoisted input projection computed
  as one big MXU GEMM outside the kernel) are **streamed time-major** by the
  Pallas grid pipeline, overlapping the next step's DMA with this step's
  recurrent matmul;
* all elementwise gate math fuses with the ``[Bb, H] @ [H, G·H]`` recurrent
  matmul in a single kernel instead of separate XLA fusions per scan step.

Layout: internally time-major ``[S, T, B, ·]`` — a leading SEED axis (the
ensemble's vmap axis, grid-mapped so each member's recurrent weights stay
VMEM-resident for its whole batch×time sweep) then time-major so every grid
block has MXU/VPU friendly trailing dims ``(Bb, G·H)``. The public wrapper
takes/returns the batch-major ``[B, T, ·]`` layout the models use; S = 1 for
the single-model path (a size-1 grid dim costs nothing).

``jax.vmap`` support is NATIVE: the forward/backward pallas_calls sit behind
``jax.custom_batching.custom_vmap`` whose rule dispatches the stacked inputs
onto the seed grid axis. JAX's generic pallas batching rule would instead
insert a squeezed block at the operand's batch dim — which lands mid-array
for the recurrent weights and violates the TPU "last two block dims" layout
constraint (a lowering error interpret-mode CI cannot see). One vmap level
is supported — exactly the ensemble's seed axis; don't nest vmaps over this
op.

Training support is a full ``jax.custom_vjp``: the backward kernel walks the
grid in reverse time order, **recomputes the gates** from the saved per-step
states (one extra recurrent matmul instead of materializing 4·H activations
per step), and accumulates ``dW_h`` into a VMEM-resident f32 block (one per
seed) that is written back once at the end.

Masking semantics match models/rnn.py exactly: an invalid month HOLDS the
carried state, so left-padded short histories keep the initial zero state
until the first valid month.

Multi-device caveat: a ``pallas_call`` is opaque to GSPMD — under a
data-parallel mesh it must sit inside ``shard_map`` (each shard runs its own
kernel on its local batch), which is exactly how the trainers run it
(train/loop.py, train/ensemble.py).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.custom_batching import custom_vmap
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_GATES = {"lstm": 4, "gru": 3}


# ---------------------------------------------------------------------------
# Shared step math (used by forward kernel, backward recompute, and the
# pure-jnp reference that tests validate against).
# ---------------------------------------------------------------------------


def _lstm_gates(gates: jax.Array, forget_bias: float):
    """Raw gate pre-activations [.., 4H] → (i, f, g, o) activations."""
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    return (jax.nn.sigmoid(i), jax.nn.sigmoid(f + forget_bias),
            jnp.tanh(g), jax.nn.sigmoid(o))


def _gru_parts(xw: jax.Array, hw: jax.Array):
    """Split projections and apply the reset-after-projection GRU math.

    Returns (z, r, n, hn) — hn (the raw h-side candidate projection) is
    needed again by the backward pass.
    """
    xz, xr, xn = jnp.split(xw, 3, axis=-1)
    hz, hr, hn = jnp.split(hw, 3, axis=-1)
    z = jax.nn.sigmoid(xz + hz)
    r = jax.nn.sigmoid(xr + hr)
    n = jnp.tanh(xn + r * hn)
    return z, r, n, hn


def rnn_scan_reference(cell: str, xw: jax.Array, wh: jax.Array, m: jax.Array,
                       forget_bias: float = 1.0) -> jax.Array:
    """Pure lax.scan reference of the fused recurrence (f32 carry).

    Args match :func:`rnn_scan`. Used as the ground truth in tests and as
    the CPU fallback; numerically identical to the Pallas kernels up to
    matmul accumulation order.
    """
    B, T, G = xw.shape
    H = G // _GATES[cell]
    h0 = jnp.zeros((B, H), jnp.float32)
    whf = wh.astype(jnp.float32)

    def step(carry, inp):
        xw_t, m_t = inp
        keep = m_t.astype(jnp.float32)[:, None]
        if cell == "lstm":
            h, c = carry
            gates = xw_t.astype(jnp.float32) + h @ whf
            i, f, g, o = _lstm_gates(gates, forget_bias)
            c_new = f * c + i * g
            h_new = o * jnp.tanh(c_new)
            h = keep * h_new + (1.0 - keep) * h
            c = keep * c_new + (1.0 - keep) * c
            return (h, c), h
        h = carry
        hw = h @ whf
        z, r, n, _ = _gru_parts(xw_t.astype(jnp.float32), hw)
        h_new = (1.0 - z) * n + z * h
        h = keep * h_new + (1.0 - keep) * h
        return h, h

    carry0 = (h0, h0) if cell == "lstm" else h0
    xs = (xw.swapaxes(0, 1), m.swapaxes(0, 1))
    _, h_all = jax.lax.scan(step, carry0, xs)
    return h_all.swapaxes(0, 1).astype(xw.dtype)


# ---------------------------------------------------------------------------
# Forward kernels. Grid = (S, B blocks, T); t is the fast axis, so for each
# (seed, batch block) the pipeline sweeps t = 0..T-1 while h/c persist in
# scratch and the seed's recurrent weights stay resident in VMEM.
# ---------------------------------------------------------------------------


def _lstm_fwd_kernel(xw_ref, wh_ref, m_ref, h_out, c_out, h_s, c_s, *,
                     forget_bias: float):
    t = pl.program_id(2)

    @pl.when(t == 0)
    def _():
        h_s[...] = jnp.zeros_like(h_s)
        c_s[...] = jnp.zeros_like(c_s)

    h, c = h_s[...], c_s[...]
    gates = xw_ref[0, 0].astype(jnp.float32) + jnp.dot(
        h.astype(wh_ref.dtype), wh_ref[0], preferred_element_type=jnp.float32)
    i, f, g, o = _lstm_gates(gates, forget_bias)
    c_new = f * c + i * g
    h_new = o * jnp.tanh(c_new)
    keep = m_ref[0, 0].astype(jnp.float32)
    h = keep * h_new + (1.0 - keep) * h
    c = keep * c_new + (1.0 - keep) * c
    h_s[...], c_s[...] = h, c
    h_out[0, 0] = h.astype(h_out.dtype)
    c_out[0, 0] = c.astype(c_out.dtype)


def _gru_fwd_kernel(xw_ref, wh_ref, m_ref, h_out, h_s):
    t = pl.program_id(2)

    @pl.when(t == 0)
    def _():
        h_s[...] = jnp.zeros_like(h_s)

    h = h_s[...]
    hw = jnp.dot(h.astype(wh_ref.dtype), wh_ref[0],
                 preferred_element_type=jnp.float32)
    z, r, n, _ = _gru_parts(xw_ref[0, 0].astype(jnp.float32), hw)
    h_new = (1.0 - z) * n + z * h
    keep = m_ref[0, 0].astype(jnp.float32)
    h = keep * h_new + (1.0 - keep) * h
    h_s[...] = h
    h_out[0, 0] = h.astype(h_out.dtype)


# ---------------------------------------------------------------------------
# Backward kernels. Grid = (S, B blocks, T) with time index maps REVERSED
# (grid step t touches real time tr = T-1-t). Gates are recomputed from the
# saved previous state; dW_h accumulates into a per-seed constant-index-map
# output block that stays VMEM-resident for that seed's whole sweep.
# ---------------------------------------------------------------------------


def _lstm_bwd_kernel(xw_ref, wh_ref, m_ref, hp_ref, cp_ref, cc_ref, dh_ref,
                     dxw_ref, dwh_ref, dh_s, dc_s, *, forget_bias: float):
    t = pl.program_id(2)
    T = pl.num_programs(2)

    @pl.when(t == 0)
    def _():
        dh_s[...] = jnp.zeros_like(dh_s)
        dc_s[...] = jnp.zeros_like(dc_s)

    @pl.when((pl.program_id(1) == 0) & (t == 0))
    def _():
        dwh_ref[...] = jnp.zeros_like(dwh_ref)

    # tr == 0 (grid t == T-1): the previous state is the zero initial state;
    # the clamped index map re-reads step 0, so override with zeros.
    first = t == T - 1
    h_prev = jnp.where(first, 0.0, hp_ref[0, 0].astype(jnp.float32))
    c_prev = jnp.where(first, 0.0, cp_ref[0, 0].astype(jnp.float32))
    c_cur = cc_ref[0, 0].astype(jnp.float32)  # masked c_t; safe, see below
    keep = m_ref[0, 0].astype(jnp.float32)

    gates = xw_ref[0, 0].astype(jnp.float32) + jnp.dot(
        h_prev.astype(wh_ref.dtype), wh_ref[0],
        preferred_element_type=jnp.float32)
    i, f, g, o = _lstm_gates(gates, forget_bias)

    dh_t = dh_ref[0, 0].astype(jnp.float32) + dh_s[...]
    dc_t = dc_s[...]
    # Mask blend: h_t = keep·h_new + (1-keep)·h_prev (same for c). Every
    # gate-path grad below carries a ``keep`` factor, so substituting the
    # *masked* c_t for c_new is exact — where they differ (keep=0) the
    # terms using it are zero.
    dh_new = keep * dh_t
    dc_new = keep * dc_t
    tc = jnp.tanh(c_cur)
    do = dh_new * tc
    dc_tot = dc_new + dh_new * o * (1.0 - tc * tc)
    di = dc_tot * g
    df = dc_tot * c_prev
    dg = dc_tot * i
    d_gates = jnp.concatenate([
        di * i * (1.0 - i),
        df * f * (1.0 - f),
        dg * (1.0 - g * g),
        do * o * (1.0 - o),
    ], axis=-1)
    dxw_ref[0, 0] = d_gates.astype(dxw_ref.dtype)
    # dh_prev: direct (masked-out) path + through the recurrent matmul.
    dh_s[...] = (1.0 - keep) * dh_t + jax.lax.dot_general(
        d_gates, wh_ref[0].astype(jnp.float32),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    dc_s[...] = (1.0 - keep) * dc_t + dc_tot * f
    dwh_ref[0] += jax.lax.dot_general(
        h_prev, d_gates, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _gru_bwd_kernel(xw_ref, wh_ref, m_ref, hp_ref, dh_ref,
                    dxw_ref, dwh_ref, dh_s):
    t = pl.program_id(2)
    T = pl.num_programs(2)

    @pl.when(t == 0)
    def _():
        dh_s[...] = jnp.zeros_like(dh_s)

    @pl.when((pl.program_id(1) == 0) & (t == 0))
    def _():
        dwh_ref[...] = jnp.zeros_like(dwh_ref)

    first = t == T - 1
    h_prev = jnp.where(first, 0.0, hp_ref[0, 0].astype(jnp.float32))
    keep = m_ref[0, 0].astype(jnp.float32)

    hw = jnp.dot(h_prev.astype(wh_ref.dtype), wh_ref[0],
                 preferred_element_type=jnp.float32)
    z, r, n, hn = _gru_parts(xw_ref[0, 0].astype(jnp.float32), hw)

    dh_t = dh_ref[0, 0].astype(jnp.float32) + dh_s[...]
    dh_new = keep * dh_t
    dz = dh_new * (h_prev - n)
    dn_raw = dh_new * (1.0 - z) * (1.0 - n * n)
    dr = dn_raw * hn
    d_hz = dz * z * (1.0 - z)
    d_hr = dr * r * (1.0 - r)
    d_hn = dn_raw * r
    d_hw = jnp.concatenate([d_hz, d_hr, d_hn], axis=-1)
    # x-side pre-activations share the z/r grads; the candidate's x side
    # skips the reset gate (reset-after-projection variant).
    dxw_ref[0, 0] = jnp.concatenate(
        [d_hz, d_hr, dn_raw], axis=-1).astype(dxw_ref.dtype)
    dh_s[...] = (1.0 - keep) * dh_t + dh_new * z + jax.lax.dot_general(
        d_hw, wh_ref[0].astype(jnp.float32),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    dwh_ref[0] += jax.lax.dot_general(
        h_prev, d_hw, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


# ---------------------------------------------------------------------------
# pallas_call plumbing: all calls are seed-batched ([S, T, Bp, ·]); S = 1
# for the unbatched public op.
# ---------------------------------------------------------------------------


def _round_up(x: int, mult: int) -> int:
    return -(-x // mult) * mult


def _blocks(B: int, block_b: Optional[int]) -> Tuple[int, int]:
    """(padded B, block size): batch padded to a multiple of the block."""
    bb = block_b or min(512, _round_up(B, 8))
    return _round_up(B, bb), bb


def _seed_extent(name: str, *arrays) -> int:
    """Common seed extent of leading axes that are each S or 1 (size-1 =
    shared across seeds — read via a pinned index map, never materialized
    S times in HBM)."""
    S = 1
    for a in arrays:
        s = a.shape[0]
        if s != 1 and s != S:
            if S != 1:
                raise ValueError(
                    f"{name}: seed extents disagree ({s} vs {S})")
            S = s
    return S


def _ensure_seed(outs, axis_size: int):
    """Broadcast kernel outputs up to the vmap axis size — only the
    degenerate all-operands-shared vmap produces S=1 outputs here."""
    if outs[0].shape[0] != axis_size:
        outs = tuple(jnp.broadcast_to(o, (axis_size,) + o.shape[1:])
                     for o in outs)
    return tuple(outs)


def _sidx(extent: int):
    """Seed coordinate for an operand's index map: the real grid coordinate
    when the operand is seed-stacked, pinned 0 when shared (size-1)."""
    return (lambda s: s) if extent > 1 else (lambda s: 0)


def _pinned_spec(block_shape, extent: int) -> pl.BlockSpec:
    """Whole-array VMEM block (weights/bias) that varies only with the seed
    coordinate — pinned to 0 for seed-shared (size-1) operands."""
    sx = _sidx(extent)
    zeros = (0,) * (len(block_shape) - 1)
    return pl.BlockSpec(block_shape, lambda s, i, t: (sx(s),) + zeros,
                        memory_space=pltpu.VMEM)


def _rev(T: int, sx):
    """Reverse-time index map (grid step t ↦ real time T-1-t)."""
    return lambda s, i, t: (sx(s), T - 1 - t, i, 0)


def _rev_prev(T: int, sx):
    """Reverse-time map shifted one step earlier, clamped at 0 (the t=0
    read is overridden with the zero initial state in the kernels)."""
    return lambda s, i, t: (sx(s), jnp.maximum(T - 2 - t, 0), i, 0)


def _to_time_major(x, m, bb_pad: int):
    """[.., B, T, ·] batch-major → ([.., T, Bp, ·], [.., T, Bp, 1]) time-
    major with the batch dim zero-padded by ``bb_pad`` rows (padded rows
    are masked out, so they contribute zero to every gradient)."""
    x_t = jnp.swapaxes(x, -3, -2)
    m_t = jnp.swapaxes(m, -2, -1)[..., None]
    if bb_pad:
        pad = [(0, 0)] * (x_t.ndim - 2) + [(0, bb_pad), (0, 0)]
        x_t = jnp.pad(x_t, pad)
        m_t = jnp.pad(m_t, pad)
    return x_t, m_t


def _fwd_call(cell: str, xw_t, wh, m_t, forget_bias, bb, interpret):
    """Run the forward kernel on seed-stacked time-major inputs.

    xw_t: [S|1, T, Bp, G·H]; wh: [S|1, H, G·H]; m_t: [S|1, T, Bp, 1] —
    size-1 leading axes are shared across seeds. Returns h_all
    [S, T, Bp, H] (+ c_all for LSTM) in xw's dtype.
    """
    _, T, Bp, G = xw_t.shape
    S = _seed_extent("rnn_scan", xw_t, wh, m_t)
    H = G // _GATES[cell]
    grid = (S, Bp // bb, T)
    vmem = pltpu.VMEM
    sx, sm = _sidx(xw_t.shape[0]), _sidx(m_t.shape[0])
    in_specs = [
        pl.BlockSpec((1, 1, bb, G), lambda s, i, t: (sx(s), t, i, 0),
                     memory_space=vmem),
        _pinned_spec((1, H, G), wh.shape[0]),
        pl.BlockSpec((1, 1, bb, 1), lambda s, i, t: (sm(s), t, i, 0),
                     memory_space=vmem),
    ]
    state_spec = pl.BlockSpec((1, 1, bb, H), lambda s, i, t: (s, t, i, 0),
                              memory_space=vmem)
    state_shape = jax.ShapeDtypeStruct((S, T, Bp, H), xw_t.dtype)
    scratch = pltpu.VMEM((bb, H), jnp.float32)
    if cell == "lstm":
        return pl.pallas_call(
            functools.partial(_lstm_fwd_kernel, forget_bias=forget_bias),
            grid=grid, in_specs=in_specs,
            out_specs=(state_spec, state_spec),
            out_shape=(state_shape, state_shape),
            scratch_shapes=[scratch, scratch],
            interpret=interpret,
        )(xw_t, wh, m_t)
    return (pl.pallas_call(
        _gru_fwd_kernel,
        grid=grid, in_specs=in_specs,
        out_specs=state_spec, out_shape=state_shape,
        scratch_shapes=[scratch],
        interpret=interpret,
    )(xw_t, wh, m_t),)


def _bwd_call(cell: str, xw_t, wh, m_t, saved, dh_t, forget_bias, bb,
              interpret):
    """Reverse-time backward kernel → (dxw_t [S,T,Bp,G], dwh f32 [S,H,G]).

    Size-1 leading axes mark seed-shared operands, as in :func:`_fwd_call`.
    """
    _, T, Bp, G = xw_t.shape
    S = _seed_extent("rnn_scan bwd", xw_t, wh, m_t, *saved, dh_t)
    H = G // _GATES[cell]
    grid = (S, Bp // bb, T)
    rev = functools.partial(_rev, T)
    rev_prev = functools.partial(_rev_prev, T)
    vmem = pltpu.VMEM

    def state_spec(n):
        return pl.BlockSpec((1, 1, bb, H), rev(_sidx(n)), memory_space=vmem)

    def prev_spec(n):
        return pl.BlockSpec((1, 1, bb, H), rev_prev(_sidx(n)),
                            memory_space=vmem)

    in_specs = [
        pl.BlockSpec((1, 1, bb, G), rev(_sidx(xw_t.shape[0])),
                     memory_space=vmem),
        _pinned_spec((1, H, G), wh.shape[0]),
        pl.BlockSpec((1, 1, bb, 1), rev(_sidx(m_t.shape[0])),
                     memory_space=vmem),
    ]
    if cell == "lstm":
        h_all, c_all = saved
        in_specs += [prev_spec(h_all.shape[0]), prev_spec(c_all.shape[0]),
                     state_spec(c_all.shape[0])]
        inputs = (xw_t, wh, m_t, h_all, c_all, c_all, dh_t)
        kernel = functools.partial(_lstm_bwd_kernel, forget_bias=forget_bias)
        n_scratch = 2
    else:
        (h_all,) = saved
        in_specs += [prev_spec(h_all.shape[0])]
        inputs = (xw_t, wh, m_t, h_all, dh_t)
        kernel = _gru_bwd_kernel
        n_scratch = 1
    in_specs.append(state_spec(dh_t.shape[0]))  # dh upstream
    dxw_t, dwh = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=(pl.BlockSpec((1, 1, bb, G), rev(lambda s: s),
                                memory_space=vmem),
                   pl.BlockSpec((1, H, G), lambda s, i, t: (s, 0, 0),
                                memory_space=vmem)),
        out_shape=(jax.ShapeDtypeStruct((S, T, Bp, G), xw_t.dtype),
                   jax.ShapeDtypeStruct((S, H, G), jnp.float32)),
        scratch_shapes=[pltpu.VMEM((bb, H), jnp.float32)] * n_scratch,
        interpret=interpret,
    )(*inputs)
    return dxw_t, dwh


def _seed_axis(batched: bool, x: jax.Array) -> jax.Array:
    """custom_vmap rule operand → leading seed axis: batched args arrive
    with the vmap axis at the front; shared args get a SIZE-1 axis — the
    kernels read them in place via pinned index maps, never S HBM copies."""
    return x if batched else x[None]


@functools.lru_cache(maxsize=None)
def _make_scan(cell: str, forget_bias: float, block_b: Optional[int],
               interpret: bool):
    """Build the custom-VJP fused scan for one static configuration.

    Structure: ``scan`` is a ``jax.custom_vjp`` whose fwd/bwd run the Pallas
    kernels through ``custom_vmap``-wrapped ops — an unbatched call runs the
    kernel with a size-1 seed grid axis; a vmapped call (the ensemble's seed
    axis) dispatches the stacked operands onto the real seed grid axis. This
    composition (custom_vjp outermost) is the one that supports
    ``vmap(grad(...))``; the reverse nesting breaks reverse-mode AD.
    """

    # ---- forward op: [S|1, B, T, G] stacked impl shared by the
    # unbatched (S = 1) and vmapped (seed-axis) paths. Besides the kernel
    # outputs it returns the time-major padded xw_t/m_t views so the
    # backward pass reuses them as residuals instead of re-transposing
    # the largest activation every step.

    def fwd_stacked(xw, wh, m):
        B = xw.shape[-3]
        Bp, bb = _blocks(B, block_b)
        xw_t, m_t = _to_time_major(xw, m, Bp - B)
        return (xw_t, m_t) + _fwd_call(cell, xw_t, wh, m_t, forget_bias,
                                       bb, interpret)

    @custom_vmap
    def fwd_op(xw, wh, m):
        out = fwd_stacked(xw[None], wh[None], m[None])
        return tuple(s[0] for s in out)  # drop the size-1 seed axis

    @fwd_op.def_vmap
    def _fwd_vmap(axis_size, in_batched, xw, wh, m):
        xw_t, m_t, *kout = fwd_stacked(_seed_axis(in_batched[0], xw),
                                       _seed_axis(in_batched[1], wh),
                                       _seed_axis(in_batched[2], m))
        kout = _ensure_seed(kout, axis_size)
        # xw_t/m_t stay unbatched when their sources are shared — keeping
        # a shared residual SHARED avoids S HBM copies on the eval path.
        xw_t = xw_t if in_batched[0] else xw_t[0]
        m_t = m_t if in_batched[2] else m_t[0]
        return ((xw_t, m_t, *kout),
                (in_batched[0], in_batched[2]) + (True,) * len(kout))

    # ---- backward op: reverse-time kernel over the kernel-layout
    # residuals — xw_t/m_t [T, Bp, ·] from fwd_op and the saved per-step
    # states [T, Bp, H] (each stacked [S, ...] under vmap). Only the
    # upstream dh arrives batch-major.

    def bwd_stacked(xw_t, wh, m_t, saved, dh):
        Bp = xw_t.shape[-2]
        B = dh.shape[-3]
        _, bb = _blocks(B, block_b)
        dh_t = jnp.swapaxes(dh, -3, -2)
        if Bp != B:
            pad = [(0, 0)] * (dh_t.ndim - 2) + [(0, Bp - B), (0, 0)]
            dh_t = jnp.pad(dh_t, pad)
        dxw_t, dwh = _bwd_call(cell, xw_t, wh, m_t, saved,
                               dh_t.astype(xw_t.dtype), forget_bias, bb,
                               interpret)
        return jnp.swapaxes(dxw_t, 1, 2)[:, :B], dwh

    @custom_vmap
    def bwd_op(xw_t, wh, m_t, saved, dh):
        dxw, dwh = bwd_stacked(xw_t[None], wh[None], m_t[None],
                               tuple(s[None] for s in saved), dh[None])
        return dxw[0], dwh[0]

    @bwd_op.def_vmap
    def _bwd_vmap(axis_size, in_batched, xw_t, wh, m_t, saved, dh):
        out = bwd_stacked(_seed_axis(in_batched[0], xw_t),
                          _seed_axis(in_batched[1], wh),
                          _seed_axis(in_batched[2], m_t),
                          tuple(_seed_axis(b, s)
                                for b, s in zip(in_batched[3], saved)),
                          _seed_axis(in_batched[4], dh))
        return _ensure_seed(out, axis_size), (True, True)

    # ---- public custom-VJP op ----------------------------------------

    @jax.custom_vjp
    def scan(xw, wh, m):
        out = fwd_op(xw, wh, m)
        return jnp.swapaxes(out[2], 0, 1)[:xw.shape[0]]

    def fwd(xw, wh, m):
        out = fwd_op(xw, wh, m)
        h = jnp.swapaxes(out[2], 0, 1)[:xw.shape[0]]
        return h, (out[0], wh, out[1], out[2:])

    def bwd(res, dh):
        xw_t, wh, m_t, saved = res
        dxw, dwh = bwd_op(xw_t, wh, m_t, saved, dh)
        # The mask is data, never a trained quantity — no gradient.
        return dxw, dwh.astype(wh.dtype), jnp.zeros(dh.shape[:-1], dh.dtype)

    scan.defvjp(fwd, bwd)
    return scan


def rnn_scan(cell: str, xw: jax.Array, wh: jax.Array, m: jax.Array, *,
             forget_bias: float = 1.0, block_b: Optional[int] = None,
             interpret: Optional[bool] = None) -> jax.Array:
    """Fused masked RNN recurrence as one Pallas kernel (differentiable).

    Args:
      cell: "lstm" | "gru".
      xw: ``[B, T, G·H]`` hoisted input projection (``x @ W_x + b`` for all
        gates; G = 4 for LSTM ifgo, 3 for GRU zrn), f32 or bf16.
      wh: ``[H, G·H]`` recurrent gate weights.
      m: ``[B, T]`` step validity (bool or float); invalid steps hold state.
      forget_bias: LSTM forget-gate bias (ignored for GRU).
      block_b: batch block size per grid step (default: min(512, B rounded
        up to 8)); B is padded to a multiple of it.
      interpret: force Pallas interpret mode; default auto — True off-TPU so
        the same code runs in CPU CI (SURVEY.md §5's simulated-mesh testing).

    ``jax.vmap`` over any combination of the three operands maps onto the
    kernels' native seed grid axis (ONE vmap level — the ensemble's).

    Returns:
      ``[B, T, H]`` per-step hidden states in ``xw.dtype``.
    """
    if cell not in _GATES:
        raise ValueError(f"cell must be one of {sorted(_GATES)}")
    if xw.shape[-1] % _GATES[cell]:
        raise ValueError(
            f"xw last dim {xw.shape[-1]} not divisible by {_GATES[cell]}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # Cast the mask to the compute dtype BEFORE entering the custom-VJP
    # function: a bool primal would demand a float0 cotangent from bwd.
    return _make_scan(cell, float(forget_bias), block_b, bool(interpret))(
        xw, wh, m.astype(xw.dtype))


# ---------------------------------------------------------------------------
# Fused-projection variant: the input projection moves INSIDE the kernel.
#
# The plain ``rnn_scan`` consumes the hoisted gate projection
# ``xw = x @ Wx + b`` — a [B, T, G·H] tensor that a separate GEMM writes to
# HBM and the kernel streams back in. At G·H = 4·128 that round-trip is the
# single largest HBM flow in the train step (~4× the embed activations it
# was projected from). ``rnn_scan_fused`` streams the H-wide layer input
# instead and computes the projection per step next to the recurrent
# matmul: HBM traffic drops ~3× for the same FLOPs placement (two
# [bb, H] @ [H, G·H] MXU dots per step instead of one), and the backward
# kernel produces the H-wide ``d h_in`` plus in-VMEM dWx/dWh/db
# accumulators. Same masking semantics, same custom_vmap seed-axis
# dispatch, same checkpoint-compatible parameter tree
# (models/rnn.py scan_impl="pallas_fused").
# ---------------------------------------------------------------------------


def _lstm_fused_fwd_kernel(hin_ref, wx_ref, b_ref, wh_ref, m_ref, h_out,
                           c_out, h_s, c_s, *, forget_bias: float):
    t = pl.program_id(2)

    @pl.when(t == 0)
    def _():
        h_s[...] = jnp.zeros_like(h_s)
        c_s[...] = jnp.zeros_like(c_s)

    h, c = h_s[...], c_s[...]
    gates = (jnp.dot(hin_ref[0, 0], wx_ref[0],
                     preferred_element_type=jnp.float32)
             + b_ref[0, 0].astype(jnp.float32)
             + jnp.dot(h.astype(wh_ref.dtype), wh_ref[0],
                       preferred_element_type=jnp.float32))
    i, f, g, o = _lstm_gates(gates, forget_bias)
    c_new = f * c + i * g
    h_new = o * jnp.tanh(c_new)
    keep = m_ref[0, 0].astype(jnp.float32)
    h = keep * h_new + (1.0 - keep) * h
    c = keep * c_new + (1.0 - keep) * c
    h_s[...], c_s[...] = h, c
    h_out[0, 0] = h.astype(h_out.dtype)
    c_out[0, 0] = c.astype(c_out.dtype)


def _gru_fused_fwd_kernel(hin_ref, wx_ref, b_ref, wh_ref, m_ref, h_out, h_s):
    t = pl.program_id(2)

    @pl.when(t == 0)
    def _():
        h_s[...] = jnp.zeros_like(h_s)

    h = h_s[...]
    xw = (jnp.dot(hin_ref[0, 0], wx_ref[0],
                  preferred_element_type=jnp.float32)
          + b_ref[0, 0].astype(jnp.float32))
    hw = jnp.dot(h.astype(wh_ref.dtype), wh_ref[0],
                 preferred_element_type=jnp.float32)
    z, r, n, _ = _gru_parts(xw, hw)
    h_new = (1.0 - z) * n + z * h
    keep = m_ref[0, 0].astype(jnp.float32)
    h = keep * h_new + (1.0 - keep) * h
    h_s[...] = h
    h_out[0, 0] = h.astype(h_out.dtype)


def _lstm_fused_bwd_kernel(hin_ref, wx_ref, b_ref, wh_ref, m_ref, hp_ref,
                           cp_ref, cc_ref, dh_ref, dhin_ref, dwx_ref,
                           dwh_ref, db_ref, dh_s, dc_s, *,
                           forget_bias: float):
    t = pl.program_id(2)
    T = pl.num_programs(2)

    @pl.when(t == 0)
    def _():
        dh_s[...] = jnp.zeros_like(dh_s)
        dc_s[...] = jnp.zeros_like(dc_s)

    @pl.when((pl.program_id(1) == 0) & (t == 0))
    def _():
        dwx_ref[...] = jnp.zeros_like(dwx_ref)
        dwh_ref[...] = jnp.zeros_like(dwh_ref)
        db_ref[...] = jnp.zeros_like(db_ref)

    first = t == T - 1
    h_prev = jnp.where(first, 0.0, hp_ref[0, 0].astype(jnp.float32))
    c_prev = jnp.where(first, 0.0, cp_ref[0, 0].astype(jnp.float32))
    c_cur = cc_ref[0, 0].astype(jnp.float32)
    keep = m_ref[0, 0].astype(jnp.float32)
    hin = hin_ref[0, 0]

    gates = (jnp.dot(hin, wx_ref[0], preferred_element_type=jnp.float32)
             + b_ref[0, 0].astype(jnp.float32)
             + jnp.dot(h_prev.astype(wh_ref.dtype), wh_ref[0],
                       preferred_element_type=jnp.float32))
    i, f, g, o = _lstm_gates(gates, forget_bias)

    dh_t = dh_ref[0, 0].astype(jnp.float32) + dh_s[...]
    dc_t = dc_s[...]
    dh_new = keep * dh_t
    dc_new = keep * dc_t
    tc = jnp.tanh(c_cur)
    do = dh_new * tc
    dc_tot = dc_new + dh_new * o * (1.0 - tc * tc)
    di = dc_tot * g
    df = dc_tot * c_prev
    dg = dc_tot * i
    d_gates = jnp.concatenate([
        di * i * (1.0 - i),
        df * f * (1.0 - f),
        dg * (1.0 - g * g),
        do * o * (1.0 - o),
    ], axis=-1)
    dhin_ref[0, 0] = jax.lax.dot_general(
        d_gates, wx_ref[0].astype(jnp.float32),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dhin_ref.dtype)
    dh_s[...] = (1.0 - keep) * dh_t + jax.lax.dot_general(
        d_gates, wh_ref[0].astype(jnp.float32),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    dc_s[...] = (1.0 - keep) * dc_t + dc_tot * f
    dwx_ref[0] += jax.lax.dot_general(
        hin.astype(jnp.float32), d_gates,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    dwh_ref[0] += jax.lax.dot_general(
        h_prev, d_gates, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    db_ref[0, 0] += d_gates.sum(axis=0)


def _gru_fused_bwd_kernel(hin_ref, wx_ref, b_ref, wh_ref, m_ref, hp_ref,
                          dh_ref, dhin_ref, dwx_ref, dwh_ref, db_ref, dh_s):
    t = pl.program_id(2)
    T = pl.num_programs(2)

    @pl.when(t == 0)
    def _():
        dh_s[...] = jnp.zeros_like(dh_s)

    @pl.when((pl.program_id(1) == 0) & (t == 0))
    def _():
        dwx_ref[...] = jnp.zeros_like(dwx_ref)
        dwh_ref[...] = jnp.zeros_like(dwh_ref)
        db_ref[...] = jnp.zeros_like(db_ref)

    first = t == T - 1
    h_prev = jnp.where(first, 0.0, hp_ref[0, 0].astype(jnp.float32))
    keep = m_ref[0, 0].astype(jnp.float32)
    hin = hin_ref[0, 0]

    xw = (jnp.dot(hin, wx_ref[0], preferred_element_type=jnp.float32)
          + b_ref[0, 0].astype(jnp.float32))
    hw = jnp.dot(h_prev.astype(wh_ref.dtype), wh_ref[0],
                 preferred_element_type=jnp.float32)
    z, r, n, hn = _gru_parts(xw, hw)

    dh_t = dh_ref[0, 0].astype(jnp.float32) + dh_s[...]
    dh_new = keep * dh_t
    dz = dh_new * (h_prev - n)
    dn_raw = dh_new * (1.0 - z) * (1.0 - n * n)
    dr = dn_raw * hn
    d_hz = dz * z * (1.0 - z)
    d_hr = dr * r * (1.0 - r)
    d_hn = dn_raw * r
    d_hw = jnp.concatenate([d_hz, d_hr, d_hn], axis=-1)
    d_xw = jnp.concatenate([d_hz, d_hr, dn_raw], axis=-1)
    dhin_ref[0, 0] = jax.lax.dot_general(
        d_xw, wx_ref[0].astype(jnp.float32),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dhin_ref.dtype)
    dh_s[...] = (1.0 - keep) * dh_t + dh_new * z + jax.lax.dot_general(
        d_hw, wh_ref[0].astype(jnp.float32),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    dwx_ref[0] += jax.lax.dot_general(
        hin.astype(jnp.float32), d_xw,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    dwh_ref[0] += jax.lax.dot_general(
        h_prev, d_hw, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    db_ref[0, 0] += d_xw.sum(axis=0)


def _fused_fwd_call(cell, hin_t, wx, b, wh, m_t, forget_bias, bb, interpret):
    """Fused forward on seed-stacked time-major inputs: hin_t
    [S|1, T, Bp, H]; wx/wh [S|1, H, G·H]; b [S|1, 1, G·H]; m_t
    [S|1, T, Bp, 1] → h_all (+ c_all) [S, T, Bp, H]."""
    _, T, Bp, H = hin_t.shape
    S = _seed_extent("rnn_scan_fused", hin_t, wx, b, wh, m_t)
    G = wx.shape[-1]
    grid = (S, Bp // bb, T)
    vmem = pltpu.VMEM
    shin, sm = _sidx(hin_t.shape[0]), _sidx(m_t.shape[0])
    in_specs = [
        pl.BlockSpec((1, 1, bb, H), lambda s, i, t: (shin(s), t, i, 0),
                     memory_space=vmem),
        _pinned_spec((1, H, G), wx.shape[0]),
        _pinned_spec((1, 1, G), b.shape[0]),
        _pinned_spec((1, H, G), wh.shape[0]),
        pl.BlockSpec((1, 1, bb, 1), lambda s, i, t: (sm(s), t, i, 0),
                     memory_space=vmem),
    ]
    state_spec = pl.BlockSpec((1, 1, bb, H), lambda s, i, t: (s, t, i, 0),
                              memory_space=vmem)
    state_shape = jax.ShapeDtypeStruct((S, T, Bp, H), hin_t.dtype)
    scratch = pltpu.VMEM((bb, H), jnp.float32)
    if cell == "lstm":
        return pl.pallas_call(
            functools.partial(_lstm_fused_fwd_kernel,
                              forget_bias=forget_bias),
            grid=grid, in_specs=in_specs,
            out_specs=(state_spec, state_spec),
            out_shape=(state_shape, state_shape),
            scratch_shapes=[scratch, scratch],
            interpret=interpret,
        )(hin_t, wx, b, wh, m_t)
    return (pl.pallas_call(
        _gru_fused_fwd_kernel,
        grid=grid, in_specs=in_specs,
        out_specs=state_spec, out_shape=state_shape,
        scratch_shapes=[scratch],
        interpret=interpret,
    )(hin_t, wx, b, wh, m_t),)


def _fused_bwd_call(cell, hin_t, wx, b, wh, m_t, saved, dh_t, forget_bias,
                    bb, interpret):
    """Reverse-time fused backward → (dhin_t [S,T,Bp,H], dwx f32 [S,H,G],
    dwh f32 [S,H,G], db f32 [S,1,G])."""
    _, T, Bp, H = hin_t.shape
    S = _seed_extent("rnn_scan_fused bwd", hin_t, wx, b, wh, m_t, *saved,
                     dh_t)
    G = wx.shape[-1]
    grid = (S, Bp // bb, T)
    rev = functools.partial(_rev, T)
    rev_prev = functools.partial(_rev_prev, T)
    vmem = pltpu.VMEM

    def state_spec(n):
        return pl.BlockSpec((1, 1, bb, H), rev(_sidx(n)), memory_space=vmem)

    def prev_spec(n):
        return pl.BlockSpec((1, 1, bb, H), rev_prev(_sidx(n)),
                            memory_space=vmem)

    in_specs = [
        state_spec(hin_t.shape[0]),
        _pinned_spec((1, H, G), wx.shape[0]),
        _pinned_spec((1, 1, G), b.shape[0]),
        _pinned_spec((1, H, G), wh.shape[0]),
        pl.BlockSpec((1, 1, bb, 1), rev(_sidx(m_t.shape[0])),
                     memory_space=vmem),
    ]
    if cell == "lstm":
        h_all, c_all = saved
        in_specs += [prev_spec(h_all.shape[0]), prev_spec(c_all.shape[0]),
                     state_spec(c_all.shape[0])]
        inputs = (hin_t, wx, b, wh, m_t, h_all, c_all, c_all, dh_t)
        kernel = functools.partial(_lstm_fused_bwd_kernel,
                                   forget_bias=forget_bias)
        n_scratch = 2
    else:
        (h_all,) = saved
        in_specs += [prev_spec(h_all.shape[0])]
        inputs = (hin_t, wx, b, wh, m_t, h_all, dh_t)
        kernel = _gru_fused_bwd_kernel
        n_scratch = 1
    in_specs.append(state_spec(dh_t.shape[0]))  # dh upstream
    ident = lambda s, i, t: (s, 0, 0)  # noqa: E731
    dhin_t, dwx, dwh, db = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=(
            pl.BlockSpec((1, 1, bb, H), rev(lambda s: s),
                         memory_space=vmem),
            pl.BlockSpec((1, H, G), ident, memory_space=vmem),
            pl.BlockSpec((1, H, G), ident, memory_space=vmem),
            pl.BlockSpec((1, 1, G), ident, memory_space=vmem),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((S, T, Bp, H), hin_t.dtype),
            jax.ShapeDtypeStruct((S, H, G), jnp.float32),
            jax.ShapeDtypeStruct((S, H, G), jnp.float32),
            jax.ShapeDtypeStruct((S, 1, G), jnp.float32),
        ),
        scratch_shapes=[pltpu.VMEM((bb, H), jnp.float32)] * n_scratch,
        interpret=interpret,
    )(*inputs)
    return dhin_t, dwx, dwh, db


@functools.lru_cache(maxsize=None)
def _make_fused_scan(cell: str, forget_bias: float, block_b: Optional[int],
                     interpret: bool):
    """custom-VJP fused-projection scan (same structure as _make_scan)."""

    def fwd_stacked(hin, wx, b, wh, m):
        B = hin.shape[-3]
        Bp, bb = _blocks(B, block_b)
        hin_t, m_t = _to_time_major(hin, m, Bp - B)
        return (hin_t, m_t) + _fused_fwd_call(
            cell, hin_t, wx, b, wh, m_t, forget_bias, bb, interpret)

    @custom_vmap
    def fwd_op(hin, wx, b, wh, m):
        out = fwd_stacked(hin[None], wx[None], b[None], wh[None], m[None])
        return tuple(s[0] for s in out)

    @fwd_op.def_vmap
    def _fwd_vmap(axis_size, in_batched, hin, wx, b, wh, m):
        hin_t, m_t, *kout = fwd_stacked(
            _seed_axis(in_batched[0], hin), _seed_axis(in_batched[1], wx),
            _seed_axis(in_batched[2], b), _seed_axis(in_batched[3], wh),
            _seed_axis(in_batched[4], m))
        kout = _ensure_seed(kout, axis_size)
        hin_t = hin_t if in_batched[0] else hin_t[0]
        m_t = m_t if in_batched[4] else m_t[0]
        return ((hin_t, m_t, *kout),
                (in_batched[0], in_batched[4]) + (True,) * len(kout))

    def bwd_stacked(hin_t, wx, b, wh, m_t, saved, dh):
        Bp = hin_t.shape[-2]
        B = dh.shape[-3]
        _, bb = _blocks(B, block_b)
        dh_t = jnp.swapaxes(dh, -3, -2)
        if Bp != B:
            pad = [(0, 0)] * (dh_t.ndim - 2) + [(0, Bp - B), (0, 0)]
            dh_t = jnp.pad(dh_t, pad)
        dhin_t, dwx, dwh, db = _fused_bwd_call(
            cell, hin_t, wx, b, wh, m_t, saved, dh_t.astype(hin_t.dtype),
            forget_bias, bb, interpret)
        return jnp.swapaxes(dhin_t, 1, 2)[:, :B], dwx, dwh, db

    @custom_vmap
    def bwd_op(hin_t, wx, b, wh, m_t, saved, dh):
        dhin, dwx, dwh, db = bwd_stacked(
            hin_t[None], wx[None], b[None], wh[None], m_t[None],
            tuple(s[None] for s in saved), dh[None])
        return dhin[0], dwx[0], dwh[0], db[0]

    @bwd_op.def_vmap
    def _bwd_vmap(axis_size, in_batched, hin_t, wx, b, wh, m_t, saved, dh):
        out = bwd_stacked(
            _seed_axis(in_batched[0], hin_t),
            _seed_axis(in_batched[1], wx),
            _seed_axis(in_batched[2], b),
            _seed_axis(in_batched[3], wh),
            _seed_axis(in_batched[4], m_t),
            tuple(_seed_axis(bt, s)
                  for bt, s in zip(in_batched[5], saved)),
            _seed_axis(in_batched[6], dh))
        return _ensure_seed(out, axis_size), (True,) * 4

    @jax.custom_vjp
    def scan(hin, wx, b, wh, m):
        out = fwd_op(hin, wx, b, wh, m)
        return jnp.swapaxes(out[2], 0, 1)[:hin.shape[0]]

    def fwd(hin, wx, b, wh, m):
        out = fwd_op(hin, wx, b, wh, m)
        h = jnp.swapaxes(out[2], 0, 1)[:hin.shape[0]]
        return h, (out[0], wx, b, wh, out[1], out[2:])

    def bwd(res, dh):
        hin_t, wx, b, wh, m_t, saved = res
        dhin, dwx, dwh, db = bwd_op(hin_t, wx, b, wh, m_t, saved, dh)
        # b's primal inside scan is [1, G] (rnn_scan_fused adds the axis).
        return (dhin, dwx.astype(wx.dtype), db.astype(b.dtype),
                dwh.astype(wh.dtype), jnp.zeros(dh.shape[:-1], dh.dtype))

    scan.defvjp(fwd, bwd)
    return scan


def rnn_scan_fused(cell: str, hin: jax.Array, wx: jax.Array, b: jax.Array,
                   wh: jax.Array, m: jax.Array, *, forget_bias: float = 1.0,
                   block_b: Optional[int] = None,
                   interpret: Optional[bool] = None) -> jax.Array:
    """Fused masked recurrence with the gate input projection computed
    in-kernel (differentiable; see the section comment for why).

    Args:
      cell: "lstm" | "gru".
      hin: ``[B, T, H]`` layer input (the embed/previous-layer output).
      wx: ``[H, G·H]`` gate input-projection weights.
      b: ``[G·H]`` gate bias.
      wh: ``[H, G·H]`` recurrent gate weights.
      m: ``[B, T]`` step validity; invalid steps hold state.
      forget_bias / block_b / interpret: as :func:`rnn_scan`.

    Returns ``[B, T, H]`` per-step hidden states in ``hin.dtype``.
    """
    if cell not in _GATES:
        raise ValueError(f"cell must be one of {sorted(_GATES)}")
    H = hin.shape[-1]
    G = _GATES[cell] * H
    if wx.shape != (H, G) or wh.shape != (H, G) or b.shape != (G,):
        raise ValueError(
            f"expected wx/wh [{H},{G}] and b [{G}], got "
            f"{wx.shape}/{wh.shape}/{b.shape}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _make_fused_scan(cell, float(forget_bias), block_b,
                            bool(interpret))(
        hin, wx, b[None], wh, m.astype(hin.dtype))
