"""Losses and metrics: masked regression losses, cross-sectional rank-IC."""

from lfm_quant_tpu.ops.losses import (
    gaussian_nll,
    masked_huber,
    masked_mse,
    rank_ic_loss,
    soft_rank,
)
from lfm_quant_tpu.ops.metrics import pearson_ic, spearman_ic

__all__ = [
    "masked_mse",
    "masked_huber",
    "gaussian_nll",
    "soft_rank",
    "rank_ic_loss",
    "pearson_ic",
    "spearman_ic",
]
