"""Losses and metrics: masked regression losses, cross-sectional rank-IC."""

from lfm_quant_tpu.ops.losses import (
    finalize_loss,
    gaussian_nll,
    make_loss_parts,
    masked_huber,
    masked_mse,
    rank_ic_loss,
    soft_rank,
)
from lfm_quant_tpu.ops.metrics import hard_ranks, pearson_ic, spearman_ic

__all__ = [
    "masked_mse",
    "masked_huber",
    "gaussian_nll",
    "soft_rank",
    "rank_ic_loss",
    "make_loss_parts",
    "finalize_loss",
    "hard_ranks",
    "pearson_ic",
    "spearman_ic",
]
