"""Training losses (L3/L4 boundary).

Parity targets: the reference's forecast regression loss and the
**cross-sectional rank-IC loss** of ladder config 3 (SURVEY.md §3;
BASELINE.json:9 — "GRU + cross-sectional rank-IC loss"). The reference code
was unobservable (SURVEY.md §0); the rank-IC construction below is the
standard differentiable Spearman surrogate: pairwise-sigmoid soft ranks,
then a Pearson correlation of soft ranks per month.

Shape convention: all cross-sectional losses take ``[D, Bf]`` arrays — D
months per batch, Bf firms per month (the windowing layout from
data/windows.py). Ranking happens along the LAST axis only, so under data
parallelism the D axis shards freely and no collective is needed
(SURVEY.md §8 step 8's correctness trap).

Weights: ``w`` is the sampler's padding weight (0 for padded slots); every
loss treats w=0 entries as absent.
"""

from __future__ import annotations

import jax.numpy as jnp


def _acc(x):
    """Promote sub-f32 values (bf16/f16) to f32 before reduction — the
    mixed-precision lane's f32-reduction contract (DESIGN.md §17). In
    practice the head already emits f32 and targets stay f32, so every
    production loss reduces in f32 regardless; this pins the property
    for any caller that hands raw bf16 tensors in. No-op for f32/f64."""
    dt = jnp.promote_types(x.dtype, jnp.float32)
    return x.astype(dt) if x.dtype != dt else x


def _weighted_mean(x, w, axis=None):
    x = _acc(x)
    w = w.astype(x.dtype)
    return (x * w).sum(axis=axis) / jnp.maximum(w.sum(axis=axis), 1e-12)


def masked_mse(pred, target, w):
    """Weighted mean squared error over real (w>0) samples → scalar."""
    return _weighted_mean((pred - target) ** 2, w)


def masked_huber(pred, target, w, delta: float = 1.0):
    """Weighted Huber loss → scalar (robust to fundamental outliers)."""
    err = jnp.abs(pred - target)
    quad = jnp.minimum(err, delta)
    lin = err - quad
    return _weighted_mean(0.5 * quad**2 + delta * lin, w)


def gaussian_nll(mean, log_var, target, w):
    """Heteroscedastic Gaussian NLL for the uncertainty head → scalar.

    (Uncertainty-aware LFM lineage — SURVEY.md §1 [BACKGROUND].)
    """
    nll = 0.5 * (log_var + (target - mean) ** 2 * jnp.exp(-log_var))
    return _weighted_mean(nll, w)


def soft_rank(x, w, temperature: float = 1.0):
    """Differentiable ranks along the last axis.

    ``soft_rank[i] = sum_j w_j * sigmoid((x_i - x_j) / temperature)`` — a
    smooth count of how many (real) elements each element exceeds. As
    temperature → 0 this approaches the hard rank (in [0, n-1] up to the
    0.5 self-comparison). O(n²) pairwise — one [D, Bf, Bf] batched outer
    difference, which XLA maps straight onto the MXU/VPU; at monthly
    cross-section sizes (≤ a few thousand firms) this is cheap.

    Padded entries (w=0) neither receive meaningful ranks nor influence
    real ranks.
    """
    diff = (x[..., :, None] - x[..., None, :]) / temperature
    p = jnp.where(w[..., None, :] > 0, jnp.asarray(1.0, x.dtype) /
                  (1.0 + jnp.exp(-diff)), 0.0)
    return p.sum(axis=-1)


def _center_corr(a, b, w):
    """Weighted Pearson correlation along the last axis → [...] (per month)."""
    wa = _weighted_mean(a, w, axis=-1)[..., None]
    wb = _weighted_mean(b, w, axis=-1)[..., None]
    ac, bc = (a - wa) * w, (b - wb) * w
    cov = (ac * bc).sum(axis=-1)
    va = (ac * ac).sum(axis=-1)
    vb = (bc * bc).sum(axis=-1)
    return cov / jnp.maximum(jnp.sqrt(va * vb), 1e-8)


def rank_ic_loss(pred, target, w, temperature: float = 0.5):
    """Negative mean per-month soft Spearman correlation → scalar.

    ``pred, target, w: [D, Bf]``; ranks are computed within each month (last
    axis), correlations averaged over months, negated so lower is better.
    Target ranks use a small temperature (closer to hard ranks) since no
    gradient flows through the target side.
    """
    pred = pred.astype(jnp.float32)  # ranks count to n; bf16's 8 mantissa
    target = target.astype(jnp.float32)  # bits quantize ranks past n≈256
    pr = soft_rank(pred, w, temperature)
    tr = soft_rank(target, w, temperature=1e-3)
    ic = _center_corr(pr, tr, w.astype(pred.dtype))
    return -ic.mean()


# ---- numerator/denominator decompositions ---------------------------------
#
# Every loss above is a ratio of two data-sums: a weighted error sum over a
# normalizer (total weight, or month count for rank-IC). Data-parallel
# training under ``shard_map`` needs the two sums SEPARATELY so the global
# loss can be assembled with one psum per part:
#
#     loss = psum(num_local) / psum(den_local)
#
# Normalizing per shard first would weight shards equally regardless of how
# much real (w>0) data each holds — wrong whenever padding is uneven. The
# ``finalize_loss`` epsilon matches ``_weighted_mean``'s, so
# ``finalize_loss(*parts(out, y, w))`` == the plain loss exactly.


def finalize_loss(num, den):
    """num/den with _weighted_mean's zero-protection."""
    return num / jnp.maximum(den, 1e-12)


def _sum_parts(errs, w):
    errs = _acc(errs)  # f32 accumulators (see _acc) — num/den and their
    w = w.astype(errs.dtype)  # psums must never accumulate in bf16
    return (errs * w).sum(), w.sum()


def make_loss_parts(name: str):
    """Loss name → fn(out, y, w) -> (num, den) with
    ``finalize_loss(num, den) == make_loss_fn(name)(out, y, w)``."""
    if name == "mse":
        return lambda out, y, w: _sum_parts((out - y) ** 2, w)
    if name == "huber":
        def huber_parts(out, y, w, delta=1.0):
            err = jnp.abs(out - y)
            quad = jnp.minimum(err, delta)
            return _sum_parts(0.5 * quad**2 + delta * (err - quad), w)
        return huber_parts
    if name == "nll":
        def nll_parts(out, y, w):
            mean, log_var = out
            nll = 0.5 * (log_var + (y - mean) ** 2 * jnp.exp(-log_var))
            return _sum_parts(nll, w)
        return nll_parts
    if name == "rank_ic":
        def rank_ic_parts(out, y, w, temperature=0.5):
            out = out.astype(jnp.float32)  # see rank_ic_loss: full-universe
            y = y.astype(jnp.float32)  # cross-sections overflow bf16 ranks
            pr = soft_rank(out, w, temperature)
            tr = soft_rank(y, w, temperature=1e-3)
            ic = _center_corr(pr, tr, w.astype(out.dtype))
            return (-ic).sum(), jnp.asarray(ic.size, ic.dtype)
        return rank_ic_parts
    raise ValueError(f"unknown loss {name!r}; use mse|huber|rank_ic|nll")
