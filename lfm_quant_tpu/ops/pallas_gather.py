"""Pallas TPU kernel for the window gather — exact-window DMAs from HBM.

The hot input path (data/windows.py): every train/eval step turns an int32
index batch into ``[D, Bf, W, F]`` windows from the HBM-resident packed
panel ``xm [N, T, F+1]``. The XLA fast path does a contiguous *firm-row*
gather (``xm[firm_idx]`` → ``[D, Bf, T, F+1]``) then slices the window.
Profiling (scripts/profile_bench.py) shows that gather at ~56% of the
whole train step once the RNN runs as a fused Pallas kernel — and it is
NOT bandwidth-bound: the gathered bytes would take ~30× less time at HBM
speed; the cost is the scalar-indexed gather op plus the materialized
``[D, Bf, T, F+1]`` intermediate.

This kernel instead issues one async DMA per firm for EXACTLY the window
bytes — ``xm[f, start:start+W, :]`` — straight from the panel left in HBM
into the output's VMEM block, ``block_f`` copies in flight per grid step.
Indices arrive via ``PrefetchScalarGridSpec`` so source addresses are
known before the body runs.

Lane padding: Mosaic requires DMA-sliced arrays to have 128-aligned lane
(last) dims, so the panel is stored feature-padded to 128
(``pad_lanes`` / ``device_panel(..., lane_pad=True)``). That makes the
DMA read ``W·128`` instead of ``W·F`` elements per window — still ~4×
fewer bytes than the XLA path's full rows at the ladder geometry, and the
op-overhead win dominates regardless.

Young anchors (t < W-1): the slice start clamps to 0 and the wrapper
rolls the window in XLA afterwards (``jnp.roll`` handles traced shifts;
the rolled-in future months are masked False and zero-filled). All firms
of a date share the anchor, so the roll is per-date uniform.

No VJP: the panel is data, not parameters — gradients never flow through
the gather (the trainers differentiate w.r.t. params only).

GSPMD caveat (same as ops/pallas_rnn.py): a pallas_call is opaque to the
partitioner — auto-selected only when the step runs un-partitioned; the
XLA gather remains the default under a mesh.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.custom_batching import custom_vmap
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANE = 128  # TPU lane width: DMA-sliced arrays need lane-dim alignment
_SUBLANE = 8  # month-dim tiling: DMA slice starts/extents must align to it

# "Leave this operand unblocked in HBM": newer jax spells it pltpu.HBM;
# jax 0.4.x only has the ANY memory space (TPUMemorySpace.ANY), which for
# an unblocked input means the same thing — the kernel DMAs from it
# manually. Resolved once here so the kernel body stays version-agnostic.
_HBM = getattr(pltpu, "HBM", pltpu.ANY)


def padded_months(n_months: int) -> int:
    """Month count after ``pad_months`` — the single source of truth for
    the sublane alignment shared with data/windows.py (device_panel,
    resolve_gather_impl)."""
    return -(-n_months // _SUBLANE) * _SUBLANE


def padded_lanes(width: int) -> int:
    """Packed width after ``pad_lanes``."""
    return -(-width // _LANE) * _LANE


def _aligned_span(window: int, n_months: int):
    """Static 8-aligned DMA extent covering any window placement.

    bf16 HBM memrefs are sublane-tiled (8, 128)(2, 1): DMA slice starts and
    extents on the month dim must be 8-aligned. The kernel therefore
    fetches a SUPERWINDOW of static width ``w_pad`` starting at the
    aligned-down true start; the wrapper slices the real window out per
    date. Returns (w_pad, max_start8); None when the panel is too short
    for an aligned span (callers fall back to the XLA path).

    ``n_months`` must be a multiple of 8 (``pad_months``): an 8-aligned
    span of 8-multiple width can only end on an 8-aligned offset, so with
    T % 8 != 0 the last T % 8 months are unreachable and tail anchors
    would silently clamp to a window shifted up to 7 months early —
    exactly the newest data. Month-padding (zeros → validity column 0)
    removes the case instead of special-casing it.
    """
    if n_months % _SUBLANE:
        return None  # callers must month-pad the panel first (pad_months)
    # Clamping to n_months keeps near-window-length panels on the fast
    # path: with w_pad == n_months, max_start8 == 0 and the offset bound
    # off <= n_months - window == w_pad - window still holds.
    w_pad = min(-(-window // _SUBLANE) * _SUBLANE + _SUBLANE, n_months)
    if w_pad < window:
        return None
    return w_pad, n_months - w_pad


def _gather_kernel(fi_ref, ti_ref, xm_hbm, out_ref, sems, *, window: int,
                   n_months: int, w_pad: int, max_start8: int, bf: int,
                   bb: int):
    """Grid (D, Bf//bb): DMA bb aligned superwindows for one date.

    fi_ref:  [D*Bf] int32 scalar-prefetch (flattened firm indices).
    ti_ref:  [D] int32 scalar-prefetch (anchor month per date).
    xm_hbm:  [N, T, 128k] lane-padded packed panel, left in HBM.
    out_ref: [1, bb, w_pad, 128k] VMEM block of the output.
    sems:    DMA semaphore array, one per in-flight firm copy.
    """
    d = pl.program_id(0)
    j = pl.program_id(1)
    t = ti_ref[d]
    start = jnp.clip(t - (window - 1), 0, n_months - window)
    start8 = pl.multiple_of(
        jnp.minimum((start // 8) * 8, max_start8), 8)

    def issue(i):
        f = fi_ref[d * bf + j * bb + i]
        return pltpu.make_async_copy(
            xm_hbm.at[f, pl.ds(start8, w_pad), :],
            out_ref.at[0, i],
            sems.at[i],
        )

    for i in range(bb):
        issue(i).start()
    for i in range(bb):
        issue(i).wait()


@functools.lru_cache(maxsize=None)
def _make_gather(window: int, n_months: int, bf: int, bb: int,
                 interpret: bool):
    w_pad, max_start8 = _aligned_span(window, n_months)

    def call_flat(xm, firm_idx, time_idx):
        D = firm_idx.shape[0]
        Fp = xm.shape[-1]
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(D, bf // bb),
            in_specs=[pl.BlockSpec(memory_space=_HBM)],
            out_specs=pl.BlockSpec(
                (1, bb, w_pad, Fp), lambda d, j, fi, ti: (d, j, 0, 0),
                memory_space=pltpu.VMEM),
            scratch_shapes=[pltpu.SemaphoreType.DMA((bb,))],
        )
        kernel = functools.partial(
            _gather_kernel, window=window, n_months=n_months, w_pad=w_pad,
            max_start8=max_start8, bf=bf, bb=bb)
        return pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((D, bf, w_pad, Fp), xm.dtype),
            interpret=interpret,
        )(firm_idx.reshape(-1), time_idx, xm)

    # ``jax.vmap`` (the ensemble's seed axis over per-seed index batches)
    # folds seeds into the kernel's date grid axis — one pallas_call with
    # S·D grid rows. JAX's generic batching rule would instead wrap the
    # scalar-prefetch call in a lax.scan, serializing S kernel dispatches
    # per train step and breaking the DMA pipeline at each seed boundary.

    @custom_vmap
    def call(xm, firm_idx, time_idx):
        return call_flat(xm, firm_idx, time_idx)

    @call.def_vmap
    def _call_vmap(axis_size, in_batched, xm, firm_idx, time_idx):
        xm_b, fi_b, ti_b = in_batched
        if not fi_b:
            firm_idx = jnp.broadcast_to(firm_idx,
                                        (axis_size,) + firm_idx.shape)
        if not ti_b:
            time_idx = jnp.broadcast_to(time_idx,
                                        (axis_size,) + time_idx.shape)
        if xm_b:
            # Per-seed panels: nothing to fold (the kernel reads ONE panel
            # from HBM). Rare/unused in-tree; keep the serial semantics.
            return jax.lax.map(
                lambda args: call_flat(*args), (xm, firm_idx, time_idx)
            ), True
        S, D, bf_ = firm_idx.shape
        out = call_flat(xm, firm_idx.reshape(S * D, bf_),
                        time_idx.reshape(S * D))
        return out.reshape(S, D, *out.shape[1:]), True

    return call


def pad_lanes(xm: jax.Array) -> jax.Array:
    """Zero-pad the packed panel's feature dim to a lane multiple.

    Mosaic (this jaxlib) rejects DMA slices of arrays whose last dim is
    not 128-aligned — even full-extent ones. Production callers store the
    panel pre-padded (``device_panel(..., lane_pad=True)``); the padding
    is zeros, so the validity column position (logical Fp-1) is the only
    bookkeeping.
    """
    pad = padded_lanes(xm.shape[-1]) - xm.shape[-1]
    if pad == 0:
        return xm
    return jnp.pad(xm, ((0, 0), (0, 0), (0, pad)))


def pad_months(xm: jax.Array) -> jax.Array:
    """Zero-pad the packed panel's month dim to a multiple of 8.

    Required by ``_aligned_span``: 8-aligned superwindow DMAs can never
    reach the last ``T % 8`` months of an unpadded panel (the span end is
    8-aligned), so tail anchors would fetch windows shifted up to 7 months
    early. The padding is zeros, so the validity column marks the phantom
    months invalid; real windows never extend past the last true month
    (``start <= T_true - W``), only superwindow overfetch touches them.
    Production callers store the panel pre-padded
    (``device_panel(..., lane_pad=True)`` pads months AND lanes).
    """
    pad = padded_months(xm.shape[1]) - xm.shape[1]
    if pad == 0:
        return xm
    return jnp.pad(xm, ((0, 0), (0, pad), (0, 0)))


def gather_windows_pallas(
    xm: jax.Array,
    firm_idx: jax.Array,
    time_idx: jax.Array,
    window: int,
    fp: Optional[int] = None,
    block_f: Optional[int] = None,
    interpret: Optional[bool] = None,
):
    """Exact-window gather over the packed panel, as one Pallas kernel.

    Same contract as ``data.windows.gather_windows_packed`` (the [D, Bf]
    date layout, T >= W): returns ``(x [D, Bf, W, F], m [D, Bf, W])`` with
    ``x`` in ``xm.dtype``.

    Args:
      xm: ``[N, T, Fp]`` packed panel — lane-padded (``pad_lanes``) and
        month-padded to a multiple of 8 (``pad_months``) for zero-copy
        dispatch; un-padded inputs are padded here (a per-call copy: fine
        for tests, wasteful in a train step).
      fp: the LOGICAL packed width (features + validity column) before any
        lane padding; defaults to ``xm.shape[-1]``.
    """
    D, bf = firm_idx.shape
    if time_idx.shape != (D,):
        raise ValueError(f"expected time_idx [D={D}], got {time_idx.shape}")
    if xm.shape[1] < window:
        raise ValueError("panel shorter than the window; use the XLA path")
    fp = fp or xm.shape[-1]
    xm = pad_months(pad_lanes(xm))  # no-ops when stored pre-padded
    T = xm.shape[1]
    span = _aligned_span(window, T)
    if span is None:
        raise ValueError("panel too short for an aligned DMA span; use the "
                         "XLA path")
    w_pad, max_start8 = span
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if block_f is None:
        # Largest divisor of Bf whose output block stays under ~2.5 MB —
        # measured sweet spot (128 at the bf16 ladder geometry: 2.6× the
        # XLA gather; 256 thrashes VMEM double-buffering and loses).
        blk_bytes = w_pad * xm.shape[-1] * xm.dtype.itemsize
        block_f = next(b for b in (128, 64, 32, 16, 8, 4, 2, 1)
                       if bf % b == 0 and b * blk_bytes <= (5 << 20) // 2)
    packed = _make_gather(window, T, bf, block_f, bool(interpret))(
        xm, firm_idx, time_idx)

    # The kernel fetched an 8-aligned superwindow: cut the true window out
    # (per-date offset), then roll young anchors so the anchor sits at the
    # LAST position and mask off the rolled-in months. All XLA-side: these
    # ops run on the small [D, Bf, W, Fp] output, not the panel.
    start = jnp.clip(time_idx - (window - 1), 0, T - window)
    start8 = jnp.minimum((start // 8) * 8, max_start8)
    off = start - start8  # [D], 0 <= off <= w_pad - window
    packed = jax.vmap(
        lambda p, o: jax.lax.dynamic_slice_in_dim(p, o, window, axis=-2)
    )(packed, off)
    shift = (window - 1) - (time_idx - start)  # [D]
    packed = jax.vmap(lambda p, s: jnp.roll(p, s, axis=-2))(packed, shift)
    pos = jnp.arange(window, dtype=jnp.int32)
    live = pos[None, :] >= shift[:, None]  # [D, W]
    m = (packed[..., fp - 1] != 0) & live[:, None, :]
    # Contract parity with the XLA path: invalid months are zero-filled.
    x = jnp.where(m[..., None], packed[..., :fp - 1],
                  jnp.zeros((), packed.dtype))
    return x, m
