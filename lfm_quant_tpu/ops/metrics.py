"""Evaluation metrics: exact per-month information coefficients.

Used by validation/early-stopping (L4) and the backtest report (SURVEY.md
§4.3). Exact (non-differentiable) counterparts of ops/losses.py.
"""

from __future__ import annotations

import jax.numpy as jnp


def _acc(x):
    """Promote sub-f32 inputs (bf16/f16) to f32 before any reduction —
    the mixed-precision lane's f32-accumulator contract (DESIGN.md §17):
    ICs, ranks and error sums drive early-stop DECISIONS and must never
    quantize at bf16's 8 mantissa bits. f32/f64 inputs pass through
    untouched, so every existing full-precision path is bit-unchanged."""
    dt = jnp.promote_types(x.dtype, jnp.float32)
    return x.astype(dt) if x.dtype != dt else x


def _masked_pearson(a, b, w):
    a, b = _acc(a), _acc(b)
    w = w.astype(a.dtype)
    denom = jnp.maximum(w.sum(axis=-1, keepdims=True), 1e-12)
    ma = (a * w).sum(axis=-1, keepdims=True) / denom
    mb = (b * w).sum(axis=-1, keepdims=True) / denom
    ac, bc = (a - ma) * w, (b - mb) * w
    cov = (ac * bc).sum(axis=-1)
    va = (ac * ac).sum(axis=-1)
    vb = (bc * bc).sum(axis=-1)
    return cov / jnp.maximum(jnp.sqrt(va * vb), 1e-8)


def pearson_ic(pred, target, w):
    """Per-month Pearson IC along the last axis → [...] correlations."""
    return _masked_pearson(pred, target, w)


def hard_ranks(x, w):
    """Exact competition-free average ranks of real entries along last axis.

    Padded entries are pushed to +inf so they occupy the top rank slots and
    never perturb real entries' ranks; their rank values are meaningless and
    must be masked out by the caller (we multiply by w downstream). Ties get
    distinct ranks in FIRST-INDEX order (``jnp.argsort`` is stable) —
    the same defined tie-break as the numpy backtest engine's stable
    double argsort, which is what lets the fused backtest
    (backtest/jax_engine.py) match the reference exactly on tied
    forecasts. Public because that engine shares ranks across its IC
    computations: target/return ranks are computed ONCE per month and
    paired against each aggregation mode's forecast ranks via
    :func:`pearson_ic` — ``spearman_ic`` is exactly that composition.
    """
    x = _acc(x)  # bf16 ranks are exact only to n≈256 — rank in ≥f32
    big = jnp.asarray(jnp.finfo(x.dtype).max, x.dtype)
    xs = jnp.where(w > 0, x, big)
    order = jnp.argsort(xs, axis=-1)
    arange = jnp.broadcast_to(jnp.arange(x.shape[-1], dtype=x.dtype), xs.shape)
    # scatter: rank[order[i]] = i
    return jnp.put_along_axis(
        jnp.zeros_like(xs), order, arange, axis=-1, inplace=False
    )


_hard_ranks = hard_ranks  # back-compat alias (pre-PR-2 private name)


def spearman_ic(pred, target, w):
    """Exact per-month Spearman rank correlation along the last axis.

    Matches ``scipy.stats.spearmanr`` on untied data (validated in tests).
    """
    pr = hard_ranks(pred, w)
    tr = hard_ranks(target, w)
    return _masked_pearson(pr, tr, w)


def noise_recovery_rho(targets, forecast, unc_std, valid, min_months: int = 8):
    """Per-firm noise-profile recovery: Spearman ρ between a model's
    predicted uncertainty and each firm's realized residual spread.

    The het-testbed diagnostic (``synthetic_panel(het_noise>0)``): an
    aleatoric estimator that works must rank firms by noisiness. ONE
    implementation shared by the CI gate
    (tests/test_train.py noise-profile test) and the evidence-ledger
    reproducer (scripts/evidence_probes.py mcdropout) — the protocol
    (residual definition, ``min_months`` firm filter, rank statistic)
    must never diverge between them.

    Args are full-panel-shaped [N, T] numpy arrays (``forecast``/
    ``unc_std`` as returned by ``Trainer.predict``); returns a float.
    """
    import numpy as np

    resid = np.where(valid, targets - forecast, np.nan)
    has = np.isfinite(resid).sum(axis=1) >= min_months
    pred_i = np.nanmean(np.where(valid, unc_std, np.nan)[has], axis=1)
    true_i = np.nanstd(resid[has], axis=1)
    return float(spearman_ic(pred_i, true_i, np.ones_like(pred_i)))
