"""Multi-host initialization (SURVEY.md §6 "distributed communication
backend"): the reference scaled with per-GPU ``tf.distribute`` on one host;
the TPU-native story is SPMD over every chip jax can see. Within one slice
that needs nothing; across hosts/slices, each process calls
``jax.distributed.initialize`` once at startup and ``jax.devices()`` then
spans the pod — all mesh/sharding code in parallel/mesh.py is host-count
agnostic by construction, and XLA routes collectives over ICI within a
slice and DCN across slices.

Configuration via environment (the launcher sets these per process):

  LFM_COORDINATOR    — "host:port" of process 0.
  LFM_NUM_PROCESSES  — total process count.
  LFM_PROCESS_ID     — this process's rank.

On managed TPU platforms (GKE/Cloud TPU) jax auto-detects these; calling
``jax.distributed.initialize()`` with no args suffices, so an empty env is
ALSO fine there — set LFM_AUTO_DISTRIBUTED=1 to opt in to argless init.
"""

from __future__ import annotations

import os
from typing import Optional


def maybe_initialize(env: Optional[dict] = None) -> bool:
    """Initialize jax.distributed from the environment when configured.

    Returns True if initialize() was called. Raises ValueError on a
    partially-specified configuration (a silent single-host fallback on a
    half-configured pod would train on 1/N of the data with no error).
    """
    env = os.environ if env is None else env
    keys = ("LFM_COORDINATOR", "LFM_NUM_PROCESSES", "LFM_PROCESS_ID")
    present = [k for k in keys if env.get(k)]
    if env.get("LFM_AUTO_DISTRIBUTED"):
        import jax

        jax.distributed.initialize()
        return True
    if not present:
        return False
    if len(present) < len(keys):
        missing = sorted(set(keys) - set(present))
        raise ValueError(
            f"partial multi-host config: {present} set but {missing} "
            "missing — refusing to guess")
    import jax

    jax.distributed.initialize(
        coordinator_address=env["LFM_COORDINATOR"],
        num_processes=int(env["LFM_NUM_PROCESSES"]),
        process_id=int(env["LFM_PROCESS_ID"]),
    )
    return True
