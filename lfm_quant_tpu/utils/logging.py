"""Structured run metrics: JSONL scalar stream per run directory.

SURVEY.md §6 (metrics/observability): scalar metrics (loss, val IC,
firm-months/sec) to JSONL + structured run dir per seed. TensorBoard is
deliberately NOT in the loop — plain files keep the training path free of
TF (BASELINE.json:5 "no GPU/TF in the loop").
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional


class MetricsLogger:
    """Append-only JSONL metric stream (one dict per line, ts + step added)."""

    def __init__(self, run_dir: Optional[str], filename: str = "metrics.jsonl",
                 echo: bool = False):
        self.run_dir = run_dir
        self.echo = echo
        self._fh = None
        if run_dir is not None:
            os.makedirs(run_dir, exist_ok=True)
            self._fh = open(os.path.join(run_dir, filename), "a", buffering=1)

    def log(self, step: int, **metrics: Any) -> Dict[str, Any]:
        rec = {"ts": time.time(), "step": step}
        rec.update(
            {k: (float(v) if hasattr(v, "__float__") else v)
             for k, v in metrics.items()}
        )
        if self._fh:
            self._fh.write(json.dumps(rec) + "\n")
        if self.echo:
            shown = {k: v for k, v in rec.items() if k != "ts"}
            print(json.dumps(shown))
        return rec

    def close(self):
        if self._fh:
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
