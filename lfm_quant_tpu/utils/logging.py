"""Structured run metrics: JSONL scalar stream per run directory.

SURVEY.md §6 (metrics/observability): scalar metrics (loss, val IC,
firm-months/sec) to JSONL + structured run dir per seed. TensorBoard is
deliberately NOT in the loop — plain files keep the training path free of
TF (BASELINE.json:5 "no GPU/TF in the loop").
"""

from __future__ import annotations

import json
import math
import os
import time
from typing import Any, Dict, Optional


def _finite(v: Any) -> Any:
    """Non-finite floats → None, RECURSIVELY through containers:
    ``json.dumps(float("nan"))`` emits a bare ``NaN`` token, which is
    NOT valid JSON — a NaN'd val_ic would corrupt the ``metrics.jsonl``
    line that crash-resume reconciliation (train/loop.py
    ``FitHarness._recover_best``) and every strict-JSON consumer reads.
    ``null`` round-trips everywhere and is unambiguous in the stream.
    The recursion depth must match the ``allow_nan=False`` strictness
    the writers enforce — a NaN nested in a logged list must sanitize,
    not raise. Shared by the telemetry span/trace emitters
    (utils/telemetry.py), which state the same contract."""
    if isinstance(v, float):
        return v if math.isfinite(v) else None
    if isinstance(v, (list, tuple)):
        return [_finite(x) for x in v]
    if isinstance(v, dict):
        return {k: _finite(x) for k, x in v.items()}
    return v


class MetricsLogger:
    """Append-only JSONL metric stream (one dict per line, ts + step added).

    Every line is STRICT JSON: non-finite floats are serialized as
    ``null`` (see :func:`_finite`); the dict returned to the caller
    keeps the original values."""

    def __init__(self, run_dir: Optional[str], filename: str = "metrics.jsonl",
                 echo: bool = False):
        self.run_dir = run_dir
        self.echo = echo
        self._fh = None
        if run_dir is not None:
            os.makedirs(run_dir, exist_ok=True)
            self._fh = open(os.path.join(run_dir, filename), "a", buffering=1)

    def log(self, step: int, **metrics: Any) -> Dict[str, Any]:
        rec = {"ts": time.time(), "step": step}
        rec.update(
            {k: (float(v) if hasattr(v, "__float__") else v)
             for k, v in metrics.items()}
        )
        if self._fh or self.echo:
            line = {k: _finite(v) for k, v in rec.items()}
            if self._fh:
                self._fh.write(json.dumps(line, allow_nan=False) + "\n")
            if self.echo:
                shown = {k: v for k, v in line.items() if k != "ts"}
                print(json.dumps(shown, allow_nan=False))
        return rec

    def close(self):
        if self._fh:
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
