"""Live metrics plane: O(1)-per-event instruments + Prometheus text.

Everything observable before this module was post-hoc: the telemetry
spans/ledger (utils/telemetry.py) and ``scripts/trace_report.py`` read
a FINISHED run dir, and the chaos layer's shed/retry/breaker counters
(PR 10) could not be scraped while the service was actually degrading.
This module is the pull-side half: always-on, in-process instruments a
live endpoint (``serve.py /metrics``) can render at any moment —

* :class:`LogHistogram` — fixed log-spaced-bucket latency histograms:
  O(1) record (one ``log``, one index clamp), lock-guarded per the
  ``telemetry.CounterRegistry`` convention (request threads, the
  batcher thread and a refresh fit all record concurrently), mergeable
  across label sets, with quantile estimates whose error is bounded by
  ONE BUCKET'S RELATIVE RESOLUTION (the growth factor — ~12% at the
  default 20 buckets/decade; tests/test_metrics.py pins the estimate
  against the exact ``serve/stats.py percentile`` twins on the same
  stream). Histograms are labeled per (universe, width-bucket): the
  Khomenko-style bucketed request stream means a bucket-ladder
  regression must be attributable per bucket, not hidden in a blended
  histogram.
* :class:`WindowedRing` — last ~5 minutes in ~10 s rings: O(1) add,
  O(rings) read, the rate/availability substrate the SLO burn windows
  (serve/monitor.py) sum over. Old rings expire by overwrite — no
  allocation, no unbounded growth on a long-lived service.
* **Gauges** — point-in-time values (queue depth, zoo entries, resident
  panel/param bytes, ``circuit_state``, ``slo_burn``, drift PSI), set
  by the monitor at collection time.
* :class:`ScoreSketch` — the score-drift monitor's distribution sketch:
  running moments (count/mean/M2) plus a fixed-edge histogram. At
  publish each zoo generation is stamped with a REFERENCE sketch of its
  batch-scored months; served scores stream into a LIVE sketch with
  the same edges; :meth:`ScoreSketch.psi` is the PSI-style divergence
  the ``score_drift_psi`` gauge reports and the (knob-gated) publish
  veto reads (DESIGN.md §19).
* :func:`render_prometheus` — Prometheus text exposition (format
  0.0.4) over this registry PLUS the absorbed ``telemetry.COUNTERS``
  (every counter the spans already attribute is scrapeable live as
  ``lfm_<name>_total`` — one counter store, two consumers, no drift).

Knobs: ``LFM_METRICS`` (default ON; ``0`` = exact no-op — every
mutator returns on one env read, nothing records, nothing allocates,
and no metrics code path ever touches a device: no device_get, no
block_until_ready, no trace — the measured non-interference contract
of the ``metrics`` test lane), ``LFM_SLO_P99_MS`` / ``LFM_SLO_AVAIL``
(the declared SLO objectives the burn rates are computed against),
``LFM_DRIFT_MAX`` (the PSI threshold), ``LFM_DRIFT_GATE`` (default
OFF: whether a breached drift gauge VETOES the next atomic publish).
"""

from __future__ import annotations

import math
import os
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple


def enabled() -> bool:
    """Master kill switch: ``LFM_METRICS=0`` disables every mutator in
    this module (exact no-op — one env read and a compare; the
    telemetry-layer convention)."""
    return os.environ.get("LFM_METRICS", "1") != "0"


# ---- SLO / drift knobs ---------------------------------------------------


def slo_p99_ms_default() -> float:
    """``LFM_SLO_P99_MS``: the declared p99 latency objective in ms —
    requests slower than this consume latency error budget (default
    250; <= 0 disables the latency SLO)."""
    return float(os.environ.get("LFM_SLO_P99_MS", "250"))


def slo_avail_default() -> float:
    """``LFM_SLO_AVAIL``: the declared availability objective as a
    fraction (default 0.999 — an error budget of 0.1% of requests;
    <= 0 or >= 1 disables the availability SLO)."""
    return float(os.environ.get("LFM_SLO_AVAIL", "0.999"))


def drift_max_default() -> float:
    """``LFM_DRIFT_MAX``: the PSI divergence past which a generation's
    served-score distribution counts as DRIFTED from its publish-time
    reference (default 0.2 — between the classic 0.1 "moderate" and
    0.25 "major" PSI rules of thumb; <= 0 disables drift evaluation)."""
    return float(os.environ.get("LFM_DRIFT_MAX", "0.2"))


def drift_gate_enabled() -> bool:
    """``LFM_DRIFT_GATE``: when ``1``, a universe whose served scores
    breach ``LFM_DRIFT_MAX`` VETOES its next atomic publish
    (serve/errors.py DriftVetoError) — the first concrete piece of the
    ROADMAP 5b risk gate. Default OFF: the gauge and /healthz detail
    flip either way; blocking an operator's publish is an opt-in."""
    return os.environ.get("LFM_DRIFT_GATE", "0") == "1"


# ---- log-spaced histogram ------------------------------------------------


class LogHistogram:
    """Fixed log-spaced-bucket histogram: O(1) record, lock-guarded,
    mergeable, bounded-error quantiles.

    Bucket ``i`` (1-based) holds values in ``(lo·g^(i-1), lo·g^i]``
    with ``g = 10^(1/buckets_per_decade)``; bucket 0 is the underflow
    (``<= lo``), the last bucket the overflow (``> hi``). Estimated
    quantiles interpolate inside one bucket, so they can never be off
    by more than that bucket's width — a RELATIVE error of ``g − 1``
    (:attr:`rel_resolution`, ~12.2% at the default 20 buckets/decade).
    Exact ``count``/``sum``/``min``/``max`` are tracked alongside, so
    totals and means carry no bucketing error at all."""

    __slots__ = ("lo", "hi", "growth", "_log_lo", "_inv_log_g",
                 "_counts", "count", "sum", "vmin", "vmax", "_lock",
                 "_exemplars")

    def __init__(self, lo: float = 1e-2, hi: float = 1e5,
                 buckets_per_decade: int = 20):
        if not (0 < lo < hi):
            raise ValueError(f"need 0 < lo < hi, got lo={lo} hi={hi}")
        self.lo = float(lo)
        self.hi = float(hi)
        self.growth = 10.0 ** (1.0 / max(1, int(buckets_per_decade)))
        self._log_lo = math.log(self.lo)
        self._inv_log_g = 1.0 / math.log(self.growth)
        n = int(math.ceil((math.log(self.hi) - self._log_lo)
                          * self._inv_log_g))
        # [underflow] + n log buckets + [overflow]
        self._counts = [0] * (n + 2)
        self.count = 0
        self.sum = 0.0
        self.vmin = math.inf
        self.vmax = -math.inf
        # Exemplars (DESIGN.md §21): per bucket, the LAST (trace_id,
        # value, unix_ts) that landed there — one fixed slot per
        # bucket (the classic Prometheus client behavior), so a p99
        # bucket points at a real request id whose full phase
        # breakdown lives in the span record / slow-trace tracker.
        # Bounded by construction: at most one tuple per bucket.
        self._exemplars: Dict[int, Tuple[str, float, float]] = {}
        self._lock = threading.Lock()

    @property
    def rel_resolution(self) -> float:
        """The one-bucket relative error bound of estimated quantiles."""
        return self.growth - 1.0

    def _index(self, v: float) -> int:
        if v <= self.lo:
            return 0
        # ceil() so bucket i's upper bound lo·g^i is INCLUSIVE — the
        # cumulative counts then match the Prometheus `le` semantics.
        i = int(math.ceil((math.log(v) - self._log_lo) * self._inv_log_g))
        return min(max(i, 1), len(self._counts) - 1)

    def record(self, v: float, exemplar: Optional[str] = None) -> None:
        v = float(v)
        i = self._index(v)
        with self._lock:
            self._counts[i] += 1
            self.count += 1
            self.sum += v
            if v < self.vmin:
                self.vmin = v
            if v > self.vmax:
                self.vmax = v
            if exemplar is not None:
                self._exemplars[i] = (exemplar, v, time.time())

    def exemplars(self) -> List[Dict[str, Any]]:
        """The per-bucket exemplars, ascending by bucket bound: each a
        ``{le, trace_id, value, ts}`` record — the trace ids a scrape
        consumer (or an incident bundle reader) follows back to real
        request traces. Kept OUT of the text exposition on purpose:
        OpenMetrics exemplar syntax is not valid Prometheus text 0.0.4
        and would break every existing parse twin; the JSON surfaces
        (``metrics_snapshot()``, incident bundles) carry them instead."""
        with self._lock:
            items = sorted(self._exemplars.items())
        out = []
        for i, (t, v, ts) in items:
            le = self.upper_bound(i)
            out.append({"le": (le if math.isfinite(le) else None),
                        "trace_id": t, "value": v, "ts": ts})
        return out

    def merge(self, other: "LogHistogram") -> None:
        """Fold another histogram of the SAME geometry into this one
        (label-set rollups — e.g. all universes into one ladder view)."""
        if (other.lo, other.hi, other.growth) != (self.lo, self.hi,
                                                  self.growth):
            raise ValueError("cannot merge histograms with different "
                             "bucket geometry")
        with other._lock:
            counts = list(other._counts)
            cnt, s = other.count, other.sum
            vmin, vmax = other.vmin, other.vmax
        with self._lock:
            for i, c in enumerate(counts):
                self._counts[i] += c
            self.count += cnt
            self.sum += s
            self.vmin = min(self.vmin, vmin)
            self.vmax = max(self.vmax, vmax)

    def upper_bound(self, i: int) -> float:
        """Bucket i's inclusive upper bound (+inf for the overflow)."""
        if i >= len(self._counts) - 1:
            return math.inf
        return self.lo * self.growth ** i

    def quantile(self, q: float) -> Optional[float]:
        """Estimated ``q``-quantile (q in [0, 100], the percentile
        convention of ``serve/stats.py``): linear interpolation inside
        the covering bucket, clamped to the exact observed min/max so
        degenerate streams (all-equal values) estimate exactly."""
        with self._lock:
            if self.count == 0:
                return None
            counts = list(self._counts)
            total = self.count
            vmin, vmax = self.vmin, self.vmax
        rank = (total - 1) * q / 100.0
        cum = 0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if cum + c > rank:
                # Interpolate inside bucket i between its bounds.
                lo_b = self.lo * self.growth ** (i - 1) if i >= 1 else vmin
                hi_b = self.upper_bound(i)
                if not math.isfinite(hi_b):
                    hi_b = vmax
                frac = (rank - cum + 0.5) / c
                est = lo_b + (hi_b - lo_b) * min(max(frac, 0.0), 1.0)
                return float(min(max(est, vmin), vmax))
            cum += c
        return float(vmax)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            counts = list(self._counts)
            out = {"count": self.count, "sum": round(self.sum, 6),
                   "min": (None if self.count == 0 else self.vmin),
                   "max": (None if self.count == 0 else self.vmax)}
        out["p50"] = self.quantile(50.0)
        out["p99"] = self.quantile(99.0)
        out["nonzero_buckets"] = sum(1 for c in counts if c)
        return out

    def prom_snapshot(self) -> Tuple[List[Tuple[float, int]], int, float]:
        """One locked read of ``(prom_buckets, count, sum)`` — the
        exposition needs the three CONSISTENT (a record landing between
        a bucket copy and an unlocked count read would emit a
        ``_count`` larger than its own +Inf bucket, violating the
        Prometheus histogram invariant scrape consumers assume)."""
        with self._lock:
            counts = list(self._counts)
            total = self.count
            s = self.sum
        out: List[Tuple[float, int]] = []
        cum = 0
        last = max((i for i, c in enumerate(counts) if c), default=-1)
        # Walk finite buckets only (cap BEFORE the overflow slot): the
        # overflow's upper bound IS +Inf, so walking into it would emit
        # a duplicate +Inf series beside the total appended below —
        # Prometheus rejects the whole scrape on duplicate samples.
        for i, c in enumerate(counts[:min(last + 1, len(counts) - 1)]):
            cum += c
            out.append((self.upper_bound(i), cum))
        out.append((math.inf, total))
        return out, total, s

    def prom_buckets(self) -> List[Tuple[float, int]]:
        """Cumulative ``(le_upper_bound, count)`` pairs, Prometheus
        histogram semantics (only buckets up to the last non-empty one
        plus the +Inf total — a 142-bucket ladder would otherwise emit
        a page of zeros per label set)."""
        return self.prom_snapshot()[0]


# ---- windowed ring aggregation -------------------------------------------


class WindowedRing:
    """Sliding-window event aggregation: ``rings`` slots of ``ring_s``
    seconds each (default 30 × 10 s = the last 5 minutes). ``add`` is
    O(1) (index, maybe reset, accumulate); ``total``/``rate`` sum the
    slots still inside the asked window. Slots expire by overwrite —
    constant memory on an always-on service. ``now`` is injectable for
    deterministic tests."""

    __slots__ = ("ring_s", "rings", "_epoch", "_val", "_lock")

    def __init__(self, ring_s: float = 10.0, rings: int = 30):
        self.ring_s = float(ring_s)
        self.rings = max(2, int(rings))
        self._epoch = [-1] * self.rings   # absolute ring index, -1 empty
        self._val = [0.0] * self.rings
        self._lock = threading.Lock()

    @property
    def span_s(self) -> float:
        """The longest window this ring can answer for."""
        return self.ring_s * self.rings

    def add(self, value: float = 1.0, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        epoch = int(now / self.ring_s)
        slot = epoch % self.rings
        with self._lock:
            if self._epoch[slot] != epoch:
                self._epoch[slot] = epoch
                self._val[slot] = 0.0
            self._val[slot] += value

    def total(self, window_s: float, now: Optional[float] = None) -> float:
        """Sum of values recorded within the last ``window_s`` seconds
        (quantized to whole rings — the youngest ``ceil(window/ring)``
        of them; a ring is counted iff it could hold in-window events)."""
        now = time.monotonic() if now is None else now
        epoch = int(now / self.ring_s)
        n_rings = min(self.rings,
                      max(1, int(math.ceil(window_s / self.ring_s))))
        oldest = epoch - n_rings + 1
        with self._lock:
            return sum(v for e, v in zip(self._epoch, self._val)
                       if e >= oldest)

    def rate(self, window_s: float, now: Optional[float] = None) -> float:
        """Events (or value) per second over the window."""
        w = min(max(window_s, self.ring_s), self.span_s)
        return self.total(window_s, now) / w


# ---- score-drift sketch --------------------------------------------------


class ScoreSketch:
    """A score-distribution sketch: running moments + fixed-edge
    histogram. The REFERENCE sketch (built once at publish from the
    generation's batch-scored months) defines the bin edges; the LIVE
    sketch (streamed from served responses) shares them, so the two are
    always comparable. PSI is the classic population-stability index
    over the shared bins with Laplace smoothing.

    Recording is lock-guarded — the batcher thread streams while
    /metrics scrapes — and comes in two shapes: :meth:`record`
    (vectorized, one ``np.histogram`` per call) and
    :meth:`record_lazy`, the serving hot path. The lazy path matters:
    numpy calls RELEASE the GIL, and on the batcher's critical path a
    GIL release under closed-loop client contention costs a scheduling
    quantum, not microseconds (measured ~16% of serve throughput when
    the histogram ran per dispatch). ``record_lazy`` is a bare list
    append under the lock — O(1), no numpy, no GIL release — and every
    READER folds the pending arrays down first (plus an amortized
    inline fold past ``LAZY_FOLD_LIMIT`` so an unscraped service can't
    grow the buffer unboundedly)."""

    __slots__ = ("edges", "_counts", "n", "_sum", "_sumsq", "_lock",
                 "_pending")

    #: Pending lazy-record arrays folded inline past this many entries
    #: (amortized: one vectorized fold per LIMIT batches).
    LAZY_FOLD_LIMIT = 256

    def __init__(self, edges):
        import numpy as np

        self.edges = np.asarray(edges, np.float64)
        if self.edges.ndim != 1 or self.edges.size < 2:
            raise ValueError("ScoreSketch needs >= 2 bin edges")
        # len(edges)-1 interior bins + underflow + overflow
        self._counts = np.zeros(self.edges.size + 1, np.int64)
        self.n = 0
        self._sum = 0.0
        self._sumsq = 0.0
        self._pending: List[Any] = []
        self._lock = threading.Lock()

    @classmethod
    def reference(cls, scores, bins: int = 16,
                  span_sigmas: float = 4.0) -> "ScoreSketch":
        """Build the publish-time reference: linear edges over
        mean ± ``span_sigmas``·std of the reference scores (degenerate
        distributions widen to a unit span), then record them."""
        import numpy as np

        s = np.asarray(scores, np.float64).ravel()
        s = s[np.isfinite(s)]
        if s.size == 0:
            raise ValueError("reference sketch needs at least one "
                             "finite score")
        mu = float(s.mean())
        sd = float(s.std())
        if not (sd > 0):
            sd = max(abs(mu), 1.0) * 1e-3
        half = span_sigmas * sd
        sk = cls(np.linspace(mu - half, mu + half, max(2, int(bins)) + 1))
        sk.record(s)
        return sk

    def live_twin(self) -> "ScoreSketch":
        """An empty sketch over the SAME edges — what served scores
        stream into."""
        return ScoreSketch(self.edges)

    def record_lazy(self, arr) -> None:
        """The serving hot path: O(1) append under the lock — no
        numpy, no GIL release on the batcher's critical path. Folded
        into the counts by the next reader (or inline, amortized, past
        ``LAZY_FOLD_LIMIT`` pending arrays)."""
        fold_now = None
        with self._lock:
            self._pending.append(arr)
            if len(self._pending) >= self.LAZY_FOLD_LIMIT:
                fold_now, self._pending = self._pending, []
        if fold_now is not None:
            self._fold(fold_now)

    def drain(self) -> None:
        """Fold every pending lazy record down into the counts (all
        readers call this first, so lazy mass is never invisible)."""
        with self._lock:
            if not self._pending:
                return
            pending, self._pending = self._pending, []
        self._fold(pending)

    def _fold(self, arrays) -> None:
        import numpy as np

        self.record(arrays[0] if len(arrays) == 1
                    else np.concatenate(
                        [np.asarray(a, np.float64).ravel()
                         for a in arrays]))

    def size(self) -> int:
        """Scores recorded so far, pending lazy mass included."""
        with self._lock:
            return self.n + sum(int(getattr(a, "size", 0))
                                for a in self._pending)

    def record(self, arr) -> None:
        import numpy as np

        a = np.asarray(arr, np.float64).ravel()
        a = a[np.isfinite(a)]
        if a.size == 0:
            return
        inner, _ = np.histogram(a, bins=self.edges)
        under = int((a <= self.edges[0]).sum())
        over = int((a > self.edges[-1]).sum())
        # np.histogram's first bin is closed on the left — keep values
        # exactly at edge[0] in the underflow for a stable partition.
        first = int((a == self.edges[0]).sum())
        with self._lock:
            self._counts[0] += under
            self._counts[1:-1] += inner
            self._counts[1] -= first
            self._counts[-1] += over
            self.n += int(a.size)
            self._sum += float(a.sum())
            self._sumsq += float((a * a).sum())

    # -- introspection -------------------------------------------------

    def mean(self) -> Optional[float]:
        self.drain()
        with self._lock:
            return self._sum / self.n if self.n else None

    def std(self) -> Optional[float]:
        self.drain()
        with self._lock:
            if self.n == 0:
                return None
            var = self._sumsq / self.n - (self._sum / self.n) ** 2
            return math.sqrt(max(var, 0.0))

    def counts(self):
        self.drain()
        with self._lock:
            return self._counts.copy()

    def psi(self, live: "ScoreSketch") -> Optional[float]:
        """Population-stability index of ``live`` against this
        reference over the shared bins (None until the live sketch has
        any mass). Laplace-smoothed so empty bins cannot produce
        infinities; 0 = identical, ~0.1 moderate shift, > 0.25 major
        (the classic rule of thumb — ``LFM_DRIFT_MAX`` defaults between
        them at 0.2)."""
        import numpy as np

        if (self.edges.size != live.edges.size
                or not bool(np.all(self.edges == live.edges))):
            raise ValueError("psi() needs sketches over the same edges")
        ref_c = self.counts().astype(np.float64)
        live_c = live.counts().astype(np.float64)
        if ref_c.sum() == 0 or live_c.sum() == 0:
            return None
        p = (ref_c + 0.5) / (ref_c.sum() + 0.5 * ref_c.size)
        q = (live_c + 0.5) / (live_c.sum() + 0.5 * live_c.size)
        return float(np.sum((q - p) * np.log(q / p)))

    def snapshot(self) -> Dict[str, Any]:
        self.drain()
        with self._lock:
            return {"n": int(self.n),
                    "mean": (self._sum / self.n if self.n else None),
                    "lo": float(self.edges[0]),
                    "hi": float(self.edges[-1]),
                    "bins": int(self.edges.size - 1)}

    # -- durable serialization (serve/persist.py, DESIGN.md §20) -------

    def to_state(self) -> Dict[str, Any]:
        """JSON-serializable full state (edges + counts + moments) —
        what the durable zoo store writes at publish so a restore can
        re-stamp the drift reference WITHOUT re-scoring a single month.
        Lazy mass is drained first, so the state is exact."""
        self.drain()
        with self._lock:
            return {"edges": [float(e) for e in self.edges],
                    "counts": [int(c) for c in self._counts],
                    "n": int(self.n),
                    "sum": float(self._sum),
                    "sumsq": float(self._sumsq)}

    @classmethod
    def from_state(cls, state: Dict[str, Any]) -> "ScoreSketch":
        """Rebuild a sketch from :meth:`to_state` output. Loud on a
        malformed state (wrong counts length) — a durable artifact that
        half-parses must never silently stamp a wrong reference."""
        sk = cls(state["edges"])
        import numpy as np

        counts = np.asarray(state["counts"], np.int64)
        if counts.shape != sk._counts.shape:
            raise ValueError(
                f"sketch state counts length {counts.size} does not match "
                f"{sk._counts.size} for {sk.edges.size} edges")
        sk._counts = counts
        sk.n = int(state["n"])
        sk._sum = float(state["sum"])
        sk._sumsq = float(state["sumsq"])
        return sk


# ---- registry ------------------------------------------------------------

LabelTuple = Tuple[Tuple[str, str], ...]


def _labels(kw: Dict[str, Any]) -> LabelTuple:
    return tuple(sorted((k, str(v)) for k, v in kw.items()))


class MetricsRegistry:
    """The process-wide instrument store: named histograms, windowed
    rings and gauges, each keyed by (name, sorted label tuple).
    Creation is guarded by the registry lock; each instrument then
    guards its own mutation (two-level locking so a 142-bucket
    histogram write never serializes against an unrelated gauge set).
    Every mutator is an EXACT no-op under ``LFM_METRICS=0``."""

    def __init__(self):
        self._hists: Dict[Tuple[str, LabelTuple], LogHistogram] = {}
        self._rings: Dict[Tuple[str, LabelTuple], WindowedRing] = {}
        self._gauges: Dict[Tuple[str, LabelTuple], float] = {}
        self._lock = threading.Lock()

    # -- mutators (all gated on enabled()) ----------------------------

    def observe(self, name: str, value: float, **labels) -> None:
        """Record one value into the named histogram (created on first
        use with the default latency geometry)."""
        if not enabled():
            return
        self.histogram(name, **labels).record(value)

    def mark(self, name: str, value: float = 1.0,
             now: Optional[float] = None, **labels) -> None:
        """Add to the named windowed ring (rates / SLO events)."""
        if not enabled():
            return
        self.ring(name, **labels).add(value, now=now)

    def gauge(self, name: str, value: float, **labels) -> None:
        if not enabled():
            return
        with self._lock:
            self._gauges[(name, _labels(labels))] = value

    def clear_gauges(self, name: str) -> None:
        """Drop every label set of the named gauge. Per-entity gauges
        (drift PSI per (universe, generation), param bytes per
        universe) are re-set at each collection — without clearing
        first, a retired generation's PSI or an evicted universe's
        bytes would sit in the exposition forever, firing alerts for a
        series that no longer serves."""
        with self._lock:
            for key in [k for k in self._gauges if k[0] == name]:
                del self._gauges[key]

    # -- instrument access --------------------------------------------

    def histogram(self, name: str, **labels) -> LogHistogram:
        key = (name, _labels(labels))
        h = self._hists.get(key)
        if h is None:
            with self._lock:
                h = self._hists.get(key)
                if h is None:
                    h = self._hists[key] = LogHistogram()
        return h

    def ring(self, name: str, **labels) -> WindowedRing:
        key = (name, _labels(labels))
        r = self._rings.get(key)
        if r is None:
            with self._lock:
                r = self._rings.get(key)
                if r is None:
                    r = self._rings[key] = WindowedRing()
        return r

    def merged_histogram(self, name: str) -> Optional[LogHistogram]:
        """All label sets of ``name`` folded into one histogram (the
        blended view — per-label histograms stay the primary record)."""
        with self._lock:
            hists = [h for (n, _), h in self._hists.items() if n == name]
        if not hists:
            return None
        bpd = int(round(1.0 / math.log10(hists[0].growth)))
        out = LogHistogram(hists[0].lo, hists[0].hi,
                           buckets_per_decade=bpd)
        for h in hists:
            out.merge(h)
        return out

    def window_total(self, name: str, window_s: float,
                     now: Optional[float] = None, **labels) -> float:
        key = (name, _labels(labels))
        r = self._rings.get(key)
        return r.total(window_s, now=now) if r is not None else 0.0

    # -- introspection -------------------------------------------------

    def exemplar_snapshot(self, name: Optional[str] = None
                          ) -> Dict[str, List[Dict[str, Any]]]:
        """Every histogram's exemplars (``name`` filters), keyed the
        snapshot way — the JSON surface trace ids ride out on (the
        text exposition stays exemplar-free; see
        :meth:`LogHistogram.exemplars`)."""
        with self._lock:
            hists = [(k, h) for k, h in sorted(self._hists.items())
                     if name is None or k[0] == name]
        out: Dict[str, List[Dict[str, Any]]] = {}
        for k, h in hists:
            ex = h.exemplars()
            if ex:
                out[_fmt_key(k)] = ex
        return out

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            hists = dict(self._hists)
            rings = dict(self._rings)
            gauges = dict(self._gauges)
        return {
            "histograms": {_fmt_key(k): h.snapshot()
                           for k, h in sorted(hists.items())},
            "rates_per_sec": {
                _fmt_key(k): {"60s": round(r.rate(60.0), 4),
                              "300s": round(r.rate(300.0), 4)}
                for k, r in sorted(rings.items())},
            "gauges": {_fmt_key(k): v for k, v in sorted(gauges.items())},
        }

    def reset(self) -> None:
        with self._lock:
            self._hists.clear()
            self._rings.clear()
            self._gauges.clear()


def _fmt_key(key: Tuple[str, LabelTuple]) -> str:
    name, labels = key
    if not labels:
        return name
    return name + "{" + ",".join(f"{k}={v}" for k, v in labels) + "}"


#: The process-wide registry (the ``telemetry.COUNTERS`` convention:
#: one store, many writers, scraped by serve/monitor.py).
METRICS = MetricsRegistry()


# ---- Prometheus text exposition ------------------------------------------


def _prom_name(name: str) -> str:
    return "lfm_" + "".join(c if c.isalnum() or c == "_" else "_"
                            for c in name)


def _prom_labels(labels: LabelTuple) -> str:
    if not labels:
        return ""
    body = ",".join(
        '{}="{}"'.format(k, str(v).replace("\\", "\\\\")
                         .replace('"', '\\"'))
        for k, v in labels)
    return "{" + body + "}"


def _prom_num(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(float(v)) if isinstance(v, float) else str(v)


def render_prometheus(registry: MetricsRegistry = None,
                      counters: Optional[Dict[str, Any]] = None,
                      ts: Optional[float] = None) -> str:
    """The ``GET /metrics`` document: every histogram/ring/gauge in the
    registry plus the absorbed telemetry counters, Prometheus text
    format 0.0.4. Pure host-side string building over locked snapshots
    — no device work, ever."""
    registry = METRICS if registry is None else registry
    lines: List[str] = []
    with registry._lock:
        hists = sorted(registry._hists.items())
        rings = sorted(registry._rings.items())
        gauges = sorted(registry._gauges.items())

    seen_types: set = set()

    def _typ(pname: str, kind: str, help_: str) -> None:
        if pname not in seen_types:
            seen_types.add(pname)
            lines.append(f"# HELP {pname} {help_}")
            lines.append(f"# TYPE {pname} {kind}")

    for (name, labels), h in hists:
        pname = _prom_name(name)
        _typ(pname, "histogram",
             f"log-spaced histogram of {name} (utils/metrics.py)")
        base = _prom_labels(labels)[1:-1] if labels else ""
        pairs, count, hsum = h.prom_snapshot()
        for le, cum in pairs:
            lab = (base + "," if base else "") + f'le="{_prom_num(le)}"'
            lines.append(f"{pname}_bucket{{{lab}}} {cum}")
        lines.append(f"{pname}_sum{_prom_labels(labels)} "
                     f"{_prom_num(hsum)}")
        lines.append(f"{pname}_count{_prom_labels(labels)} {count}")

    for (name, labels), r in rings:
        pname = _prom_name(name) + "_rate_per_sec"
        _typ(pname, "gauge",
             f"windowed rate of {name} (ring aggregation)")
        for w in (60, 300):
            lab = dict(labels)
            lab["window"] = f"{w}s"
            lines.append(f"{pname}{_prom_labels(_labels(lab))} "
                         f"{_prom_num(round(r.rate(float(w)), 6))}")

    for (name, labels), v in gauges:
        pname = _prom_name(name)
        _typ(pname, "gauge", f"{name} (utils/metrics.py gauge)")
        lines.append(f"{pname}{_prom_labels(labels)} {_prom_num(v)}")

    if counters:
        for name in sorted(counters):
            v = counters[name]
            if not isinstance(v, (int, float)):
                continue
            pname = _prom_name(name) + "_total"
            _typ(pname, "counter",
                 f"process-wide counter {name} (telemetry registry)")
            lines.append(f"{pname} {_prom_num(float(v))}")

    pts = _prom_name("scrape_ts_seconds")
    _typ(pts, "gauge", "unix time of this scrape")
    lines.append(f"{pts} {repr(time.time() if ts is None else ts)}")
    return "\n".join(lines) + "\n"


def hist_quantile_from_buckets(pairs: Sequence[Tuple[float, float]],
                               q: float) -> Optional[float]:
    """Estimated ``q``-quantile (q in [0, 100]) from CUMULATIVE
    ``(le_upper_bound, count)`` histogram pairs — the scrape-side twin
    of :meth:`LogHistogram.quantile` (same rank rule, same in-bucket
    interpolation), for consumers that only hold a rendered
    ``/metrics`` document. The VERBATIM twin lives in
    ``scripts/trace_report.py`` (no package dependency there); the
    metrics test lane pins the two against each other and against the
    in-process histogram on the same stream."""
    if not pairs:
        return None
    pairs = sorted(pairs, key=lambda p: p[0])
    total = pairs[-1][1]
    if total <= 0:
        return None
    rank = (total - 1) * q / 100.0
    prev_le, prev_cum = 0.0, 0.0
    for le, cum in pairs:
        if cum > rank and cum > prev_cum:
            if not math.isfinite(le):
                return float(prev_le)  # overflow bucket: clamp
            c = cum - prev_cum
            frac = (rank - prev_cum + 0.5) / c
            return float(prev_le + (le - prev_le)
                         * min(max(frac, 0.0), 1.0))
        if math.isfinite(le):
            prev_le, prev_cum = le, max(prev_cum, cum)
    return float(prev_le)


# ---- scrape parsing (shared with scripts/trace_report.py twin) -----------


def parse_prometheus(text: str) -> Dict[str, List[Tuple[Dict[str, str],
                                                        float]]]:
    """Parse a Prometheus text scrape into name → [(labels, value)].
    The VERBATIM twin lives in ``scripts/trace_report.py`` (which must
    stay importable with no package dependency); the metrics test lane
    cross-checks the two on the same scrape, the percentile-twin
    discipline applied to parsing."""
    out: Dict[str, List[Tuple[Dict[str, str], float]]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            head, _, val = line.rpartition(" ")
            if "{" in head:
                name, _, rest = head.partition("{")
                body = rest.rsplit("}", 1)[0]
                labels: Dict[str, str] = {}
                for part in body.split(","):
                    if not part:
                        continue
                    k, _, v = part.partition("=")
                    labels[k.strip()] = v.strip().strip('"')
            else:
                name, labels = head, {}
            v = float("inf") if val == "+Inf" else float(val)
            out.setdefault(name.strip(), []).append((labels, v))
        except ValueError:
            continue  # never die on a foreign exposition line
    return out
