"""Numerical sanitizers (SURVEY.md §6 "race detection / sanitizers" row).

JAX's functional purity removes in-model data races by construction; the
numerical failure modes that remain (NaN/Inf from bad losses, exploding
grads, bf16 overflow) are caught by jax's debug-nans machinery plus chex
shape/finiteness asserts at the step boundary. ``sanitized()`` is the CI
mode: any NaN/Inf produced inside jit raises at the op that made it.
"""

from __future__ import annotations

import contextlib

import jax


@contextlib.contextmanager
def sanitized(nans: bool = True, infs: bool = True):
    """Context manager enabling jax_debug_nans/_infs for the enclosed code.

    Slows execution (disables some fusion; re-runs failing ops eagerly to
    locate them) — CI/debug only, never in the benchmark path.
    """
    prev_nans = jax.config.jax_debug_nans
    prev_infs = jax.config.jax_debug_infs
    try:
        jax.config.update("jax_debug_nans", nans)
        jax.config.update("jax_debug_infs", infs)
        yield
    finally:
        jax.config.update("jax_debug_nans", prev_nans)
        jax.config.update("jax_debug_infs", prev_infs)


def assert_finite_tree(tree, name: str = "tree"):
    """Host-side finiteness check over a pytree (eval/test helper)."""
    import numpy as np

    bad = [
        path
        for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]
        if not np.all(np.isfinite(np.asarray(leaf)))
    ]
    if bad:
        raise FloatingPointError(f"non-finite leaves in {name}: {bad}")
