"""Utilities: throughput/profiling harness, structured metric logging."""

from lfm_quant_tpu.utils.debug import assert_finite_tree, sanitized
from lfm_quant_tpu.utils.logging import MetricsLogger
from lfm_quant_tpu.utils.profiling import StepTimer, trace_context

__all__ = [
    "MetricsLogger",
    "StepTimer",
    "trace_context",
    "sanitized",
    "assert_finite_tree",
]
