"""Utilities: throughput/profiling harness, structured metric logging,
and the unified run-telemetry layer (spans, counters, manifests)."""

from lfm_quant_tpu.utils import telemetry
from lfm_quant_tpu.utils.debug import assert_finite_tree, sanitized
from lfm_quant_tpu.utils.logging import MetricsLogger
from lfm_quant_tpu.utils.profiling import StepTimer, trace_context

__all__ = [
    "MetricsLogger",
    "StepTimer",
    "telemetry",
    "trace_context",
    "sanitized",
    "assert_finite_tree",
]
