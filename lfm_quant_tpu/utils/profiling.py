"""Profiling / throughput harness (SURVEY.md §6: tracing subsystem).

The reference's observability was at most TF timeline/TensorBoard
[BACKGROUND]; the TPU-native equivalents are ``jax.profiler`` traces
(Perfetto-viewable) and a ``block_until_ready`` wall-clock harness that
reports **firm-months/sec/chip** — the driver's primary metric
(BASELINE.json:2).

Definition used throughout: one *firm-month* = one (firm, month) panel
observation consumed by the model. A training step over ``B`` windows of
length ``W`` with ``v`` real (non-padded) samples processes ``v × W``
firm-months.
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional

import jax


@contextlib.contextmanager
def trace_context(log_dir: Optional[str]):
    """Wrap a region in a jax.profiler trace when ``log_dir`` is set.

    View with Perfetto (ui.perfetto.dev) or TensorBoard's profile plugin.
    """
    if log_dir:
        jax.profiler.start_trace(log_dir)
        try:
            yield
        finally:
            jax.profiler.stop_trace()
    else:
        yield


class StepTimer:
    """Wall-clock step timer with device-sync and firm-month accounting.

    Usage:
        t = StepTimer()
        t.start()                      # syncs + stamps
        out = step(...)                # async dispatch
        t.stop(out, firm_months=n)     # block_until_ready + stamp
        t.throughput()                 # firm-months/sec over recorded steps
    """

    def __init__(self):
        self.reset()

    def reset(self):
        self._t0 = None
        self.seconds = 0.0
        self.firm_months = 0.0
        self.steps = 0

    def start(self):
        self._t0 = time.perf_counter()

    def stop(self, device_out=None, firm_months: float = 0.0):
        if device_out is not None:
            jax.block_until_ready(device_out)
        dt = time.perf_counter() - self._t0
        self.seconds += dt
        self.firm_months += firm_months
        self.steps += 1
        return dt

    def throughput(self) -> float:
        """firm-months/sec over all recorded steps (0 if nothing timed)."""
        return self.firm_months / self.seconds if self.seconds > 0 else 0.0
