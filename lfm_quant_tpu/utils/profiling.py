"""Profiling / throughput harness (SURVEY.md §6: tracing subsystem).

The reference's observability was at most TF timeline/TensorBoard
[BACKGROUND]; the TPU-native equivalents are ``jax.profiler`` traces
(Perfetto-viewable) and a ``block_until_ready`` wall-clock harness that
reports **firm-months/sec/chip** — the driver's primary metric
(BASELINE.json:2).

Definition used throughout: one *firm-month* = one (firm, month) panel
observation consumed by the model. A training step over ``B`` windows of
length ``W`` with ``v`` real (non-padded) samples processes ``v × W``
firm-months.

Since the unified telemetry layer (utils/telemetry.py), the counters
here are a fixed-field **view** over the process-wide named-counter
registry ``telemetry.COUNTERS``: every bump lands in the registry, so
spans get per-span counter deltas while ``REUSE_COUNTERS``'s
snapshot/delta surface (and every lane that asserts on it) keeps
working unchanged.
"""

from __future__ import annotations

import contextlib
import functools
import threading
import time
import warnings
from typing import Callable, Dict, Optional, Union

import jax

from lfm_quant_tpu.utils.telemetry import COUNTERS


@contextlib.contextmanager
def trace_context(log_dir: Optional[str]):
    """Wrap a region in a jax.profiler trace when ``log_dir`` is set.

    View with Perfetto (ui.perfetto.dev) or TensorBoard's profile plugin.
    """
    if log_dir:
        jax.profiler.start_trace(log_dir)
        try:
            yield
        finally:
            jax.profiler.stop_trace()
    else:
        yield


class StepTimer:
    """Wall-clock step timer with device-sync and firm-month accounting.

    Usage:
        t = StepTimer()
        t.start()                      # syncs + stamps
        out = step(...)                # async dispatch
        t.stop(out, firm_months=n)     # block_until_ready + stamp
        t.throughput()                 # firm-months/sec over recorded steps
    """

    def __init__(self):
        self.reset()

    def reset(self):
        self._t0 = None
        self.seconds = 0.0
        self.firm_months = 0.0
        self.steps = 0

    def start(self):
        self._t0 = time.perf_counter()

    def stop(self, device_out=None, firm_months: float = 0.0):
        """Record one interval since :meth:`start` (blocking on
        ``device_out`` first). Calling it with no matching ``start()``
        ever issued is a caller bug, but an interval of "since the
        epoch" would silently poison every later throughput number — so
        it warns and records nothing instead of raising an opaque
        ``TypeError`` off ``None`` arithmetic."""
        if self._t0 is None:
            warnings.warn(
                "StepTimer.stop() called before start() — no interval is "
                "open; ignoring this stop (throughput unchanged)",
                RuntimeWarning, stacklevel=2)
            return 0.0
        if device_out is not None:
            jax.block_until_ready(device_out)
        dt = time.perf_counter() - self._t0
        self.seconds += dt
        self.firm_months += firm_months
        self.steps += 1
        return dt

    def throughput(self) -> float:
        """firm-months/sec over all recorded steps (0 if nothing timed)."""
        return self.firm_months / self.seconds if self.seconds > 0 else 0.0


class ReuseCounters:
    """Process-wide compile/transfer accounting for the cross-fold reuse
    layer (train/reuse.py). The point of the walk-forward reuse work is
    that fold k+1 pays ZERO re-tracing and ZERO panel H2D re-transfer —
    these counters make that a measured, assertable property (fold
    records in train/walkforward.py, the ``walkforward_reuse`` bench
    metric, and the ``reuse``-marked regression tests) instead of a
    claim.

    Storage lives in ``telemetry.COUNTERS`` (each field is a property
    over the registry), so the same counters feed per-span deltas in the
    telemetry layer; this class is the stable fixed-field view the reuse
    and pipeline lanes assert against.

    * ``jit_traces`` — number of times a reuse-layer jitted program was
      (re)traced. Python trace == XLA (re)compile for these programs:
      each wrapper body (see :func:`count_traces`) only executes when
      jax.jit misses its executable cache for a new input signature.
    * ``panel_transfers`` / ``panel_bytes`` — device_panel H2D transfer
      events and their approximate wire bytes (data/windows.py).
    * ``program_cache_hits`` / ``_misses`` — compiled-program cache
      outcomes (train/reuse.py); a miss means a trainer had to BUILD
      fresh jit wrappers (which then trace lazily on first dispatch).
    * ``panel_cache_hits`` — device-panel residency cache hits (a fold
      bound an already-resident panel instead of re-transferring).
    * ``host_syncs`` / ``host_sync_s`` — blocking device→host fetches on
      the training path (:func:`timed_device_get`) and the wall seconds
      (float) spent blocked in them. The async epoch pipeline's contract
      is ONE such fetch per epoch (loss + grad-norm + per-month val IC +
      mse + step in a single ``jax.device_get``) instead of a scatter of
      ``float()``/``np.asarray`` syncs.
    * ``device_idle_s`` — host-observed device-idle seconds (float).
      Lock-step mode: the gap between draining the dispatch pipeline (an
      epoch's scalars fetched with nothing else in flight) and the next
      dispatch — the serial host window (sampling, eval sync,
      checkpoint writes) the one-epoch-lookahead pipeline
      (train/pipeline.py, ``LFM_ASYNC``) exists to hide. Async mode: a
      LOWER bound from non-blocking readiness probes — the in-flight
      epoch observed already-complete at the end of a loop iteration
      accrues idle until the next dispatch (an epoch finishing mid-gap
      contributes zero). A proxy either way, not a hardware counter:
      non-zero means real measured idle; zero means none observed.
    """

    _FIELDS = ("jit_traces", "panel_transfers", "panel_bytes",
               "program_cache_hits", "program_cache_misses",
               "panel_cache_hits", "host_syncs", "host_sync_s",
               "device_idle_s")

    def snapshot(self) -> Dict[str, Union[int, float]]:
        """Current value of every field (``host_sync_s`` /
        ``device_idle_s`` are float seconds; the rest int counts)."""
        get = COUNTERS.get
        return {f: get(f) for f in self._FIELDS}

    def delta(self, since: Dict[str, Union[int, float]]
              ) -> Dict[str, Union[int, float]]:
        """Counter increments since a :meth:`snapshot`."""
        now = self.snapshot()
        return {k: now[k] - since.get(k, 0) for k in now}

    def reset(self) -> None:
        for f in self._FIELDS:
            COUNTERS.set(f, 0)


def _field_property(name: str) -> property:
    return property(lambda self: COUNTERS.get(name),
                    lambda self, v: COUNTERS.set(name, v))


for _f in ReuseCounters._FIELDS:
    setattr(ReuseCounters, _f, _field_property(_f))
del _f


#: The process-wide instance every hook point bumps. Deltas (snapshot /
#: delta pairs) are the supported read pattern — absolute values mix all
#: trainers ever built in the process.
REUSE_COUNTERS = ReuseCounters()


def timed_device_get(tree):
    """``jax.device_get`` with host-sync accounting: bumps
    ``REUSE_COUNTERS.host_syncs`` and adds the blocked wall time to
    ``host_sync_s``. The training loop routes EVERY blocking device→host
    fetch through here, which is what makes "one sync per epoch" a
    measured property (fold records in train/walkforward.py, the
    ``epoch_pipeline`` bench metric) instead of a claim.

    Also a chaos-lane fault site (``device_get``, utils/faults.py):
    every counted host sync is injectable, so the failure path of "the
    one blocking fetch per epoch died" is testable on demand. Exact
    no-op when ``LFM_FAULTS`` is unset."""
    from lfm_quant_tpu.utils import faults

    faults.check("device_get")
    t0 = time.perf_counter()
    out = jax.device_get(tree)
    COUNTERS.bump("host_syncs")
    COUNTERS.bump("host_sync_s", time.perf_counter() - t0)
    return out


#: When True, :func:`count_traces` wrappers do NOT bump ``jit_traces``:
#: the program-ledger analysis path (train/reuse.py) re-lowers an
#: already-traced program for cost/memory analysis, and that re-trace is
#: bookkeeping, not a new compiled program on the training path — the
#: reuse lane's zero-trace contract must not see it.
_TRACE_COUNT_SUSPENDED = False


@contextlib.contextmanager
def suspend_trace_counting():
    """Suppress ``jit_traces`` bumps inside the block (single-threaded
    use only — the ledger analysis runs on the dispatching thread)."""
    global _TRACE_COUNT_SUSPENDED
    prev = _TRACE_COUNT_SUSPENDED
    _TRACE_COUNT_SUSPENDED = True
    try:
        yield
    finally:
        _TRACE_COUNT_SUSPENDED = prev


#: Per-thread trace-start stamp (perf_counter at the latest counted
#: trace entry on this thread). The ledger stopwatch (train/reuse.py
#: _LedgeredJit) reads it AFTER a call it detected as traced, so warm
#: calls pay zero clock reads — both the near-zero-overhead contract
#: and the tick-parity contract frozen-clock test harnesses rely on
#: (an uncounted extra read per warm dispatch used to land a caller's
#: interval on one tick and divide by zero).
_TRACE_TLS = threading.local()


def last_trace_t0() -> Optional[float]:
    """perf_counter stamp of this thread's most recent counted trace
    entry (None if the thread never traced a counted program)."""
    return getattr(_TRACE_TLS, "t0", None)


def thread_trace_count() -> int:
    """This THREAD's counted-trace total. The ledger stopwatch compares
    it across a dispatch to decide "this call traced on this thread" —
    the global ``jit_traces`` counter can move on another thread, and
    the t0 stamp VALUE can legitimately repeat under a monkeypatched
    test clock, but this integer only moves when this thread traces."""
    return getattr(_TRACE_TLS, "n", 0)


def count_traces(name: str, fn: Callable) -> Callable:
    """Wrap the OUTERMOST callable handed to ``jax.jit`` so every trace
    bumps ``REUSE_COUNTERS.jit_traces``. The wrapper body runs exactly
    when jit traces (a cached executable skips Python entirely), so the
    counter equals the number of XLA compilations these programs cost.
    Each counted trace also stamps :func:`last_trace_t0` — the ledger's
    compile-stopwatch start, read only on calls that traced.
    ``functools.wraps`` keeps the signature visible for static_argnames
    resolution. ``name`` is for debuggability in tracebacks only."""

    @functools.wraps(fn)
    def traced(*args, **kwargs):
        if not _TRACE_COUNT_SUSPENDED:
            _TRACE_TLS.t0 = time.perf_counter()
            _TRACE_TLS.n = getattr(_TRACE_TLS, "n", 0) + 1
            COUNTERS.bump("jit_traces")
        return fn(*args, **kwargs)

    traced.__qualname__ = f"count_traces[{name}]"
    return traced
