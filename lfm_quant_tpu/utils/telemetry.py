"""Unified run telemetry: span tracing, counters, ledger, manifests.

PRs 1–3 each grew an ad-hoc instrument — ``ReuseCounters`` globals,
``StepTimer`` wall clocks, scattered ``metrics.jsonl`` lines, bench-only
timers. This module is the one observability layer those instruments
now feed, so an operator can see *where* a run's time and HBM went from
the run directory alone (``scripts/trace_report.py``) instead of
re-running bench:

* **Span tracer** — hierarchical wall-clock spans
  (run → fold → fit → epoch → {sample, h2d, dispatch, eval_sync, ckpt}
  → scoring dispatches) emitted two ways per run dir: ``spans.jsonl``
  (one line per closed span, crash-safe append) and ``trace.json``
  (Chrome-trace/Perfetto event stream, written at run finish — load it
  at ui.perfetto.dev). Sync spans nest via a thread-local stack and
  emit complete ("X") events; epochs — which OVERLAP under the async
  pipeline's one-epoch lookahead — are async ("b"/"e") spans keyed by
  id, so the overlap is visible instead of mangled.
* **Named-counter registry** (:data:`COUNTERS`) — the process-wide
  counter store that ABSORBS ``utils/profiling.py`` ``ReuseCounters``
  (kept as a compatibility view over this registry): every span
  snapshots the registry on entry and records the non-zero deltas on
  exit, so counters get per-span attribution instead of only
  process-wide totals. Overlapping async epoch spans snapshot the same
  process-wide registry, so their deltas can double-count across the
  overlap window — leaf sync spans carry the exact attribution.
* **Program ledger** (:func:`record_program_build`, fed by
  ``train/reuse.py ledger_jit``) — per-compiled-program build records:
  compile wall seconds, and (when a run is active; guarded for
  jax-0.4.x availability) XLA ``cost_analysis`` FLOPs/bytes and
  ``memory_analysis`` HBM footprint. In-memory for bench introspection,
  appended to ``ledger.jsonl`` when a run dir is attached.
* **Run manifest** (:func:`write_manifest`) — resolved config, LFM_*
  knob states, jax/jaxlib versions, device topology and git sha at run
  start, written by the train.py / backtest.py entry points through
  :func:`run_scope`.

Gating: ``LFM_TELEMETRY`` (default ON; ``0`` disables everything this
module adds). Span/ledger EMISSION additionally requires an active run
(:func:`run_scope` — the entry points attach one when they have a run
dir), so library code can instrument unconditionally. The disabled
path is near-zero overhead — an env read and a None check — and no
telemetry code path ever touches a device: no ``device_get``, no
``block_until_ready``, no trace. The ``reuse`` and ``pipeline`` lanes
(zero extra jit traces, zero warm-fold panel H2D, exactly one blocking
host sync per epoch) hold with the knob in either state, and
``tests/test_telemetry.py`` pins that.
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional


def _jsonsafe(d: Dict[str, Any]) -> Dict[str, Any]:
    """Non-finite floats → None (recursive): a bare ``NaN`` token would
    corrupt the strict-JSON span stream and trace.json. One policy,
    one implementation — shared with the metrics stream."""
    from lfm_quant_tpu.utils.logging import _finite

    return {k: _finite(v) for k, v in d.items()}


def enabled() -> bool:
    """Master kill switch: ``LFM_TELEMETRY=0`` disables spans, ledger
    recording and manifests (the counters in :data:`COUNTERS` stay live
    — the reuse/pipeline lanes assert on them and they predate this
    module)."""
    return os.environ.get("LFM_TELEMETRY", "1") != "0"


# ---- named-counter registry ---------------------------------------------


class CounterRegistry:
    """Process-wide named counters (int or float), safe under concurrent
    writers. The pre-serving design was lock-free (every hot-path writer
    — trace counting, H2D accounting, host-sync timing — ran on the one
    dispatching thread, so a ``dict`` read-modify-write under the GIL
    was enough); the scoring service broke that assumption: request
    threads, the micro-batcher thread and a refresh fit all bump
    concurrently, and ``c[name] = c.get(name, 0) + value`` loses
    increments when two threads interleave between the read and the
    store. Every mutation now takes the registry lock — an uncontended
    ``threading.Lock`` is tens of nanoseconds against multi-ms
    dispatches, and the reuse/pipeline lanes' non-interference contract
    is re-measured with the lock in place. ``get`` stays lock-free (a
    single dict read is atomic under the GIL; staleness by one in-flight
    bump was always possible for cross-thread readers and remains the
    documented worst case)."""

    __slots__ = ("_c", "_lock")

    def __init__(self):
        self._c: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def bump(self, name: str, value=1) -> None:
        with self._lock:
            c = self._c
            c[name] = c.get(name, 0) + value

    def peak(self, name: str, value) -> None:
        """Monotone max: record ``value`` if it exceeds the current one
        (queue-depth high-water marks and the like)."""
        with self._lock:
            c = self._c
            if value > c.get(name, 0):
                c[name] = value

    def get(self, name: str):
        return self._c.get(name, 0)

    def set(self, name: str, value) -> None:
        with self._lock:
            self._c[name] = value

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._c)

    def delta(self, since: Dict[str, Any]) -> Dict[str, Any]:
        """Non-zero counter increments since a :meth:`snapshot` (keys
        absent from ``since`` count from 0)."""
        with self._lock:
            return {k: v - since.get(k, 0) for k, v in self._c.items()
                    if v != since.get(k, 0)}

    def reset(self) -> None:
        with self._lock:
            self._c.clear()


#: The registry every instrument bumps. ``ReuseCounters``
#: (utils/profiling.py) is a fixed-field compatibility view over it.
COUNTERS = CounterRegistry()


# ---- span tracer ---------------------------------------------------------

_TL = threading.local()  # .stack: [span name, ...] per thread


def _fresh_path(run_dir: str, stem: str, ext: str, pid: int) -> str:
    """Atomically CLAIM ``<stem>.<ext>`` (O_CREAT|O_EXCL — exactly one
    process wins even when several race on the same run dir, e.g. a
    multi-host pod's ranks or a backtest launched beside a live train),
    else fall back to ``<stem>.<pid>.<ext>``: later processes must
    never clobber the first one's artifact (the train run's
    manifest/trace are the canonical ones; a follow-up backtest gets
    its own files). The claimed empty file is atomically replaced with
    real content by the caller."""
    path = os.path.join(run_dir, f"{stem}.{ext}")
    try:
        os.close(os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY))
        return path
    except FileExistsError:
        return os.path.join(run_dir, f"{stem}.{pid}.{ext}")


def _stack() -> List[str]:
    s = getattr(_TL, "stack", None)
    if s is None:
        s = _TL.stack = []
    return s


class _NullSpan:
    """Shared no-op span: the disabled/inactive path allocates nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **args) -> None:
        pass

    def end(self, **args) -> None:
        pass


_NULL = _NullSpan()


class _Span:
    """A sync (nested, thread-local) span → one "X" trace event."""

    __slots__ = ("_run", "name", "cat", "args", "_t0", "_wall0", "_c0",
                 "_parent", "_depth")

    def __init__(self, run: "TelemetryRun", name: str, cat: str,
                 args: Dict[str, Any]):
        self._run = run
        self.name = name
        self.cat = cat
        self.args = args

    def set(self, **args) -> None:
        """Attach result args before the span closes (e.g. epochs_run)."""
        self.args.update(args)

    def __enter__(self):
        st = _stack()
        self._parent = st[-1] if st else None
        self._depth = len(st)
        st.append(self.name)
        self._c0 = COUNTERS.snapshot()
        self._wall0 = time.time()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dur = time.perf_counter() - self._t0
        st = _stack()
        if st and st[-1] == self.name:
            st.pop()
        self._run._record(self.name, self.cat, self._wall0, self._t0, dur,
                          self.args, COUNTERS.delta(self._c0),
                          parent=self._parent, depth=self._depth)
        return False


class _AsyncSpan:
    """An id-keyed span that may overlap others on the same thread (the
    pipeline's in-flight epochs) → a "b"/"e" trace event pair."""

    __slots__ = ("_run", "name", "cat", "args", "_t0", "_wall0", "_c0",
                 "_id", "_parent", "_done")

    def __init__(self, run: "TelemetryRun", name: str, cat: str,
                 args: Dict[str, Any]):
        self._run = run
        self.name = name
        self.cat = cat
        self.args = args
        st = _stack()
        self._parent = st[-1] if st else None
        self._id = run._next_id()
        self._c0 = COUNTERS.snapshot()
        self._wall0 = time.time()
        self._t0 = time.perf_counter()
        self._done = False
        run._event("b", name, cat, self._t0, args=dict(args), id=self._id)

    def set(self, **args) -> None:
        self.args.update(args)

    def end(self, **args) -> None:
        if self._done:
            return
        self._done = True
        self.args.update(args)
        dur = time.perf_counter() - self._t0
        self._run._event("e", self.name, self.cat, time.perf_counter(),
                         args={}, id=self._id)
        self._run._record(self.name, self.cat, self._wall0, self._t0, dur,
                          self.args, COUNTERS.delta(self._c0),
                          parent=self._parent, depth=None, event=False)


class TelemetryRun:
    """One activated run: open span stream + streamed Chrome events.

    ``spans.jsonl`` gets a line per CLOSED span as it closes (line-
    buffered append — a crash loses at most the in-flight spans). The
    Chrome-trace stream is written the same way: the trace file is
    claimed at run START (the first process owns the canonical
    ``trace.json``; racers get ``trace.<pid>.json``) and every event
    streams to it line-buffered with a trailing comma — O(1) host
    memory over arbitrarily long runs, and a crash leaves a truncated
    array Perfetto still loads (its JSON importer tolerates an
    unterminated ``traceEvents``). :meth:`finish` writes the closing
    sentinel + bracket (strict JSON from then on) plus a run-level
    record in the jsonl stream carrying the run's wall time and counter
    deltas — what ``trace_report`` rolls up."""

    def __init__(self, run_dir: str):
        self.run_dir = run_dir
        os.makedirs(run_dir, exist_ok=True)
        self._fh: Optional[io.TextIOBase] = open(
            os.path.join(run_dir, "spans.jsonl"), "a", buffering=1)
        self._pid = os.getpid()
        self.trace_path = _fresh_path(run_dir, "trace", "json", self._pid)
        self._trace_fh: Optional[io.TextIOBase] = open(
            self.trace_path, "w", buffering=1)
        self._trace_fh.write('{"displayTimeUnit": "ms", "traceEvents": [\n')
        self._lock = threading.Lock()
        self._ids = 0
        self._t0 = time.perf_counter()
        self._wall0 = time.time()
        self._c0 = COUNTERS.snapshot()
        self.n_spans = 0
        self._threads_named: set = set()

    def counters_at_start(self) -> Dict[str, Any]:
        """The counter-registry snapshot taken when this run attached —
        the baseline that turns process-LIFETIME counter totals into
        run-scoped deltas (the run record's ``d`` uses it at finish;
        the incident bundles use it mid-run, DESIGN.md §21)."""
        return dict(self._c0)

    # -- low-level emission ------------------------------------------

    def _next_id(self) -> int:
        with self._lock:
            self._ids += 1
            return self._ids

    def _us(self, t_perf: float) -> float:
        return (t_perf - self._t0) * 1e6

    def _event(self, ph: str, name: str, cat: str, t_perf: float, *,
               args: Dict[str, Any], dur_s: Optional[float] = None,
               id: Optional[int] = None) -> None:
        tid = threading.get_ident()
        ev = {"name": name, "cat": cat or "span", "ph": ph,
              "ts": round(self._us(t_perf), 1), "pid": self._pid,
              "tid": tid, "args": _jsonsafe(args)}
        if dur_s is not None:
            ev["dur"] = round(dur_s * 1e6, 1)
        if id is not None:
            ev["id"] = id
        with self._lock:
            if self._trace_fh is None:
                return
            if tid not in self._threads_named:
                self._threads_named.add(tid)
                self._trace_fh.write(json.dumps({
                    "name": "thread_name", "ph": "M", "pid": self._pid,
                    "tid": tid,
                    "args": {"name": threading.current_thread().name}})
                    + ",\n")
            self._trace_fh.write(json.dumps(ev, default=str) + ",\n")

    def _record(self, name: str, cat: str, wall0: float, t0_perf: float,
                dur_s: float, args: Dict[str, Any],
                deltas: Dict[str, Any], *, parent: Optional[str],
                depth: Optional[int], event: bool = True) -> None:
        rec = {"name": name, "cat": cat, "ts": round(wall0, 6),
               "dur_s": round(dur_s, 6), "parent": parent,
               "thread": threading.current_thread().name}
        if depth is not None:
            rec["depth"] = depth
        if args:
            rec["args"] = _jsonsafe(args)
        if deltas:
            rec["d"] = _jsonsafe(
                {k: (round(v, 6) if isinstance(v, float) else v)
                 for k, v in deltas.items()})
        line = json.dumps(rec, default=str) + "\n"
        if event:
            self._event("X", name, cat, t0_perf, args={**args, **deltas},
                        dur_s=dur_s)
        with self._lock:
            if self._fh is None:
                return
            self.n_spans += 1
            self._fh.write(line)

    def ledger_line(self, entry: Dict[str, Any]) -> None:
        """Append a program-ledger record to ``ledger.jsonl``."""
        try:
            with self._lock:
                if self._fh is None:
                    return
                with open(os.path.join(self.run_dir, "ledger.jsonl"),
                          "a") as fh:
                    fh.write(json.dumps(entry, default=str) + "\n")
        except OSError:
            pass  # ledger is best-effort; never kill a training run

    # -- lifecycle ----------------------------------------------------

    def finish(self) -> None:
        """Write the run record, terminate the trace document (a final
        sentinel metadata event absorbs the streamed trailing comma)
        and close both streams. A run dir accumulates processes (train,
        then backtest, then a resume): ``spans.jsonl`` appends; each
        process has its own trace document (claimed at start)."""
        global _ACTIVE
        dur = time.perf_counter() - self._t0
        self._event("X", "run", "run", self._t0, args={}, dur_s=dur)
        self._record("run", "run", self._wall0, self._t0, dur,
                     {"n_spans": self.n_spans},
                     COUNTERS.delta(self._c0), parent=None, depth=0,
                     event=False)
        with self._lock:
            if self._fh is None:
                return
            self._fh.close()
            self._fh = None
            self._trace_fh.write(json.dumps(
                {"name": "trace_end", "ph": "M", "pid": self._pid,
                 "args": {"n_spans": self.n_spans}}) + "\n]}\n")
            self._trace_fh.close()
            self._trace_fh = None
        if _ACTIVE is self:
            _ACTIVE = None


_ACTIVE: Optional[TelemetryRun] = None


def active_run() -> Optional[TelemetryRun]:
    return _ACTIVE if enabled() else None


def span(name: str, cat: str = "span", **args):
    """A sync span context manager; no-op (shared singleton, no
    allocation beyond the kwargs dict) when telemetry is disabled or no
    run is active. ``with telemetry.span("sample", epoch=3): ...``"""
    run = _ACTIVE
    if run is None or not enabled():
        return _NULL
    return _Span(run, name, cat, args)


def begin_async(name: str, cat: str = "epoch", **args):
    """Begin an async (overlappable) span; call ``.end(**args)`` to
    close it. Used for the pipeline's in-flight epochs, which overlap
    on the dispatching thread."""
    run = _ACTIVE
    if run is None or not enabled():
        return _NULL
    return _AsyncSpan(run, name, cat, args)


def instant(name: str, cat: str = "mark", **args) -> None:
    """A zero-duration marker event (early stop, fold boundary, ...).
    Emitted to the Chrome-trace stream AND as a zero-duration spans.jsonl
    record, so offline rollups (scripts/trace_report.py — e.g. the
    fold-stack section's per-fold ``fold_stopped`` marks) can read
    markers without parsing the trace file.

    Every instant ALSO lands in the black-box flight recorder
    (``utils/flight.py``) — BEFORE the run-active gate, because the
    recorder's whole point is capturing breaker transitions, fault
    injections, publishes and quarantines on processes that never
    attached a run dir (the incident bundles of DESIGN.md §21)."""
    from lfm_quant_tpu.utils import flight

    flight.note(name, cat, args)
    run = _ACTIVE
    if run is None or not enabled():
        return
    t0 = time.perf_counter()
    run._event("i", name, cat, t0, args=args)
    stack = _stack()
    run._record(name, cat, time.time(), t0, 0.0, args, {},
                parent=stack[-1] if stack else None, depth=len(stack),
                event=False)


# ---- run manifest --------------------------------------------------------

#: Resolved-knob probes for the manifest: name → zero-arg callable.
_KNOB_PROBES = (
    ("program_reuse", "lfm_quant_tpu.train.reuse", "reuse_enabled"),
    ("donation", "lfm_quant_tpu.train.reuse", "donation_enabled"),
    ("async_pipeline", "lfm_quant_tpu.train.reuse", "async_enabled"),
    ("async_ckpt", "lfm_quant_tpu.train.reuse", "async_ckpt_enabled"),
    ("foldstack", "lfm_quant_tpu.train.reuse", "foldstack_enabled"),
    ("buckets", "lfm_quant_tpu.buckets", "buckets_enabled"),
    ("jax_backtest", "lfm_quant_tpu.backtest", "jax_backtest_enabled"),
    # Compute-precision lane (LFM_PRECISION, DESIGN.md §17): the env
    # resolution ("f32"/"bf16") — per-config overrides additionally land
    # in the manifest's config block. scripts/check_knobs.py pins that
    # every probed knob here resolves.
    ("precision", "lfm_quant_tpu.config", "resolve_precision"),
    # Live metrics plane (LFM_METRICS, DESIGN.md §19): whether the
    # always-on instruments record at all (the /metrics kill switch).
    ("metrics", "lfm_quant_tpu.utils.metrics", "enabled"),
    # Durable serving state (LFM_ZOO_PERSIST, DESIGN.md §20): whether
    # published zoo generations are journaled to a durable store.
    ("zoo_persist", "lfm_quant_tpu.serve.persist", "persist_enabled"),
    # Black-box flight recorder (LFM_FLIGHT, DESIGN.md §21): whether
    # the always-on event ring records (the incident-bundle evidence).
    ("flight", "lfm_quant_tpu.utils.flight", "enabled"),
    # Fleet serving (LFM_FLEET, DESIGN.md §22): whether serve.py runs
    # N subprocess members behind the failover router.
    ("fleet", "lfm_quant_tpu.serve.fleet", "fleet_enabled"),
)


def _git_sha() -> Optional[str]:
    try:
        import subprocess

        out = subprocess.run(["git", "rev-parse", "HEAD"],
                             capture_output=True, text=True, timeout=5)
        return out.stdout.strip() or None if out.returncode == 0 else None
    except Exception:
        return None


_BUILD_INFO: Optional[Dict[str, Any]] = None


def build_info() -> Dict[str, Any]:
    """Fleet/host identity, cached after first probe: git sha, jax /
    jaxlib versions, backend, resolved compute dtype, device count,
    hostname and pid — the ROADMAP item-2 groundwork. One record, two
    consumers: the ``build_info`` gauge labels on ``/metrics``
    (serve/monitor.py — how a fleet aggregator tells WHICH build a
    scrape came from) and the host-identity block stamped into every
    incident bundle (serve/incident.py). Every probe degrades to None
    rather than failing a serving process."""
    global _BUILD_INFO
    if _BUILD_INFO is not None:
        info = dict(_BUILD_INFO)
    else:
        import socket

        info = {
            "git_sha": _git_sha(),
            "host": socket.gethostname(),
            "pid": os.getpid(),
            "python": sys.version.split()[0],
        }
        _probe_build_env(info)
        _BUILD_INFO = dict(info)
    # The precision lane is re-resolved per call (config-over-env, can
    # flip in-process — the amp lane does); everything above is
    # process-constant and cached.
    try:
        from lfm_quant_tpu.config import resolve_precision

        info["dtype"] = resolve_precision()
    except Exception:
        info["dtype"] = None
    return info


def _probe_build_env(info: Dict[str, Any]) -> None:
    try:
        import jax
        import jaxlib

        info["jax"] = jax.__version__
        info["jaxlib"] = jaxlib.__version__
        info["backend"] = jax.default_backend()
        info["device_count"] = len(jax.devices())
    except Exception:
        info.setdefault("jax", None)
        info.setdefault("jaxlib", None)
        info.setdefault("backend", None)
        info.setdefault("device_count", None)


def build_manifest(config: Any = None,
                   extra: Optional[Dict[str, Any]] = None
                   ) -> Dict[str, Any]:
    """The run-start provenance record: everything needed to interpret
    (and re-run) the run dir's artifacts. Every probe degrades to an
    error string rather than failing the run."""
    import dataclasses

    m: Dict[str, Any] = {
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "argv": list(sys.argv),
        "cwd": os.getcwd(),
        "python": sys.version.split()[0],
        "pid": os.getpid(),
        "git_sha": _git_sha(),
        "env_lfm": {k: v for k, v in sorted(os.environ.items())
                    if k.startswith("LFM_")},
    }
    if config is not None:
        try:
            m["config"] = (dataclasses.asdict(config)
                           if dataclasses.is_dataclass(config) else config)
        except Exception as e:
            m["config"] = f"<unserializable: {e!r}>"
    knobs: Dict[str, Any] = {"telemetry": enabled()}
    for name, mod, fn in _KNOB_PROBES:
        try:
            import importlib

            knobs[name] = getattr(importlib.import_module(mod), fn)()
        except Exception:
            knobs[name] = None
    m["knobs"] = knobs
    try:
        import jax
        import jaxlib

        devs = jax.devices()
        m["jax"] = {
            "jax_version": jax.__version__,
            "jaxlib_version": jaxlib.__version__,
            "backend": jax.default_backend(),
            "device_count": len(devs),
            "local_device_count": jax.local_device_count(),
            "process_count": jax.process_count(),
            "device_kinds": sorted({d.device_kind for d in devs}),
        }
    except Exception as e:
        m["jax"] = f"<unavailable: {e!r}>"
    if extra:
        m.update(extra)
    return m


def write_manifest(run_dir: str, config: Any = None,
                   extra: Optional[Dict[str, Any]] = None
                   ) -> Optional[Dict[str, Any]]:
    """Atomically write the run manifest into ``run_dir`` (no-op when
    telemetry is disabled). The first process owns ``manifest.json``;
    later ones (a backtest pass over a train run dir) write
    ``manifest.<pid>.json`` so the training provenance survives.
    Returns the manifest dict."""
    if not enabled():
        return None
    m = build_manifest(config, extra)
    os.makedirs(run_dir, exist_ok=True)
    path = _fresh_path(run_dir, "manifest", "json", os.getpid())
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(m, fh, indent=2, default=str)
    os.replace(tmp, path)
    return m


# ---- run lifecycle -------------------------------------------------------


def start_run(run_dir: str, config: Any = None,
              extra: Optional[Dict[str, Any]] = None
              ) -> Optional[TelemetryRun]:
    """Activate span/ledger emission into ``run_dir`` and write the run
    manifest. Returns None (and does nothing) when telemetry is
    disabled or a run is already active — nested activations keep the
    outermost run (one process = one trace stream)."""
    global _ACTIVE
    if not enabled() or _ACTIVE is not None:
        return None
    write_manifest(run_dir, config, extra)
    _ACTIVE = TelemetryRun(run_dir)
    return _ACTIVE


@contextlib.contextmanager
def run_scope(run_dir: Optional[str], config: Any = None,
              extra: Optional[Dict[str, Any]] = None):
    """Context manager the CLI entry points wrap their work in:
    manifest + span emission on entry, ``trace.json`` + run record on
    exit. A None run dir (or disabled telemetry, or an already-active
    run) degrades to a no-op."""
    run = start_run(run_dir, config, extra) if run_dir else None
    try:
        yield run
    finally:
        if run is not None:
            run.finish()


# ---- program ledger ------------------------------------------------------

_LEDGER: List[Dict[str, Any]] = []
_LEDGER_LOCK = threading.Lock()


def analysis_mode() -> str:
    """``LFM_TELEMETRY_ANALYSIS``: ``auto`` (default — while a
    telemetry run is active, run the CHEAP XLA ``cost_analysis`` on the
    lowering only; tests and bench, with no active run, pay nothing),
    ``1`` (additionally run the ``memory_analysis`` HBM footprint,
    which costs a second full XLA compile per program — opt-in because
    it lands synchronously on the training path's cold start), ``0``
    (never analyze)."""
    return os.environ.get("LFM_TELEMETRY_ANALYSIS", "auto")


def analysis_active() -> bool:
    mode = analysis_mode()
    if mode == "0" or not enabled():
        return False
    return mode == "1" or _ACTIVE is not None


def deep_analysis_active() -> bool:
    """Whether the compile()-backed ``memory_analysis`` runs too —
    ``LFM_TELEMETRY_ANALYSIS=1`` only. Roughly doubles each program's
    cold compile wall time, so it is never on by default."""
    return enabled() and analysis_mode() == "1"


def record_program_build(entry: Dict[str, Any]) -> None:
    """Append a program-build record (from ``train/reuse.py
    ledger_jit``) to the in-process ledger and, when a run is active,
    to the run dir's ``ledger.jsonl``."""
    entry = dict(entry)
    entry.setdefault("ts", time.time())
    with _LEDGER_LOCK:
        _LEDGER.append(entry)
    COUNTERS.bump("program_builds")
    COUNTERS.bump("compile_s", entry.get("compile_s", 0.0))
    run = _ACTIVE
    if run is not None and enabled():
        run.ledger_line(entry)


def program_ledger() -> List[Dict[str, Any]]:
    """A copy of the in-process program-build ledger."""
    with _LEDGER_LOCK:
        return list(_LEDGER)


def program_ledger_totals() -> Dict[str, float]:
    """Rollup for bench rows: total builds and compile wall seconds."""
    with _LEDGER_LOCK:
        return {"builds": len(_LEDGER),
                "compile_s": sum(e.get("compile_s", 0.0) for e in _LEDGER)}
